#!/usr/bin/env python
"""Benchmark: word-count throughput, TPU path vs the sequential oracle.

This measures exactly BASELINE.json's metric — word-count MB/s on a pg-style
corpus versus the sequential reference semantics (`main/mrsequential.go`),
with mr-out-* diff parity as a hard gate.  The oracle is this repo's
line-for-line-semantics port of `main/mrsequential.go:38-86`; the TPU path is
the whole-corpus fused program (`dsi_tpu/ops/corpus_wc.py`): pieced async
uploads, ONE tokenize/sort/group/count launch over the merged corpus, ONE
position-coded D2H pull (~8 bytes per unique word), host-side output files
partitioned by the reference's `ihash % NReduce` (`mr/worker.go:33-37,76`).
The program is compiled through the persistent AOT executable cache
(`dsi_tpu/backends/aotcache.py`), so only the first-ever process on a
machine pays the XLA compile.

The timed region runs DSI_BENCH_REPS times (default 5) and the best rep is
reported — the axon tunnel's transfer bandwidth fluctuates by >10x between
moments, and min-of-N is the standard way to report a machine's capability
rather than the tunnel's worst congestion instant.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": MB/s, "unit": "MB/s", "vs_baseline": speedup}
`vs_baseline` is TPU MB/s over oracle MB/s measured in the same run on the
same corpus (the reference publishes no numbers of its own — BASELINE.md).

Robustness discipline (the always-emit-a-verdict rule of the reference's
harness, test-mr.sh:55-59): the oracle runs FIRST and needs no accelerator,
so its MB/s is always captured; the TPU half runs in a watchdog subprocess
(the axon device-init path has been observed to hang > 25 min) with bounded
retries and a global deadline.  If every TPU attempt fails (e.g. the tunnel
outage in BASELINE.md's incident log), the same pipeline is measured once
on the CPU backend and reported with ``tpu_error`` + a port-probe
``diagnosis`` attached — separating "framework broken" from "tunnel down".
Every failure mode still emits the JSON line before exit.  Diagnostics go
to stderr.

Environment knobs:
  DSI_BENCH_TPU_TIMEOUTS  per-attempt child timeouts, seconds (default
                          "1200,420,240" — first attempt covers a cold
                          axon compile (219 s observed round 2, can
                          exceed 900 s); later ones assume the
                          persistent AOT cache is warm)
  DSI_BENCH_DEADLINE_S    global wall budget for the TPU half (default
                          2100).  An attempt only starts if >= 60 s of
                          budget remain (anything less cannot even cover
                          device init), so values under 60 disable the TPU
                          half entirely.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Persistent compile cache: the TPU path's programs compile once per corpus
# shape; later bench runs (and the driver's) skip straight to execution.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jaxcache"))

N_FILES = int(os.environ.get("DSI_BENCH_FILES", "8"))
FILE_SIZE = int(os.environ.get("DSI_BENCH_FILE_SIZE",
                               str((2 << 20) - 64)))  # pads to 2^21 on device
N_REDUCE = 10
# Overridable so tests (and ad-hoc small-corpus runs) don't overwrite the
# canonical .bench corpus/oracle the warm loop's parity checks rely on.
WORKDIR = (os.environ.get("DSI_BENCH_WORKDIR")
           or os.path.join(REPO, ".bench"))
ORACLE_OUT = os.path.join(WORKDIR, "mr-correct.txt")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_oracle(files) -> tuple[float, float]:
    """Sequential oracle (mrsequential.go:38-86 semantics); pure host CPU."""
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential
    from dsi_tpu.utils.tracing import Span

    with Span("bench.oracle") as pt:
        run_sequential(wc.Map, wc.Reduce, files, ORACLE_OUT)
    dt = pt.elapsed_s
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    return dt, total_mb / dt


def tpu_child(result_path: str) -> int:
    """Child-process body: device init + kernel path + parity check.

    Everything that can hang (axon backend init, compiles) happens here, so
    the parent's kill-on-timeout recovers from any of it.  Writes a JSON
    result to ``result_path``; parent treats a missing file as failure.
    """
    from dsi_tpu.backends import aotcache
    from dsi_tpu.ops.corpus_wc import corpus_wordcount, write_corpus_output
    from dsi_tpu.utils.corpus import ensure_corpus
    from dsi_tpu.utils.tracing import Span

    def emit(obj: dict) -> None:
        # Per-thread temp name: the init-watchdog thread and the main
        # thread may both emit around the init deadline; a shared temp
        # file could tear.  Both os.replace targets are atomic.
        import threading

        tmp = f"{result_path}.tmp{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, result_path)

    # Same deterministic list as the parent's oracle run — NOT a directory
    # glob, which would sweep in stale pg-*.txt files from an older corpus
    # configuration and guarantee a parity mismatch.
    files = ensure_corpus(WORKDIR, n_files=N_FILES, file_size=FILE_SIZE)

    # Graceful-shutdown seam for the parent watchdog's SIGTERM: SystemExit
    # unwinds the interpreter so the PJRT client's destructor releases the
    # device claim (a SIGKILL here wedges the claim for later processes).
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()
    import jax

    # Self-bounded init: a wedged device claim blocks jax.devices() inside
    # a C call indefinitely (signals deferred, so only SIGKILL from outside
    # works).  This daemon thread turns that into a clean, fast error
    # verdict: no claim is held pre-init, so _exit is safe here.
    # (When run under the full bench, the parent watchdog's init deadline
    # is the backstop; set this BELOW it — onchip_evidence.sh uses 150 <
    # the parent's 180 — so the clean child verdict wins the race.)
    try:
        init_timeout = float(
            os.environ.get("DSI_CHILD_INIT_TIMEOUT", "0") or 0)
    except ValueError:
        log("ignoring malformed DSI_CHILD_INIT_TIMEOUT")
        init_timeout = 0.0
    import threading

    init_settled = threading.Event()  # set once jax.devices() returns/raises
    if init_timeout > 0:
        def _init_watchdog():
            # wait() (not sleep) + a 5 s grace re-check close the race
            # where init completes right at the deadline: _exit on a
            # process holding a live claim would wedge the device.
            if init_settled.wait(init_timeout):
                return
            if init_settled.wait(5.0):
                return
            emit({"error": f"device init exceeded {init_timeout:.0f}s "
                           "(outage or wedged claim)"})
            if init_settled.is_set():
                # Init completed during the emit itself: a verdict file
                # now wrongly claims failure, but exiting would be worse
                # (_exit on a live claim wedges the device) — let the
                # main thread overwrite the verdict with the real one.
                return
            os._exit(3)

        threading.Thread(target=_init_watchdog, daemon=True).start()

    t0 = time.perf_counter()
    try:
        devices = jax.devices()
    except RuntimeError as e:
        init_settled.set()
        emit({"error": f"device init failed: {e}"})
        return 1
    init_settled.set()
    init_s = time.perf_counter() - t0
    platform = devices[0].platform
    log(f"child: devices={devices} init={init_s:.1f}s")
    # Tell the watchdog parent init completed: a wedged device claim hangs
    # inside jax.devices() indefinitely (observed on this platform), and the
    # parent fails the attempt fast when this marker doesn't appear.
    with open(result_path + ".init", "w") as f:
        f.write(f"{init_s:.1f}")

    def run_once(pack6: bool):
        phases = {"mode": "pack6" if pack6 else "raw"}
        t0 = time.perf_counter()
        raws = []
        for p in files:
            with open(p, "rb") as f:
                raws.append(f.read())
        phases["read_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        res = corpus_wordcount(raws, pack6=pack6)
        phases["kernel_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        if res is not None:
            write_corpus_output(res, N_REDUCE, WORKDIR)
        phases["write_s"] = round(time.perf_counter() - t0, 3)
        return res, phases

    # Warm-up (untimed): loads both AOT executables (or pays the one-time
    # XLA compiles and saves them), warms the first-D2H path (~0.5-3 s
    # one-time on this platform), and produces one full output set.
    with Span("bench.warmup") as pt:
        for pack6 in (False, True):
            wres, _ = run_once(pack6)
            if wres is None:
                emit({"error": "kernel fell back to host on this corpus",
                      "permanent": True})
                return 1
    warmup_s = pt.elapsed_s
    compile_s = aotcache.stats["compiled_s"]
    log(f"warmup {warmup_s:.2f}s (aot: {aotcache.stats})")

    # Reps alternate raw / 6-bit-packed uploads; best-of-N then picks the
    # winning transport empirically for this moment's tunnel bandwidth.
    reps = max(1, int(os.environ.get("DSI_BENCH_REPS", "5")))
    dt, best_phases = None, {}
    for rep in range(reps):
        t_all = time.perf_counter()
        res, phases = run_once(pack6=rep % 2 == 1)
        rep_s = time.perf_counter() - t_all
        log(f"rep {rep + 1}/{reps}: {rep_s:.3f}s {phases}")
        if res is None:
            emit({"error": "kernel fell back mid-run", "permanent": True})
            return 1
        if dt is None or rep_s < dt:
            dt, best_phases = rep_s, phases

    tpu_lines = []
    for r in range(N_REDUCE):
        with open(os.path.join(WORKDIR, f"mr-out-{r}"),
                  encoding="utf-8") as f:
            tpu_lines.extend(l for l in f if l.strip())
    tpu_lines.sort()
    with open(ORACLE_OUT, encoding="utf-8") as f:
        oracle_lines = sorted(l for l in f if l.strip())

    parity = tpu_lines == oracle_lines
    if not parity:
        import itertools
        for i, (a, b) in enumerate(
                itertools.zip_longest(tpu_lines, oracle_lines)):
            if a != b:
                log(f"first diff at line {i}: tpu={a!r} oracle={b!r} (lines:"
                    f" tpu={len(tpu_lines)} oracle={len(oracle_lines)})")
                break

    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    phases = {"init_s": round(init_s, 1),
              "compile_s": round(compile_s, 3),
              "warmup_s": round(warmup_s, 3),
              "aot_loads": aotcache.stats["loads"],
              "reps": reps}
    phases.update(best_phases)
    emit({"tpu_s": round(dt, 3), "tpu_mbps": round(total_mb / dt, 2),
          "parity": parity, "platform": platform, "phases": phases})
    return 0


def run_tpu_watchdogged() -> dict:
    """Run the TPU half in a subprocess with per-attempt timeouts and a
    global deadline; return its result dict or {"error": ...}."""
    # Malformed env knobs must not break the always-emit-a-verdict
    # contract: fall back to defaults rather than raising past main().
    try:
        timeouts = [
            float(x) for x in os.environ.get(
                "DSI_BENCH_TPU_TIMEOUTS", "1200,420,240").split(",")]
    except ValueError:
        log("ignoring malformed DSI_BENCH_TPU_TIMEOUTS")
        timeouts = [1200.0, 420.0, 240.0]
    try:
        budget_s = float(os.environ.get("DSI_BENCH_DEADLINE_S", "2100"))
    except ValueError:
        log("ignoring malformed DSI_BENCH_DEADLINE_S")
        budget_s = 2100.0
    deadline = time.monotonic() + budget_s
    result_path = os.path.join(WORKDIR, "tpu-result.json")
    last_err = "no attempt ran"
    for attempt, budget in enumerate(timeouts, 1):
        remaining = deadline - time.monotonic()
        if remaining < 60:
            last_err += f"; global deadline reached before attempt {attempt}"
            break
        budget = min(budget, remaining)
        for suffix in ("", ".init"):
            try:
                os.remove(result_path + suffix)
            except OSError:
                pass
        log(f"tpu attempt {attempt}/{len(timeouts)} (timeout {budget:.0f}s)")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-child",
             result_path], stdout=sys.stderr)
        timed_out = False
        # Fail fast on a wedged device claim: the child drops a marker file
        # the moment jax.devices() returns; no marker within the init budget
        # means the claim is hung and the whole attempt budget would be
        # wasted inside device init.
        try:
            init_budget = float(os.environ.get("DSI_BENCH_INIT_TIMEOUT", "180"))
        except ValueError:
            init_budget = 180.0
        init_deadline = time.monotonic() + min(init_budget, budget)
        attempt_deadline = time.monotonic() + budget
        rc = None
        while True:
            try:
                rc = proc.wait(timeout=2.0)
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.monotonic()
            if now >= attempt_deadline or (
                    not os.path.exists(result_path + ".init")
                    and now >= init_deadline):
                if os.path.exists(result_path + ".init"):
                    # Post-init child: SIGTERM + grace so its handler can
                    # unwind the PJRT client and release the device claim
                    # (a SIGKILL mid-claim wedges the device for later
                    # processes — BASELINE.md incident log).
                    proc.terminate()
                    try:
                        rc = proc.wait(timeout=20.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        rc = proc.wait()
                else:
                    # Init-hang: the child is blocked inside the
                    # jax.devices() C call, where CPython cannot run the
                    # SIGTERM handler anyway — waiting 20 s would just burn
                    # deadline budget before the same SIGKILL.  A polling
                    # pre-init client holds no claim, so the kill is safe.
                    proc.kill()
                    rc = proc.wait()
                timed_out = True
                if not os.path.exists(result_path + ".init"):
                    log(f"attempt {attempt}: device init hung "
                        f">{min(init_budget, budget):.0f}s (wedged claim?)")
                break
        if os.path.exists(result_path):
            # Even after a timeout: the child writes its result atomically as
            # its LAST act, so a child that measured successfully but hung in
            # interpreter/JAX teardown still produced a valid verdict.
            with open(result_path) as f:
                res = json.load(f)
            if "error" not in res:
                return res
            if res.get("permanent"):
                # Deterministic failure (kernel fallback on this corpus):
                # retrying cannot change the outcome.
                return res
            last_err = f"attempt {attempt}: {res['error']}"
        elif timed_out:
            if not os.path.exists(result_path + ".init"):
                last_err = (f"attempt {attempt}: device init never completed "
                            "(wedged claim?)")
                probes = probe_tunnel_ports()
                if not any(up for _, _, up in probes):
                    # Every tunnel port is closed: further attempts cannot
                    # init either — stop burning the caller's budget (the
                    # driver's external timeout is finite) and let the CPU
                    # fallback produce the verdict sooner.
                    last_err += ("; all tunnel ports closed "
                                 f"({diagnose_tunnel(probes)})")
                    log(last_err)
                    break
            else:
                last_err = f"attempt {attempt} timed out after {budget:.0f}s"
        else:
            last_err = f"attempt {attempt} exited rc={rc} with no result"
        log(last_err)
        # Cool down only when another attempt can actually run afterwards.
        if (attempt < len(timeouts)
                and deadline - time.monotonic() >= 60 + 15):
            time.sleep(15.0)
    return {"error": last_err}


def run_cpu_fallback() -> dict:
    """When every TPU attempt fails (device outage), measure the SAME fused
    pipeline on the CPU backend — one bounded child with the platform
    pinned.  An explicitly-labeled cpu number with the tpu error attached
    is strictly more informative than a bare zero: it separates 'the
    framework is broken' from 'the tunnel is down'."""
    result_path = os.path.join(WORKDIR, "cpu-result.json")
    try:
        os.remove(result_path)
    except OSError:
        pass
    env = dict(os.environ)
    env["DSI_JAX_PLATFORM"] = "cpu"
    log("tpu unavailable; measuring the same pipeline on the cpu backend")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--tpu-child",
         result_path], stdout=sys.stderr, env=env)
    try:
        proc.wait(timeout=900.0)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    if os.path.exists(result_path):
        with open(result_path) as f:
            return json.load(f)
    return {"error": "cpu fallback produced no result"}


def probe_tunnel_ports() -> list[tuple[str, int, bool]]:
    """(name, port, open?) for each forwarded axon tunnel port."""
    import socket

    out = []
    for port, name in ((8083, "stateless"), (8082, "session"),
                       (8113, "compile")):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=3):
                out.append((name, port, True))
        except OSError:
            out.append((name, port, False))
    return out


def diagnose_tunnel(probes=None) -> str:
    """One-line state of the axon tunnel's forwarded ports, so a bench
    failure record distinguishes an infrastructure outage (ports closed /
    backend unavailable — BASELINE.md incident log) from a framework bug."""
    return "; ".join(
        f"{name}:{port} {'open' if up else 'CLOSED'}"
        for name, port, up in (probes or probe_tunnel_ports()))


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(WORKDIR, n_files=N_FILES, file_size=FILE_SIZE)
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    log(f"corpus: {len(files)} files, {total_mb:.1f} MB")

    oracle_s, oracle_mbps = run_oracle(files)
    log(f"oracle (mrsequential semantics): {oracle_s:.2f}s = "
        f"{oracle_mbps:.2f} MB/s")

    res = run_tpu_watchdogged()
    tpu_error = None
    if "error" in res and not res.get("permanent"):
        tpu_error = res["error"]
        # Honor the deadline knob here too: under 60 s is the documented
        # "disable the accelerator half" mode and must stay fast — the
        # fallback child would add minutes past the caller's budget.
        try:
            fb_budget = float(os.environ.get("DSI_BENCH_DEADLINE_S", "2100"))
        except ValueError:
            fb_budget = 2100.0
        if fb_budget >= 60:
            res = run_cpu_fallback()
    if "error" in res:
        out = {"metric": "wc_tpu_throughput", "value": 0,
               "unit": "MB/s", "vs_baseline": 0,
               "oracle_mbps": round(oracle_mbps, 2),
               "error": res["error"],
               "diagnosis": diagnose_tunnel()}
        if tpu_error:
            out["tpu_error"] = tpu_error
        print(json.dumps(out))
        sys.exit(1)
    log(f"tpu path: {res['tpu_s']:.3f}s = {res['tpu_mbps']:.2f} MB/s  "
        f"phases={res['phases']}")
    log(f"parity (sort mr-out-* vs oracle, test-mr.sh:52-53): {res['parity']}")
    if not res["parity"]:
        out = {"metric": "wc_tpu_throughput", "value": 0,
               "unit": "MB/s", "vs_baseline": 0,
               "oracle_mbps": round(oracle_mbps, 2),
               "error": "parity mismatch",
               "platform": res.get("platform", "?")}
        if tpu_error:  # the mismatching run was the CPU fallback
            out["tpu_error"] = tpu_error
            out["diagnosis"] = diagnose_tunnel()
        print(json.dumps(out))
        sys.exit(1)

    out = {
        "metric": "wc_tpu_throughput",
        "value": res["tpu_mbps"],
        "unit": "MB/s",
        "vs_baseline": round(res["tpu_mbps"] / oracle_mbps, 2),
        "platform": res["platform"],
        "oracle_mbps": round(oracle_mbps, 2),
        "phases": res["phases"],
    }
    if tpu_error:
        # The number above was measured on the CPU FALLBACK backend: the
        # TPU half failed (tunnel outage etc.) and this run proves the
        # pipeline, not the chip.  A distinct metric name keeps it out of
        # any TPU-throughput trend; tpu_error + diagnosis say why.
        out["metric"] = "wc_cpu_fallback_throughput"
        out["tpu_error"] = tpu_error
        out["diagnosis"] = diagnose_tunnel()
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--tpu-child":
        sys.exit(tpu_child(sys.argv[2]))
    main()
