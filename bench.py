#!/usr/bin/env python
"""Benchmark: word-count throughput, TPU path vs the sequential oracle.

This measures exactly BASELINE.json's metric — word-count MB/s on a pg-style
corpus versus the sequential reference semantics (`main/mrsequential.go`),
with mr-out-* diff parity as a hard gate.  The oracle is this repo's
line-for-line-semantics port of `main/mrsequential.go:38-86`; the TPU path is
the fused tokenize/group/count kernel (`dsi_tpu/ops/wordcount.py`) per input
split + host merge + partitioned `mr-out-<r>` files using the reference's
`ihash % NReduce` partitioner (`mr/worker.go:33-37,76`).

Prints ONE JSON line on stdout:
  {"metric": ..., "value": MB/s, "unit": "MB/s", "vs_baseline": speedup}
`vs_baseline` is TPU MB/s over oracle MB/s measured in the same run on the
same corpus (the reference publishes no numbers of its own — BASELINE.md).
Parity failure reports value 0.  Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent compile cache: the TPU path's programs compile once per corpus
# shape; later bench runs (and the driver's) skip straight to execution.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jaxcache"))

N_FILES = 8
FILE_SIZE = (2 << 20) - 64  # pads to exactly 2^21 on device
N_REDUCE = 10
WORKDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_oracle(files) -> tuple[list, float, float]:
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential

    out = os.path.join(WORKDIR, "mr-correct.txt")
    t0 = time.perf_counter()
    run_sequential(wc.Map, wc.Reduce, files, out)
    dt = time.perf_counter() - t0
    with open(out) as f:
        lines = sorted(l for l in f if l.strip())
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    return lines, dt, total_mb / dt


def run_tpu(files) -> tuple[list, float, float, dict]:
    from dsi_tpu.ops.wordcount import count_words_host_result, count_words_many
    from dsi_tpu.parallel.shuffle import write_partitioned_output

    # Warm-up: compile the kernel on the first split (cached thereafter).
    with open(files[0], "rb") as f:
        first = f.read()
    t0 = time.perf_counter()
    count_words_host_result(first)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    t1 = time.perf_counter()
    raws = []
    for p in files:
        with open(p, "rb") as f:
            raws.append(f.read())
    read_s = time.perf_counter() - t1

    t1 = time.perf_counter()
    merged: dict = {}
    for p, res in zip(files, count_words_many(raws)):
        if res is None:  # host fallback would go here; corpus is ASCII
            raise RuntimeError(f"kernel fell back on {p}")
        for w, (c, h) in res.items():
            if w in merged:
                merged[w] = (merged[w][0] + c, merged[w][1])
            else:
                merged[w] = (c, h % N_REDUCE)
    kern_s = time.perf_counter() - t1

    t1 = time.perf_counter()
    write_partitioned_output(merged, N_REDUCE, WORKDIR)
    write_s = time.perf_counter() - t1
    dt = time.perf_counter() - t0

    lines = []
    for r in range(N_REDUCE):
        with open(os.path.join(WORKDIR, f"mr-out-{r}")) as f:
            lines.extend(l for l in f if l.strip())
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    phases = {"compile_s": round(compile_s, 3), "read_s": round(read_s, 3),
              "kernel_s": round(kern_s, 3), "write_s": round(write_s, 3)}
    return sorted(lines), dt, total_mb / dt, phases


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(WORKDIR, n_files=N_FILES, file_size=FILE_SIZE)
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    log(f"corpus: {len(files)} files, {total_mb:.1f} MB")

    import jax

    devices = None
    for attempt in range(3):  # the TPU relay can be transiently unavailable
        try:
            devices = jax.devices()
            break
        except RuntimeError as e:
            log(f"device init attempt {attempt + 1}/3 failed: {e}")
            if attempt < 2:
                time.sleep(60)
    if devices is None:
        print(json.dumps({"metric": "wc_tpu_throughput", "value": 0,
                          "unit": "MB/s", "vs_baseline": 0,
                          "error": "accelerator unavailable"}))
        sys.exit(1)
    platform = devices[0].platform
    log(f"devices: {devices}")

    oracle_lines, oracle_s, oracle_mbps = run_oracle(files)
    log(f"oracle (mrsequential semantics): {oracle_s:.2f}s = "
        f"{oracle_mbps:.2f} MB/s, {len(oracle_lines)} unique words")

    tpu_lines, tpu_s, tpu_mbps, phases = run_tpu(files)
    log(f"tpu path: {tpu_s:.3f}s = {tpu_mbps:.2f} MB/s  phases={phases}")

    parity = tpu_lines == oracle_lines
    log(f"parity (sort mr-out-* vs oracle, test-mr.sh:52-53): {parity}")
    if not parity:
        import itertools

        for i, (a, b) in enumerate(
                itertools.zip_longest(tpu_lines, oracle_lines)):
            if a != b:
                log(f"first diff at line {i}: tpu={a!r} oracle={b!r} "
                    f"(lines: tpu={len(tpu_lines)} oracle={len(oracle_lines)})")
                break
        print(json.dumps({"metric": "wc_tpu_throughput", "value": 0,
                          "unit": "MB/s", "vs_baseline": 0,
                          "error": "parity mismatch"}))
        sys.exit(1)

    print(json.dumps({
        "metric": "wc_tpu_throughput",
        "value": round(tpu_mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(tpu_mbps / oracle_mbps, 2),
        "platform": platform,
        "oracle_mbps": round(oracle_mbps, 2),
    }))


if __name__ == "__main__":
    main()
