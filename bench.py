#!/usr/bin/env python
"""Benchmark: word-count throughput, TPU path vs the sequential oracle.

This measures exactly BASELINE.json's metric — word-count MB/s on a pg-style
corpus versus the sequential reference semantics (`main/mrsequential.go`),
with mr-out-* diff parity as a hard gate.  The oracle is this repo's
line-for-line-semantics port of `main/mrsequential.go:38-86`; the TPU path is
the whole-corpus fused program (`dsi_tpu/ops/corpus_wc.py`): pieced async
uploads, ONE tokenize/sort/group/count launch over the merged corpus, ONE
position-coded D2H pull (~8 bytes per unique word), host-side output files
partitioned by the reference's `ihash % NReduce` (`mr/worker.go:33-37,76`).
The program is compiled through the persistent AOT executable cache
(`dsi_tpu/backends/aotcache.py`), so only the first-ever process on a
machine pays the XLA compile.

The timed region runs DSI_BENCH_REPS times (default 5): when the pack6
program is already in the AOT cache, the first two reps probe the raw and
6-bit-packed upload transports once each and every later rep commits to
the winner; when it is not cached, the run is raw-only — a cold pack6
compile mid-bench would gamble the attempt budget on a second
multi-minute remote compile (DSI_BENCH_TRANSPORT pins the choice,
DSI_BENCH_WARM_ALL=1 — set by scripts/warm_loop.sh — forces both
programs warm regardless).  The best rep is the headline — the axon
tunnel's transfer bandwidth fluctuates by >10x between moments, and
min-of-N is the standard way to report a machine's capability rather than
the tunnel's worst congestion instant — with the median reported alongside
(``median_mbps``) so the variance stays visible.  A second row measures
the bounded-memory streaming path over DSI_BENCH_STREAM_MB (default 64) of
cycled corpus (``stream_mbps``, with its own exact-count parity gate).

Prints ONE JSON line on stdout:
  {"metric": ..., "value": MB/s, "unit": "MB/s", "vs_baseline": speedup}
`vs_baseline` is TPU MB/s over oracle MB/s measured in the same run on the
same corpus (the reference publishes no numbers of its own — BASELINE.md).

Robustness discipline (the always-emit-a-verdict rule of the reference's
harness, test-mr.sh:55-59): the oracle runs FIRST and needs no accelerator,
so its MB/s is always captured; the TPU half runs in a watchdog subprocess
(the axon device-init path has been observed to hang > 25 min) with bounded
retries and a global deadline.  If every TPU attempt fails (e.g. the tunnel
outage in BASELINE.md's incident log), the same pipeline is measured once
on the CPU backend and reported with ``tpu_error`` + a port-probe
``diagnosis`` attached — separating "framework broken" from "tunnel down".
Every failure mode still emits the JSON line before exit.  Diagnostics go
to stderr.

Environment knobs:
  DSI_BENCH_TPU_TIMEOUTS  per-attempt child timeouts, seconds (default
                          "1200,420,240" — first attempt covers a cold
                          axon compile (219 s observed round 2, can
                          exceed 900 s); later ones assume the
                          persistent AOT cache is warm)
  DSI_BENCH_DEADLINE_S    global wall budget for the TPU half (default
                          2100).  An attempt only starts if >= 60 s of
                          budget remain (anything less cannot even cover
                          device init), so values under 60 disable the TPU
                          half entirely.  The CPU fallback is bounded by
                          whatever remains of this budget (60 s floor,
                          900 s cap).
  DSI_BENCH_STREAM_MB     size of the streaming-path row (default 64;
                          0 disables it).  The row only runs against a
                          warm AOT cache and never pre-empts the headline
                          verdict (which is emitted first).  The row runs
                          at the streaming engine's pipeline depth
                          (DSI_STREAM_PIPELINE_DEPTH, default 2) and
                          reports per-phase seconds as ``stream_phases``.
  DSI_BENCH_KERNEL_REPS   reps for the wire-independent kernel-only row
                          (default 5; 0 disables): upload one stream
                          chunk once, run the wc step K times on the
                          HBM-resident buffer, report median kernel-only
                          MB/s per grouper (kernel_sort_mbps /
                          kernel_hash_mbps).  Gated on the non-donated
                          rep programs being AOT-persisted on
                          accelerators.
  DSI_BENCH_TFIDF_MB      size of the TF-IDF engine row (default 16;
                          0 disables; accelerators run it only when the
                          knob is set explicitly): the pipelined wave
                          walk over the cycled corpus, token-invariant
                          gated, with tfidf_phases mirroring
                          stream_phases.
  DSI_BENCH_GREP_MB       size of the streaming-grep engine row (default
                          16; 0 disables; accelerators opt-in like the
                          tfidf row): grep_streaming over the cycled
                          corpus, parity-gated line-for-line against the
                          host-grep oracle, with grep_phases and the
                          oracle's own MB/s alongside.
                          DSI_BENCH_GREP_PATTERN picks the literal
                          (default "the"); DSI_BENCH_GREP_DEVICE_ACC=1
                          folds the match histogram + top-k candidates
                          on device (dsi_tpu/device/topk.py).
  DSI_BENCH_CKPT          the stream row's checkpoint/restore cost keys
                          (dsi_tpu/ckpt), a cadence-1 sync-vs-async A/B:
                          ckpt_overhead_pct (sync-full, the PR-5 path)
                          vs ckpt_async_overhead_pct (overlapped commits
                          + incremental saves), ckpt_full_bytes_per_save
                          vs ckpt_delta_bytes_per_save, and resume_gap_s
                          from the delta CHAIN — every pass parity-
                          gated.  CPU boxes run it whenever the stream
                          row measured; accelerators opt in with 1 (four
                          more stream passes on a time-boxed window);
                          0 disables.
  DSI_BENCH_SPEC_MB       size of the speculative-execution A/B row
                          (default 4; 0 disables): the same shard job
                          with one injected slow worker, backup
                          dispatch on vs --no-spec — spec_backup_mbps
                          vs spec_nobackup_mbps, spec_backup_fired,
                          spec_duplicate_commits (must be 0), each arm
                          parity-gated vs the sequential oracle.
  DSI_BENCH_NET_MB        size of the network-data-plane A/B row
                          (default 4; 0 disables): the same multi-file
                          wordcount with shuffle over localhost TCP and
                          private per-worker workdirs (mrrun --net) vs
                          the shared-directory plane — net_shuffle_mbps
                          vs net_fs_mbps, plus net_ratio (raw/wire
                          through the line codec) and locality_hits,
                          each arm parity-gated vs the oracle.
  DSI_BENCH_FRAMEWORK_MB  corpus size for the distributed N-worker row
                          (default 48; 0 disables it; auto-shrunk so its
                          oracle pass costs ~100 s on a slow box, skipped
                          outright when even the floor would exceed
                          ~240 s).  The row runs AFTER the accelerator
                          half, outside DSI_BENCH_DEADLINE_S: worst-case
                          total bench wall is deadline + CPU fallback
                          (<= 900 s) + row (<= ~240 + its own
                          DSI_BENCH_FRAMEWORK_TIMEOUT, default 300 s).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Persistent compile cache: the TPU path's programs compile once per corpus
# shape; later bench runs (and the driver's) skip straight to execution.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jaxcache"))

N_FILES = int(os.environ.get("DSI_BENCH_FILES", "8"))
FILE_SIZE = int(os.environ.get("DSI_BENCH_FILE_SIZE",
                               str((2 << 20) - 64)))  # pads to 2^21 on device
N_REDUCE = 10
# Stream-row program shape — ONE definition shared by the cache-existence
# gate and the wordcount_streaming call in run_stream_row, so the probed
# key cannot drift from the key the run compiles (these must also stay in
# lockstep with scripts/warm_kernels.py --phase stream and
# onchip_evidence.sh's --u-cap).  2 MiB chunks with a 2^15 unique
# capacity measured 11.3 vs 8.4 MB/s for the former 1 MiB/2^14 shape on
# the CPU backend (fewer step boundaries, no capacity widening on the
# bench corpus's ~24k uniques/chunk).
STREAM_CHUNK_BYTES = 1 << 21
STREAM_U_CAP = 1 << 15
# Overridable so tests (and ad-hoc small-corpus runs) don't overwrite the
# canonical .bench corpus/oracle the warm loop's parity checks rely on.
WORKDIR = (os.environ.get("DSI_BENCH_WORKDIR")
           or os.path.join(REPO, ".bench"))
ORACLE_OUT = os.path.join(WORKDIR, "mr-correct.txt")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def env_float(name: str, default: float) -> float:
    """Float env knob with the always-emit-a-verdict discipline: malformed
    values fall back to the default (logged) instead of raising."""
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        log(f"ignoring malformed {name}")
        return default


def run_oracle(files) -> tuple[float, float]:
    """Sequential oracle (mrsequential.go:38-86 semantics); pure host CPU."""
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential
    from dsi_tpu.utils.tracing import Span

    with Span("bench.oracle") as pt:
        run_sequential(wc.Map, wc.Reduce, files, ORACLE_OUT)
    dt = pt.elapsed_s
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    return dt, total_mb / dt


def tpu_child(result_path: str) -> int:
    """Child-process body: device init + kernel path + parity check.

    Everything that can hang (axon backend init, compiles) happens here, so
    the parent's kill-on-timeout recovers from any of it.  Writes a JSON
    result to ``result_path``; parent treats a missing file as failure.
    """
    from dsi_tpu.backends import aotcache
    from dsi_tpu.ops.corpus_wc import (corpus_executable_persisted,
                                       corpus_wordcount, write_corpus_output)
    from dsi_tpu.utils.corpus import ensure_corpus
    from dsi_tpu.utils.tracing import Span

    def emit(obj: dict) -> None:
        # Per-thread temp name: the init-watchdog thread and the main
        # thread may both emit around the init deadline; a shared temp
        # file could tear.  Both os.replace targets are atomic.
        import threading

        tmp = f"{result_path}.tmp{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, result_path)

    # Same deterministic list as the parent's oracle run — NOT a directory
    # glob, which would sweep in stale pg-*.txt files from an older corpus
    # configuration and guarantee a parity mismatch.
    files = ensure_corpus(WORKDIR, n_files=N_FILES, file_size=FILE_SIZE)

    # Graceful-shutdown seam for the parent watchdog's SIGTERM: SystemExit
    # unwinds the interpreter so the PJRT client's destructor releases the
    # device claim (a SIGKILL here wedges the claim for later processes).
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()
    import jax

    # Self-bounded init: a wedged device claim blocks jax.devices() inside
    # a C call indefinitely (signals deferred, so only SIGKILL from outside
    # works).  This daemon thread turns that into a clean, fast error
    # verdict: no claim is held pre-init, so _exit is safe here.
    # (When run under the full bench, the parent watchdog's init deadline
    # is the backstop; set this BELOW it — onchip_evidence.sh uses 150 <
    # the parent's 180 — so the clean child verdict wins the race.)
    init_timeout = env_float("DSI_CHILD_INIT_TIMEOUT", 0.0)
    import threading

    init_settled = threading.Event()  # set once jax.devices() returns/raises
    # The settle lock serializes the watchdog's final decision against the
    # main thread's completion mark (ADVICE r3: the unlocked re-check left
    # the whole emit duration as a TOCTOU window): once the main thread
    # has acquired the lock and set the flag, _exit cannot fire.  The
    # residual hazard is inherent — the device claim goes live inside the
    # jax.devices() C call, so a window between the claim appearing and
    # _settle() acquiring the lock cannot be closed from Python; the 5 s
    # grace re-check plus this lock make it as narrow as the runtime
    # allows.
    settle_lock = threading.Lock()

    def _settle():
        with settle_lock:
            init_settled.set()

    if init_timeout > 0:
        def _init_watchdog():
            # wait() (not sleep) + a 5 s grace re-check narrow the race
            # where init completes right at the deadline; the lock below
            # closes it.
            if init_settled.wait(init_timeout):
                return
            if init_settled.wait(5.0):
                return
            emit({"error": f"device init exceeded {init_timeout:.0f}s "
                           "(outage or wedged claim)"})
            with settle_lock:
                if init_settled.is_set():
                    # Init completed during the emit: a verdict file now
                    # wrongly claims failure, but exiting would be worse
                    # (_exit on a live claim wedges the device) — let the
                    # main thread overwrite the verdict with the real one.
                    return
                os._exit(3)

        threading.Thread(target=_init_watchdog, daemon=True).start()

    t0 = time.perf_counter()
    try:
        devices = jax.devices()
    except RuntimeError as e:
        _settle()
        emit({"error": f"device init failed: {e}"})
        return 1
    _settle()
    init_s = time.perf_counter() - t0
    platform = devices[0].platform
    log(f"child: devices={devices} init={init_s:.1f}s")
    # Tell the watchdog parent init completed: a wedged device claim hangs
    # inside jax.devices() indefinitely (observed on this platform), and the
    # parent fails the attempt fast when this marker doesn't appear.
    with open(result_path + ".init", "w") as f:
        f.write(f"{init_s:.1f}")

    def run_once(pack6: bool):
        phases = {"mode": "pack6" if pack6 else "raw"}
        t0 = time.perf_counter()
        raws = []
        for p in files:
            with open(p, "rb") as f:
                raws.append(f.read())
        phases["read_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        res = corpus_wordcount(raws, pack6=pack6)
        phases["kernel_s"] = round(time.perf_counter() - t0, 3)
        # Upload sub-phase (inside kernel_s) when corpus_wc routes its
        # piece transfer through ops/xfer.put_views; 0.0 means it didn't
        # (pre-integration artifact or host fallback) — omit the keys
        # rather than report a phase that wasn't measured.
        from dsi_tpu.ops import xfer
        if xfer.stats["upload_s"] > 0:
            phases["upload_s"] = round(xfer.stats["upload_s"], 3)
            phases["upload"] = xfer.stats["upload_mode"]
            xfer.stats["upload_s"] = 0.0
        t0 = time.perf_counter()
        if res is not None:
            write_corpus_output(res, N_REDUCE, WORKDIR)
        phases["write_s"] = round(time.perf_counter() - t0, 3)
        return res, phases

    # Warm-up (untimed): loads the AOT executables (or pays the one-time
    # XLA compiles and saves them), warms the first-D2H path (~0.5-3 s
    # one-time on this platform), and produces one full output set.
    #
    # Transport eligibility: raw is mandatory; the pack6 program is only
    # touched when its executable is ALREADY persisted — a cold pack6
    # compile here would gamble the attempt budget on a second
    # multi-minute remote compile after raw's, and short tunnel windows
    # have died exactly there (BASELINE.md, 2026-07-31).  Compiling pack6
    # is the warm chain's explicit job: warm_loop.sh sets
    # DSI_BENCH_WARM_ALL=1 to force both.  DSI_BENCH_TRANSPORT=raw|pack6
    # pins the choice outright (pack6 compiles if it must).
    transport = os.environ.get("DSI_BENCH_TRANSPORT", "auto")
    warm_all = os.environ.get("DSI_BENCH_WARM_ALL") == "1"
    if transport == "auto" and not warm_all:
        raws0 = []
        for p in files:
            with open(p, "rb") as f:
                raws0.append(f.read())
        pack6_eligible = corpus_executable_persisted(raws0, pack6=True)
        del raws0  # probe-only copy; run_once reads files per rep
        if not pack6_eligible:
            log("pack6 transport skipped: executable not in the AOT cache "
                "(cold compile risk); raw-only run")
    else:
        pack6_eligible = transport != "raw"
    with Span("bench.warmup") as pt:
        for pack6 in ((False, True) if pack6_eligible else (False,)):
            wres, _ = run_once(pack6)
            if wres is None:
                emit({"error": "kernel fell back to host on this corpus",
                      "permanent": True})
                return 1
    warmup_s = pt.elapsed_s
    compile_s = aotcache.stats["compiled_s"]
    log(f"warmup {warmup_s:.2f}s (aot: {aotcache.stats})")

    # Transport selection: probe each of raw / 6-bit-packed uploads ONCE,
    # then commit every remaining rep to the winner (VERDICT r3 weakness
    # #1: alternating every other rep burned half the reps on a known
    # loser — pack6 measured ~3x slower than raw whenever the tunnel was
    # healthy).  Min-of-N still reports the machine's capability; the
    # median is reported alongside so congestion variance stays visible.
    reps = max(1, int(os.environ.get("DSI_BENCH_REPS", "5")))
    times_by_mode: dict = {False: [], True: []}

    def pack6_winning() -> bool:
        t = min(times_by_mode[True], default=1e18)
        f = min(times_by_mode[False], default=1e18)
        return t < f

    # Upload-mode probe (corpus_wc routes uploads through ops/xfer): sync
    # and async piecing differ >10x in OPPOSITE directions between
    # healthy and degraded tunnel states (scripts/probe_tunnel.py,
    # 2026-07-31: async 0.6 vs single-shot 5.8 MB/s degraded; async up to
    # 1.2 GB/s healthy), so rep 0 runs async, rep 1 sync, and the rest
    # commit to the winner — the same probe-once shape as the transport
    # choice above.  Probed only when the transport dimension is NOT also
    # being probed (raw-only run): two probes on the same early reps
    # would conflate their signals.  DSI_UPLOAD_MODE pins the choice; CPU
    # runs skip the probe (no tunnel to adapt to).
    upload_pin = os.environ.get("DSI_UPLOAD_MODE")
    times_by_upload: dict = {"async": [], "sync": []}
    upload_probe = (upload_pin is None and platform != "cpu"
                    and not pack6_eligible and transport != "pack6"
                    and reps >= 2)

    def upload_winner() -> str:
        a = min(times_by_upload["async"], default=1e18)
        s = min(times_by_upload["sync"], default=1e18)
        return "sync" if s < a else "async"

    rep_times = []
    dt, best_phases = None, {}
    for rep in range(reps):
        if transport == "pack6":
            pack6 = True
        elif not pack6_eligible:
            pack6 = False  # raw pinned, or pack6 program not cached
        elif reps >= 2 and rep == 0:
            pack6 = False
        elif reps >= 2 and rep == 1:
            pack6 = True
        elif rep == 2 and reps > 3 and pack6_winning():
            # Upset guard: raw is the healthy-tunnel favourite (pack6
            # measured ~3x slower whenever the link was clean), so a
            # pack6 probe win usually means raw's single probe landed on
            # a congestion spike — spend exactly one rep re-probing raw
            # before committing the rest.
            pack6 = False
        else:
            pack6 = pack6_winning()
        if upload_probe:
            um = ("async", "sync")[rep] if rep < 2 else upload_winner()
            os.environ["DSI_UPLOAD_MODE"] = um
        t_all = time.perf_counter()
        res, phases = run_once(pack6=pack6)
        rep_s = time.perf_counter() - t_all
        log(f"rep {rep + 1}/{reps}: {rep_s:.3f}s {phases}")
        if res is None:
            emit({"error": "kernel fell back mid-run", "permanent": True})
            return 1
        if upload_probe and "upload_s" not in phases:
            # corpus_wc didn't route this rep through ops/xfer.put_views
            # (host fallback or pre-integration build): the knob is inert
            # — stop probing so phases['uploads'] can't claim modes that
            # never ran.
            upload_probe = False
            os.environ.pop("DSI_UPLOAD_MODE", None)
        if upload_probe:
            times_by_upload[um].append(rep_s)
        times_by_mode[pack6].append(rep_s)
        rep_times.append(rep_s)
        if dt is None or rep_s < dt:
            dt, best_phases = rep_s, phases
    if upload_probe:
        os.environ.pop("DSI_UPLOAD_MODE", None)

    tpu_lines = []
    for r in range(N_REDUCE):
        with open(os.path.join(WORKDIR, f"mr-out-{r}"),
                  encoding="utf-8") as f:
            tpu_lines.extend(l for l in f if l.strip())
    tpu_lines.sort()
    with open(ORACLE_OUT, encoding="utf-8") as f:
        oracle_lines = sorted(l for l in f if l.strip())

    parity = tpu_lines == oracle_lines
    if not parity:
        import itertools
        for i, (a, b) in enumerate(
                itertools.zip_longest(tpu_lines, oracle_lines)):
            if a != b:
                log(f"first diff at line {i}: tpu={a!r} oracle={b!r} (lines:"
                    f" tpu={len(tpu_lines)} oracle={len(oracle_lines)})")
                break

    import statistics

    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    median_s = statistics.median(rep_times)
    phases = {"init_s": round(init_s, 1),
              "compile_s": round(compile_s, 3),
              "warmup_s": round(warmup_s, 3),
              "aot_loads": aotcache.stats["loads"],
              "reps": reps,
              "transports": "+".join(
                  m for m, used in (("raw", times_by_mode[False]),
                                    ("pack6", times_by_mode[True])) if used),
              "median_s": round(median_s, 3)}
    if upload_pin:
        phases["uploads"] = f"pin:{upload_pin}"
    elif any(times_by_upload.values()):
        phases["uploads"] = "+".join(
            m for m in ("async", "sync") if times_by_upload[m])
    phases.update(best_phases)
    result = {"tpu_s": round(dt, 3), "tpu_mbps": round(total_mb / dt, 2),
              "median_mbps": round(total_mb / median_s, 2),
              "total_mb": round(total_mb, 2),
              "parity": parity, "platform": platform, "phases": phases}
    # The headline verdict is complete and durable from here on: emit it
    # BEFORE the stream row so a parent timeout mid-stream still finds a
    # valid result file (emit is atomic; last write wins).  The
    # provisional marker rides the SAME first emit — a two-emit sequence
    # would leave a SIGTERM window producing a verdict with no stream key
    # at all, violating the XOR contract test_bench_contract.py locks in.
    stream_mb = stream_row_mb()
    if parity and stream_mb > 0:
        result["stream_skipped"] = ("stream row started but did not "
                                    "complete (interrupted?)")
    emit(result)
    if parity and stream_mb > 0:
        try:
            # Bench hygiene (ISSUE 13): the stream row's engine passes
            # run with DSI_AOT_FRESH=1 on 1-device CPU — the persisted
            # -AOT-load segfault repro'd by scripts/aot_flake_repro.py
            # lives exactly there, and a bench round must not roll
            # those dice.
            with aot_fresh_cpu_guard():
                stream = run_stream_row(files, compile_s, stream_mb)
        except Exception as e:  # never trade the headline for the row
            stream = {"stream_skipped":
                      f"stream row failed: {type(e).__name__}: {e}"}
        result.pop("stream_skipped", None)
        result.update(stream)
        emit(result)
    # Wire-independent kernel-only row + the TF-IDF, grep, and
    # wire/ingest engine rows: same never-trade-the-verdict discipline
    # — each re-emits the (already durable) result with its keys or a
    # skip reason.  The grep and wire rows share the stream row's
    # DSI_AOT_FRESH CPU hygiene (their engine passes load the same
    # flake-prone entries).
    if parity:
        for key, row_fn, fresh in (
                ("kernel_skipped", run_kernel_row, False),
                ("tfidf_skipped", run_tfidf_row, False),
                ("grep_skipped", run_grep_row, True),
                ("wire_skipped", run_wire_ingest_row, True)):
            try:
                with (aot_fresh_cpu_guard() if fresh
                      else contextlib.nullcontext()):
                    result.update(row_fn(files))
            except Exception as e:
                result[key] = f"row failed: {type(e).__name__}: {e}"
            emit(result)
    return 0



def run_provenance() -> dict:
    """Attribution keys stamped into EVERY verdict (success, parity
    failure, tunnel-down error alike), so a ``scripts/bench_diff.py``
    comparison across BENCH_r*.json rounds can say WHAT produced each
    number — a throughput delta between two different jax versions or
    hosts is an environment change, not a code regression.  Every key
    degrades to "unknown" rather than failing the bench, and
    bench_diff treats missing/unknown as non-comparable, so old
    artifacts without the block stay diffable (backfill-tolerant)."""
    prov = {}
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        prov["git_sha"] = r.stdout.strip() or "unknown"
    except Exception:
        prov["git_sha"] = "unknown"
    try:  # version without importing jax into the parent process
        from importlib import metadata

        prov["jax_version"] = metadata.version("jax")
    except Exception:
        prov["jax_version"] = "unknown"
    import platform as _platform
    import socket

    prov["platform"] = f"{_platform.system()}-{_platform.machine()}"
    prov["hostname"] = socket.gethostname()
    prov["python"] = _platform.python_version()
    # The repo runs x64 SCOPED (utils/jaxcompat.x64_scoped) unless the
    # env pins it globally — record which, it changes kernel numerics.
    prov["x64"] = os.environ.get("JAX_ENABLE_X64", "scoped")
    prov["utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    return prov


def bench_tracer():
    """The bench's handle on the unified tracer (dsi_tpu/obs):
    DSI_BENCH_TRACE=1 turns on in-memory span buffering so the engine
    rows publish per-phase span rollups (``stream_spans``/``tfidf_spans``
    /``grep_spans``) in the verdict; DSI_TRACE_DIR additionally flushes
    the full trace durably at process exit (atomicio durable writes,
    ``.tmp-*`` reap on configure — the ckpt store's discipline)."""
    from dsi_tpu.obs import get_tracer

    tr = get_tracer()
    if os.environ.get("DSI_BENCH_TRACE") == "1":
        tr.enabled = True
    return tr


@contextlib.contextmanager
def aot_fresh_cpu_guard():
    """Run an engine row with ``DSI_AOT_FRESH=1`` on 1-device CPU: the
    attributed persisted-AOT-load fault (scripts/aot_flake_repro.py —
    SIGSEGV/heap corruption inside ``deserialize_and_load`` at the
    widen shapes, CHANGES.md PR 8/PR 12) lives exclusively on that
    configuration, so bench rounds there compile fresh (seconds on
    CPU, still in-process-memoized across a row's passes) instead of
    gambling a round on the known flake.  Accelerators and multi-device
    meshes are untouched — loads are the whole point there — and an
    explicit DSI_AOT_FRESH from the caller always wins."""
    import jax

    want = (jax.devices()[0].platform == "cpu"
            and len(jax.devices()) == 1
            and "DSI_AOT_FRESH" not in os.environ)
    if want:
        os.environ["DSI_AOT_FRESH"] = "1"
    try:
        yield
    finally:
        if want:
            os.environ.pop("DSI_AOT_FRESH", None)


def stream_row_mb() -> float:
    return env_float("DSI_BENCH_STREAM_MB", 64.0)


def run_stream_row(files, corpus_compile_s: float, stream_mb: float) -> dict:
    """Measure the bounded-memory streaming path (VERDICT r3 task 8: the
    headline number alone is the 16.7 MB fused-program special case) by
    cycling the bench corpus ``stream_mb`` worth through
    ``wordcount_streaming`` on the process's device mesh, with exact-count
    parity against the oracle file scaled by the cycle count.

    Always returns either a measured row or a ``stream_skipped`` reason —
    a missing row in the verdict is a contract violation.  A parity
    mismatch suppresses the throughput number (a rate for wrong counts
    must never enter a trend) and ships as a skip reason instead.
    Cold-process guard: if the corpus phase had to compile (no warm AOT
    cache), the stream row would add its own remote compiles to an
    already-slow attempt and risk the parent watchdog's budget — skip and
    say so; the warm loop (scripts/warm_kernels.py) pre-compiles the
    stream programs precisely so the driver's run takes this path warm.
    """
    if corpus_compile_s > 60:
        return {"stream_skipped":
                f"cold process (corpus compile {corpus_compile_s:.0f}s); "
                "stream row runs only against a warm AOT cache"}

    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import (stream_files,
                                            stream_programs_persisted,
                                            wordcount_streaming)

    # Same discipline as the pack6 transport: on the tunnel platform a
    # cold stream-program compile costs tens of minutes — never gamble a
    # bench window on it; compiling these is the warm ladder's phase-C
    # job (scripts/warm_kernels.py --phase stream).  Exempt: CPU
    # processes (the fallback path, tests — compiles in seconds) and
    # multi-device meshes (the AOT cache is by-design unused there, so
    # the probe could never pass and in-process compile is the only
    # path — the pre-gate behavior).
    import jax

    # DSI_BENCH_STREAM_DEVICE_ACC=1 runs the row with the device-resident
    # accumulator (device/table.py): folds on device, host pulls every
    # DSI_STREAM_SYNC_EVERY steps — BENCH_r06+ compares stream_phases
    # with and without it (the gate below then also demands the fold
    # programs be warm: a cold fold compile is the same remote-compile
    # hazard as a cold step compile).
    device_acc = os.environ.get("DSI_BENCH_STREAM_DEVICE_ACC") == "1"
    if (jax.devices()[0].platform != "cpu"
            and len(jax.devices()) == 1
            and os.environ.get("DSI_BENCH_WARM_ALL") != "1"
            and not stream_programs_persisted(
                chunk_bytes=STREAM_CHUNK_BYTES, u_cap=STREAM_U_CAP,
                n_reduce=N_REDUCE, device_accumulate=device_acc)):
        return {"stream_skipped":
                "stream programs not in the AOT cache (cold compile "
                "risk); warm via scripts/warm_kernels.py --phase stream"}
    from dsi_tpu.utils.tracing import Span

    corpus_bytes = sum(os.path.getsize(p) for p in files)
    cycles = max(1, round(stream_mb * 1e6 / corpus_bytes))

    def blocks():
        for c in range(cycles):
            if c:
                yield b"\n"
            yield from stream_files(files)

    mesh = default_mesh()
    pstats: dict = {}
    tracer = bench_tracer()
    mark = tracer.mark()
    with Span("bench.stream") as pt:
        acc = wordcount_streaming(blocks(), mesh=mesh, n_reduce=N_REDUCE,
                                  chunk_bytes=STREAM_CHUNK_BYTES,
                                  u_cap=STREAM_U_CAP, aot=True,
                                  device_accumulate=device_acc,
                                  pipeline_stats=pstats)
    dt = pt.elapsed_s
    if acc is None:
        return {"stream_skipped": "stream needed the host path "
                                  "(non-ASCII or >64-byte word)"}

    oracle: dict = {}
    with open(ORACLE_OUT, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                w, _, c = line.rstrip("\n").rpartition(" ")
                oracle[w] = int(c)
    parity = (len(acc) == len(oracle)
              and all(acc.get(w, (0, 0))[0] == c * cycles
                      for w, c in oracle.items()))
    mb = corpus_bytes * cycles / 1e6
    # Per-phase attribution (mirrors the TPU path's ``phases`` dict):
    # lets BENCH_r06+ say WHERE stream throughput went — kernel-bound,
    # or batch/upload/pull/merge overhead the pipeline failed to hide —
    # and, with device accumulation, show the pull amortization
    # (step_pulls vs sync_pulls: per-step D2H vs ceil(steps/K)+widens).
    phases = {k: pstats[k] for k in ("batch_s", "batch_wait_s", "upload_s",
                                     "kernel_s", "pull_s", "merge_s",
                                     "replay_s", "depth", "replays",
                                     "device_accumulate", "sync_every",
                                     "step_pulls", "folds", "fold_s",
                                     "fold_overflows", "sync_pulls",
                                     "sync_s", "widens", "widen_s",
                                     "table_cap")
              if k in pstats}
    log(f"stream row: {mb:.1f} MB in {dt:.2f}s = {mb / dt:.2f} MB/s "
        f"(cycles={cycles}, parity={parity}, phases={phases})")
    if not parity:
        return {"stream_skipped": f"parity mismatch over {mb:.1f} MB "
                                  f"(throughput suppressed)",
                "stream_parity": False}
    row = {"stream_mbps": round(mb / dt, 2), "stream_mb": round(mb, 1),
           "stream_s": round(dt, 2), "stream_parity": True,
           "stream_phases": phases}
    if tracer.enabled:
        # The per-phase span rollup (dsi_tpu/obs): same measurements as
        # stream_phases plus per-span counts/max — BENCH_r*.json carries
        # it whenever the bench runs traced (DSI_BENCH_TRACE=1 buffers
        # in-memory; DSI_TRACE_DIR also flushes the full trace durably).
        row["stream_spans"] = tracer.rollup(mark)
    try:
        row.update(run_stream_ckpt_row(files, mesh, device_acc, oracle,
                                       corpus_bytes, stream_mb))
    except Exception as e:  # never trade the stream row for the ckpt one
        row["ckpt_skipped"] = f"ckpt row failed: {type(e).__name__}: {e}"
    return row


def run_stream_ckpt_row(files, mesh, device_acc, oracle,
                        corpus_bytes, stream_mb) -> dict:
    """The checkpoint/restore cost row riding the stream row
    (``dsi_tpu/ckpt``), now a CADENCE-1 sync-vs-async A/B (ISSUE 8):
    four passes over a bounded slice of the stream — a plain WARM pass
    (its own baseline: the stream row's pass may have paid one-time
    compiles, which would make a naive comparison report negative
    overhead), a sync-full checkpointed pass at ``checkpoint_every=1``
    (``ckpt_overhead_pct`` — the PR-5 path, every save a stall-and-
    write full image), an async+incremental pass at the same cadence
    (``ckpt_async_overhead_pct`` — captures overlap the pipeline
    window, saves ship deltas with a periodic full re-base;
    ``ckpt_delta_bytes_per_save`` vs ``ckpt_full_bytes_per_save`` is
    the payload A/B), and a resumed pass from the async pass's delta
    CHAIN (``resume_gap_s`` = load + re-apply deltas + re-upload +
    re-warm + seek), each parity-gated against the oracle counts.

    Cadence 1 is the deliberate, hostile setting: it is the ROADMAP's
    serving-daemon eviction target and the cadence where snapshot cost
    decides whether checkpointing is on by default.

    The slice is capped at ~16 MB (overhead is a ratio; it does not
    need the full row size, and four extra 64 MB passes would threaten
    the CPU-fallback wall budget).  CPU boxes run it whenever the
    stream row measured; accelerators opt in via ``DSI_BENCH_CKPT=1``
    (four more stream passes on a time-boxed tunnel window must be a
    choice, not a default), and ``DSI_BENCH_CKPT=0`` disables
    everywhere.  Always returns measured keys XOR ``ckpt_skipped`` —
    the bench-contract discipline; the per-save delta-bytes key rides
    only when the pass produced at least one delta
    (``ckpt_deltas`` >= 1 — a one-step slice has nothing to
    increment).
    """
    explicit = os.environ.get("DSI_BENCH_CKPT")
    if explicit == "0":
        return {"ckpt_skipped": "disabled (DSI_BENCH_CKPT=0)"}
    import jax

    if jax.devices()[0].platform != "cpu" and explicit != "1":
        return {"ckpt_skipped": "accelerator ckpt row is opt-in "
                                "(set DSI_BENCH_CKPT=1)"}
    import shutil

    from dsi_tpu.parallel.streaming import (stream_files,
                                            wordcount_streaming)
    from dsi_tpu.utils.tracing import Span

    ckpt_dir = os.path.join(WORKDIR, "ckpt-row")
    async_dir = os.path.join(WORKDIR, "ckpt-row-async")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    shutil.rmtree(async_dir, ignore_errors=True)
    cycles = max(1, round(min(stream_mb, 16.0) * 1e6 / corpus_bytes))

    def blocks():
        for c in range(cycles):
            if c:
                yield b"\n"
            yield from stream_files(files)

    def run(**kw):
        pstats: dict = {}
        with Span("bench.stream_ckpt") as pt:
            acc = wordcount_streaming(
                blocks(), mesh=mesh, n_reduce=N_REDUCE,
                chunk_bytes=STREAM_CHUNK_BYTES, u_cap=STREAM_U_CAP,
                aot=True, device_accumulate=device_acc,
                pipeline_stats=pstats, **kw)
        ok = (acc is not None and len(acc) == len(oracle)
              and all(acc.get(w, (0, 0))[0] == c * cycles
                      for w, c in oracle.items()))
        return ok, pt.elapsed_s, pstats

    every = 1  # the A/B's whole point: snapshot EVERY confirmed step
    try:
        base_ok, base_s, _ = run()  # warm plain baseline
        if not base_ok:
            return {"ckpt_skipped": "baseline pass parity mismatch"}
        ck_ok, ck_s, pstats = run(checkpoint_dir=ckpt_dir,
                                  checkpoint_every=every)
        saves = pstats.get("ckpt_saves", 0)
        if not ck_ok:
            return {"ckpt_skipped": "checkpointed pass parity mismatch "
                                    "(overhead suppressed)"}
        if not saves:
            return {"ckpt_skipped": f"stream too short to checkpoint "
                                    f"(0 saves at every={every})"}
        overhead = 100.0 * (ck_s - base_s) / base_s
        full_per_save = pstats.get("ckpt_full_bytes", 0) / saves
        as_ok, as_s, astats = run(checkpoint_dir=async_dir,
                                  checkpoint_every=every,
                                  checkpoint_async=True,
                                  checkpoint_delta=True)
        if not as_ok:
            return {"ckpt_skipped": "async+delta pass parity mismatch "
                                    "(A/B suppressed)"}
        as_overhead = 100.0 * (as_s - base_s) / base_s
        deltas = astats.get("ckpt_deltas", 0)
        # Resume from the async pass's chain — the stronger restore:
        # base image + ordered deltas re-applied, not one flat load.
        resume_ok, _, rstats = run(checkpoint_dir=async_dir,
                                   checkpoint_every=every,
                                   checkpoint_async=True,
                                   checkpoint_delta=True, resume=True)
    finally:
        # Every exit path — skip returns and exceptions included — must
        # drop the row's snapshot files, or stale state-*.npz piles up
        # in the bench workdir across runs.
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        shutil.rmtree(async_dir, ignore_errors=True)
    log(f"ckpt row (cadence 1): sync-full {overhead:.1f}% "
        f"({ck_s:.2f}s) vs async+delta {as_overhead:.1f}% ({as_s:.2f}s) "
        f"over {base_s:.2f}s warm; {saves} saves "
        f"({full_per_save:.0f} B/full) vs {astats.get('ckpt_saves', 0)} "
        f"saves / {deltas} deltas "
        f"({astats.get('ckpt_delta_bytes', 0) / max(1, deltas):.0f} "
        f"B/delta, barrier {astats.get('ckpt_barrier_s', 0)}s); resume "
        f"gap {rstats.get('resume_gap_s', 0)}s from cursor "
        f"{rstats.get('resume_cursor', 0)} (parity={resume_ok})")
    if not resume_ok:
        return {"ckpt_skipped": "resume parity mismatch (gap suppressed)",
                "resume_parity": False}
    row = {"ckpt_overhead_pct": round(overhead, 1),
           "ckpt_async_overhead_pct": round(as_overhead, 1),
           "ckpt_every": every, "ckpt_saves": saves,
           "ckpt_deltas": deltas,
           "ckpt_full_bytes_per_save": round(full_per_save),
           "ckpt_barrier_s": round(astats.get("ckpt_barrier_s", 0.0), 4),
           "resume_gap_s": rstats.get("resume_gap_s", 0.0),
           "resume_parity": True}
    if deltas:
        row["ckpt_delta_bytes_per_save"] = round(
            astats.get("ckpt_delta_bytes", 0) / deltas)
        # Compressed-delta attribution (ISSUE 13,
        # DSI_STREAM_CKPT_COMPRESS default "deltas"): what the same
        # delta arrays would have cost raw, and the resulting ratio —
        # the >= 2x ckpt_delta_bytes evidence rides these two keys.
        raw = astats.get("ckpt_delta_raw_bytes", 0)
        if raw:
            row["ckpt_delta_raw_bytes_per_save"] = round(raw / deltas)
            row["ckpt_compress_ratio"] = round(
                raw / max(1, astats.get("ckpt_delta_bytes", 0)), 2)
            row["ckpt_compress_s"] = round(
                astats.get("ckpt_compress_s", 0.0), 4)
    return row


def run_kernel_row(files) -> dict:
    """Wire-independent kernel-only measurement (VERDICT r5 missing #1):
    upload ONE stream-shaped chunk, run the wc step DSI_BENCH_KERNEL_REPS
    times (default 5; 0 disables) on the HBM-resident buffer, report the
    median kernel-only MB/s per grouper variant — so a ~60 s healthy-
    tunnel window yields an on-chip compute number even when multi-minute
    corpus transfers can't finish.  Running BOTH groupers (both are in
    the warm ladder as of this round) makes the sort-vs-hash kernel gap
    a measured bench artifact instead of a CPU-only extrapolation.

    Gate: on accelerators the non-donated rep programs must already be
    persisted (scripts/warm_kernels.py --phase stream warms them) — a
    cold compile here is the same remote-compile hazard as everywhere
    else.  CPU processes compile in seconds and always run.
    """
    reps = int(env_float("DSI_BENCH_KERNEL_REPS", 5))
    if reps <= 0:
        return {}
    import statistics

    import jax
    import numpy as np

    from dsi_tpu.ops.wordcount import warm_groupers
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import (batch_stream,
                                            kernel_row_persisted,
                                            stream_files,
                                            stream_kernel_reps)

    mesh = default_mesh()
    n_dev = mesh.devices.size
    single = len(jax.devices()) == 1
    if (jax.devices()[0].platform != "cpu" and single
            and os.environ.get("DSI_BENCH_WARM_ALL") != "1"
            and not kernel_row_persisted(mesh=mesh,
                                         chunk_bytes=STREAM_CHUNK_BYTES,
                                         n_reduce=N_REDUCE,
                                         u_cap=STREAM_U_CAP)):
        return {"kernel_skipped":
                "kernel-row programs not in the AOT cache (cold compile "
                "risk); warm via scripts/warm_kernels.py --phase stream"}
    chunk = next(batch_stream(stream_files(files), n_dev,
                              STREAM_CHUNK_BYTES))
    chunk = np.array(chunk)  # detach from the batch-stream buffer
    mb = float(np.count_nonzero(chunk)) / 1e6  # honest: bytes processed
    out = {"kernel_reps": reps, "kernel_mb": round(mb, 2)}
    for g in warm_groupers():
        times, exact = stream_kernel_reps(
            chunk, mesh=mesh, n_reduce=N_REDUCE, u_cap=STREAM_U_CAP,
            reps=reps, grouper=g, aot=single)
        med = statistics.median(times)
        log(f"kernel row [{g}]: {mb:.2f} MB x {reps} reps, median "
            f"{med:.3f}s = {mb / med:.2f} MB/s (exact={exact})")
        if exact:  # a rate for an overflowing kernel never enters a trend
            out[f"kernel_{g}_mbps"] = round(mb / med, 2)
        else:
            out[f"kernel_{g}_skipped"] = "kernel overflowed at this shape"
    return out


def run_tfidf_row(files) -> dict:
    """The TF-IDF engine row (DSI_BENCH_TFIDF_MB, default 16; 0
    disables): the pipelined wave walk (``parallel/tfidf.py``) over the
    bench corpus cycled to ~the requested size, with the whole-corpus
    token invariant as the parity gate (sum of tf over all postings ==
    the oracle's total token count x cycles) and ``tfidf_phases`` (the
    engine's ``wave_phases``) mirroring ``stream_phases``.

    On accelerators the row runs only when explicitly requested
    (DSI_BENCH_TFIDF_MB set): the wave programs are not yet in the warm
    ladder, and an implicit multi-minute cold compile must never ride
    the default bench."""
    explicit = "DSI_BENCH_TFIDF_MB" in os.environ
    mb = env_float("DSI_BENCH_TFIDF_MB", 16.0)
    if mb <= 0:
        return {}
    import jax

    if jax.devices()[0].platform != "cpu" and not explicit:
        return {"tfidf_skipped": "accelerator tfidf row is opt-in "
                                 "(set DSI_BENCH_TFIDF_MB)"}
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import FileDocs, tfidf_sharded
    from dsi_tpu.utils.tracing import Span

    corpus_bytes = sum(os.path.getsize(p) for p in files)
    cycles = max(1, round(mb * 1e6 / corpus_bytes))
    # Lazy docs: each cycle of the corpus is its own document set, read
    # from disk per wave — the row's host footprint stays O(postings),
    # never O(corpus) (the FileDocs rationale).
    docs = FileDocs(list(files) * cycles)
    total_mb = sum(docs.lengths) / 1e6
    phases: dict = {}
    tracer = bench_tracer()
    mark = tracer.mark()
    with Span("bench.tfidf") as pt:
        res = tfidf_sharded(docs, mesh=default_mesh(), n_reduce=N_REDUCE,
                            u_cap=STREAM_U_CAP, packed=True,
                            wave_stats=phases)
    dt = pt.elapsed_s
    if res is None:
        return {"tfidf_skipped": "tfidf needed the host path "
                                 "(non-ASCII or >64-byte word)"}
    # Token invariant: every (word, doc) posting's tf sums to the total
    # token count the oracle already established for this corpus.
    oracle_tokens = 0
    with open(ORACLE_OUT, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                oracle_tokens += int(line.rstrip("\n").rpartition(" ")[2])
    got_tokens = int(res.tfs.astype("int64").sum())
    parity = got_tokens == oracle_tokens * cycles and len(res) > 0
    phases = {k: (round(v, 4) if isinstance(v, float) else v)
              for k, v in phases.items()}
    log(f"tfidf row: {total_mb:.1f} MB in {dt:.2f}s = "
        f"{total_mb / dt:.2f} MB/s (cycles={cycles}, parity={parity}, "
        f"phases={phases})")
    if not parity:
        return {"tfidf_skipped": f"token invariant failed "
                                 f"({got_tokens} != "
                                 f"{oracle_tokens * cycles})",
                "tfidf_parity": False}
    row = {"tfidf_mbps": round(total_mb / dt, 2),
           "tfidf_mb": round(total_mb, 1), "tfidf_s": round(dt, 2),
           "tfidf_parity": True, "tfidf_phases": phases}
    if tracer.enabled:
        row["tfidf_spans"] = tracer.rollup(mark)
    return row


def run_grep_row(files) -> dict:
    """The streaming grep engine row (DSI_BENCH_GREP_MB, default 16; 0
    disables; accelerators run it only when the knob is set explicitly):
    ``grep_streaming`` (``parallel/grepstream.py``) over the bench
    corpus cycled to ~the requested size, parity-gated against the
    single-pass host-grep oracle (same lines, matched counts,
    occurrences, histogram, and top-k — any divergence suppresses the
    rate), with ``grep_phases`` mirroring ``stream_phases`` and the
    oracle's own MB/s alongside (``grep_oracle_mbps``) so the row reads
    as engine-vs-host, not a bare number.

    DSI_BENCH_GREP_PATTERN picks the literal (default "the");
    DSI_BENCH_GREP_DEVICE_ACC=1 runs the row with the on-device top-k/
    histogram service (device/topk.py) folding confirmed steps and
    pulling every DSI_STREAM_SYNC_EVERY steps — step_pulls vs
    sync_pulls/widens is the amortization BENCH_r06+ compares.
    """
    explicit = "DSI_BENCH_GREP_MB" in os.environ
    mb = env_float("DSI_BENCH_GREP_MB", 16.0)
    if mb <= 0:
        return {}
    import jax

    pattern = os.environ.get("DSI_BENCH_GREP_PATTERN", "the")
    if jax.devices()[0].platform != "cpu" and not explicit:
        return {"grep_skipped": "accelerator grep row is opt-in "
                                "(set DSI_BENCH_GREP_MB)"}
    from dsi_tpu.parallel.grepstream import (GREP_CHUNK_BYTES,
                                             grep_host_oracle,
                                             grep_streaming,
                                             grepstream_persisted)
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import stream_files
    from dsi_tpu.utils.tracing import Span

    device_acc = os.environ.get("DSI_BENCH_GREP_DEVICE_ACC") == "1"
    single = len(jax.devices()) == 1
    aot = jax.devices()[0].platform != "cpu" and single
    if (aot and os.environ.get("DSI_BENCH_WARM_ALL") != "1"
            and not grepstream_persisted(chunk_bytes=GREP_CHUNK_BYTES,
                                         pattern_len=len(pattern),
                                         device_accumulate=device_acc)):
        return {"grep_skipped":
                "grep stream programs not in the AOT cache (cold compile "
                "risk); warm via scripts/warm_kernels.py --phase grep"}

    corpus_bytes = sum(os.path.getsize(p) for p in files)
    cycles = max(1, round(mb * 1e6 / corpus_bytes))

    def blocks():
        for c in range(cycles):
            if c:
                yield b"\n"
            yield from stream_files(files)

    # The oracle first: parity ground truth AND the host baseline rate.
    with Span("bench.grep_oracle") as pt:
        want = grep_host_oracle(blocks(), pattern)
    oracle_s = pt.elapsed_s
    total_mb = corpus_bytes * cycles / 1e6

    mesh = default_mesh()
    pstats: dict = {}
    tracer = bench_tracer()
    mark = tracer.mark()
    with Span("bench.grep") as pt:
        res = grep_streaming(blocks(), pattern, mesh=mesh,
                             chunk_bytes=GREP_CHUNK_BYTES, aot=aot,
                             device_accumulate=device_acc,
                             pipeline_stats=pstats)
    dt = pt.elapsed_s
    if res is None:
        return {"grep_skipped": "grep stream needed the host path "
                                "(non-literal pattern or over-wide line)"}
    parity = res == want
    phases = {k: pstats[k] for k in ("batch_s", "batch_wait_s", "upload_s",
                                     "kernel_s", "pull_s", "merge_s",
                                     "replay_s", "depth", "replays",
                                     "l_cap", "device_accumulate",
                                     "sync_every", "step_pulls", "folds",
                                     "fold_s", "fold_overflows",
                                     "sync_pulls", "sync_s", "widens",
                                     "widen_s", "table_cap",
                                     "topk_snapshots", "hist_folds",
                                     "hist_pulls")
              if k in pstats}
    log(f"grep row: {total_mb:.1f} MB in {dt:.2f}s = {total_mb / dt:.2f} "
        f"MB/s vs oracle {total_mb / oracle_s:.2f} MB/s (pattern="
        f"{pattern!r}, matched={res.matched}, parity={parity}, "
        f"phases={phases})")
    if not parity:
        return {"grep_skipped": f"parity mismatch vs host-grep oracle "
                                f"over {total_mb:.1f} MB (throughput "
                                f"suppressed)",
                "grep_parity": False}
    row = {"grep_mbps": round(total_mb / dt, 2),
           "grep_mb": round(total_mb, 1), "grep_s": round(dt, 2),
           "grep_matched": res.matched,
           "grep_oracle_mbps": round(total_mb / oracle_s, 2),
           "grep_vs_oracle": round(oracle_s / dt, 2),
           "grep_parity": True, "grep_phases": phases}
    if tracer.enabled:
        row["grep_spans"] = tracer.rollup(mark)
    return row


def run_wire_ingest_row(files) -> dict:
    """The compressed-wire + parallel-ingest A/B row (ISSUE 13,
    ``DSI_BENCH_WIRE``): three measurements over the bench corpus, each
    parity-gated and measured-XOR-skipped like every engine row.

    * **Shuffle-payload codec**: one real ``mapreduce_step`` over a
      stream-shaped chunk, its pulled packed table run through
      ``wirecodec.pack_rows`` — ``wire_ratio`` (raw valid-row bytes /
      packed bytes, the OSDI'04 combiner-compression analogue) with
      ``wire_parity`` the bit-exact unpack round-trip.
    * **Chunk-upload codec**: ``wordcount_streaming`` with
      ``wire_upload`` on vs off over the same cycled blocks —
      ``wire_upload_ratio``/``wire_decode_s`` with
      ``wire_upload_parity`` the result-dict equality (the decode
      prologue's end-to-end bit-identity evidence).
    * **Parallel ingest**: the same stream read through the
      ``utils/ioread.py`` reader pool (readers=4) vs inline reads —
      ``ingest_materialize_s`` vs ``ingest_serial_materialize_s`` (the
      read wall leaving the producer thread) plus
      ``readahead_hit_pct``, with ``ingest_parity`` the result
      equality.

    CPU boxes run it whenever the bench does; accelerators opt in via
    ``DSI_BENCH_WIRE=1`` (and additionally require the decode
    prologues persisted — ``warm_kernels.py --phase wire``);
    ``DSI_BENCH_WIRE=0`` disables everywhere."""
    explicit = os.environ.get("DSI_BENCH_WIRE")
    if explicit == "0":
        return {"wire_skipped": "disabled (DSI_BENCH_WIRE=0)"}
    import jax
    import numpy as np

    if jax.devices()[0].platform != "cpu" and explicit != "1":
        return {"wire_skipped": "accelerator wire/ingest row is opt-in "
                                "(set DSI_BENCH_WIRE=1)"}
    from dsi_tpu.ops import wirecodec
    from dsi_tpu.parallel.shuffle import (_slice_pack, default_mesh,
                                          mapreduce_step, occupied_prefix)
    from dsi_tpu.parallel.streaming import (batch_stream, stream_files,
                                            wordcount_streaming)
    from dsi_tpu.utils.ioread import ParallelBlocks
    from dsi_tpu.utils.tracing import Span

    mesh = default_mesh()
    n_dev = mesh.devices.size
    if (jax.devices()[0].platform != "cpu"
            and len(jax.devices()) == 1
            and os.environ.get("DSI_BENCH_WARM_ALL") != "1"
            and not wirecodec.wire_programs_persisted(
                mesh=mesh, chunk_bytes=STREAM_CHUNK_BYTES)):
        return {"wire_skipped":
                "wire decode programs not in the AOT cache (cold "
                "compile risk); warm via scripts/warm_kernels.py "
                "--phase wire"}

    # ── shuffle-payload codec on one REAL step's pulled table ──
    chunk = np.array(next(batch_stream(stream_files(files), n_dev,
                                       STREAM_CHUNK_BYTES)))
    keys, lens, cnts, parts, scal = mapreduce_step(
        chunk, n_dev=n_dev, n_reduce=N_REDUCE, max_word_len=16,
        u_cap=STREAM_U_CAP, mesh=mesh, t_cap_frac=4)
    scal_np = np.asarray(scal)
    if scal_np[:, 4].any() or scal_np[:, 3].any():
        return {"wire_skipped": "probe step overflowed/non-ASCII at the "
                                "bench shape (payload unusable)"}
    nus = scal_np[:, 0].astype(np.int64)
    mp = occupied_prefix(int(nus.max()), keys.shape[1])
    packed = np.asarray(_slice_pack(keys, lens, cnts, parts, mp=mp))
    with Span("bench.wire_pack") as pt:
        blob = wirecodec.pack_rows(packed, nus)
    rows2, nus2 = wirecodec.unpack_rows(blob)
    wire_parity = (np.array_equal(nus2, nus)
                   and all(np.array_equal(rows2[d, :int(nus[d])],
                                          packed[d, :int(nus[d])])
                           for d in range(n_dev)))
    raw_bytes = wirecodec.rows_raw_bytes(nus, keys.shape[2])
    if not wire_parity:
        return {"wire_skipped": "pack_rows round-trip mismatch "
                                "(ratio suppressed)",
                "wire_parity": False}
    row = {"wire_parity": True,
           "wire_ratio": round(raw_bytes / len(blob), 2),
           "wire_raw_kb": round(raw_bytes / 1e3, 1),
           "wire_packed_kb": round(len(blob) / 1e3, 1),
           "wire_pack_s": round(pt.elapsed_s, 4)}
    log(f"wire row: shuffle payload {raw_bytes / 1e3:.0f} kB -> "
        f"{len(blob) / 1e3:.0f} kB packed = x{row['wire_ratio']} "
        f"(parity={wire_parity}, pack {pt.elapsed_s:.3f}s)")

    # ── chunk-upload codec + ingest A/B over a bounded slice ──
    corpus_bytes = sum(os.path.getsize(p) for p in files)
    ab_mb = min(env_float("DSI_BENCH_WIRE_MB", 16.0), 64.0)
    cycles = max(1, round(ab_mb * 1e6 / corpus_bytes))
    paths = list(files) * cycles

    def run(source, **kw):
        pstats: dict = {}
        with Span("bench.wire_ab") as pt:
            acc = wordcount_streaming(
                source, mesh=mesh, n_reduce=N_REDUCE,
                chunk_bytes=STREAM_CHUNK_BYTES, u_cap=STREAM_U_CAP,
                aot=True, pipeline_stats=pstats, **kw)
        return acc, pt.elapsed_s, pstats

    def blocks():
        for i, p in enumerate(paths):
            if i:
                yield b"\n"
            yield from stream_files([p])

    base_acc, base_s, _ = run(blocks())
    wired_acc, wired_s, wstats = run(blocks(), wire_upload=True)
    if base_acc is None or wired_acc != base_acc:
        row["wire_upload_parity"] = False
        row["wire_skipped"] = ("wire_upload pass diverged from the raw "
                               "pass (A/B suppressed)")
        return row
    row.update({"wire_upload_parity": True,
                "wire_upload_ratio": wstats.get("wire_ratio", 0.0),
                "wire_upload_steps": wstats.get("wire_steps", 0),
                "wire_raw_steps": wstats.get("wire_raw_steps", 0),
                "wire_decode_s": round(wstats.get("decode_s", 0.0), 4)})
    log(f"wire row: upload codec x{row['wire_upload_ratio']} over "
        f"{wstats.get('wire_steps', 0)} steps "
        f"({wstats.get('wire_raw_steps', 0)} raw fallbacks), wall "
        f"{wired_s:.2f}s vs {base_s:.2f}s raw, decode "
        f"{row['wire_decode_s']}s")

    pool = ParallelBlocks(paths, readers=4)
    pool_acc, pool_s, pstats = run(pool)
    if pool_acc != base_acc:
        row["ingest_parity"] = False
        row["wire_skipped"] = ("reader-pool pass diverged from inline "
                               "reads (ingest A/B suppressed)")
        return row
    # A FRESH serial pass, not the first one's stats: the first pass
    # pays one-time costs (in-process compiles under the CPU
    # DSI_AOT_FRESH hygiene, first-touch page faults) that interleave
    # with the producer thread and inflate its materialize wall —
    # reusing it as the baseline would flatter the pool by exactly
    # that noise.  Warm-vs-warm is the honest A/B.
    serial_acc, serial_s, sstats = run(blocks())
    row.update({"ingest_parity": True, "ingest_readers": 4,
                "readahead_hit_pct": pstats.get("readahead_hit_pct", 0.0),
                "ingest_materialize_s": pstats.get("batch_s", 0.0),
                "ingest_serial_materialize_s": sstats.get("batch_s", 0.0),
                "ingest_wait_s": pstats.get("ingest_wait_s", 0.0)})
    log(f"ingest A/B: materialize {row['ingest_materialize_s']}s "
        f"(readers=4, hit {row['readahead_hit_pct']}%, wall {pool_s:.2f}s)"
        f" vs {row['ingest_serial_materialize_s']}s inline "
        f"(wall {serial_s:.2f}s)")
    return row


def framework_row_mb() -> float:
    return env_float("DSI_BENCH_FRAMEWORK_MB", 48.0)


def run_framework_row(bench_oracle_mbps: float) -> dict:
    """The reference's own headline measurement (VERDICT r4 task 2): the
    REAL distributed framework — coordinator + N worker processes over the
    pull-RPC control plane and shared-FS data plane — versus the
    sequential oracle on the same corpus (``main/test-mr.sh:36-53`` vs
    ``main/mrsequential.go:25-87``).  Chip-independent: host-backend
    workers, so the row exists even during a tunnel outage.

    N = max(3, available cores) — the reference runs 3 workers
    (``test-mr.sh:43-45``); more cores, more workers.  ``framework_cores``
    rides the row because the speedup physically cannot exceed the core
    count: on a 1-core box the distributed run CANNOT beat the sequential
    oracle (process parallelism has nothing to run on), and the row must
    say so rather than look like a framework defect.

    Timing starts when workers spawn (coordinator already listening) and
    stops when the last worker exits (workers exit on TaskStatus=DONE,
    ``mr/worker.go:51-53`` semantics) — excluding the coordinator's 1 Hz
    done-poll + exit-grace, which are fixed constants, not job work.

    Always returns either a measured row or ``framework_skipped``; parity
    mismatch suppresses the throughput (same discipline as the stream
    row).
    """
    mb = framework_row_mb()
    if mb <= 0:
        return {}
    import shutil

    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential
    from dsi_tpu.utils.corpus import ensure_corpus
    from dsi_tpu.utils.tracing import Span

    budget = env_float("DSI_BENCH_FRAMEWORK_TIMEOUT", 300.0)
    # Never trade the verdict for the row: the row runs BEFORE the one
    # JSON line is printed, so its wall must stay bounded on ANY box.
    # The in-process oracle pass cannot be preempted — scale the corpus
    # so it costs ~100 s at this box's just-measured oracle rate (a slow
    # box gets a smaller, still-valid row), and on a box so slow that
    # even the 6 MB floor would blow the bound, skip outright.  Total
    # row wall is therefore <= ~240 (oracle estimate cap) + budget +
    # 30 s coordinator wait + corpus generation — documented in the
    # module header alongside DSI_BENCH_DEADLINE_S (which bounds the
    # accelerator half only).
    if bench_oracle_mbps > 0:
        mb = min(mb, max(6.0, bench_oracle_mbps * 100))
    est_oracle_s = (mb / bench_oracle_mbps * 1.3 + 10
                    if bench_oracle_mbps > 0 else 120.0)
    if est_oracle_s > 240:
        return {"framework_skipped":
                f"box too slow for a bounded row (oracle estimate "
                f"{est_oracle_s:.0f}s at {bench_oracle_mbps:.2f} MB/s)"}
    n_workers = max(3, len(os.sched_getaffinity(0)))
    fw_dir = os.path.join(WORKDIR, "fw")
    shutil.rmtree(fw_dir, ignore_errors=True)
    os.makedirs(fw_dir)
    n_files = max(n_workers, round(mb * 1e6 / FILE_SIZE))
    files = ensure_corpus(os.path.join(WORKDIR, "fw-corpus"),
                          n_files=n_files, file_size=FILE_SIZE)
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6

    # Oracle at THIS scale: the parity ground truth and the same-corpus
    # baseline the speedup is computed against.
    oracle_out = os.path.join(fw_dir, "mr-correct.txt")
    with Span("bench.fw_oracle") as pt:
        run_sequential(wc.Map, wc.Reduce, files, oracle_out)
    fw_oracle_mbps = total_mb / pt.elapsed_s

    # The native library builds lazily on first use (up to ~2 min of
    # g++, once per machine); force it now so no worker pays it inside
    # the timed window — and so the backend label below is TRUTHFUL: if
    # the build is unavailable, every native task body would silently
    # decline to the Python path, and reporting 'native' for a
    # pure-Python run would mislabel the measurement.
    from dsi_tpu import native

    native_ok = native.available()

    # Native-sequential oracle twin (VERDICT r5 weak #2): the SAME C++
    # task bodies the distributed workers run, executed sequentially in
    # THIS process with no coordinator/RPC/respawn machinery — so the
    # framework row's headline speedup decomposes honestly into
    # language-speedup (native_oracle / python oracle) x framework-
    # efficiency (framework / native_oracle).  Without it, an 11.3x
    # framework-vs-oracle reads as distributed-systems magic when most
    # of it is compiled task bodies.
    native_row = run_native_oracle_row(files, oracle_out, total_mb,
                                       native_ok, fw_oracle_mbps)

    env = dict(os.environ)
    env["DSI_MR_SOCKET"] = os.path.join(fw_dir, "mr.sock")
    # cwd is the sandbox, so the repo must reach the children via
    # PYTHONPATH (the bench process itself gets it from sys.path.insert).
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    # Host-backend workers never touch jax; without this the axon
    # sitecustomize hook imports jax (+ PJRT registration) in EVERY child
    # interpreter — ~2.3 s per process, serialized on a 1-core box, which
    # would measure the site hook instead of the framework.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    coord = subprocess.Popen(
        [sys.executable, "-m", "dsi_tpu.cli.mrcoordinator", *files],
        cwd=fw_dir, env=env, stdout=sys.stderr, stderr=sys.stderr)
    workers: list = []

    def reap(reason: str) -> dict:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
        log(f"framework row skipped: {reason}")
        return {"framework_skipped": reason}

    # Every exit path below must reap: the explicit skip paths do it via
    # reap(), but an UNEXPECTED exception (worker spawn OSError, oracle
    # read failure) used to leave orphan coordinator/worker processes
    # contending for the core through the rest of the bench (ADVICE r5
    # item 1).  The finally is a no-op on the normal path — every child
    # has already been wait()ed.
    try:
        row = _run_framework_body(coord, workers, reap, env, fw_dir,
                                  oracle_out, total_mb, n_workers,
                                  native_ok, budget, fw_oracle_mbps)
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
        # Killed writers leave .tmp-* commit orphans (atomic_write's
        # temp prefix) — in the framework sandbox, and in the stream
        # row's checkpoint dir when an earlier interrupted bench died
        # mid-save.  Both directories are quiesced here, so the reap is
        # safe by construction.
        from dsi_tpu.utils.atomicio import reap_tmp_files

        reap_tmp_files(fw_dir)
        reap_tmp_files(os.path.join(WORKDIR, "ckpt-row"))
    row.update(native_row)
    if "framework_mbps" in row and "native_oracle_mbps" in row:
        # The decomposition: framework_vs_oracle ==
        # native_vs_python x framework_vs_native (up to rounding).
        row["framework_vs_native"] = round(
            row["framework_mbps"] / row["native_oracle_mbps"], 2)
    return row


def mesh_child(args_json: str) -> int:
    """Child entry for the mesh A/B row: one ``wordcount_streaming``
    pass over the given corpus on the (env-forced) 8-device virtual
    mesh, mesh-sharded or host-merge per config, printing one JSON line
    — result CRC (the parity bar), throughput, and the pull/widen/
    imbalance counters the parent compares."""
    import zlib

    cfg = json.loads(args_json)
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import (stream_files,
                                            wordcount_streaming)

    mesh = default_mesh(int(cfg["n_dev"]))

    def blocks():
        for c in range(int(cfg["cycles"])):
            if c:
                yield b"\n"
            yield from stream_files(cfg["files"])

    pstats: dict = {}
    t0 = time.perf_counter()
    # depth=1 pins BOTH passes to the lockstep path: the row measures
    # the pull SHAPE (pre-merged vs N partials), not pipelining — and on
    # the forced-8-vdev CPU mesh this jaxlib's collectives are flaky
    # when two in-flight programs both carry an all_to_all (observed
    # glibc heap corruption / misrouted rows at MB-scale shapes; real
    # chips execute in order and are unaffected).
    acc = wordcount_streaming(
        blocks(), mesh=mesh, n_reduce=N_REDUCE,
        chunk_bytes=int(cfg["chunk_bytes"]), u_cap=int(cfg["u_cap"]),
        depth=1, device_accumulate=True,
        mesh_shards=int(cfg["mesh_shards"]), pipeline_stats=pstats)
    dt = time.perf_counter() - t0
    if acc is None:
        print(json.dumps({"error": "stream needed the host path"}))
        return 1
    crc = zlib.crc32(repr(sorted(acc.items())).encode())
    out = {"crc": crc, "mbps": round(cfg["mb"] / dt, 2),
           "uniques": len(acc)}
    for k in ("pull_bytes", "sync_pulls", "widens", "shard_widens",
              "shard_imbalance", "folds", "steps"):
        if k in pstats:
            out[k] = pstats[k]
    print(json.dumps(out))
    return 0


def run_mesh_row() -> dict:
    """Mesh-vs-host-merge A/B on the 8-device virtual CPU mesh (ISSUE 7
    satellite): the same stream run twice in subprocesses — device
    services mesh-sharded (``mesh_shards=8``: ihash-routed shuffle-fold,
    per-shard widens, pre-merged occupied-prefix pulls) versus the
    host-merge device-accumulate path — reporting ``mesh_shuffle_mbps``
    A/B throughput, host bytes pulled per sync both ways, and the
    per-shard widen/imbalance counters.  Chip-independent structural
    evidence (the multichip dryrun's bench twin): subprocesses because
    the virtual 8-device mesh needs ``XLA_FLAGS`` set before jax
    imports.  Parity bar: both children's result CRCs must match (each
    child is the engine whose own parity grid is pinned by tier-1).
    Measured keys XOR ``mesh_skipped`` — the bench-contract discipline.
    ``DSI_BENCH_MESH_SHARDS=0`` disables; other values set the degree."""
    try:
        shards = int(os.environ.get("DSI_BENCH_MESH_SHARDS", "8"))
    except ValueError:
        shards = 8
    if shards <= 0:
        return {"mesh_skipped": "disabled (DSI_BENCH_MESH_SHARDS=0)"}
    mb = env_float("DSI_BENCH_MESH_MB", 4.0)
    # Controlled-vocabulary corpus (the multichip dryrun's discipline):
    # the row isolates the pull-SHAPE effect — with ~6k uniques the
    # hash-balanced shards' occupied prefix rounds to half the
    # partition-placed (n_reduce % n_dev) tables' — and an uncontrolled
    # corpus whose window vocabulary saturates the table capacity would
    # show both paths pulling full-capacity blocks, i.e. nothing.
    import numpy as np

    mesh_dir = os.path.join(WORKDIR, "mesh-corpus")
    os.makedirs(mesh_dir, exist_ok=True)
    path = os.path.join(mesh_dir, "corpus.txt")
    if not os.path.exists(path):
        rng = np.random.default_rng(7)
        vocab = ["".join(chr(97 + (i // 26 ** j) % 26) for j in range(4))
                 for i in range(6000)]
        toks = rng.integers(0, len(vocab), size=200_000)
        with open(path, "w") as f:
            f.write(" ".join(vocab[int(i)] for i in toks))
    files = [path]
    corpus_bytes = os.path.getsize(path)
    cycles = max(1, round(mb * 1e6 / corpus_bytes))
    cfg = {"files": files, "cycles": cycles,
           "mb": corpus_bytes * cycles / 1e6, "n_dev": shards,
           "chunk_bytes": 1 << 17, "u_cap": 1 << 10}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={shards}"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    budget = env_float("DSI_BENCH_MESH_TIMEOUT", 240.0)

    def child(mesh_shards: int) -> dict:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-child",
             json.dumps({**cfg, "mesh_shards": mesh_shards})],
            capture_output=True, text=True, timeout=budget, env=env)
        if p.returncode != 0:
            raise RuntimeError(f"mesh child rc={p.returncode}: "
                               f"{p.stderr[-400:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    # One retry absorbs the virtual mesh's residual collective flake
    # (a crashed child or a torn exchange fails the CRC gate — the gate
    # never lets a wrong pass publish throughput).
    host = meshed = None
    for attempt in (1, 2):
        try:
            host = child(0)
            meshed = child(shards)
        except Exception as e:
            if attempt == 2:
                return {"mesh_skipped": f"mesh row failed: "
                                        f"{type(e).__name__}: {e}"}
            continue
        if host["crc"] == meshed["crc"]:
            break
        if attempt == 2:
            return {"mesh_skipped": "mesh/host-merge parity mismatch "
                                    "(throughput suppressed)",
                    "mesh_parity": False}
    row = {"mesh_shards": shards, "mesh_parity": True,
           "mesh_mb": round(cfg["mb"], 1),
           "mesh_shuffle_mbps": meshed["mbps"],
           "mesh_host_mbps": host["mbps"],
           "mesh_pull_bytes_per_sync": round(
               meshed["pull_bytes"] / max(1, meshed["sync_pulls"])),
           "mesh_host_pull_bytes_per_sync": round(
               host["pull_bytes"] / max(1, host["sync_pulls"])),
           "mesh_shard_widens": meshed.get("shard_widens", []),
           "mesh_shard_imbalance": meshed.get("shard_imbalance", 0.0)}
    log(f"mesh row: {row['mesh_mb']} MB x2 on {shards} virtual devices — "
        f"shuffle {row['mesh_shuffle_mbps']} MB/s vs host-merge "
        f"{row['mesh_host_mbps']} MB/s, pull bytes/sync "
        f"{row['mesh_pull_bytes_per_sync']} vs "
        f"{row['mesh_host_pull_bytes_per_sync']}, imbalance "
        f"{row['mesh_shard_imbalance']}")
    return row


def run_serve_row() -> dict:
    """The serving-daemon A/B (ISSUE 11 satellite): M small word-count
    jobs submitted to the packed resident daemon (``dsi_tpu/serve``,
    one ``mrserve`` subprocess on the 8-vdev CPU mesh) versus the SAME
    M jobs run serially as one-shot ``wcstream`` CLIs — each of which
    pays its own process start + jax init + compile, which is exactly
    the cost the daemon exists to amortize.  Reports
    ``serve_packed_mbps`` / ``serve_oneshot_mbps`` (wall MB/s over the
    submit-to-done window vs the serial CLI loop) and
    ``serve_amortized_warm_s`` (the daemon's boot-to-ready cost divided
    across the M tenants).  Parity bar: every tenant's daemon output
    must byte-compare equal to the sequential oracle, or the row
    suppresses its throughput.  Measured keys XOR ``serve_skipped`` —
    the bench-contract discipline.  ``DSI_BENCH_SERVE_JOBS`` (default
    8; 0 disables) and ``DSI_BENCH_SERVE_MB`` (per-job MB, default 1)
    size it; chip-independent (host subprocesses), so it rides every
    verdict branch like the mesh row."""
    try:
        jobs = int(os.environ.get("DSI_BENCH_SERVE_JOBS", "8"))
    except ValueError:
        jobs = 8
    if jobs <= 0:
        return {"serve_skipped": "disabled (DSI_BENCH_SERVE_JOBS=0)"}
    per_mb = env_float("DSI_BENCH_SERVE_MB", 1.0)
    import shutil
    import tempfile

    from dsi_tpu.serve import client as sv

    sdir = os.path.join(WORKDIR, "serve-row")
    shutil.rmtree(sdir, ignore_errors=True)
    os.makedirs(sdir)
    spool = os.path.join(sdir, "spool")
    # AF_UNIX socket paths cap at ~108 bytes; WORKDIR can be deep.
    sock = os.path.join(tempfile.mkdtemp(prefix="dsi-bench-sv-"),
                        "s.sock")
    files = []
    for i in range(jobs):
        path = os.path.join(sdir, f"t{i}.txt")
        vocab = [f"t{i}w{j:04d}" for j in range(600)]
        line = " ".join(vocab) + "\n"
        reps = max(1, round(per_mb * 1e6 / len(line)))
        with open(path, "w") as f:
            f.write(line * reps)
        files.append(path)
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    # Per-tenant oracles, no jax in this (parent) process.
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential

    oracles = {}
    for i, p in enumerate(files):
        out = p + ".oracle"
        run_sequential(wc.Map, wc.Reduce, [p], out)
        with open(out, encoding="utf-8") as f:
            oracles[i] = sorted(l for l in f if l.strip())
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    budget = env_float("DSI_BENCH_SERVE_TIMEOUT", 300.0)

    # ── packed daemon half ──
    t_boot = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dsi_tpu.cli.mrserve", "--spool", spool,
         "--socket", sock, "--chunk-bytes", "65536"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        sv.wait_ready(sock, timeout=budget)
        warm_s = time.perf_counter() - t_boot
        t0 = time.perf_counter()
        reps = [sv.submit(sock, f"t{i}", [files[i]])
                for i in range(jobs)]
        final = sv.wait(sock, [r["job_id"] for r in reps],
                        timeout=budget)
        packed_s = time.perf_counter() - t0
        bad = [j for j, r in final.items() if r["state"] != "done"]
        if bad:
            return {"serve_skipped": f"daemon jobs failed: {bad}"}
        for i, rep in enumerate(reps):
            got = []
            for r in range(10):
                with open(os.path.join(rep["out_dir"], f"mr-out-{r}"),
                          encoding="utf-8") as f:
                    got.extend(l for l in f if l.strip())
            if sorted(got) != oracles[i]:
                return {"serve_skipped": f"tenant t{i} parity mismatch "
                                         f"(throughput suppressed)",
                        "serve_parity": False}
        try:
            sv.shutdown(sock)
            proc.wait(timeout=30)
        except Exception:
            proc.kill()
    except Exception as e:
        return {"serve_skipped": f"daemon half failed: "
                                 f"{type(e).__name__}: {e}"}
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # ── one-shot serial half: the same M jobs, a fresh CLI each ──
    t1 = time.perf_counter()
    for i, p in enumerate(files):
        wd = os.path.join(sdir, f"oneshot-{i}")
        os.makedirs(wd, exist_ok=True)
        r = subprocess.run(
            [sys.executable, "-m", "dsi_tpu.cli.wcstream",
             "--workdir", wd, p],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=budget)
        if r.returncode != 0:
            return {"serve_skipped": f"one-shot CLI {i} rc="
                                     f"{r.returncode}: {r.stderr[-200:]}"}
    oneshot_s = time.perf_counter() - t1
    row = {"serve_jobs": jobs, "serve_mb": round(total_mb, 2),
           "serve_parity": True,
           "serve_packed_mbps": round(total_mb / packed_s, 2),
           "serve_oneshot_mbps": round(total_mb / oneshot_s, 2),
           "serve_amortized_warm_s": round(warm_s / jobs, 3)}
    log(f"serve row: {jobs} jobs x {per_mb} MB — packed daemon "
        f"{row['serve_packed_mbps']} MB/s ({packed_s:.2f}s after "
        f"{warm_s:.2f}s boot = {row['serve_amortized_warm_s']}s/tenant) "
        f"vs serial one-shot CLIs {row['serve_oneshot_mbps']} MB/s "
        f"({oneshot_s:.2f}s)")
    return row


def _grep_oracle_payload(data: bytes, pattern: str) -> bytes:
    """The daemon's ``grep.json`` bytes for one tenant, computed with
    no jax import in this (parent) process: a pure-python replica of
    ``grep_host_oracle`` (overlapping occurrence counts, unterminated
    tail counts as a line) serialized exactly as
    ``ServeDaemon._write_grep_result`` spells it.  The latency row's
    per-tenant byte-parity ground truth."""
    pat = pattern.encode("ascii")
    bins, topk = 8, 16
    hist = [0] * bins
    matched = occurrences = line_no = 0
    cands = []
    parts = data.split(b"\n")
    carry = parts.pop()
    if carry:
        parts.append(carry)
    for line in parts:
        occ, i = 0, line.find(pat)
        while i >= 0:
            occ += 1
            i = line.find(pat, i + 1)
        hist[min(occ, bins - 1)] += 1
        if occ:
            matched += 1
            occurrences += occ
            cands.append((line_no, occ))
        line_no += 1
    top = sorted(cands, key=lambda r: (-r[1], r[0]))[:topk]
    return json.dumps(
        {"lines": line_no, "matched": matched,
         "occurrences": occurrences, "hist": hist,
         "topk": [list(r) for r in top]},
        sort_keys=True).encode("utf-8")


def run_serve_latency_row() -> dict:
    """The serving-QoS latency A/B (ISSUE 19 tentpole): N grep tenants
    submitted at once to the resident daemon with packed grep lanes
    (``serve/pack.py`` — up to 8 tenants per device dispatch) versus
    the SAME N tenants against a daemon running grep as
    time-multiplexed step objects (``--no-pack-grep``, the pre-packing
    behaviour).  Per-job latency is the daemon's own clock —
    ``done_ts - submitted_ts`` from the job journal — and the row
    reports nearest-rank p50/p99 across tenants for each arm
    (``serve_pack_p50_s``/``serve_pack_p99_s`` vs
    ``serve_tmux_p50_s``/``serve_tmux_p99_s``).  Parity bar: every
    tenant's ``grep.json`` must byte-compare equal to the no-jax host
    oracle in BOTH arms or the row suppresses its latencies.  Measured
    keys XOR ``serve_lat_skipped``.  ``DSI_BENCH_SERVE_LAT_TENANTS``
    (default 64; 0 disables), ``DSI_BENCH_SERVE_LAT_KB`` (per-tenant
    input, default 24) and ``DSI_BENCH_SERVE_LAT_TIMEOUT`` size it;
    chip-independent (host subprocesses on the 8-vdev CPU mesh)."""
    try:
        tenants = int(os.environ.get("DSI_BENCH_SERVE_LAT_TENANTS", "64"))
    except ValueError:
        tenants = 64
    if tenants <= 0:
        return {"serve_lat_skipped":
                "disabled (DSI_BENCH_SERVE_LAT_TENANTS=0)"}
    per_kb = env_float("DSI_BENCH_SERVE_LAT_KB", 24.0)
    budget = env_float("DSI_BENCH_SERVE_LAT_TIMEOUT", 300.0)
    import shutil
    import tempfile

    from dsi_tpu.serve import client as sv

    sdir = os.path.join(WORKDIR, "serve-lat")
    shutil.rmtree(sdir, ignore_errors=True)
    os.makedirs(sdir)
    files, pats, oracle = [], [], {}
    for i in range(tenants):
        # Same pattern LENGTH across tenants (one packed shape group,
        # the dense-wave case), distinct pattern BYTES per tenant.
        pat = f"w{i:04d}"
        lines = []
        j = 0
        size = 0
        want = int(per_kb * 1024)
        while size < want:
            line = (f"{pat} " * (j % 4) + f"filler{j % 97} text\n")
            lines.append(line)
            size += len(line)
            j += 1
        path = os.path.join(sdir, f"g{i}.txt")
        with open(path, "w") as f:
            f.writelines(lines)
        files.append(path)
        pats.append(pat)
        with open(path, "rb") as f:
            oracle[i] = _grep_oracle_payload(f.read(), pat)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()

    def pctl(lats: list, q: float) -> float:
        s = sorted(lats)
        return s[min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))]

    def arm(name: str, packed: bool):
        """One daemon run: submit every tenant, wait, return (per-job
        latencies, packed-step count) or raise."""
        spool = os.path.join(sdir, f"spool-{name}")
        # AF_UNIX socket paths cap at ~108 bytes; WORKDIR can be deep.
        sock = os.path.join(tempfile.mkdtemp(prefix="dsi-bench-lat-"),
                            "s.sock")
        cmd = [sys.executable, "-m", "dsi_tpu.cli.mrserve",
               "--spool", spool, "--socket", sock,
               "--chunk-bytes", "65536",
               "--max-resident", str(tenants),
               "--quota-steps", "1000000"]
        if not packed:
            cmd.append("--no-pack-grep")
        proc = subprocess.Popen(
            cmd, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            sv.wait_ready(sock, timeout=budget)
            reps = [sv.submit(sock, f"g{i}", [files[i]], app="grep",
                              pattern=pats[i])
                    for i in range(tenants)]
            final = sv.wait(sock, [r["job_id"] for r in reps],
                            timeout=budget)
            bad = [j for j, r in final.items() if r["state"] != "done"]
            if bad:
                raise RuntimeError(f"{name} arm jobs failed: {bad[:4]}")
            lats = []
            for i, rep in enumerate(reps):
                job = final[rep["job_id"]]
                lats.append(max(0.0, float(job["done_ts"])
                                 - float(job["submitted_ts"])))
                with open(os.path.join(rep["out_dir"], "grep.json"),
                          "rb") as f:
                    if f.read() != oracle[i]:
                        raise AssertionError(
                            f"{name} arm tenant g{i} parity mismatch")
            steps = int(sv.ping(sock).get("grep_packed_steps") or 0)
            try:
                sv.shutdown(sock)
                proc.wait(timeout=30)
            except Exception:
                proc.kill()
            return lats, steps
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    try:
        pack_lats, pack_steps = arm("pack", True)
        tmux_lats, _ = arm("tmux", False)
    except AssertionError as e:
        return {"serve_lat_skipped": f"{e} (latency suppressed)",
                "serve_lat_parity": False}
    except Exception as e:
        return {"serve_lat_skipped": f"latency row failed: "
                                     f"{type(e).__name__}: {e}"}
    row = {"serve_lat_tenants": tenants,
           "serve_lat_kb": round(per_kb, 1),
           "serve_lat_parity": True,
           "serve_lat_packed_steps": pack_steps,
           "serve_pack_p50_s": round(pctl(pack_lats, 0.50), 4),
           "serve_pack_p99_s": round(pctl(pack_lats, 0.99), 4),
           "serve_tmux_p50_s": round(pctl(tmux_lats, 0.50), 4),
           "serve_tmux_p99_s": round(pctl(tmux_lats, 0.99), 4)}
    log(f"serve latency row: {tenants} grep tenants x {per_kb:.0f} KB — "
        f"packed p50/p99 {row['serve_pack_p50_s']}/"
        f"{row['serve_pack_p99_s']}s ({pack_steps} packed steps) vs "
        f"time-multiplexed p50/p99 {row['serve_tmux_p50_s']}/"
        f"{row['serve_tmux_p99_s']}s")
    return row


def run_plan_row() -> dict:
    """The plan-layer A/B (ISSUE 14 satellite): one grep→wordcount
    CHAIN with the matching-line intermediate device-resident
    (``dsi_tpu/plan``, ``planrun`` subprocess) versus the SAME two
    stages run staged — full host materialization between them, the
    6.5840 shape.  Reports ``plan_chained_mbps`` / ``plan_staged_mbps``
    (corpus MB over each run's summed stage walls, from the CLI's
    ``--stats-json``), ``plan_intermediate_bytes`` (host-crossing
    handoff bytes of the chained run — MUST be 0, the ``plan_zero_copy``
    bool gates it) vs ``plan_staged_intermediate_bytes`` (the full
    materialization), parity-gated by byte-comparing the runs'
    mr-out-* sets.  ISSUE 16 adds a third arm: the PIPELINED chained
    run (``--pipeline`` — the wordcount consumes sealed relay buffers
    while the grep still produces) reporting ``plan_pipelined_mbps``
    and the attributed overlap wall ``plan_overlap_s``, byte-parity
    gated against both other arms.  Runs in fresh subprocesses on
    1-device CPU under
    ``DSI_AOT_FRESH=1`` like the other stream rows (the attributed
    persisted-AOT-load flake stays out of bench rounds), so it is
    chip-independent and rides every verdict branch.  Measured keys XOR
    ``plan_skipped`` — the bench-contract discipline.
    ``DSI_BENCH_PLAN_MB`` (default 8; 0 disables) sizes it."""
    mb = env_float("DSI_BENCH_PLAN_MB", 8.0)
    if mb <= 0:
        return {"plan_skipped": "disabled (DSI_BENCH_PLAN_MB=0)"}
    budget = env_float("DSI_BENCH_PLAN_TIMEOUT", 300.0)
    import shutil

    pdir = os.path.join(WORKDIR, "plan-row")
    shutil.rmtree(pdir, ignore_errors=True)
    os.makedirs(pdir)
    corpus_path = os.path.join(pdir, "corpus.txt")
    with open(corpus_path, "w") as f:
        i = 0
        written = 0
        target = mb * 1e6
        while written < target:
            if i % 3 == 0:
                line = (f"dsi chain w{i % 211:03d} step keeps bytes on "
                        f"device w{i % 97:02d} dsi\n")
            else:
                line = f"filler row{i} nothing matches here at all\n"
            f.write(line)
            written += len(line)
            i += 1
    total_mb = os.path.getsize(corpus_path) / 1e6
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # 1-device CPU + fresh compiles: the stream rows' AOT-flake hygiene
    # (aot_fresh_cpu_guard), in subprocess form.
    env.pop("XLA_FLAGS", None)
    env["DSI_AOT_FRESH"] = "1"

    def one(mode: str) -> tuple[dict, str]:
        wd = os.path.join(pdir, mode)
        sj = os.path.join(pdir, f"{mode}.stats.json")
        cmd = [sys.executable, "-m", "dsi_tpu.cli.planrun",
               "--chain", "grep-wc", "--pattern", "dsi",
               "--chunk-bytes", str(1 << 20),
               "--workdir", wd, "--stats-json", sj, corpus_path]
        if mode == "staged":
            cmd.insert(-1, "--staged")
        elif mode == "pipelined":
            cmd.insert(-1, "--pipeline")
        r = subprocess.run(cmd, env=env,
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           capture_output=True, text=True, timeout=budget)
        if r.returncode != 0:
            raise RuntimeError(f"{mode} planrun rc={r.returncode}: "
                               f"{r.stderr[-300:]}")
        with open(sj, encoding="utf-8") as f:
            return json.load(f), wd

    try:
        chained, wd_c = one("chained")
        staged, wd_s = one("staged")
        pipelined, wd_p = one("pipelined")
    except Exception as e:
        return {"plan_skipped": f"plan row failed: "
                                f"{type(e).__name__}: {e}"}

    def outset(wd: str) -> list:
        got = []
        for r in range(10):
            with open(os.path.join(wd, f"mr-out-{r}"),
                      encoding="utf-8") as f:
                got.extend(l for l in f if l.strip())
        return sorted(got)

    try:
        want = outset(wd_s)
        parity = outset(wd_c) == want and outset(wd_p) == want
    except OSError as e:
        return {"plan_skipped": f"missing chain output: {e}"}
    if not parity:
        return {"plan_skipped": "chained/pipelined vs staged parity "
                                "mismatch (throughput suppressed)",
                "plan_parity": False}
    inter_c = int(chained.get("plan_intermediate_bytes", -1))
    inter_s = int(staged.get("plan_intermediate_bytes", 0))
    chained_s = float(chained.get("plan_s", 0.0)) or 1e-9
    staged_s = float(staged.get("plan_s", 0.0)) or 1e-9
    pipe_s = float(pipelined.get("plan_s", 0.0)) or 1e-9
    row = {"plan_mb": round(total_mb, 2), "plan_parity": True,
           "plan_zero_copy": inter_c == 0,
           "plan_chained_mbps": round(total_mb / chained_s, 2),
           "plan_staged_mbps": round(total_mb / staged_s, 2),
           "plan_pipelined_mbps": round(total_mb / pipe_s, 2),
           "plan_overlap_s": float(pipelined.get("plan_overlap_s",
                                                 0.0)),
           "plan_intermediate_bytes": inter_c,
           "plan_staged_intermediate_bytes": inter_s,
           "plan_stage_walls": chained.get("plan_stage_walls", {})}
    log(f"plan row: {total_mb:.1f} MB grep→wc — chained "
        f"{row['plan_chained_mbps']} MB/s ({chained_s:.2f}s, "
        f"{inter_c} host bytes between stages) vs staged "
        f"{row['plan_staged_mbps']} MB/s ({staged_s:.2f}s, "
        f"{inter_s} host bytes); pipelined "
        f"{row['plan_pipelined_mbps']} MB/s ({pipe_s:.2f}s, "
        f"{row['plan_overlap_s']:.2f}s overlapped)")
    return row


def run_spec_row() -> dict:
    """The speculative-execution A/B (ISSUE 15 satellite): one shard
    job with an INJECTED slow shard (worker 0 sleeps per advance
    slice), run twice in fresh subprocess fleets — backup dispatch ON
    (``spec_backup_mbps``) vs ``--no-spec`` (``spec_nobackup_mbps``).
    Reports ``spec_backup_fired`` (backup dispatches in the armed run —
    the row is only meaningful when >= 1), ``spec_duplicate_commits``
    (journal double-commits across BOTH arms — MUST be 0; the
    first-commit-wins gate), and ``spec_resumed`` (attempts that
    restored a checkpoint chain).  Each arm is parity-gated against the
    sequential host oracle by ``shardrun --check`` (exit 2 = mismatch,
    throughput suppressed).  ISSUE 16 adds a third arm under the SAME
    injected straggler: ``--resplit`` (dynamic re-split — the
    straggler's remaining range splits into sub-shards for the idle
    workers instead of one full-range backup), reporting
    ``spec_resplit_mbps`` / ``spec_resplits`` / ``spec_subshards``;
    its duplicate commits fold into the same must-be-0 gate.  The
    re-split trigger is load-dependent, so that arm skips honestly
    (``spec_resplit_skipped``) when no re-split fired, without
    suppressing the backup half.  Chip-independent (1-device CPU
    workers), measured keys XOR ``spec_skipped``.  ``DSI_BENCH_SPEC_MB``
    (default 4; 0 disables) sizes it."""
    mb = env_float("DSI_BENCH_SPEC_MB", 4.0)
    if mb <= 0:
        return {"spec_skipped": "disabled (DSI_BENCH_SPEC_MB=0)"}
    budget = env_float("DSI_BENCH_SPEC_TIMEOUT", 300.0)
    import shutil

    sdir = os.path.join(WORKDIR, "spec-row")
    shutil.rmtree(sdir, ignore_errors=True)
    os.makedirs(sdir)
    corpus_path = os.path.join(sdir, "corpus.txt")
    with open(corpus_path, "w") as f:
        i = 0
        written = 0
        target = mb * 1e6
        while written < target:
            line = (" ".join(
                "spec" + chr(ord("a") + (i + j) % 23) * 2
                for j in range(9)) + "\n")
            f.write(line)
            written += len(line)
            i += 1
    total_mb = os.path.getsize(corpus_path) / 1e6
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1-device CPU workers
    env["DSI_AOT_FRESH"] = "1"  # the stream rows' CPU flake hygiene

    def one(mode: str) -> dict:
        wd = os.path.join(sdir, mode)
        sj = os.path.join(sdir, f"{mode}.stats.json")
        e = dict(env)
        e["DSI_MR_SOCKET"] = os.path.join(sdir, f"{mode}.sock")
        cmd = [sys.executable, "-m", "dsi_tpu.cli.shardrun",
               "--workers", "3", "--shards", "3",
               "--workdir", wd, "--chunk-bytes", str(1 << 16),
               "--ckpt-secs", "0.2", "--progress-s", "0.1",
               "--spec-floor", "2.0", "--shard-timeout", "120",
               "--slow-worker", "0:1.0",
               "--check", "--stats-json", sj, corpus_path]
        if mode == "nobackup":
            cmd.insert(-1, "--no-spec")
        elif mode == "resplit":
            cmd.insert(-1, "--resplit")
        r = subprocess.run(cmd, env=e,
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           capture_output=True, text=True,
                           timeout=budget)
        if r.returncode == 2:
            raise RuntimeError(f"{mode} arm parity mismatch")
        if r.returncode != 0:
            raise RuntimeError(f"{mode} shardrun rc={r.returncode}: "
                               f"{r.stderr[-300:]}")
        with open(sj, encoding="utf-8") as f:
            return json.load(f)

    try:
        backup = one("backup")
        nobackup = one("nobackup")
    except Exception as e:
        return {"spec_skipped": f"spec row failed: "
                                f"{type(e).__name__}: {e}"}
    resplit, resplit_skip = None, None
    try:
        resplit = one("resplit")
    except Exception as e:
        resplit_skip = (f"resplit arm failed: "
                        f"{type(e).__name__}: {e}")
    dup = (int(backup.get("duplicate_commits", 0))
           + int(nobackup.get("duplicate_commits", 0))
           + int((resplit or {}).get("duplicate_commits", 0)))
    backup_s = float(backup.get("wall_s", 0.0)) or 1e-9
    nobackup_s = float(nobackup.get("wall_s", 0.0)) or 1e-9
    row = {"spec_mb": round(total_mb, 2), "spec_parity": True,
           "spec_backup_mbps": round(total_mb / backup_s, 2),
           "spec_nobackup_mbps": round(total_mb / nobackup_s, 2),
           "spec_backup_fired": int(backup.get("backup_dispatches", 0)),
           "spec_duplicate_commits": dup,
           # Bool twin of duplicate_commits for the bench_diff gate: a
           # healthy old value of 0 reads "unknown" under the numeric
           # lower-better rule (the plan_zero_copy precedent), so the
           # bool carries the first-commit-wins regression gate.
           "spec_exactly_once": dup == 0,
           "spec_resumed": int(backup.get("resumed_attempts", 0)),
           "spec_commit_losses": int(backup.get("commit_losses", 0))}
    if resplit is not None and not int(resplit.get("resplits", 0)):
        resplit_skip = ("no re-split fired (straggler finished or "
                        "remainder under the split floor — backup "
                        "fallback ran)")
    if resplit_skip is not None:
        row["spec_resplit_skipped"] = resplit_skip
    else:
        resplit_s = float(resplit.get("wall_s", 0.0)) or 1e-9
        row.update({
            "spec_resplit_mbps": round(total_mb / resplit_s, 2),
            "spec_resplits": int(resplit["resplits"]),
            "spec_subshards": int(resplit.get("subshard_dispatches",
                                              0))})
    log(f"spec row: {total_mb:.1f} MB, slow shard injected — backup "
        f"{row['spec_backup_mbps']} MB/s ({backup_s:.2f}s, "
        f"{row['spec_backup_fired']} backups, {row['spec_resumed']} "
        f"resumed) vs no-backup {row['spec_nobackup_mbps']} MB/s "
        f"({nobackup_s:.2f}s); duplicate commits {dup}")
    if "spec_resplit_mbps" in row:
        log(f"spec row resplit arm: {row['spec_resplit_mbps']} MB/s "
            f"({resplit_s:.2f}s, {row['spec_resplits']} resplits -> "
            f"{row['spec_subshards']} sub-shards)")
    else:
        log(f"spec row resplit arm skipped: {row['spec_resplit_skipped']}")
    return row


def run_net_row() -> dict:
    """The network-data-plane A/B (ISSUE 17 satellite): the SAME
    multi-file wordcount job run twice in fresh ``mrrun`` fleets —
    shuffle over localhost TCP with per-worker PRIVATE workdirs
    (``--net``: ``net_shuffle_mbps``) vs the shared-directory data
    plane (``net_fs_mbps``).  Both arms are parity-gated against the
    sequential oracle by ``mrrun --check`` (exit 2 = mismatch, row
    suppressed).  The net arm also reports ``net_ratio`` (raw/wire —
    the PR-13 line codec's leverage on the shuffle link, gated >= 1.5
    by the acceptance bar) and ``locality_hits`` (reduce tasks placed
    on the host already holding their biggest input share).
    Chip-independent (host-backend CPU workers), measured keys XOR
    ``net_skipped``.  ``DSI_BENCH_NET_MB`` (default 4; 0 disables)
    sizes it."""
    mb = env_float("DSI_BENCH_NET_MB", 4.0)
    if mb <= 0:
        return {"net_skipped": "disabled (DSI_BENCH_NET_MB=0)"}
    budget = env_float("DSI_BENCH_NET_TIMEOUT", 300.0)
    import shutil

    ndir = os.path.join(WORKDIR, "net-row")
    shutil.rmtree(ndir, ignore_errors=True)
    os.makedirs(ndir)
    # Several input files: multiple map producers spread across the
    # workers, so the net arm's shuffle really crosses the wire (one
    # file would let locality placement make every fetch local).
    n_files = 4
    paths, total = [], 0
    for fi in range(n_files):
        path = os.path.join(ndir, f"corpus-{fi}.txt")
        with open(path, "w") as f:
            i = 0
            written = 0
            while written < mb * 1e6 / n_files:
                line = (" ".join(
                    "net" + chr(ord("a") + (fi + i + j) % 23) * 2
                    for j in range(9)) + "\n")
                f.write(line)
                written += len(line)
                i += 1
        total += os.path.getsize(path)
        paths.append(path)
    total_mb = total / 1e6
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1-device CPU workers
    env["DSI_AOT_FRESH"] = "1"
    # mrrun's children run with cwd=workdir: keep the package importable
    # there even when it is not installed (the test-sandbox case).
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))

    def one(mode: str) -> tuple:
        wd = os.path.join(ndir, mode)
        os.makedirs(wd, exist_ok=True)
        sj = os.path.join(ndir, f"{mode}.stats.json")
        e = dict(env)
        e["DSI_MR_SOCKET"] = os.path.join(ndir, f"{mode}.sock")
        cmd = [sys.executable, "-m", "dsi_tpu.cli.mrrun",
               "--workers", "2", "--nreduce", "4", "--workdir", wd,
               "--check", "--stats-json", sj]
        if mode == "net":
            cmd.append("--net")
        cmd += ["wc"] + paths
        t0 = time.perf_counter()
        r = subprocess.run(cmd, env=e,
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           capture_output=True, text=True,
                           timeout=budget)
        dt = time.perf_counter() - t0
        if r.returncode == 2:
            raise RuntimeError(f"{mode} arm parity mismatch")
        if r.returncode != 0:
            raise RuntimeError(f"{mode} mrrun rc={r.returncode}: "
                               f"{r.stderr[-300:]}")
        stats = {}
        if os.path.exists(sj):
            with open(sj, encoding="utf-8") as f:
                stats = json.load(f)
        return dt, stats

    try:
        net_s, net = one("net")
        fs_s, _fs = one("fs")
    except Exception as e:
        return {"net_skipped": f"net row failed: "
                               f"{type(e).__name__}: {e}"}
    row = {"net_mb": round(total_mb, 2), "net_parity": True,
           "net_shuffle_mbps": round(total_mb / (net_s or 1e-9), 2),
           "net_fs_mbps": round(total_mb / (fs_s or 1e-9), 2),
           "net_ratio": float(net.get("net_ratio", 0.0)),
           "net_fetches": int(net.get("net_fetches", 0)),
           "net_local_reads": int(net.get("net_local_reads", 0)),
           "locality_hits": int(net.get("locality_hits", 0)),
           "net_refetches": int(net.get("net_refetches", 0))}
    log(f"net row: {total_mb:.1f} MB over {n_files} files — shuffle/TCP "
        f"{row['net_shuffle_mbps']} MB/s ({net_s:.2f}s, "
        f"{row['net_fetches']} fetches + {row['net_local_reads']} "
        f"local, codec ratio {row['net_ratio']}, "
        f"{row['locality_hits']} locality hits) vs shared-dir "
        f"{row['net_fs_mbps']} MB/s ({fs_s:.2f}s)")
    return row


def run_net_pipeline_row() -> dict:
    """The overlapped-shuffle A/B (ISSUE 18): the SAME reduce-side
    fetch plan — P partitions spread across S in-process partition
    servers — pulled twice, serial (window 1: one blocking fetch at a
    time, the pre-pipeline path) vs pipelined (``FetchPipeline`` at
    the default window).  Localhost TCP is far too fast for prefetch
    to show, so every server runs with an injected per-chunk serve
    latency (``DSI_NET_CHUNK_SLEEP_S`` — the ``chunk_hook`` sleep,
    identical on BOTH arms); the pipelined arm hides it by keeping
    several streams in flight, which is exactly the claim
    ``net_pipelined_mbps``/``net_serial_mbps`` measures.  Parity-gated:
    both arms must yield byte-identical payload sequences (producer
    order) or the row is suppressed.  ``net_overlap_s`` (dialer wire
    time hidden behind the consumer) comes from the pipelined arm's
    stats.  Chip-independent, measured keys XOR
    ``net_pipeline_skipped``.  ``DSI_BENCH_NET_PIPE_MB`` (default 2;
    0 disables) sizes it; ``DSI_BENCH_NET_PIPE_SLEEP`` (default 0.03)
    is the injected per-chunk latency."""
    mb = env_float("DSI_BENCH_NET_PIPE_MB", 2.0)
    if mb <= 0:
        return {"net_pipeline_skipped":
                "disabled (DSI_BENCH_NET_PIPE_MB=0)"}
    sleep_s = env_float("DSI_BENCH_NET_PIPE_SLEEP", 0.03)
    import shutil

    from dsi_tpu.net.fetch import (DEFAULT_FETCH_WINDOW, FetchPipeline,
                                   fetch_partition)
    from dsi_tpu.net.partsrv import PartitionServer

    ndir = os.path.join(WORKDIR, "net-pipe-row")
    shutil.rmtree(ndir, ignore_errors=True)
    n_srv, n_part = 4, 8
    part_bytes = int(mb * 1e6 / n_part)
    servers = []
    old = os.environ.get("DSI_NET_CHUNK_SLEEP_S")
    os.environ["DSI_NET_CHUNK_SLEEP_S"] = str(sleep_s)
    try:
        items = []
        for p in range(n_part):
            if p < n_srv:
                srv = PartitionServer(os.path.join(ndir, f"srv-{p}"))
                srv.start()
                servers.append(srv)
            srv = servers[p % n_srv]
            name = f"mr-{p}-0"
            line = f"pipe{p:02d} " * 16 + "\n"
            srv.put(name, (line * (part_bytes // len(line) + 1))
                    [:part_bytes].encode())
            items.append((p, srv.address, name))
        total_mb = n_part * part_bytes / 1e6

        t0 = time.perf_counter()
        serial = [fetch_partition(a, n) for _, a, n in items]
        serial_s = time.perf_counter() - t0

        io_b: dict = {}
        t0 = time.perf_counter()
        piped = [raw for _, raw in
                 FetchPipeline(items, window=DEFAULT_FETCH_WINDOW,
                               stats=io_b)]
        piped_s = time.perf_counter() - t0
    except Exception as e:
        return {"net_pipeline_skipped": f"net pipeline row failed: "
                                        f"{type(e).__name__}: {e}"}
    finally:
        for srv in servers:
            srv.close()
        if old is None:
            os.environ.pop("DSI_NET_CHUNK_SLEEP_S", None)
        else:
            os.environ["DSI_NET_CHUNK_SLEEP_S"] = old
        shutil.rmtree(ndir, ignore_errors=True)
    if serial != piped:
        return {"net_pipeline_skipped":
                "parity mismatch: pipelined payloads != serial"}
    row = {"net_pipe_mb": round(total_mb, 2),
           "net_pipeline_parity": True,
           "net_serial_mbps": round(total_mb / (serial_s or 1e-9), 2),
           "net_pipelined_mbps": round(total_mb / (piped_s or 1e-9), 2),
           "net_overlap_s": float(io_b.get("net_overlap_s", 0.0)),
           "net_fetch_wait_s": float(io_b.get("net_fetch_wait_s", 0.0))}
    log(f"net pipeline row: {total_mb:.1f} MB over {n_part} partitions "
        f"x {n_srv} servers ({sleep_s}s/chunk injected) — pipelined "
        f"(window {DEFAULT_FETCH_WINDOW}) {row['net_pipelined_mbps']} "
        f"MB/s ({piped_s:.2f}s, overlap {row['net_overlap_s']}s) vs "
        f"serial {row['net_serial_mbps']} MB/s ({serial_s:.2f}s)")
    return row


def run_replica_row() -> dict:
    """The replicated-control-plane A/B (ISSUE 20): the same shard job
    run in fresh subprocess fleets three ways — a single in-process
    coordinator (``replica_single_mbps``), a 3-replica Raft group with
    nothing failing (``replica_group_mbps`` — its wall over the single
    arm's is ``replica_overhead_pct``, the price of majority-committing
    every journal record), and the same group with the LEADER kill -9'd
    mid-job.  The chaos arm reports ``replica_failover_s`` (kill
    instant → the first coordinator answer served by the NEW leader —
    THE tentpole number, gates lower-better in bench_diff) and the term
    handoff.  ``replica_exactly_once`` is the bool gate: zero duplicate
    commits in every arm's stats AND no shard with two commit records
    in ANY replica's journal across both group arms.  Every arm is
    parity-gated against the sequential host oracle by ``shardrun
    --check`` (exit 2 = mismatch).  Chip-independent (1-device CPU
    workers), measured keys XOR ``replica_skipped``.
    ``DSI_BENCH_REPLICA_MB`` (default 4; 0 disables) sizes it."""
    mb = env_float("DSI_BENCH_REPLICA_MB", 4.0)
    if mb <= 0:
        return {"replica_skipped": "disabled (DSI_BENCH_REPLICA_MB=0)"}
    budget = env_float("DSI_BENCH_REPLICA_TIMEOUT", 300.0)
    import shutil

    rdir = os.path.join(WORKDIR, "replica-row")
    shutil.rmtree(rdir, ignore_errors=True)
    os.makedirs(rdir)
    corpus_path = os.path.join(rdir, "corpus.txt")
    with open(corpus_path, "w") as f:
        i = 0
        written = 0
        target = mb * 1e6
        while written < target:
            line = (" ".join(
                "rep" + chr(ord("a") + (i + j) % 19) * 2
                for j in range(9)) + "\n")
            f.write(line)
            written += len(line)
            i += 1
    total_mb = os.path.getsize(corpus_path) / 1e6
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1-device CPU workers
    env["DSI_AOT_FRESH"] = "1"  # the stream rows' CPU flake hygiene

    def one(mode: str) -> dict:
        wd = os.path.join(rdir, mode)
        sj = os.path.join(rdir, f"{mode}.stats.json")
        e = dict(env)
        cmd = [sys.executable, "-m", "dsi_tpu.cli.shardrun",
               "--workers", "2", "--shards", "4",
               "--workdir", wd, "--chunk-bytes", str(1 << 16),
               "--progress-s", "0.1", "--shard-timeout", "120",
               "--check", "--stats-json", sj, corpus_path]
        if mode == "single":
            e["DSI_MR_SOCKET"] = os.path.join(rdir, "single.sock")
        else:
            cmd[-1:-1] = ["--replicas", "3"]
            if mode == "failover":
                cmd[-1:-1] = ["--kill-leader-after", "1.0"]
        r = subprocess.run(cmd, env=e,
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           capture_output=True, text=True,
                           timeout=budget)
        if r.returncode == 2:
            raise RuntimeError(f"{mode} arm parity mismatch")
        if r.returncode != 0:
            raise RuntimeError(f"{mode} shardrun rc={r.returncode}: "
                               f"{r.stderr[-300:]}")
        with open(sj, encoding="utf-8") as f:
            return json.load(f)

    def journal_dups(mode: str) -> int:
        """Shard records appearing MORE than once in any one replica
        journal — the cross-term first-commit-wins audit."""
        import glob

        dups = 0
        for path in sorted(glob.glob(
                os.path.join(rdir, mode, "replica-*.journal"))):
            per: dict = {}
            with open(path, encoding="utf-8") as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("kind") == "shard":
                        per[rec["task"]] = per.get(rec["task"], 0) + 1
            dups += sum(n - 1 for n in per.values() if n > 1)
        return dups

    try:
        single = one("single")
        group = one("group")
        failover = one("failover")
    except Exception as e:
        return {"replica_skipped": f"replica row failed: "
                                   f"{type(e).__name__}: {e}"}
    dup = (int(single.get("duplicate_commits", 0))
           + int(group.get("duplicate_commits", 0))
           + int(failover.get("duplicate_commits", 0))
           + journal_dups("group") + journal_dups("failover"))
    single_s = float(single.get("wall_s", 0.0)) or 1e-9
    group_s = float(group.get("wall_s", 0.0)) or 1e-9
    failover_s_wall = float(failover.get("wall_s", 0.0)) or 1e-9
    row = {"replica_mb": round(total_mb, 2), "replica_parity": True,
           "replica_single_mbps": round(total_mb / single_s, 2),
           "replica_group_mbps": round(total_mb / group_s, 2),
           "replica_chaos_mbps": round(total_mb / failover_s_wall, 2),
           "replica_overhead_pct": round(
               (group_s - single_s) / single_s * 100.0, 1),
           "replica_failover_s": float(
               failover.get("replica_failover_s", 0.0)),
           "replica_terms": [int(failover.get("replica_old_term", 0)),
                             int(failover.get("replica_new_term", 0))],
           "replica_duplicate_commits": dup,
           # Bool twin for the bench_diff gate (the spec_exactly_once
           # precedent): a healthy old value of 0 reads "unknown" under
           # the numeric lower-better rule, so the bool carries the
           # first-commit-wins-across-terms regression gate.
           "replica_exactly_once": dup == 0}
    log(f"replica row: {total_mb:.1f} MB — single {row['replica_single_mbps']} "
        f"MB/s ({single_s:.2f}s) vs 3-replica group "
        f"{row['replica_group_mbps']} MB/s ({group_s:.2f}s, "
        f"+{row['replica_overhead_pct']}%); leader kill -9 arm "
        f"{row['replica_chaos_mbps']} MB/s ({failover_s_wall:.2f}s), "
        f"failover {row['replica_failover_s']}s (term "
        f"{row['replica_terms'][0]} -> {row['replica_terms'][1]}), "
        f"duplicate commits {dup}")
    return row


def run_native_oracle_row(files, oracle_out, total_mb, native_ok,
                          fw_oracle_mbps) -> dict:
    """Sequential run of the SAME C++ task bodies the native-backend
    workers execute (``dsi_tpu/native`` wcjob: map each file, write the
    mr-X-Y intermediates, reduce each partition) with no framework at
    all — the compiled-language twin of the python oracle.  Parity vs
    the python oracle's output is the gate; a declined native body (the
    library degrades on non-ASCII etc.) skips the row honestly."""
    if not native_ok:
        return {"native_oracle_skipped": "native library unavailable"}
    import shutil

    from dsi_tpu import native
    from dsi_tpu.utils.tracing import Span

    ndir = os.path.join(os.path.dirname(oracle_out), "native-seq")
    shutil.rmtree(ndir, ignore_errors=True)
    os.makedirs(ndir)
    out_blobs = []
    with Span("bench.native_oracle") as pt:
        for m, p in enumerate(files):
            blobs = native.wc_map_file(p, N_REDUCE)
            if blobs is None:
                return {"native_oracle_skipped":
                        "native map body declined this split"}
            for r, blob in enumerate(blobs):
                with open(os.path.join(ndir, f"mr-{m}-{r}"), "wb") as f:
                    f.write(blob)
        for r in range(N_REDUCE):
            blob = native.wc_reduce(ndir, r, len(files))
            if blob is None:
                return {"native_oracle_skipped":
                        "native reduce body declined"}
            out_blobs.append(blob)
    dt = pt.elapsed_s
    got = sorted(l for b in out_blobs
                 for l in b.decode("utf-8").splitlines() if l.strip())
    with open(oracle_out, encoding="utf-8") as f:
        want = sorted(l.rstrip("\n") for l in f if l.strip())
    if got != want:
        return {"native_oracle_skipped":
                "parity mismatch vs python oracle (rate suppressed)"}
    mbps = total_mb / dt
    log(f"native-sequential oracle: {total_mb:.1f} MB in {dt:.2f}s = "
        f"{mbps:.2f} MB/s ({mbps / fw_oracle_mbps:.2f}x the python "
        "oracle)")
    return {"native_oracle_mbps": round(mbps, 2),
            "native_vs_python": round(mbps / fw_oracle_mbps, 2)}


def _run_framework_body(coord, workers, reap, env, fw_dir, oracle_out,
                        total_mb, n_workers, native_ok, budget,
                        fw_oracle_mbps) -> dict:
    """The measured portion of :func:`run_framework_row`, factored out so
    the caller's try/finally reaps children on ANY exit.  ``workers`` is
    the caller's (initially empty) list and is mutated in place — the
    finally must see the same list object the spawns land in."""
    deadline = time.monotonic() + 15.0
    while not os.path.exists(env["DSI_MR_SOCKET"]):
        if coord.poll() is not None or time.monotonic() > deadline:
            return reap("coordinator did not open its socket")
        time.sleep(0.05)

    # Workers run the combiner app on the native (C++ task-body) backend
    # by default — the host data plane at compiled speed, the moral
    # equivalent of the reference's compiled-Go workers; output is
    # byte-identical to wc's (parity gate below).  Chip-independent
    # either way.
    fw_backend = os.environ.get("DSI_BENCH_FRAMEWORK_BACKEND", "native")
    if fw_backend == "native" and not native_ok:
        fw_backend = "host"  # label what actually runs
    # The accelerated backends need the combiner app (it declares the
    # native/tpu task bodies); plain host runs the reference-semantics
    # wc.  Either way the final output is byte-identical (parity gate).
    fw_app = "wc" if fw_backend == "host" else "tpu_wc"
    t0 = time.perf_counter()
    workers[:] = [
        subprocess.Popen([sys.executable, "-m", "dsi_tpu.cli.mrworker",
                          "--backend", fw_backend, fw_app],
                         cwd=fw_dir, env=env, stdout=sys.stderr,
                         stderr=sys.stderr)
        for _ in range(n_workers)]
    deadline = time.monotonic() + budget
    for p in workers:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            return reap(f"worker still running after {budget:.0f}s")
    dt = time.perf_counter() - t0
    if any(p.returncode != 0 for p in workers):
        return reap("worker exited nonzero")
    try:
        coord.wait(timeout=30.0)
    except subprocess.TimeoutExpired:
        return reap("coordinator did not exit after job completion")

    fw_lines = []
    for r in range(N_REDUCE):
        try:
            with open(os.path.join(fw_dir, f"mr-out-{r}"),
                      encoding="utf-8") as f:
                fw_lines.extend(l for l in f if l.strip())
        except OSError:
            return reap(f"missing output partition mr-out-{r}")
    fw_lines.sort()
    with open(oracle_out, encoding="utf-8") as f:
        oracle_lines = sorted(l for l in f if l.strip())
    parity = fw_lines == oracle_lines
    fw_mbps = total_mb / dt
    log(f"framework row: {total_mb:.1f} MB, {n_workers} workers on "
        f"{len(os.sched_getaffinity(0))} core(s): {dt:.2f}s = "
        f"{fw_mbps:.2f} MB/s vs oracle {fw_oracle_mbps:.2f} MB/s "
        f"(parity={parity})")
    if not parity:
        return {"framework_skipped": "parity mismatch (throughput "
                                     "suppressed)",
                "framework_parity": False}
    return {"framework_mbps": round(fw_mbps, 2),
            "framework_s": round(dt, 2),
            "framework_mb": round(total_mb, 1),
            "framework_workers": n_workers,
            "framework_cores": len(os.sched_getaffinity(0)),
            "framework_backend": fw_backend,
            "framework_oracle_mbps": round(fw_oracle_mbps, 2),
            "framework_vs_oracle": round(fw_mbps / fw_oracle_mbps, 2),
            "framework_parity": True}


def global_budget_s() -> float:
    """The TPU half's wall budget (DSI_BENCH_DEADLINE_S)."""
    return env_float("DSI_BENCH_DEADLINE_S", 2100.0)


def run_tpu_watchdogged(deadline: float) -> dict:
    """Run the TPU half in a subprocess with per-attempt timeouts, bounded
    by the caller's monotonic ``deadline``; return its result dict or
    {"error": ...}."""
    try:
        timeouts = [
            float(x) for x in os.environ.get(
                "DSI_BENCH_TPU_TIMEOUTS", "1200,420,240").split(",")]
    except ValueError:
        log("ignoring malformed DSI_BENCH_TPU_TIMEOUTS")
        timeouts = [1200.0, 420.0, 240.0]
    result_path = os.path.join(WORKDIR, "tpu-result.json")
    last_err = "no attempt ran"
    for attempt, budget in enumerate(timeouts, 1):
        remaining = deadline - time.monotonic()
        if remaining < 60:
            last_err += f"; global deadline reached before attempt {attempt}"
            break
        budget = min(budget, remaining)
        for suffix in ("", ".init"):
            try:
                os.remove(result_path + suffix)
            except OSError:
                pass
        log(f"tpu attempt {attempt}/{len(timeouts)} (timeout {budget:.0f}s)")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-child",
             result_path], stdout=sys.stderr)
        timed_out = False
        # Fail fast on a wedged device claim: the child drops a marker file
        # the moment jax.devices() returns; no marker within the init budget
        # means the claim is hung and the whole attempt budget would be
        # wasted inside device init.
        init_budget = env_float("DSI_BENCH_INIT_TIMEOUT", 180.0)
        init_deadline = time.monotonic() + min(init_budget, budget)
        attempt_deadline = time.monotonic() + budget
        rc = None
        while True:
            try:
                rc = proc.wait(timeout=2.0)
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.monotonic()
            if now >= attempt_deadline or (
                    not os.path.exists(result_path + ".init")
                    and now >= init_deadline):
                if os.path.exists(result_path + ".init"):
                    # Post-init child: SIGTERM + grace so its handler can
                    # unwind the PJRT client and release the device claim
                    # (a SIGKILL mid-claim wedges the device for later
                    # processes — BASELINE.md incident log).
                    proc.terminate()
                    try:
                        rc = proc.wait(timeout=20.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        rc = proc.wait()
                else:
                    # Init-hang: the child is blocked inside the
                    # jax.devices() C call, where CPython cannot run the
                    # SIGTERM handler anyway — waiting 20 s would just burn
                    # deadline budget before the same SIGKILL.  A polling
                    # pre-init client holds no claim, so the kill is safe.
                    proc.kill()
                    rc = proc.wait()
                timed_out = True
                if not os.path.exists(result_path + ".init"):
                    log(f"attempt {attempt}: device init hung "
                        f">{min(init_budget, budget):.0f}s (wedged claim?)")
                break
        if os.path.exists(result_path):
            # Even after a timeout: the child writes its result atomically as
            # its LAST act, so a child that measured successfully but hung in
            # interpreter/JAX teardown still produced a valid verdict.
            with open(result_path) as f:
                res = json.load(f)
            if "error" not in res:
                return res
            if res.get("permanent"):
                # Deterministic failure (kernel fallback on this corpus):
                # retrying cannot change the outcome.
                return res
            last_err = f"attempt {attempt}: {res['error']}"
        elif timed_out:
            if not os.path.exists(result_path + ".init"):
                last_err = (f"attempt {attempt}: device init never completed "
                            "(wedged claim?)")
                probes = probe_tunnel_ports()
                if not any(up for _, _, up in probes):
                    # Every tunnel port is closed: further attempts cannot
                    # init either — stop burning the caller's budget (the
                    # driver's external timeout is finite) and let the CPU
                    # fallback produce the verdict sooner.
                    last_err += ("; all tunnel ports closed "
                                 f"({diagnose_tunnel(probes)})")
                    log(last_err)
                    break
            else:
                last_err = f"attempt {attempt} timed out after {budget:.0f}s"
        else:
            last_err = f"attempt {attempt} exited rc={rc} with no result"
        log(last_err)
        # Cool down only when another attempt can actually run afterwards.
        if (attempt < len(timeouts)
                and deadline - time.monotonic() >= 60 + 15):
            time.sleep(15.0)
    return {"error": last_err}


def run_cpu_fallback(deadline: float) -> dict:
    """When every TPU attempt fails (device outage), measure the SAME fused
    pipeline on the CPU backend — one bounded child with the platform
    pinned.  An explicitly-labeled cpu number with the tpu error attached
    is strictly more informative than a bare zero: it separates 'the
    framework is broken' from 'the tunnel is down'.

    The wait is bounded by the caller's remaining global budget (with a
    60 s floor so an exhausted-deadline fallback can still measure a small
    corpus), capped at the old fixed 900 s — ADVICE r3: an unconditional
    900 s here pushed worst-case wall time past the outer timeout
    onchip_evidence.sh wraps around bench.py, SIGKILLing bench before it
    printed any JSON line."""
    result_path = os.path.join(WORKDIR, "cpu-result.json")
    try:
        os.remove(result_path)
    except OSError:
        pass
    env = dict(os.environ)
    env["DSI_JAX_PLATFORM"] = "cpu"
    budget = min(900.0, max(60.0, deadline - time.monotonic()))
    log(f"tpu unavailable; measuring the same pipeline on the cpu backend "
        f"(budget {budget:.0f}s)")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--tpu-child",
         result_path], stdout=sys.stderr, env=env)
    try:
        proc.wait(timeout=budget)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    if os.path.exists(result_path):
        with open(result_path) as f:
            return json.load(f)
    return {"error": "cpu fallback produced no result"}


def probe_tunnel_ports() -> list[tuple[str, int, bool]]:
    """(name, port, open?) for each forwarded axon tunnel port."""
    import socket

    out = []
    for port, name in ((8083, "stateless"), (8082, "session"),
                       (8113, "compile")):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=3):
                out.append((name, port, True))
        except OSError:
            out.append((name, port, False))
    return out


def diagnose_tunnel(probes=None) -> str:
    """One-line state of the axon tunnel's forwarded ports, so a bench
    failure record distinguishes an infrastructure outage (ports closed /
    backend unavailable — BASELINE.md incident log) from a framework bug."""
    return "; ".join(
        f"{name}:{port} {'open' if up else 'CLOSED'}"
        for name, port, up in (probes or probe_tunnel_ports()))


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)
    from dsi_tpu.utils.corpus import ensure_corpus

    files = ensure_corpus(WORKDIR, n_files=N_FILES, file_size=FILE_SIZE)
    total_mb = sum(os.path.getsize(p) for p in files) / 1e6
    log(f"corpus: {len(files)} files, {total_mb:.1f} MB")
    prov = run_provenance()
    log(f"provenance: {prov}")

    oracle_s, oracle_mbps = run_oracle(files)
    log(f"oracle (mrsequential semantics): {oracle_s:.2f}s = "
        f"{oracle_mbps:.2f} MB/s")

    budget_s = global_budget_s()
    deadline = time.monotonic() + budget_s
    res = run_tpu_watchdogged(deadline)
    tpu_error = None
    if "error" in res and not res.get("permanent"):
        tpu_error = res["error"]
        # Honor the deadline knob here too: under 60 s is the documented
        # "disable the accelerator half" mode and must stay fast — the
        # fallback child would add minutes past the caller's budget.
        if budget_s >= 60:
            res = run_cpu_fallback(deadline)
    # The distributed N-worker row is chip-independent (host workers), so
    # it rides EVERY verdict branch — it is the number that exists even
    # when the tunnel is down.  The budget<60 escape hatch stays fast
    # unless the row is explicitly requested.
    fw = {}
    if budget_s >= 60 or "DSI_BENCH_FRAMEWORK_MB" in os.environ:
        try:
            fw = run_framework_row(oracle_mbps)
        except Exception as e:  # never trade the verdict for the row
            fw = {"framework_skipped":
                  f"framework row failed: {type(e).__name__}: {e}"}
    # The mesh-sharded A/B row is chip-independent too (virtual 8-device
    # CPU mesh in subprocesses) and rides every verdict branch.
    if budget_s >= 60 or "DSI_BENCH_MESH_SHARDS" in os.environ:
        try:
            fw.update(run_mesh_row())
        except Exception as e:
            fw["mesh_skipped"] = (f"mesh row failed: "
                                  f"{type(e).__name__}: {e}")
    else:
        # Measured-XOR-skipped holds on the fast path too.
        fw["mesh_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The serving-daemon A/B row: chip-independent (mrserve + one-shot
    # CLI subprocesses on the virtual CPU mesh), rides every branch.
    if budget_s >= 60 or "DSI_BENCH_SERVE_JOBS" in os.environ:
        try:
            fw.update(run_serve_row())
        except Exception as e:
            fw["serve_skipped"] = (f"serve row failed: "
                                   f"{type(e).__name__}: {e}")
    else:
        fw["serve_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The serving-QoS packed-grep latency A/B row (ISSUE 19):
    # chip-independent (two mrserve subprocesses on the virtual CPU
    # mesh), rides every branch.
    if budget_s >= 60 or "DSI_BENCH_SERVE_LAT_TENANTS" in os.environ:
        try:
            fw.update(run_serve_latency_row())
        except Exception as e:
            fw["serve_lat_skipped"] = (f"serve latency row failed: "
                                       f"{type(e).__name__}: {e}")
    else:
        fw["serve_lat_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The plan-layer chained-vs-staged A/B row (ISSUE 14):
    # chip-independent (planrun subprocesses on 1-device CPU under
    # DSI_AOT_FRESH=1, the stream rows' hygiene), rides every branch.
    if budget_s >= 60 or "DSI_BENCH_PLAN_MB" in os.environ:
        try:
            fw.update(run_plan_row())
        except Exception as e:
            fw["plan_skipped"] = (f"plan row failed: "
                                  f"{type(e).__name__}: {e}")
    else:
        fw["plan_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The speculative-execution backup-dispatch A/B row (ISSUE 15):
    # chip-independent (shardrun subprocess fleets on 1-device CPU),
    # rides every branch.
    if budget_s >= 60 or "DSI_BENCH_SPEC_MB" in os.environ:
        try:
            fw.update(run_spec_row())
        except Exception as e:
            fw["spec_skipped"] = (f"spec row failed: "
                                  f"{type(e).__name__}: {e}")
    else:
        fw["spec_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The network-data-plane shuffle-over-TCP A/B row (ISSUE 17):
    # chip-independent (mrrun subprocess fleets on 1-device CPU),
    # rides every branch.
    if budget_s >= 60 or "DSI_BENCH_NET_MB" in os.environ:
        try:
            fw.update(run_net_row())
        except Exception as e:
            fw["net_skipped"] = (f"net row failed: "
                                 f"{type(e).__name__}: {e}")
    else:
        fw["net_skipped"] = f"budget {budget_s:.0f}s < 60s"
    # The overlapped-shuffle pipelined-vs-serial fetch A/B row
    # (ISSUE 18): chip-independent (in-process partition servers with
    # injected serve latency), rides every branch.
    if budget_s >= 30 or "DSI_BENCH_NET_PIPE_MB" in os.environ:
        try:
            fw.update(run_net_pipeline_row())
        except Exception as e:
            fw["net_pipeline_skipped"] = (f"net pipeline row failed: "
                                          f"{type(e).__name__}: {e}")
    else:
        fw["net_pipeline_skipped"] = f"budget {budget_s:.0f}s < 30s"
    # The replicated-control-plane A/B row (ISSUE 20): chip-independent
    # (shardrun subprocess fleets on 1-device CPU, replicad coordinator
    # groups), rides every branch.
    if budget_s >= 60 or "DSI_BENCH_REPLICA_MB" in os.environ:
        try:
            fw.update(run_replica_row())
        except Exception as e:
            fw["replica_skipped"] = (f"replica row failed: "
                                     f"{type(e).__name__}: {e}")
    else:
        fw["replica_skipped"] = f"budget {budget_s:.0f}s < 60s"
    if "error" in res:
        out = {"metric": "wc_tpu_throughput", "value": 0,
               "unit": "MB/s", "vs_baseline": 0,
               "oracle_mbps": round(oracle_mbps, 2),
               "error": res["error"],
               "diagnosis": diagnose_tunnel()}
        if tpu_error:
            out["tpu_error"] = tpu_error
        out.update(fw)
        out["provenance"] = prov
        print(json.dumps(out))
        sys.exit(1)
    log(f"tpu path: {res['tpu_s']:.3f}s = {res['tpu_mbps']:.2f} MB/s  "
        f"phases={res['phases']}")
    log(f"parity (sort mr-out-* vs oracle, test-mr.sh:52-53): {res['parity']}")
    if not res["parity"]:
        out = {"metric": "wc_tpu_throughput", "value": 0,
               "unit": "MB/s", "vs_baseline": 0,
               "oracle_mbps": round(oracle_mbps, 2),
               "error": "parity mismatch",
               "platform": res.get("platform", "?")}
        if tpu_error:  # the mismatching run was the CPU fallback
            out["tpu_error"] = tpu_error
            out["diagnosis"] = diagnose_tunnel()
        out.update(fw)
        out["provenance"] = prov
        print(json.dumps(out))
        sys.exit(1)

    out = {
        "metric": "wc_tpu_throughput",
        "value": res["tpu_mbps"],
        "unit": "MB/s",
        "vs_baseline": round(res["tpu_mbps"] / oracle_mbps, 2),
        "platform": res["platform"],
        "oracle_mbps": round(oracle_mbps, 2),
        "phases": res["phases"],
    }
    # Honesty extras (VERDICT r3 task 8): the median alongside the min,
    # and the streaming-path row (or why it was skipped).
    if "median_mbps" in res:
        out["median_mbps"] = res["median_mbps"]
    if "total_mb" in res:  # lets summarize_onchip compute the wire
        out["total_mb"] = res["total_mb"]  # ceiling from the artifact

    for k in res:
        # Honesty rows measured in the child ride the verdict verbatim:
        # the stream row, the kernel-only rep row, the tfidf/grep engine
        # rows, the stream row's checkpoint/resume cost keys, and the
        # wire/ingest A/B keys (each either measured or carrying an
        # explicit skip reason).
        if k.startswith(("stream_", "kernel_", "tfidf_", "grep_",
                         "ckpt_", "resume_", "wire_", "ingest_",
                         "readahead_")):
            out[k] = res[k]
    out.update(fw)
    out["provenance"] = prov
    if tpu_error:
        # The number above was measured on the CPU FALLBACK backend: the
        # TPU half failed (tunnel outage etc.) and this run proves the
        # pipeline, not the chip.  A distinct metric name keeps it out of
        # any TPU-throughput trend; tpu_error + diagnosis say why.
        out["metric"] = "wc_cpu_fallback_throughput"
        out["tpu_error"] = tpu_error
        out["diagnosis"] = diagnose_tunnel()
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--tpu-child":
        sys.exit(tpu_child(sys.argv[2]))
    if len(sys.argv) >= 3 and sys.argv[1] == "--mesh-child":
        sys.exit(mesh_child(sys.argv[2]))
    main()
