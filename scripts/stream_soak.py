#!/usr/bin/env python
"""Streaming soak: N MB through the 8-device virtual mesh from a generator
(corpus never materialised), exact counts, bounded memory.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/stream_soak.py [--mb 512]
Prints one JSON line with wall time, peak RSS, and count verification.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=512)
    ap.add_argument("--chunk-bytes", type=int, default=1 << 20)
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    help="in-flight stream steps (default: "
                         "DSI_STREAM_PIPELINE_DEPTH or 2; 1 = synchronous)")
    ap.add_argument("--device-accumulate", action="store_true",
                    help="fold confirmed steps into the device-resident "
                         "merge table (dsi_tpu/device/); host pulls only "
                         "every --sync-every steps")
    ap.add_argument("--sync-every", type=int, default=None,
                    help="folds between host pulls with "
                         "--device-accumulate (default: "
                         "DSI_STREAM_SYNC_EVERY or 8)")
    ap.add_argument("--mesh-shards", type=int, default=None,
                    help="mesh-shard the device table across N shards "
                         "(implies --device-accumulate; default: "
                         "DSI_STREAM_MESH_SHARDS or 0 = off)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="enable crash-resume checkpoints (dsi_tpu/ckpt)")
    ap.add_argument("--checkpoint-every", type=int, default=None,
                    help="confirmed steps between checkpoints (default: "
                         "DSI_STREAM_CKPT_EVERY or 32)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the newest valid checkpoint in "
                         "--checkpoint-dir (kill the soak with "
                         "DSI_FAULT_POINT/DSI_FAULT_STEP to exercise it)")
    ap.add_argument("--ckpt-async", action="store_true", default=None,
                    dest="ckpt_async",
                    help="overlap checkpoint commits with the pipeline "
                         "(env DSI_STREAM_CKPT_ASYNC)")
    ap.add_argument("--ckpt-delta", action="store_true", default=None,
                    dest="ckpt_delta",
                    help="incremental checkpoints, full re-base every "
                         "DSI_STREAM_CKPT_REBASE saves (env "
                         "DSI_STREAM_CKPT_DELTA)")
    ap.add_argument("--wire-upload", action="store_true", default=None,
                    dest="wire_upload",
                    help="compress chunk uploads host-side and decode "
                         "on device as a map prologue "
                         "(ops/wirecodec.py; env DSI_STREAM_WIRE; "
                         "results bit-identical either way)")
    ap.add_argument("--trace-dir", default=None,
                    help="write the soak's unified trace (dsi_tpu/obs): "
                         "Perfetto trace.json + trace.jsonl; render "
                         "with scripts/tracecat.py")
    ap.add_argument("--statusz-port", type=int, default=None,
                    help="serve live telemetry on 127.0.0.1:PORT — "
                         "/statusz + /metrics (0 = pick a free port; "
                         "default off, env DSI_STATUSZ_PORT); arms the "
                         "stall watchdog and the live.jsonl ring")
    args = ap.parse_args()
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    # Before the jax import: /statusz answers during device init too.
    if args.statusz_port is not None or os.environ.get("DSI_STATUSZ_PORT"):
        from dsi_tpu.obs.live import start_from_args

        start_from_args(args.statusz_port, live_dir=args.trace_dir)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.streaming import wordcount_streaming

    total = args.mb << 20
    block = 4 << 20
    # Deterministic blocks: cycle a vocabulary so expected counts are exact
    # without holding the corpus anywhere.  Letter-only words (tokens are
    # maximal letter runs; digits would split them).
    words = ["".join(chr(97 + (i // 26 ** j) % 26) for j in range(3))
             for i in range(500)]
    line = (" ".join(words) + "\n").encode()
    n_lines = total // len(line)

    def blocks():
        buf = bytearray()
        for _ in range(n_lines):
            buf.extend(line)
            if len(buf) >= block:
                yield bytes(buf)
                buf.clear()
        if buf:
            yield bytes(buf)

    mesh = default_mesh(8)
    pstats: dict = {}
    t0 = time.perf_counter()
    acc = wordcount_streaming(blocks(), mesh=mesh, n_reduce=10,
                              chunk_bytes=args.chunk_bytes,
                              depth=args.pipeline_depth,
                              device_accumulate=args.device_accumulate,
                              sync_every=args.sync_every,
                              mesh_shards=args.mesh_shards,
                              checkpoint_dir=args.checkpoint_dir,
                              checkpoint_every=args.checkpoint_every,
                              checkpoint_async=args.ckpt_async,
                              checkpoint_delta=args.ckpt_delta,
                              resume=args.resume,
                              wire_upload=args.wire_upload,
                              pipeline_stats=pstats)
    dt = time.perf_counter() - t0
    assert acc is not None
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir)
    ok = all(acc.get(w, (0, 0))[0] == n_lines for w in words)
    peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    print(json.dumps({
        "streamed_mb": round(n_lines * len(line) / 1e6, 1),
        "wall_s": round(dt, 1),
        "mbps": round(n_lines * len(line) / 1e6 / dt, 2),
        "counts_exact": ok,
        "uniques": len(acc),
        "peak_rss_mb": round(peak_mb, 1),
        "pipeline": pstats,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
