#!/usr/bin/env python3
"""dsicheck — the repo's codebase-invariant static analysis gate.

Runs the ``dsi_tpu/analysis`` rule engine over the tree (default:
the ``dsi_tpu`` package) and exits non-zero on any unsuppressed
finding.  No jax/numpy required — safe as a bare-interpreter CI job
and during accelerator outages.

    python scripts/dsicheck.py                 # the tier-1 gate
    python scripts/dsicheck.py --json          # machine output
    python scripts/dsicheck.py --rules lock-guard,raw-write path/
    python scripts/dsicheck.py --list-rules
    python scripts/dsicheck.py --show-suppressed

Suppression: ``# dsicheck: allow[<rule>] <reason>`` on the finding's
line or the line above (``allow[all]`` for every rule).  Policy in
DESIGN.md "Static analysis": a suppression must say WHY the invariant
does not apply — the clean-tree test keeps the suppressed inventory
visible in review.
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dsi_tpu.analysis import core  # noqa: E402
from dsi_tpu.analysis.rules import all_rules  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dsicheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "dsi_tpu")],
                    help="files/dirs to scan (default: dsi_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.rule_id:<20} {r.summary}")
        return 0
    if args.rules:
        want = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = want - {r.rule_id for r in rules}
        if unknown:
            print(f"dsicheck: unknown rule(s): {sorted(unknown)} "
                  f"(--list-rules)", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in want]

    findings = core.run_project(REPO, args.paths, rules)
    if args.json:
        print(core.render_json(findings))
    else:
        print(core.render_human(findings,
                                show_suppressed=args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
