#!/usr/bin/env bash
#
# Flake hunter — port of the reference's main/test-mr-many.sh (C13): run the
# full suite N times sequentially, abort on first failure.  Unlike the
# reference (whose per-UID socket forbids parallel trials,
# test-mr-many.sh:10-11), each trial here sandboxes its own socket, so trials
# could even run concurrently; we keep them sequential for comparable load.
#
# Usage: scripts/test_mr_many.sh <trials> [app]

set -u
if [ $# -lt 1 ]; then
  echo "Usage: $0 numTrials [app]"
  exit 1
fi
TRIALS=$1
APP=${2:-wc}
HERE=$(cd "$(dirname "$0")" && pwd)

for i in $(seq 1 "$TRIALS"); do
  echo "*** trial $i of $TRIALS"
  if ! timeout -k 2s 900s "$HERE/test_mr.sh" "$APP"; then
    echo "*** FAILED on trial $i"
    exit 1
  fi
done
echo "*** PASSED all $TRIALS trials"
