#!/usr/bin/env bash
# Watch for the axon device to come back, then run the bench twice
# (cold process then warm process) to capture the AOT-cache hit evidence.
# Single-tenant device: this is the ONLY thing that may touch the chip
# while it runs.  Logs under /tmp/device_watch/.
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=/tmp/device_watch
mkdir -p "$OUT"
cd "$REPO"
echo "$(date -u +%H:%M:%S) watcher start" >> "$OUT/log"
while true; do
  # Long probe timeout on purpose: killing a JAX client mid-device-claim is
  # itself a wedge hazard (BASELINE.md), so give a recovering device 240 s
  # to finish init cleanly; only a still-hung probe gets killed.  Probes are
  # also spaced 10 min apart to minimize kill events while wedged.
  if timeout 240 python -c "
import jax
d = jax.devices()
import jax.numpy as jnp
assert int(jnp.arange(8).sum()) == 28
print('probe ok', d)
" >> "$OUT/log" 2>&1; then
    echo "$(date -u +%H:%M:%S) device back; bench run 1 (cold)" >> "$OUT/log"
    DSI_BENCH_TPU_TIMEOUTS=900,420,240 python bench.py \
      > "$OUT/bench1.out" 2> "$OUT/bench1.err"
    echo "$(date -u +%H:%M:%S) bench1 rc=$? ; run 2 (warm)" >> "$OUT/log"
    DSI_BENCH_TPU_TIMEOUTS=420,240 python bench.py \
      > "$OUT/bench2.out" 2> "$OUT/bench2.err"
    echo "$(date -u +%H:%M:%S) bench2 rc=$? ; watcher done" >> "$OUT/log"
    break
  fi
  echo "$(date -u +%H:%M:%S) device still wedged" >> "$OUT/log"
  sleep 600
done
