#!/usr/bin/env bash
# Watch for the axon terminal to come back, then run the bench twice
# (cold process then warm process) to capture the AOT-cache hit evidence.
#
# Diagnosis (2026-07-30): when the device is "wedged", the terminal's
# forwarded ports are simply closed — 8083 is the stateless port
# jax.devices() uses — so the cheap, side-effect-free recovery signal is a
# TCP connect to 8083, NOT a JAX client (a killed client mid-claim is
# itself a wedge hazard).  Only when the port answers do we start a real
# JAX probe, and then the benches.
#
# Single-tenant device: this is the ONLY thing that may touch the chip
# while it runs.  Logs under /tmp/device_watch/.
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=/tmp/device_watch
mkdir -p "$OUT"
cd "$REPO"
echo "$(date -u +%H:%M:%S) watcher start (port-probe mode)" >> "$OUT/log"
while true; do
  if timeout 3 bash -c 'echo > /dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) port 8083 open; JAX probe" >> "$OUT/log"
    if timeout 300 python -c "
import jax
d = jax.devices()
import jax.numpy as jnp
assert int(jnp.arange(8).sum()) == 28
print('probe ok', d)
" >> "$OUT/log" 2>&1; then
      echo "$(date -u +%H:%M:%S) device back; bench run 1 (cold)" >> "$OUT/log"
      DSI_BENCH_TPU_TIMEOUTS=900,420,240 python bench.py \
        > "$OUT/bench1.out" 2> "$OUT/bench1.err"
      echo "$(date -u +%H:%M:%S) bench1 rc=$? ; run 2 (warm)" >> "$OUT/log"
      DSI_BENCH_TPU_TIMEOUTS=420,240 python bench.py \
        > "$OUT/bench2.out" 2> "$OUT/bench2.err"
      echo "$(date -u +%H:%M:%S) bench2 rc=$? ; watcher done" >> "$OUT/log"
      break
    fi
    echo "$(date -u +%H:%M:%S) port open but JAX probe failed" >> "$OUT/log"
    sleep 120
  else
    sleep 60  # port probe is free; check every minute
  fi
done
