#!/usr/bin/env bash
#
# Integration test harness — the Python-framework port of the reference's
# main/test-mr.sh (C12 in SURVEY.md §2): fresh sandbox, sequential oracle,
# 1 coordinator + 3 workers under timeouts, merged-sorted output byte-compared
# against the oracle.  Where the reference builds with the Go race detector
# (test-mr.sh:10,19-22), our concurrency check is the differential comparison
# itself plus the unit tests' lock discipline (SURVEY.md §4).
#
# Usage: scripts/test_mr.sh [app] [backend]
#   app: wc (default), grep, indexer, tfidf, crash, tpu_wc, tpu_grep,
#        tpu_indexer
#   backend: host (default) or tpu (worker runs app device kernels; set
#            DSI_JAX_PLATFORM=cpu to exercise the kernels without a chip).
#            tfidf has its own tpu_map, so `test_mr.sh tfidf tpu` is the
#            device run (no separate tpu_tfidf app name).

set -u
APP=${1:-wc}
BACKEND=${2:-host}
REPO=$(cd "$(dirname "$0")/.." && pwd)
PY=${PYTHON:-python3}
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

# fresh sandbox cwd (test-mr.sh:13-16)
SANDBOX=$(mktemp -d /tmp/dsi-mr-test.XXXXXX)
trap 'rm -rf "$SANDBOX"' EXIT
cd "$SANDBOX"
export DSI_MR_SOCKET="$SANDBOX/mr.sock"

# inputs: generated corpus (reference pg-*.txt are not distributed; SURVEY §7.1)
$PY -c "from dsi_tpu.utils.corpus import ensure_corpus; ensure_corpus('inputs', n_files=6, file_size=300000)"
INPUTS=(inputs/pg-*.txt)

ORACLE_APP=$APP
case "$APP" in
  tpu_wc) ORACLE_APP=wc ;;          # byte-identical final output to wc
  tpu_indexer) ORACLE_APP=indexer ;;
  tpu_grep) ORACLE_APP=grep
            # The reference harness's own pattern (test-mr.sh:47): runs on
            # device via the class kernel (ops/regexk.py).
            export DSI_GREP_PATTERN=${DSI_GREP_PATTERN:-[Tt]he} ;;
esac
WORKER_ARGS=(--backend "$BACKEND")
EXTRA_COORD_ARGS=()
if [ "$APP" = crash ]; then
  ORACLE_APP=nocrash
  EXTRA_COORD_ARGS=(--task-timeout 2.0)
  export DSI_CRASH_EXIT_PROB=0.3 DSI_CRASH_STALL_PROB=0.15 DSI_CRASH_STALL_S=2.5
fi
if [ "$APP" = grep ]; then
  export DSI_GREP_PATTERN='[Tt]he'
fi
if [ "$APP" = tfidf ]; then
  # N (total docs) is job-level config a per-key reduce cannot derive
  # (apps/tfidf.py n_docs_from_env); the harness knows the input count.
  export DSI_TFIDF_NDOCS=${#INPUTS[@]}
fi

# ground truth via the sequential oracle (test-mr.sh:30-31)
$PY -m dsi_tpu.cli.mrsequential "$ORACLE_APP" "${INPUTS[@]}" --out mr-correct.txt || exit 1
sort mr-correct.txt | grep . > mr-correct-sorted.txt

echo "--- starting $APP test"
rm -f mr-out*
timeout -k 2s 180s $PY -m dsi_tpu.cli.mrcoordinator "${EXTRA_COORD_ARGS[@]}" "${INPUTS[@]}" &
COORD=$!
sleep 1  # socket-creation grace (test-mr.sh:39-40)

RESPAWN_ARGS=("${WORKER_ARGS[@]}")
if [ "$BACKEND" = tpu ] && [ -z "${DSI_JAX_PLATFORM:-}${JAX_PLATFORMS:-}" ]; then
  # Real-chip run: the tunneled TPU is single-tenant (two concurrent JAX
  # clients wedge the device claim — BASELINE.md), so exactly ONE worker
  # takes the device backend; the other two — and any crash-app respawn —
  # run the host path.  Both produce identical intermediates, so this
  # heterogeneous fleet is the reference's 3-worker shape
  # (test-mr.sh:43-45) with one accelerated member.
  RESPAWN_ARGS=(--backend host)
  timeout -k 2s 180s $PY -m dsi_tpu.cli.mrworker "${WORKER_ARGS[@]}" "$APP" &
  for _ in 1 2; do
    timeout -k 2s 180s $PY -m dsi_tpu.cli.mrworker --backend host "$APP" &
  done
else
  for _ in 1 2 3; do
    timeout -k 2s 180s $PY -m dsi_tpu.cli.mrworker "${WORKER_ARGS[@]}" "$APP" &
  done
fi

if [ "$APP" = crash ]; then
  # keep respawning workers while the coordinator lives (crashed ones die)
  while kill -0 $COORD 2>/dev/null; do
    N=$(jobs -rp | wc -l)
    if [ "$N" -lt 4 ]; then
      timeout -k 2s 180s $PY -m dsi_tpu.cli.mrworker "${RESPAWN_ARGS[@]}" "$APP" &
    fi
    sleep 0.5
  done
fi

wait $COORD
wait

sort mr-out* | grep . > mr-all.txt   # test-mr.sh:52
if cmp -s mr-all.txt mr-correct-sorted.txt; then
  echo "--- $APP test: PASS"
  exit 0
else
  echo "--- $APP output is not the same as the sequential oracle"
  echo "--- $APP test: FAIL"
  exit 1
fi
