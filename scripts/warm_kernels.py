#!/usr/bin/env python
"""Warm the per-task worker kernels' AOT cache entries on the real chip.

The integration harness (``scripts/test_mr.sh tpu_wc tpu``) runs workers
under the reference's 180 s process timeout (``test-mr.sh:43-45``) — a cold
XLA compile inside a task body would blow that budget.  This script compiles
and persists (``backends/aotcache.py``) every kernel shape those harness
runs touch, in ONE process, so harness workers only ever load serialized
executables:

* ``count_words_host_result`` at the harness split size (tpu_wc map task),
* ``grep_host_result`` at the same chunk shape (tpu_grep map task),
* the streaming step/pack programs bench.py's stream row executes
  (``parallel/streaming.py warm_stream_aot`` — shapes compiled from
  structs alone, nothing executed).

Run it once per machine after the corpus_wc warmer; rerun after any kernel
edit (the cache fingerprints kernel sources and would recompile anyway).
"""
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--file-size", type=int, default=300000,
                    help="harness split size (test_mr.sh ensure_corpus)")
    ap.add_argument("--phase", choices=("harness", "stream", "grep",
                                        "mesh", "wire", "plan", "all"),
                    default="all",
                    help="which program group to warm: 'harness' = the "
                         "per-task worker kernels test_mr.sh runs touch; "
                         "'stream' = the streaming step/pack programs; "
                         "'grep' = the grep/indexer stream engines + the "
                         "on-device top-k/histogram service; 'mesh' = the "
                         "mesh-sharded shuffle-fold programs (mesh_fold_*/"
                         "mesh_grow_*/mesh_hist_pull_*) for --mesh-shards "
                         "runs; 'wire' = the chunk-upload decode "
                         "prologues (wire_decode_*/wire_decode7_*, "
                         "ops/wirecodec.py) a --wire-upload run reaches; "
                         "'plan' = the chain-handoff programs a planrun "
                         "chain reaches (the grep *_em emit variants + "
                         "the plan_pack_* relay concat, ISSUE 14); "
                         "'all' = everything.  Remote compiles cost "
                         "tens of minutes EACH on the axon tunnel, so the "
                         "ladder (warm_loop.sh) warms the group it is "
                         "about to collect evidence with, not everything "
                         "up front.")
    args = ap.parse_args()

    from dsi_tpu.utils.corpus import ensure_corpus

    d = os.path.join(REPO, ".bench", "warmk")
    files = ensure_corpus(d, n_files=1, file_size=args.file_size)
    with open(files[0], "rb") as f:
        raw = f.read()

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()
    import jax

    print(f"devices={jax.devices()}", flush=True)

    from dsi_tpu.backends import aotcache

    if args.phase in ("harness", "all"):
        from dsi_tpu.ops.grepk import grep_host_result
        from dsi_tpu.ops.wordcount import count_words_host_result

        # Every grep tier gates dispatch on rung readiness
        # (grepk.device_ready); compiling is THIS script's job, so
        # bypass the gate for the whole harness-warm block via the one
        # unified knob (grepk.cold_ok — the old per-tier names remain
        # as aliases).
        os.environ["DSI_COLD_OK"] = "1"

        t0 = time.perf_counter()
        res = count_words_host_result(raw)
        assert res is not None and len(res) > 0
        print(f"wc kernel ({len(raw)} B split): "
              f"{time.perf_counter() - t0:.1f}s "
              f"{len(res)} uniques", flush=True)

        # Warm BOTH grouper variants at the harness shape (the `*_hg`
        # hash entries alongside sort): the run above compiled only the
        # platform-default rung, which on the chip left a
        # DSI_WC_GROUPER=hash run one remote cold compile away from the
        # measured ~1.8x kernel win (VERDICT r5 weak #3).
        from dsi_tpu.ops.wordcount import (_pad_pow2, rung0_cap,
                                           run_count_kernel, warm_groupers)

        chunk0 = _pad_pow2(raw)
        cap0 = rung0_cap(len(chunk0), 1 << 17)
        t0 = time.perf_counter()
        import jax.numpy as jnp

        dev_chunk = jnp.asarray(chunk0)
        for g in warm_groupers():
            out = run_count_kernel(dev_chunk, max_word_len=16, u_cap=cap0,
                                   t_cap_frac=4, grouper=g)
            assert int(out[4]) > 0  # n_unique: the kernel actually ran
        print(f"wc grouper variants (sort+hash, u_cap {cap0}): "
              f"{time.perf_counter() - t0:.1f}s", flush=True)

        t0 = time.perf_counter()
        lines = grep_host_result(raw, "the")
        assert lines is not None
        print(f"grep kernel: {time.perf_counter() - t0:.1f}s "
              f"{len(lines)} matching lines", flush=True)

        # Class-pattern grep kernel at the same shape — the tpu_grep
        # harness default pattern ([Tt]he, ops/regexk.py).
        from dsi_tpu.ops.regexk import classgrep_host_result

        t0 = time.perf_counter()
        clines = classgrep_host_result(raw, "[Tt]he")
        assert clines is not None
        print(f"classgrep kernel: {time.perf_counter() - t0:.1f}s "
              f"{len(clines)} matching lines", flush=True)

        # NFA matrix-scan grep kernel (tier 4, ops/nfak.py): the
        # compiled program is PATTERN-INDEPENDENT (the transition table
        # ships as an argument), so warming the smallest state bucket at
        # this shape serves every variable-length pattern of <= 12
        # atoms.  DSI_COLD_OK (already set above) bypasses the tier's
        # cold-compile gate — compiling here is this script's job.
        from dsi_tpu.ops.nfak import nfagrep_host_result
        # Pin past the dispatch cost model: this call exists to exercise
        # (and compile) the kernel; the calibration below then measures
        # both sides and decides real dispatch.
        os.environ["DSI_NFA_DISPATCH"] = "device"
        try:
            t0 = time.perf_counter()
            nlines = nfagrep_host_result(raw, "th+e")
            assert nlines is not None
            print(f"nfagrep kernel: {time.perf_counter() - t0:.1f}s "
                  f"{len(nlines)} matching lines", flush=True)

            # The run above warms only the first l_cap rung (the corpus's
            # average line is > 8 bytes, so no overflow).  The tier's
            # per-rung readiness gate (ADVICE r4) refuses device dispatch
            # unless EVERY rung it might escalate to is persisted — warm
            # the n+1 overflow rung too so short-line inputs stay on
            # device instead of falling back to host.
            from dsi_tpu.ops.grepk import line_cap_rungs
            from dsi_tpu.ops.nfak import _bucket, _nfa_compiled, \
                parse_nfa_pattern
            from dsi_tpu.ops.wordcount import _pad_pow2

            # Derive the state bucket from the warm pattern exactly the
            # way the tier does, so the two can never drift onto
            # different compiled shapes.
            _, n_atoms = parse_nfa_pattern("th+e")
            s_bucket = _bucket(n_atoms)
            n = len(_pad_pow2(raw))
            t0 = time.perf_counter()
            for l_cap in line_cap_rungs(n):
                _nfa_compiled(n, s_bucket, min(256, n), l_cap)
            print(f"nfagrep overflow rung: {time.perf_counter() - t0:.1f}s",
                  flush=True)

            # Calibrate the tier-4 dispatch cost model on THIS platform
            # (kernel vs host re): device dispatch is opt-in until a
            # measurement here proves it (ops/nfak.py tier4_preferred).
            from dsi_tpu.ops.nfak import calibrate_tier4

            t0 = time.perf_counter()
            entry = calibrate_tier4(s_bucket)
            print(f"nfagrep cost model s{s_bucket}: {entry} "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
        finally:
            del os.environ["DSI_NFA_DISPATCH"]
            del os.environ["DSI_COLD_OK"]

    if args.phase in ("stream", "all"):
        # Stream-row programs: bench.py runs wordcount_streaming(aot=True,
        # chunk_bytes=1<<20, u_cap=1<<14) on the single real device, and
        # onchip_evidence.sh's wcstream step pins --u-cap 16384 to the same
        # rungs — keep caps here in lockstep with BOTH.  Warm the start
        # rung plus one x4 widening (per-chunk vocabulary can cross 16384).
        from dsi_tpu.parallel.shuffle import default_mesh
        from dsi_tpu.parallel.streaming import (warm_kernel_row,
                                                warm_stream_aot)

        t0 = time.perf_counter()
        mesh = default_mesh()
        # The kernel-only bench row's NON-donated step programs at the
        # bench stream shape, both grouper variants (`*_hg` alongside
        # sort): the rep loop re-runs one program on an HBM-resident
        # chunk, so its executable differs from the pipeline's donated
        # one and must be warmed separately.
        warm_kernel_row(mesh=mesh, chunk_bytes=1 << 21, u_cap=1 << 15)
        # bench.py's stream row shape (STREAM_CHUNK_BYTES/STREAM_U_CAP):
        # 2 MiB chunks, 2^15 start capacity + one x4 widening.
        # device_accumulate also warms the fold/clear/pack programs so a
        # DSI_BENCH_STREAM_DEVICE_ACC=1 row passes the persisted gate.
        warm_stream_aot(mesh=mesh, chunk_bytes=1 << 21,
                        caps=(1 << 15, 1 << 17), device_accumulate=True)
        # wcstream --check's shape (onchip_evidence.sh pins --u-cap 16384).
        # device_accumulate warms the fold/clear/pack programs of the
        # device-resident accumulator service (dsi_tpu/device/) alongside
        # — the evidence script's --device-accumulate step must load,
        # never cold-compile, exactly like the step programs.
        warm_stream_aot(mesh=mesh, chunk_bytes=1 << 20,
                        caps=(1 << 14, 1 << 16), device_accumulate=True)
        # The GB-scale on-chip stream (onchip_evidence.sh step 9) uses
        # 4 MiB chunks so per-step wire latency amortizes over 4x the
        # bytes.  Warm one rung past the corpus's measured worst chunk
        # (~64.3k uniques vs the 65,536 rung — 1.8% headroom, and file
        # ordering can shift it): a widening retry on the chip must load,
        # never cold-compile.
        warm_stream_aot(mesh=mesh, chunk_bytes=1 << 22,
                        caps=(1 << 14, 1 << 16, 1 << 18))
        print(f"stream programs: {time.perf_counter() - t0:.1f}s",
              flush=True)

    if args.phase in ("grep", "all"):
        # Grep/indexer stream engines + the on-device top-k/histogram
        # service (parallel/grepstream.py, device/topk.py).  Two grep
        # shapes, both in lockstep with their consumers:
        #   * 1 MiB chunks — onchip_evidence.sh's grepstream --check
        #     step (CLI default --chunk-bytes),
        #   * GREP_CHUNK_BYTES (2 MiB) — bench.py's DSI_BENCH_GREP_MB
        #     row.
        # Both warm BOTH l_cap rungs (the optimistic and the n+1 replay
        # shape: a sticky-rung escalation on the chip must load, never
        # cold-compile) and the device-accumulate fold/snapshot
        # programs.  Pattern length 3 = the evidence/bench default
        # literal ("the"); other lengths are distinct compiled shapes —
        # rerun with your pattern before soaking a different literal.
        from dsi_tpu.parallel.grepstream import (GREP_CHUNK_BYTES,
                                                 warm_grepstream_aot,
                                                 warm_indexer_aot)
        from dsi_tpu.parallel.shuffle import default_mesh

        t0 = time.perf_counter()
        mesh = default_mesh()
        warm_grepstream_aot(mesh=mesh, chunk_bytes=1 << 20,
                            device_accumulate=True)
        warm_grepstream_aot(mesh=mesh, chunk_bytes=GREP_CHUNK_BYTES,
                            device_accumulate=True)
        # Indexer posting-wave shapes at the harness document scale (one
        # 256 KiB wave rung, both groupers) plus the df top-k folds.
        warm_indexer_aot(mesh=mesh, sizes=(1 << 18,), caps=(1 << 14,),
                         device_accumulate=True)
        print(f"grep/indexer programs: {time.perf_counter() - t0:.1f}s",
              flush=True)

    if args.phase in ("wire", "all"):
        # Chunk-upload decode prologues (ISSUE 13, ops/wirecodec.py):
        # every rung — nibble literal ladder + the 7-bit ASCII
        # fallback — at both the CLI default (1 MiB) and bench stream
        # (2 MiB) chunk shapes, so a --wire-upload/DSI_STREAM_WIRE run
        # on the chip loads serialized decoders instead of paying a
        # remote cold compile per rung the codec happens to pick.
        from dsi_tpu.ops.wirecodec import warm_wire_aot
        from dsi_tpu.parallel.shuffle import default_mesh

        t0 = time.perf_counter()
        mesh = default_mesh()
        warm_wire_aot(mesh=mesh, chunk_bytes=1 << 20)
        warm_wire_aot(mesh=mesh, chunk_bytes=1 << 21)
        print(f"wire decode programs: {time.perf_counter() - t0:.1f}s",
              flush=True)

    if args.phase in ("plan", "all"):
        # Plan-layer chain handoff (ISSUE 14): the grep emit variants
        # (*_em — both l_cap rungs at the planrun default chunk shape)
        # plus the relay's plan_pack_* concat program, so a chained
        # planrun on the chip loads instead of cold-compiling.  The
        # wordcount stage's NON-donated step programs compile per run's
        # sticky rung (the kernel row already persists the non-donated
        # 2 MiB shape; other shapes compile on first chain).
        from dsi_tpu.parallel.grepstream import warm_grepstream_aot
        from dsi_tpu.parallel.shuffle import default_mesh

        t0 = time.perf_counter()
        mesh = default_mesh()
        warm_grepstream_aot(mesh=mesh, chunk_bytes=1 << 20,
                            device_accumulate=True, emit=True)
        print(f"plan chain programs: {time.perf_counter() - t0:.1f}s",
              flush=True)

    if args.phase in ("mesh", "all"):
        # Mesh-sharded device services (ISSUE 7): the shuffle-fold
        # programs a --mesh-shards run reaches — the mesh_fold_* fold
        # with the in-program all-to-all at the stream/CLI step shapes
        # (rung 0 + one ×4 widening, with the mesh_grow_* per-shard
        # reallocation between them), the grep candidate fold + the
        # pre-merged mesh_hist_pull_*, and the step/pack programs they
        # ride (warmed by the stream/grep phases; re-warmed here so
        # --phase mesh alone is sufficient before a mesh soak).  The
        # shard degree warms at the full local mesh width — the only
        # degree a run on this machine can use end to end.
        from dsi_tpu.parallel.grepstream import warm_grepstream_aot
        from dsi_tpu.parallel.shuffle import default_mesh
        from dsi_tpu.parallel.streaming import warm_stream_aot

        t0 = time.perf_counter()
        mesh = default_mesh()
        shards = mesh.devices.size
        warm_stream_aot(mesh=mesh, chunk_bytes=1 << 20,
                        caps=(1 << 14, 1 << 16), device_accumulate=True,
                        mesh_shards=shards)
        warm_grepstream_aot(mesh=mesh, chunk_bytes=1 << 20,
                            device_accumulate=True, mesh_shards=shards)
        print(f"mesh-sharded programs (shards={shards}): "
              f"{time.perf_counter() - t0:.1f}s", flush=True)

    print(f"aot stats: {aotcache.stats}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
