#!/usr/bin/env bash
# Retry the AOT-cache warmer until the device claim clears, then stop.
#
# Each attempt is bench.py's --tpu-child run to completion (never killed —
# a SIGKILLed client mid-claim is itself a wedge hazard, BASELINE.md).  A
# failed init exits cleanly with an error verdict; we sleep and retry.
# Success = warm-result.json with no "error" key, meaning both corpus_wc
# executables are compiled AND persisted in .aotcache for every later
# process (driver bench runs included).
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
OUT=${1:-/tmp/warm_loop}
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${2:-7200} ))
n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  echo "$(date -u +%H:%M:%S) attempt $n" >> "$OUT/log"
  # Stale results must not masquerade as this attempt's verdict.
  rm -f "$REPO/.bench/warm-result.json" "$REPO/.bench/warm-result.json.init"
  # Bounded attempt, two layers: the child's own init watchdog
  # (DSI_CHILD_INIT_TIMEOUT) converts a wedged-claim init hang into a
  # clean error verdict in 4 min — so during an outage the loop cycles
  # quickly — while the outer timeout only backstops a post-init hang;
  # 3600 s covers any plausible cold compile, and TERM (not KILL) lets
  # the child's handler unwind the claim cleanly.
  # WARM_ALL: the warm child's whole job is compiling BOTH transports
  # into the persistent cache (a plain bench skips a non-cached pack6 to
  # protect its budget — this is the one process that must not skip it).
  DSI_BENCH_WARM_ALL=1 DSI_CHILD_INIT_TIMEOUT=240 timeout -k 30s 3600s \
    python -u bench.py \
    --tpu-child "$REPO/.bench/warm-result.json" >> "$OUT/attempt.log" 2>&1
  if [ -f "$REPO/.bench/warm-result.json" ] && \
     ! grep -q '"error"' "$REPO/.bench/warm-result.json"; then
    echo "$(date -u +%H:%M:%S) corpus_wc warm after $n attempts" >> "$OUT/log"
    # Also warm the per-task worker kernels the on-chip harness runs use
    # (tpu_wc / tpu_grep map shapes; see scripts/warm_kernels.py).
    # 7200 s: round 4 widened the warm set to ~17 programs (worker
    # kernels + both grep tiers + stream shapes at 1 MiB and 4 MiB
    # chunks); remote axon compiles can run minutes each.
    if timeout -k 30s 7200s python scripts/warm_kernels.py \
        >> "$OUT/kernels.log" 2>&1; then
      echo "$(date -u +%H:%M:%S) worker kernels warm" >> "$OUT/log"
      # Chain into the round's on-chip evidence collection (two bench
      # runs + on-chip harness runs) ONLY with a fully warm cache: a
      # cold-compile worker under the harness's 180 s timeout would be
      # SIGKILLed mid-claim — the wedge hazard again.  Per-run stamped
      # dir so a later round can't overwrite this round's evidence.
      EV="/tmp/onchip/$(date -u +%m%dT%H%M%S)"
      bash scripts/onchip_evidence.sh "$EV" >> "$OUT/log" 2>&1
      echo "$(date -u +%H:%M:%S) onchip evidence done (see $EV)" >> "$OUT/log"
    else
      echo "$(date -u +%H:%M:%S) warm_kernels FAILED (see kernels.log);" \
           "skipping on-chip evidence chain" >> "$OUT/log"
    fi
    exit 0
  fi
  tail -c 300 "$REPO/.bench/warm-result.json" >> "$OUT/log" 2>/dev/null
  echo >> "$OUT/log"
  sleep 120
done
echo "$(date -u +%H:%M:%S) gave up (deadline)" >> "$OUT/log"
exit 1
