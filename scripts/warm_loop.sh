#!/usr/bin/env bash
# Retry the AOT-cache warmer until the device claim clears, then stop.
#
# Each attempt is bench.py's --tpu-child run to completion (never killed —
# a SIGKILLed client mid-claim is itself a wedge hazard, BASELINE.md).  A
# failed init exits cleanly with an error verdict; we sleep and retry.
# Success = warm-result.json with no "error" key, meaning both corpus_wc
# executables are compiled AND persisted in .aotcache for every later
# process (driver bench runs included).
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
OUT=${1:-/tmp/warm_loop}
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + ${2:-7200} ))
n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  echo "$(date -u +%H:%M:%S) attempt $n" >> "$OUT/log"
  DSI_BENCH_REPS=1 python bench.py --tpu-child "$REPO/.bench/warm-result.json" \
    >> "$OUT/attempt.log" 2>&1
  if [ -f "$REPO/.bench/warm-result.json" ] && \
     ! grep -q '"error"' "$REPO/.bench/warm-result.json"; then
    echo "$(date -u +%H:%M:%S) SUCCESS after $n attempts" >> "$OUT/log"
    exit 0
  fi
  tail -c 300 "$REPO/.bench/warm-result.json" >> "$OUT/log" 2>/dev/null
  echo >> "$OUT/log"
  sleep 120
done
echo "$(date -u +%H:%M:%S) gave up (deadline)" >> "$OUT/log"
exit 1
