#!/usr/bin/env bash
# Value-ordered warm + evidence LADDER for the axon tunnel.
#
# Remote compiles cost tens of minutes EACH (outage #3 died inside ONE
# 28-minute compile), and recovery windows have lasted ~30 minutes — so
# the earlier "warm all ~19 programs, then collect evidence" sequencing
# could starve forever.  This ladder interleaves: each phase warms only
# the programs its evidence needs, then captures that evidence
# immediately.  Completed steps are marker-gated ($EV/done/<step>), so
# any restart resumes at the first missing artifact; warm steps are
# idempotent-cheap once their executables are in the AOT cache.
#
# SMALL COMPILES FIRST (reordered after outage #4): the monolithic
# corpus-program compile has now died mid-RPC at 28 min (outage #3) and
# 54 min (outage #4) — longer than every observed recovery window —
# while the harness/stream programs are many SMALL compiles that
# persist one by one, so progress accumulates across windows.  The
# ladder therefore banks the incremental evidence (harness apps,
# streaming, the 1 GB run) before gambling a window on the big compile.
#
#   P0  wire-state probe (probe_tunnel.py) — cheap, records the window
#   B1  warm the harness worker kernels (warm_kernels --phase harness)
#   B2-B7  full-framework harness on-chip: tpu_wc, tpu_grep (class),
#          tpu_grep (literal), tpu_indexer, tfidf, tpu_grep (tier-4
#          variable-length NFA pattern)
#   S1  warm the streaming step/pack programs (warm_kernels --phase stream)
#   C3  wcstream --check on the chip     C4  wcstream ~1 GB + invariant
#   A1  warm the raw corpus program   (bench --tpu-child, TRANSPORT=raw)
#   A2  bench A: fresh process, raw-only, no stream row — the headline
#       number + the AOT-hit proof (compile_s≈0, aot_loads≥1)
#   A3  bench B: repeatability sample
#   C1  warm pack6 corpus program (stream warm already banked by S1)
#   C2  bench C: full run — transport probe + stream row
#
# Evidence lands in $EV with onchip_evidence.sh-compatible filenames so
# scripts/summarize_onchip.py reads it unchanged.  Single-tenant: steps
# run strictly sequentially; nothing else may touch the chip.
#
# Usage: warm_loop.sh [OUT=/tmp/warm_loop] [BUDGET_S=14400] [EV=/tmp/onchip/ladder]
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
OUT=${1:-/tmp/warm_loop}
DEADLINE=$(( $(date +%s) + ${2:-14400} ))
EV=${3:-/tmp/onchip/ladder}
# Resume-vs-isolation: an INCOMPLETE ladder must resume in place (the
# markers are the whole point), but a COMPLETED one must not be silently
# "re-run" as an instant exit-0, nor overwritten — archive it and start
# fresh (fresh evidence against a warm cache is cheap and useful).
if [ -f "$EV/done/C2" ]; then
  mv "$EV" "$EV-$(date -u +%m%dT%H%M%S)"
fi
mkdir -p "$OUT" "$EV/done"

log() { echo "$(date -u +%H:%M:%S) $*" >> "$OUT/log"; }
left() { echo $(( DEADLINE - $(date +%s) )); }

# Fail a chip step in ~2 s while the tunnel is down instead of letting a
# JAX client hang in PJRT init polling for the step's full timeout: a
# client that entered the poll during an outage completes init the
# moment the terminal returns — and then the step timeout SIGKILLs it
# WITH a live device claim (the wedge that cost the 01:05 window).
# Port 8083 is the stateless port jax.devices() uses; probing it is
# side-effect-free.
tunnel_up() { timeout 2 bash -c 'echo > /dev/tcp/127.0.0.1/8083' 2>/dev/null; }

# A stale ambient platform pin would silently turn every step below into
# a host run with green-looking logs; a leaked DSI_GREP_PATTERN would
# demote the class-pattern grep run to the literal kernel.
log "ladder start; ambient pins: JAX_PLATFORMS='${JAX_PLATFORMS:-}' DSI_JAX_PLATFORM='${DSI_JAX_PLATFORM:-}' DSI_GREP_PATTERN='${DSI_GREP_PATTERN:-}'"
unset JAX_PLATFORMS DSI_JAX_PLATFORM DSI_GREP_PATTERN

bench_ok() {  # $1 = json path: a SUCCESSFUL TPU verdict, not an error,
              # fallback, or parity failure.  bench.py emits permanent
              # errors and parity mismatches as metric=wc_tpu_throughput
              # with value=0 and an "error" key but NO "tpu_error", so
              # both keys must be absent (mirrors summarize_onchip.py's
              # _valid_tpu_verdict).
  grep -q '"metric": "wc_tpu_throughput"' "$1" 2>/dev/null && \
  ! grep -q '"tpu_error"' "$1" && \
  ! grep -q '"error"' "$1"
}

step_A1() {
  rm -f "$REPO/.bench/warm-result.json" "$REPO/.bench/warm-result.json.init"
  # TERM (not KILL) on timeout lets a post-init child unwind its claim;
  # the child's own init watchdog turns an outage into a clean error
  # verdict in 4 min, so closed-port periods cycle fast.
  DSI_BENCH_TRANSPORT=raw DSI_BENCH_STREAM_MB=0 DSI_CHILD_INIT_TIMEOUT=240 \
    timeout -k 30s 3600s python -u bench.py \
    --tpu-child "$REPO/.bench/warm-result.json" >> "$OUT/attempt.log" 2>&1
  [ -f "$REPO/.bench/warm-result.json" ] && \
    ! grep -q '"error"' "$REPO/.bench/warm-result.json"
}

# A2/A3 pin the framework row off: it is chip-free (host workers) and
# would spend ~90 s of an open tunnel window not touching the chip — the
# full C2 verdict carries it instead, and outage-time benches (the
# driver's, during closed-port periods) measure it by default.
step_A2() {
  DSI_BENCH_STREAM_MB=0 DSI_BENCH_FRAMEWORK_MB=0 DSI_CHILD_INIT_TIMEOUT=150 \
    timeout -k 30s 2700s \
    python bench.py > "$EV/benchA.json" 2> "$EV/benchA.err"
  bench_ok "$EV/benchA.json"
}

step_A3() {
  DSI_BENCH_STREAM_MB=0 DSI_BENCH_FRAMEWORK_MB=0 DSI_CHILD_INIT_TIMEOUT=150 \
    timeout -k 30s 2700s \
    python bench.py > "$EV/benchB.json" 2> "$EV/benchB.err"
  bench_ok "$EV/benchB.json"
}

step_P0() {
  timeout -k 30s 900s python scripts/probe_tunnel.py --mb 8 \
    > "$EV/probe_tunnel.log" 2>&1
}

step_B1() {
  timeout -k 30s 7200s python scripts/warm_kernels.py --phase harness \
    >> "$OUT/kernels.log" 2>&1
}

step_S1() {
  timeout -k 30s 7200s python scripts/warm_kernels.py --phase stream \
    >> "$OUT/kernels.log" 2>&1
}

harness() {  # $1 = app, $2 = log name, [$3 = DSI_GREP_PATTERN]
  if [ -n "${3:-}" ]; then
    { time DSI_GREP_PATTERN="$3" bash scripts/test_mr.sh "$1" tpu ; } \
      > "$EV/$2" 2>&1
  else
    { time bash scripts/test_mr.sh "$1" tpu ; } > "$EV/$2" 2>&1
  fi
  grep -q "PASS" "$EV/$2"
}

step_B2() { harness tpu_wc harness_tpu_wc.log; }
step_B3() { harness tpu_grep harness_tpu_grep.log; }
step_B4() { harness tpu_grep harness_tpu_grep_literal.log the; }
step_B5() { harness tpu_indexer harness_tpu_indexer.log; }
step_B6() { harness tfidf harness_tfidf.log; }
# Tier-4 variable-length grep on-chip: B1 warmed the pattern-independent
# NFA program, so any eligible pattern at the harness shape loads warm.
step_B7() { harness tpu_grep harness_tpu_grep_nfa.log 'qu+ick|dogs?$'; }

step_C1() {
  rm -f "$REPO/.bench/warm-result.json" "$REPO/.bench/warm-result.json.init"
  # WARM_ALL compiles the pack6 program (raw loads from cache in ms).
  DSI_BENCH_WARM_ALL=1 DSI_BENCH_STREAM_MB=0 DSI_CHILD_INIT_TIMEOUT=240 \
    timeout -k 30s 3600s python -u bench.py \
    --tpu-child "$REPO/.bench/warm-result.json" >> "$OUT/attempt.log" 2>&1
  [ -f "$REPO/.bench/warm-result.json" ] && \
    ! grep -q '"error"' "$REPO/.bench/warm-result.json"
}

step_C2() {
  DSI_CHILD_INIT_TIMEOUT=150 timeout -k 30s 2700s \
    python bench.py > "$EV/benchC.json" 2> "$EV/benchC.err"
  # This step exists for the FULL verdict: a skipped or parity-failed
  # stream row must not be marked done (the headline alone is bench A/B).
  bench_ok "$EV/benchC.json" && \
  ! grep -q '"stream_skipped"' "$EV/benchC.json" && \
  grep -q '"stream_parity": true' "$EV/benchC.json"
}

step_C3() {
  python -c "from dsi_tpu.utils.corpus import ensure_corpus; \
             print(ensure_corpus('$EV/corpus', n_files=4))" \
    > "$EV/corpus.log" 2>&1 || return 1
  mkdir -p "$EV/wcstream-wd"
  # --u-cap 16384 + --aot in lockstep with warm_kernels' stream rungs.
  timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --check --devices 1 \
    --aot --u-cap 16384 \
    --workdir "$EV/wcstream-wd" "$EV"/corpus/pg-*.txt \
    > "$EV/wcstream.log" 2>&1
}

step_C4() {
  python -c "from dsi_tpu.utils.corpus import ensure_corpus; \
             ensure_corpus('$EV/corpus-1g', n_files=1024, file_size=1048576)" \
    > "$EV/corpus-1g.log" 2>&1 || return 1
  mkdir -p "$EV/wcstream-1g-wd"
  rm -f "$EV/wcstream-1g-wd"/mr-out-*
  { time timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --devices 1 \
      --aot --u-cap 16384 --chunk-bytes 4194304 \
      --workdir "$EV/wcstream-1g-wd" "$EV"/corpus-1g/pg-*.txt ; } \
    > "$EV/wcstream-1g.log" 2>&1 || return 1
  # Total-token invariant: one-pass host count catches gross miscounts.
  python scripts/token_invariant.py "$EV/corpus-1g" "$EV/wcstream-1g-wd" \
    >> "$EV/wcstream-1g.log" 2>&1
}

STEPS="P0 B1 B2 B3 B4 B5 B6 B7 S1 C3 C4 A1 A2 A3 C1 C2"
while [ "$(left)" -gt 120 ]; do
  progressed=0
  for s in $STEPS; do
    [ -f "$EV/done/$s" ] && continue
    if ! tunnel_up; then
      log "step $s skipped: tunnel down (8083 closed); backing off 120s"
      sleep 120
      break
    fi
    log "step $s start (budget left $(left)s)"
    if "step_$s"; then
      touch "$EV/done/$s"
      log "step $s DONE"
      progressed=1
    else
      log "step $s failed; backing off 120s"
      sleep 120
      break
    fi
  done
  if [ -f "$EV/done/C2" ]; then
    log "ladder COMPLETE (evidence in $EV)"
    exit 0
  fi
  # A full pass with zero progress and no failure cannot happen (the
  # first missing step either succeeds or fails), but guard anyway.
  [ "$progressed" = 0 ] && sleep 60
done
log "deadline reached; done so far: $(ls "$EV/done" 2>/dev/null | tr '\n' ' ')"
exit 1
