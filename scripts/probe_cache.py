#!/usr/bin/env python
"""Probe: why doesn't the persistent compile cache hit on the axon platform?

Checks, on the real device:
  1. backend.platform and supports_executable_serialization — the two gates
     in jax._src.compilation_cache.is_cache_used (site-packages line 84-91).
  2. whether a trivial jit writes a cache entry (with and without forcing
     _cache_used).
  3. whether PJRT executable serialization round-trips
     (jax.experimental.serialize_executable) — our fallback cache mechanism.
Everything prints to stdout; safe to rerun.
"""
import os, sys, time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jaxcache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax
import jax.numpy as jnp

t0 = time.perf_counter()
devs = jax.devices()
print(f"devices={devs} init={time.perf_counter()-t0:.1f}s", flush=True)
from jax._src import xla_bridge
backend = xla_bridge.get_backend()
print("backend.platform =", repr(backend.platform))
print("platform_version =", getattr(backend, "platform_version", "?"))
print("supports_executable_serialization =",
      getattr(backend, "supports_executable_serialization", "<absent->True>"))

import jax._src.compilation_cache as cc
print("is_cache_used(backend) =", cc.is_cache_used(backend))

cachedir = os.environ["JAX_COMPILATION_CACHE_DIR"]
before = set(os.listdir(cachedir)) if os.path.isdir(cachedir) else set()

@jax.jit
def probe_fn(x):
    return (x * 2 + 1).sum()

x = jnp.arange(4096, dtype=jnp.float32)
t0 = time.perf_counter()
probe_fn(x).block_until_ready()
print(f"tiny jit first call: {time.perf_counter()-t0:.2f}s", flush=True)
after = set(os.listdir(cachedir)) if os.path.isdir(cachedir) else set()
print("new cache entries:", sorted(after - before))

# Fallback path: AOT serialize/deserialize of a compiled executable.
try:
    from jax.experimental.serialize_executable import (
        serialize, deserialize_and_load)
    lowered = jax.jit(lambda x: (x + 3).sum()).lower(x)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    print(f"aot compile: {time.perf_counter()-t0:.2f}s", flush=True)
    t0 = time.perf_counter()
    payload, in_tree, out_tree = serialize(compiled)
    print(f"serialize ok: {len(payload)} bytes in {time.perf_counter()-t0:.2f}s",
          flush=True)
    t0 = time.perf_counter()
    loaded = deserialize_and_load(payload, in_tree, out_tree)
    print(f"deserialize ok in {time.perf_counter()-t0:.2f}s", flush=True)
    out = loaded(x)
    print("roundtrip exec ok:", out)
except Exception as e:
    import traceback; traceback.print_exc()
    print("AOT serialization FAILED:", type(e).__name__, e)

# Forced-cache path: pretend the platform is supported and see if entries
# read/write (exercises put/get_executable_and_time under axon).
cc._cache_checked, cc._cache_used = True, True
@jax.jit
def probe_fn2(x):
    return (x * 3 - 1).sum()
t0 = time.perf_counter()
probe_fn2(x).block_until_ready()
print(f"forced-cache jit first call: {time.perf_counter()-t0:.2f}s", flush=True)
after2 = set(os.listdir(cachedir)) if os.path.isdir(cachedir) else set()
print("new cache entries after force:", sorted(after2 - after))
