#!/usr/bin/env python
"""Serving-QoS soak (ISSUE 19): N tenants of mixed wc/grep jobs with a
priority mix hammer one in-process daemon through sustained
submit/shed/evict/resume churn, and every accepted job must finish
with byte parity against the host oracle.

The daemon is deliberately under-provisioned — a small admission queue
(shedding MUST engage), a small resident set and step quota (eviction
churn), tiny chunks (many steps per tenant) — because the soak's
contract is QoS under pressure, not throughput:

* zero lost jobs: every ACCEPTED submission reaches ``done`` (shed
  submissions retry through the typed-backpressure client loop until
  accepted);
* shedding engaged: the daemon's shed counter ends >= 1;
* per-tenant byte parity: wc outputs compare equal to the sequential
  oracle, grep outputs byte-compare equal to the ``grep_host_oracle``
  payload — including hostpath (non-literal pattern) tenants;
* bounded telemetry: the ``dsi_serve_*`` metrics text stays capped by
  ``metrics_tenants``, independent of N.

Usage: python scripts/serve_soak.py [--tenants 64] [--timeout S]
Prints one JSON summary line; rc 0 only when every assertion holds.
CI runs ``--tenants 64`` as a smoke; the ``slow``-marked pytest soak
runs ``run_soak(1000)`` in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_corpus(path: str, tag: str, i: int, grep_pat: str = None) -> None:
    """~4 KB of small lines; grep tenants get their pattern planted on
    a deterministic subset of lines with varying occurrence counts."""
    lines = []
    j, size = 0, 0
    while size < 4096:
        if grep_pat is not None and j % 3 != 2:
            line = (grep_pat + " ") * (j % 4) + f"x{(i * 31 + j) % 211}\n"
        else:
            line = f"{tag}w{(i * 31 + j) % 223:03d} t{j % 17}\n"
        lines.append(line)
        size += len(line)
        j += 1
    with open(path, "w") as f:
        f.writelines(lines)


def _wc_oracle(files) -> list:
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.sequential import run_sequential

    out = files[0] + ".oracle"
    run_sequential(wc.Map, wc.Reduce, files, out)
    with open(out, encoding="utf-8") as f:
        return sorted(l for l in f if l.strip())


def _wc_got(out_dir: str, n_reduce: int = 10) -> list:
    got = []
    for r in range(n_reduce):
        with open(os.path.join(out_dir, f"mr-out-{r}"),
                  encoding="utf-8") as f:
            got.extend(l for l in f if l.strip())
    return sorted(got)


def _grep_oracle_bytes(path: str, pattern: str) -> bytes:
    """The daemon's ``grep.json`` ground truth: ``grep_host_oracle``
    serialized exactly as ``ServeDaemon._write_grep_result`` spells
    it."""
    from dsi_tpu.parallel.grepstream import grep_host_oracle

    with open(path, "rb") as f:
        r = grep_host_oracle([f.read()], pattern)
    return json.dumps(
        {"lines": r.lines, "matched": r.matched,
         "occurrences": r.occurrences, "hist": list(r.hist),
         "topk": [list(t) for t in r.topk]},
        sort_keys=True).encode("utf-8")


def run_soak(tenants: int, *, timeout_s: float = None,
             workdir: str = None, submit_threads: int = 16) -> dict:
    """The soak body (importable: the slow pytest soak calls it with
    1000).  Returns the JSON summary; raises AssertionError on any
    contract violation."""
    from dsi_tpu.serve import client
    from dsi_tpu.serve.daemon import ServeDaemon

    if timeout_s is None:
        timeout_s = max(240.0, 1.2 * tenants)
    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="dsi-soak-")
    spool = os.path.join(workdir, "spool")
    sock = os.path.join(tempfile.mkdtemp(prefix="dsi-soak-sv-"), "s.sock")

    # The tenant mix: 1/2 wc, ~1/2 literal grep (two pattern lengths so
    # the packer runs >1 shape group), every 16th grep NON-literal (the
    # hostpath arm must survive the same churn).
    plan = []  # (tenant, app, pattern, path)
    for i in range(tenants):
        t = f"s{i}"
        path = os.path.join(workdir, f"{t}.txt")
        if i % 2 == 0:
            _mk_corpus(path, t, i)
            plan.append((t, "wc", None, path))
        else:
            if i % 16 == 15:
                pat = "q.*z"          # regex meta: forced host path
                _mk_corpus(path, t, i, grep_pat="qaz")
            else:
                pat = (f"q{i:03d}" if i % 4 == 1 else f"pp{i:04d}")
                _mk_corpus(path, t, i, grep_pat=pat)
            plan.append((t, "grep", pat, path))

    d = ServeDaemon(
        spool, socket_path=sock, warm=False,
        chunk_bytes=1 << 10,            # many steps per tenant
        max_resident=8, quota_steps=2,  # evict/resume churn
        checkpoint_every=2,
        max_queue=max(4, tenants // 16))  # shedding MUST engage
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    accepted = {}  # tenant -> submit reply
    errors = []
    lock = threading.Lock()

    def submitter(k: int) -> None:
        for idx in range(k, len(plan), submit_threads):
            t, app, pat, path = plan[idx]
            while True:
                try:
                    rep = client.submit(sock, t, [path], app=app,
                                        pattern=pat, priority=idx % 3,
                                        retries=4, max_backoff_s=0.5)
                    with lock:
                        accepted[t] = rep
                    break
                except client.ServeBusy:
                    if time.monotonic() > deadline:
                        with lock:
                            errors.append(f"{t}: shed past deadline")
                        return
                except Exception as e:  # noqa: BLE001 — soak reports
                    with lock:
                        errors.append(f"{t}: {type(e).__name__}: {e}")
                    return

    try:
        d.start()
        client.wait_ready(sock, timeout=min(timeout_s, 180.0))
        threads = [threading.Thread(target=submitter, args=(k,),
                                    daemon=True)
                   for k in range(submit_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout_s)
        assert not errors, errors[:5]
        assert len(accepted) == tenants, \
            f"only {len(accepted)}/{tenants} accepted"

        # One full-list RPC per poll (a per-job poll is N RPCs a tick).
        jids = {rep["job_id"] for rep in accepted.values()}
        while True:
            jobs = client.status(sock)["jobs"]
            states = {j["job_id"]: j["state"] for j in jobs
                      if j["job_id"] in jids}
            if all(s in ("done", "failed") for s in states.values()):
                break
            assert time.monotonic() < deadline, \
                f"not drained in {timeout_s}s: " \
                f"{sum(1 for s in states.values() if s not in ('done', 'failed'))} left"
            time.sleep(0.5)
        failed = [j for j, s in states.items() if s != "done"]
        assert not failed, f"lost/failed jobs: {failed[:5]}"

        # Per-tenant byte parity, every app, every arm.
        for t, app, pat, path in plan:
            rep = accepted[t]
            if app == "wc":
                assert _wc_got(rep["out_dir"]) == _wc_oracle([path]), \
                    f"{t}: wc parity"
            else:
                with open(os.path.join(rep["out_dir"], "grep.json"),
                          "rb") as f:
                    assert f.read() == _grep_oracle_bytes(path, pat), \
                        f"{t}: grep parity"

        ping = client.ping(sock)
        tstats = client.status(sock)["tenants"]
        metrics = d._metrics_section()
        mlines = len(metrics.splitlines())
        # Bounded telemetry: the per-tenant series are capped at
        # metrics_tenants regardless of N (7 per-tenant series + the
        # global block).
        bound = 7 * d.metrics_tenants + 60
        assert mlines <= bound, f"metrics unbounded: {mlines} > {bound}"
        assert ping["shed"] >= 1, "shedding never engaged"
        summary = {
            "tenants": tenants,
            "wall_s": round(time.monotonic() - t0, 2),
            "shed": ping["shed"],
            "rate_limited": ping["rate_limited"],
            "evictions": sum(s["evictions"] for s in tstats.values()),
            "resumes": sum(s["resumes"] for s in tstats.values()),
            "hostpath": sum(s["hostpath"] for s in tstats.values()),
            "packed_steps": d.packer.stats["packed_steps"],
            "grep_packed_steps":
                d.grep_packer.stats["packed_steps"] if d.grep_packer
                else 0,
            "metrics_lines": mlines,
            "parity": True,
        }
        assert summary["evictions"] >= 1 and summary["resumes"] >= 1, \
            summary
        assert summary["grep_packed_steps"] >= 1, summary
        return summary
    finally:
        d.close()
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=64)
    ap.add_argument("--timeout", type=float, default=None)
    args = ap.parse_args(argv)
    # The virtual mesh, unless the caller pinned a real one.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = \
            (os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    summary = run_soak(args.tenants, timeout_s=args.timeout)
    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
