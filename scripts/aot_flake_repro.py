#!/usr/bin/env python3
"""Reproducer harness for the persisted-AOT heap-corruption flake.

The flake (CHANGES.md PR 8, OPERATIONS.md runbook): on some boxes a
1-device CPU process that LOADS persisted ``dacc_*``/``stream_*`` AOT
entries at a widen shape intermittently dies with glibc heap
corruption (``malloc(): ... corrupted`` / segfault) — or, worse,
silently corrupts counts, which only a parity gate catches.  Tier-1
never hits it (multi-device processes skip persistence) and bench
self-suppresses via its parity gate, so every occurrence so far has
been shrugged off without attribution.

This harness makes the next occurrence attributable:

* rep 0 runs ``wcstream --devices 1 --device-accumulate`` with a small
  ``--u-cap`` over a high-cardinality corpus, forcing a table widen —
  compiling AND PERSISTING the base + widen-shape entries;
* reps 1..N rerun the identical job, now LOADING every persisted entry
  (the flake's trigger), under ``PYTHONMALLOC=debug`` (heap-corruption
  checks on every malloc/free) and ``PYTHONFAULTHANDLER=1`` (a Python
  traceback on SIGSEGV/SIGABRT), with ``--check`` as the
  silent-corruption parity oracle;
* every rep's stderr — including the aotcache ``loaded from <file>
  (digest=... shapes=...)`` attribution lines — lands in the dump dir;
  a failing rep gets a ``FAULT-<rep>.log`` naming rc, signal, and the
  exact entries loaded, and the harness exits 1.

CI runs this as an advisory (continue-on-error) job and uploads the
dump dir, so a red run is evidence, not noise.  Locally::

    python scripts/aot_flake_repro.py --reps 6 --out /tmp/aot-flake
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_corpus(path: str, mb: float) -> None:
    """High-cardinality text: enough distinct words to force a widen
    past the harness's small --u-cap."""
    words = [f"w{i:05d}" for i in range(4000)]
    line = (" ".join(words[:200]) + "\n")
    out = []
    total = 0
    i = 0
    target = int(mb * (1 << 20))
    while total < target:
        chunk = " ".join(words[(i * 37) % 3800:(i * 37) % 3800 + 200]) \
            + "\n"
        out.append(chunk)
        total += len(chunk)
        i += 1
    blob = ("".join(out))[:target].encode()
    tmp = path + f".tmp{os.getpid()}"
    # dsicheck: allow[raw-write] harness-local corpus, regenerated per run
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def run_rep(rep: int, corpus: str, out_dir: str, cache_dir: str,
            workdir: str, debug_malloc: bool) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DSI_AOT_CACHE_DIR": cache_dir,
        "PYTHONFAULTHANDLER": "1",
        # rep 0 compiles+persists; later reps must really LOAD
        "DSI_AOT_FRESH": "0",
    })
    env.pop("XLA_FLAGS", None)  # 1 device: the persistence-active shape
    if debug_malloc and rep > 0:
        env["PYTHONMALLOC"] = "debug"
    cmd = [sys.executable, "-m", "dsi_tpu.cli.wcstream",
           "--devices", "1", "--chunk-bytes", "65536", "--aot",
           "--u-cap", "512", "--device-accumulate", "--sync-every", "4",
           "--workdir", workdir, "--check", corpus]
    t0 = time.time()
    p = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=1200)
    dt = round(time.time() - t0, 1)
    with open(os.path.join(out_dir, f"rep-{rep}.stderr.log"), "w") as f:
        # dsicheck: allow[raw-write] diagnostic dump, loss-tolerable
        f.write(p.stderr)
    loads = [ln for ln in p.stderr.splitlines()
             if "loaded from" in ln and "[aotcache]" in ln]
    sig = -p.returncode if p.returncode < 0 else None
    rec = {"rep": rep, "rc": p.returncode, "signal": sig,
           "seconds": dt, "aot_loads": len(loads),
           "parity_ok": "MISMATCH" not in p.stdout + p.stderr}
    if p.returncode != 0 or not rec["parity_ok"]:
        fault = os.path.join(out_dir, f"FAULT-{rep}.log")
        with open(fault, "w") as f:  # dsicheck: allow[raw-write] dump
            f.write(f"rc={p.returncode} signal={sig} parity_ok="
                    f"{rec['parity_ok']} seconds={dt}\n\n"
                    f"== persisted entries loaded by this rep ==\n"
                    + "\n".join(loads)
                    + "\n\n== stderr tail ==\n"
                    + "\n".join(p.stderr.splitlines()[-120:]) + "\n")
        rec["fault_log"] = fault
        print(f"rep {rep}: FAULT (rc={p.returncode} signal={sig} "
              f"parity_ok={rec['parity_ok']}) -> {fault}",
              file=sys.stderr)
    else:
        print(f"rep {rep}: ok rc=0 loads={len(loads)} {dt}s",
              file=sys.stderr)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reps", type=int, default=4,
                    help="loading reps after the persist rep (default 4)")
    ap.add_argument("--mb", type=float, default=4.0,
                    help="corpus size in MiB (default 4)")
    ap.add_argument("--out", default="/tmp/aot-flake",
                    help="dump directory (uploaded by CI)")
    ap.add_argument("--no-debug-malloc", action="store_true",
                    help="skip PYTHONMALLOC=debug (timing-sensitive "
                         "repro attempts)")
    args = ap.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    cache_dir = os.path.join(args.out, "aotcache")
    workdir = os.path.join(args.out, "wd")
    os.makedirs(workdir, exist_ok=True)
    corpus = os.path.join(args.out, "corpus.txt")
    make_corpus(corpus, args.mb)

    reps = []
    failed = False
    for rep in range(args.reps + 1):
        rec = run_rep(rep, corpus, args.out, cache_dir, workdir,
                      debug_malloc=not args.no_debug_malloc)
        reps.append(rec)
        if rep == 0 and rec["rc"] != 0:
            print("rep 0 (persist pass) failed — environment problem, "
                  "not the flake; aborting", file=sys.stderr)
            failed = True
            break
        if rep > 0 and rec["aot_loads"] == 0:
            print(f"rep {rep}: WARNING: no persisted loads happened — "
                  f"the trigger is not being exercised", file=sys.stderr)
        failed = failed or rec["rc"] != 0 or not rec["parity_ok"]
    summary = {"failed": failed, "reps": reps}
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        # dsicheck: allow[raw-write] diagnostic dump, loss-tolerable
        json.dump(summary, f, indent=1)
    print(json.dumps({"aot_flake_failed": failed,
                      "reps": len(reps),
                      "out": args.out}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
