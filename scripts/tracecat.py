#!/usr/bin/env python
"""Render a dsi_tpu/obs trace as text: flame summary, slowest steps,
straggler table, control-plane digest.

Input is whatever a traced run left behind — a ``trace.jsonl`` (or
``.json``) file, or a directory of them (``mrrun --trace-dir`` leaves
one ``trace-<pid>.*`` pair per coordinator/worker process; all are
merged).  No jax, no repo imports: this reads the artifacts alone, so
it runs anywhere the trace files land (including a laptop far from the
chip that produced them).

Sections:

* header      — event counts, wall span, dropped events, counters, and
                the metrics-registry snapshot (per-engine unified phase
                dicts) embedded at flush time;
* flame       — per span-name totals (total seconds, count, mean, max)
                with text bars, sorted by total: WHERE the wall went;
* top steps   — the N slowest per-step ``finish`` spans (the pipeline
                core's per-step retire wall: deferred flag wait + merge
                or replay), with engine and step ordinal;
* stragglers  — finish spans beyond max(2x median, mean + 3 sigma): the
                outliers a speculative-execution pass would back up;
* control     — requeue/fault/assign/complete/stall/aot_load event
                digest and the per-worker heartbeat-age gauge, when
                present;
* shuffle     — the mesh-sharded fold lane (PR 7): fold-span wall,
                ``shard_widens``/``shard_imbalance``/``pull_bytes``
                counters and per-event hot-shard details;
* ckpt        — the capture/commit split (PR 8): per-half span wall
                and the ``ckpt_barrier_s``/saves/deltas/bytes
                counters;
* histograms  — the live-telemetry stage latency percentile table
                (count/p50/p90/p99/max per hot stage) embedded in the
                registry snapshot at flush.

Usage: python scripts/tracecat.py TRACE_OR_DIR [--top N]
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys


def _load_jsonl(path: str):
    meta, events = {}, []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line of a killed writer
            if rec.get("type") == "meta":
                meta = rec
            else:
                events.append(rec)
    return meta, events


def _load_chrome(path: str):
    """Fallback reader for the Perfetto ``.json`` when no ``.jsonl`` is
    around (e.g. only the Chrome file was copied off the box)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    meta = doc.get("otherData", {})
    events = []
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i", "C"):
            continue
        rec = {"ph": "I" if ev["ph"] == "i" else ev["ph"],
               "name": ev.get("name", "?"), "lane": ev.get("cat", "?"),
               "ts": ev.get("ts", 0) / 1e6, "dur": ev.get("dur", 0) / 1e6,
               "depth": 0}
        rec.update(ev.get("args") or {})
        events.append(rec)
    return meta, events


def load(path: str):
    """(metas, events) from a file or a directory of trace artifacts."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.jsonl")))
        # The live sampler's ring (obs/live.py) shares the trace dir
        # but holds wall-clock snapshots, not span events — summarized
        # separately in main(), never merged into the timeline.
        files = [f for f in files
                 if os.path.basename(f) != "live.jsonl"]
        if not files:
            files = sorted(glob.glob(os.path.join(path, "*.json")))
            files = [f for f in files if not f.endswith(".crc32")]
        if not files:
            sys.exit(f"tracecat: no trace artifacts under {path}")
    else:
        files = [path]
    metas, events = [], []
    for f in files:
        meta, evs = (_load_jsonl(f) if f.endswith(".jsonl")
                     else _load_chrome(f))
        if meta:
            meta["_file"] = os.path.basename(f)
            metas.append(meta)
        for e in evs:
            e["_file"] = os.path.basename(f)
        events.extend(evs)
    return metas, events


def _bar(frac: float, width: int = 28) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "." * (width - n)


def flame(events, out) -> None:
    rows = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        r = rows.setdefault((e.get("lane", "?"), e["name"]),
                            [0.0, 0, 0.0])
        r[0] += e.get("dur", 0.0)
        r[1] += 1
        r[2] = max(r[2], e.get("dur", 0.0))
    if not rows:
        print("  (no spans)", file=out)
        return
    top = max(r[0] for r in rows.values()) or 1.0
    print(f"  {'lane/span':<24} {'total_s':>9} {'count':>7} "
          f"{'mean_ms':>9} {'max_ms':>9}", file=out)
    for (lane, name), (tot, cnt, mx) in sorted(
            rows.items(), key=lambda kv: -kv[1][0]):
        label = f"{lane}/{name}" if lane != name else name
        print(f"  {label:<24} {tot:>9.3f} {cnt:>7} "
              f"{1e3 * tot / cnt:>9.2f} {1e3 * mx:>9.2f}  "
              f"{_bar(tot / top)}", file=out)


def _finish_spans(events):
    return [e for e in events
            if e.get("ph") == "X" and e.get("name") == "finish"]


def top_steps(events, n: int, out) -> None:
    fin = sorted(_finish_spans(events), key=lambda e: -e.get("dur", 0.0))
    if not fin:
        print("  (no per-step finish spans — not a pipeline trace?)",
              file=out)
        return
    print(f"  {'engine':<10} {'step':>6} {'dur_ms':>10}  file", file=out)
    for e in fin[:n]:
        print(f"  {e.get('engine') or '?':<10} {e.get('step', '?'):>6} "
              f"{1e3 * e.get('dur', 0.0):>10.2f}  {e.get('_file', '')}",
              file=out)


def stragglers(events, out) -> None:
    fin = _finish_spans(events)
    if len(fin) < 4:
        print("  (too few steps for outlier statistics)", file=out)
        return
    durs = sorted(e.get("dur", 0.0) for e in fin)
    n = len(durs)
    median = durs[n // 2]
    mean = sum(durs) / n
    sigma = math.sqrt(sum((d - mean) ** 2 for d in durs) / n)
    cut = max(2 * median, mean + 3 * sigma)
    bad = [e for e in fin if e.get("dur", 0.0) > cut]
    print(f"  steps={n} median={1e3 * median:.2f}ms mean={1e3 * mean:.2f}ms"
          f" sigma={1e3 * sigma:.2f}ms cutoff={1e3 * cut:.2f}ms", file=out)
    if not bad:
        print("  no stragglers past the cutoff", file=out)
        return
    for e in sorted(bad, key=lambda e: -e.get("dur", 0.0)):
        print(f"  STRAGGLER {e.get('engine') or '?'} step "
              f"{e.get('step', '?')}: {1e3 * e.get('dur', 0.0):.2f}ms "
              f"({e.get('dur', 0.0) / median:.1f}x median)", file=out)


def control(events, metas, out) -> None:
    interesting = ("requeue", "fault", "assign", "complete",
                   "duplicate_completion", "ckpt_save", "ckpt_restore",
                   "table_widen", "shard_widen", "stall", "aot_load")
    counts: dict = {}
    for e in events:
        if e.get("ph") == "I" and e.get("name") in interesting:
            counts[e["name"]] = counts.get(e["name"], 0) + 1
    if counts:
        print("  events: " + "  ".join(
            f"{k}={v}" for k, v in sorted(counts.items())), file=out)
    for e in events:
        if e.get("ph") == "I" and e.get("name") in ("requeue", "fault",
                                                    "stall"):
            extras = {k: v for k, v in e.items()
                      if k not in ("ph", "name", "lane", "ts", "dur",
                                   "depth", "_file")}
            tag = "STALL" if e["name"] == "stall" else e["name"]
            print(f"  {tag} @ {e.get('ts', 0):.3f}s: {extras}",
                  file=out)
    for meta in metas:
        gauges = (meta.get("registry") or {}).get("gauges") or {}
        hb = gauges.get("mr_worker_heartbeat_age_s")
        if hb:
            print(f"  heartbeat ages [{meta.get('_file', '?')}]: "
                  + "  ".join(f"{w}={a}s" for w, a in sorted(hb.items())),
                  file=out)
        hbh = gauges.get("mr_worker_heartbeat_hist")
        if hbh:
            for w, h in sorted(hbh.items()):
                print(f"  heartbeat gaps {w}: count={h.get('count')} "
                      f"p50={h.get('p50_ms')}ms p99={h.get('p99_ms')}ms "
                      f"max={h.get('max_ms')}ms", file=out)


def _span_totals(events, names) -> dict:
    tot: dict = {}
    for e in events:
        if e.get("ph") == "X" and e.get("name") in names:
            r = tot.setdefault(e["name"], [0.0, 0])
            r[0] += e.get("dur", 0.0)
            r[1] += 1
    return tot


def shuffle(events, metas, out) -> bool:
    """The mesh-sharded fold lane (PR 7): invisible to the original
    digest because the lane landed after it.  Returns True when there
    was anything to show."""
    folds = [e for e in events if e.get("ph") == "X"
             and e.get("lane") == "shuffle"]
    widens = [e for e in events if e.get("ph") == "I"
              and e.get("name") == "shard_widen"]
    rows = []
    for meta in metas:
        engines = (meta.get("registry") or {}).get("engines") or {}
        for eng, ph in sorted(engines.items()):
            if ph.get("mesh_shards"):
                rows.append((meta.get("_file", "?"), eng, ph))
    if not (folds or widens or rows):
        return False
    if folds:
        tot = sum(e.get("dur", 0.0) for e in folds)
        print(f"  fold spans in lane: {len(folds)}  wall={tot:.3f}s",
              file=out)
    for fname, eng, ph in rows:
        sw = ph.get("shard_widens")
        print(f"  {eng} [{fname}]: mesh_shards={ph.get('mesh_shards')} "
              f"pull_bytes={ph.get('pull_bytes')} "
              f"shard_widens={sw} (sum={sum(sw) if sw else 0}) "
              f"shard_imbalance={ph.get('shard_imbalance')}", file=out)
    for e in widens:
        extras = {k: v for k, v in e.items()
                  if k not in ("ph", "name", "lane", "ts", "dur",
                               "depth", "_file")}
        print(f"  shard_widen @ {e.get('ts', 0):.3f}s: {extras}",
              file=out)
    return True


def ckpt(events, metas, out) -> bool:
    """The async checkpoint capture/commit split (PR 8) — per-half
    wall from the spans, barrier/saves/bytes from the phase dicts."""
    tot = _span_totals(events, ("ckpt", "ckpt_capture", "ckpt_commit"))
    keys = ("ckpt_saves", "ckpt_deltas", "ckpt_barrier_s",
            "ckpt_capture_s", "ckpt_commit_s", "ckpt_full_bytes",
            "ckpt_delta_bytes", "resume_gap_s")
    rows = []
    for meta in metas:
        engines = (meta.get("registry") or {}).get("engines") or {}
        for eng, ph in sorted(engines.items()):
            kv = {k: ph[k] for k in keys if ph.get(k)}
            if kv:
                rows.append((meta.get("_file", "?"), eng, kv))
    if not (tot or rows):
        return False
    for name in ("ckpt", "ckpt_capture", "ckpt_commit"):
        if name in tot:
            t, n = tot[name]
            print(f"  {name:<14} total={t:.3f}s count={n} "
                  f"mean={1e3 * t / n:.2f}ms", file=out)
    for fname, eng, kv in rows:
        print(f"  {eng} [{fname}]: " + " ".join(
            f"{k}={round(v, 4) if isinstance(v, float) else v}"
            for k, v in kv.items()), file=out)
    return True


def wire(events, metas, out) -> bool:
    """The compressed-wire + parallel-ingest keys (ISSUE 13): decode
    span totals plus the codec/reader-pool counters from the phase
    dicts."""
    tot = _span_totals(events, ("decode",))
    keys = ("wire_steps", "wire_raw_steps", "wire_packed_bytes",
            "wire_ratio", "decode_s", "ingest_readers", "ingest_blocks",
            "readahead_hit_pct", "ingest_wait_s", "ckpt_compress",
            "ckpt_delta_raw_bytes", "ckpt_compress_s")
    rows = []
    for meta in metas:
        engines = (meta.get("registry") or {}).get("engines") or {}
        for eng, ph in sorted(engines.items()):
            kv = {k: ph[k] for k in keys if ph.get(k)}
            if kv:
                rows.append((meta.get("_file", "?"), eng, kv))
    if not (tot or rows):
        return False
    if "decode" in tot:
        t, n = tot["decode"]
        print(f"  {'decode':<14} total={t:.3f}s count={n} "
              f"mean={1e3 * t / n:.2f}ms", file=out)
    for fname, eng, kv in rows:
        print(f"  {eng} [{fname}]: " + " ".join(
            f"{k}={round(v, 4) if isinstance(v, float) else v}"
            for k, v in kv.items()), file=out)
    return True


def plan(events, metas, out) -> bool:
    """The plan layer (ISSUE 14): per-stage walls from the ``plan``
    lane's spans plus the handoff accounting — how many intermediate
    bytes the chain carried and how many of them were SAVED from the
    host round-trip (handoff minus host-crossing)."""
    walls = []
    for e in events:
        if e.get("ph") == "X" and e.get("name") == "plan":
            walls.append((e.get("stage", "?"), e.get("dur", 0.0)))
    tot = _span_totals(events, ("stage_commit",))
    keys = ("plan_stages", "plan_handoff", "plan_handoff_bytes",
            "plan_intermediate_bytes", "plan_commit_bytes",
            "plan_relay_buffers", "plan_spilled_bytes",
            "plan_restored_bytes", "plan_resumed_stages")
    rows = []
    for meta in metas:
        engines = (meta.get("registry") or {}).get("engines") or {}
        ph = engines.get("plan") or {}
        kv = {k: ph[k] for k in keys if k in ph}
        if kv:
            rows.append((meta.get("_file", "?"), kv))
    if not (walls or rows):
        return False
    for stage, dur in walls:
        print(f"  stage {stage:<14} wall={dur:.3f}s", file=out)
    if "stage_commit" in tot:
        t, n = tot["stage_commit"]
        print(f"  {'stage_commit':<20} total={t:.3f}s count={n}",
              file=out)
    for fname, kv in rows:
        saved = (kv.get("plan_handoff_bytes", 0)
                 - kv.get("plan_intermediate_bytes", 0))
        print(f"  plan [{fname}]: handoff_bytes_saved={saved} " + " ".join(
            f"{k}={v}" for k, v in kv.items()
            if not isinstance(v, dict)), file=out)
    return True


def elastic(events, metas, out) -> bool:
    """Elastic dataflow (ISSUE 16): the seal-driven stage-overlap wall
    (``stage_overlap`` spans + ``plan_overlap_s``) and the dynamic
    re-split control events — which shard split, at what cursor, into
    which sub-ranges, and how each sub-range race resolved."""
    tot = _span_totals(events, ("stage_overlap", "resplit"))
    rows = []
    for meta in metas:
        engines = (meta.get("registry") or {}).get("engines") or {}
        ph = engines.get("plan") or {}
        kv = {k: ph[k] for k in ("plan_pipelined", "plan_stage_shards",
                                 "plan_overlap_s") if k in ph}
        if kv.get("plan_pipelined") or kv.get("plan_stage_shards"):
            rows.append((meta.get("_file", "?"), kv))
    splits = [e for e in events if e.get("ph") == "I"
              and e.get("name") == "resplit_dispatch"]
    subs = {}
    for e in events:
        if e.get("ph") == "I" and e.get("name") in ("subshard_commit",
                                                    "subshard_commit_lose"):
            key = (e.get("task"), e.get("sub"))
            subs.setdefault(key, []).append(e)
    if not (tot or rows or splits or subs):
        return False
    if "stage_overlap" in tot:
        t, n = tot["stage_overlap"]
        print(f"  {'stage_overlap':<20} total={t:.3f}s count={n}",
              file=out)
    for fname, kv in rows:
        print(f"  plan [{fname}]: " + " ".join(
            f"{k}={v}" for k, v in kv.items()), file=out)
    for e in splits:
        print(f"  resplit shard {e.get('task')} @ {e.get('ts', 0):.3f}s"
              f" reason={e.get('reason')} cursor={e.get('cursor')}"
              f" straggler=a{e.get('straggler_attempt')}"
              f" ranges={e.get('ranges')}", file=out)
    for (task, sub), es in sorted(subs.items(),
                                  key=lambda kv: (str(kv[0][0]),
                                                  str(kv[0][1]))):
        wins = sum(1 for e in es if e["name"] == "subshard_commit")
        loses = len(es) - wins
        resolved = any(e.get("resolved") for e in es)
        print(f"  sub {task}.s{sub}: commits={wins} losses={loses}"
              + (" [shard resolved split]" if resolved else ""),
              file=out)
    return True


def replica(events, metas, out) -> bool:
    """The replicated control plane (ISSUE 20): terms, elections,
    app rebuild walls, the measured failover gap (last event of the
    dying term -> the next ``replica.elected``), and per-replica
    replication lag from the ``dsi_replica_applied_index`` gauges."""
    evs = sorted((e for e in events
                  if str(e.get("name", "")).startswith("replica.")),
                 key=lambda e: e.get("ts", 0.0))
    applied = []
    for meta in metas:
        gauges = (meta.get("registry") or {}).get("gauges") or {}
        if "dsi_replica_applied_index" in gauges:
            applied.append((meta.get("_file", "?"),
                            gauges.get("dsi_replica_applied_index"),
                            gauges.get("dsi_replica_term"),
                            gauges.get("dsi_replica_elections")))
    if not (evs or applied):
        return False
    terms = sorted({int(e.get("term", 0)) for e in evs})
    elected = [e for e in evs if e["name"] == "replica.elected"]
    steps = sum(1 for e in evs if e["name"] == "replica.stepdown")
    print(f"  terms seen: {terms}  elections={len(elected)} "
          f"stepdowns={steps}", file=out)
    for e in elected:
        # Failover wall as the trace sees it: the gap from the last
        # event of ANY older term to this election.  A kill -9 leader
        # emits nothing on death, so this spans the election timeout.
        prev = [p for p in evs if p.get("ts", 0.0) < e.get("ts", 0.0)
                and int(p.get("term", 0)) < int(e.get("term", 0))]
        gap = (e.get("ts", 0.0) - prev[-1].get("ts", 0.0)) if prev \
            else None
        ups = [u for u in evs if u["name"] == "replica.app_up"
               and int(u.get("term", 0)) == int(e.get("term", 0))]
        build = ups[0].get("build_s") if ups else None
        line = (f"  term {e.get('term')}: replica {e.get('node')} "
                f"elected @ {e.get('ts', 0.0):.3f}s "
                f"barrier={e.get('barrier')}")
        if gap is not None:
            line += f" failover_gap={gap:.3f}s"
        if build is not None:
            line += f" app_build={build:.3f}s"
        print(line, file=out)
    if applied:
        top = max(a[1] or 0 for a in applied)
        for fname, idx, term, elections in sorted(applied):
            lag = top - (idx or 0)
            print(f"  {fname}: applied_index={idx} term={term} "
                  f"elections_won={elections}"
                  + (f" lag={lag}" if lag else ""), file=out)
    return True


def histograms(metas, out) -> bool:
    """The stage latency percentile table (obs/hist.py) embedded in
    each trace's registry snapshot."""
    any_rows = False
    for meta in metas:
        hists = (meta.get("registry") or {}).get("histograms") or {}
        if not hists:
            continue
        if not any_rows:
            print(f"  {'stage':<14} {'count':>8} {'p50_ms':>10} "
                  f"{'p90_ms':>10} {'p99_ms':>10} {'max_ms':>10}  file",
                  file=out)
        any_rows = True
        for stage, h in sorted(hists.items()):
            print(f"  {stage:<14} {h.get('count', 0):>8} "
                  f"{h.get('p50_ms', 0):>10.3f} "
                  f"{h.get('p90_ms', 0):>10.3f} "
                  f"{h.get('p99_ms', 0):>10.3f} "
                  f"{h.get('max_ms', 0):>10.3f}  "
                  f"{meta.get('_file', '?')}", file=out)
    return any_rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="trace.jsonl / trace.json, or a "
                                  "--trace-dir directory")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest steps to list (default 10)")
    args = ap.parse_args(argv)
    metas, events = load(args.trace)
    out = sys.stdout

    spans = sum(1 for e in events if e.get("ph") == "X")
    wall = max((e.get("ts", 0) + e.get("dur", 0) for e in events),
               default=0.0)
    dropped = sum(m.get("dropped_events", 0) for m in metas)
    print(f"== tracecat: {args.trace} ==", file=out)
    print(f"  files={len(metas) or 1} events={len(events)} spans={spans} "
          f"wall={wall:.3f}s dropped={dropped}", file=out)
    ring = (os.path.join(args.trace, "live.jsonl")
            if os.path.isdir(args.trace) else None)
    if ring and os.path.exists(ring):
        try:
            with open(ring, encoding="utf-8") as f:
                samples = [l for l in f if l.strip()]
            last = json.loads(samples[-1]) if samples else {}
            print(f"  live ring: {len(samples)} samples (live.jsonl), "
                  f"last at uptime {last.get('uptime_s', '?')}s, "
                  f"pipelines={last.get('pipelines')}", file=out)
        except (OSError, ValueError):
            pass
    for meta in metas:
        if meta.get("counters"):
            print(f"  counters [{meta.get('_file', '?')}]: "
                  f"{meta['counters']}", file=out)
        engines = (meta.get("registry") or {}).get("engines") or {}
        for eng, phases in sorted(engines.items()):
            ph = {k: v for k, v in phases.items()
                  if k.endswith("_s") and isinstance(v, (int, float))
                  and v > 0}
            if ph:
                print(f"  {eng} phases [{meta.get('_file', '?')}]: "
                      + " ".join(f"{k}={round(v, 3)}"
                                 for k, v in sorted(ph.items())),
                      file=out)
    print("\n-- flame (per span name) --", file=out)
    flame(events, out)
    print(f"\n-- top {args.top} slowest steps --", file=out)
    top_steps(events, args.top, out)
    print("\n-- stragglers --", file=out)
    stragglers(events, out)
    import io

    for title, fn in (("shuffle lane", lambda o: shuffle(events, metas, o)),
                      ("ckpt capture/commit", lambda o: ckpt(events, metas,
                                                             o)),
                      ("wire codec / ingest pool",
                       lambda o: wire(events, metas, o)),
                      ("plan layer",
                       lambda o: plan(events, metas, o)),
                      ("elastic dataflow",
                       lambda o: elastic(events, metas, o)),
                      ("replica control plane",
                       lambda o: replica(events, metas, o)),
                      ("stage latency histograms",
                       lambda o: histograms(metas, o))):
        buf = io.StringIO()
        if fn(buf):  # sections that landed after the original digest:
            print(f"\n-- {title} --", file=out)  # shown only with data
            out.write(buf.getvalue())
    print("\n-- control plane --", file=out)
    control(events, metas, out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
