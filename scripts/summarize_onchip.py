#!/usr/bin/env python
"""Summarize an on-chip evidence directory into a BASELINE.md-ready block.

`scripts/onchip_evidence.sh $OUT` leaves ~10 artifacts (two bench JSON
lines, a wire probe log, four harness logs, two wcstream logs plus a
token-count invariant).  This reads one such directory and prints the
compact, citable summary the round report needs — so the evidence write-up
is mechanical and nothing gets transcribed by hand.

Usage: python scripts/summarize_onchip.py [/tmp/onchip/<stamp>]
       (default: the newest directory under /tmp/onchip)
"""
import glob
import json
import os
import re
import sys


def _evidence_dir(d: str) -> bool:
    # A real evidence dir holds the chain log or a bench artifact; the
    # chain also creates workdirs (corpus/, wcstream-wd/, ...) under a
    # default-OUT run that must not win the newest-mtime pick.
    return any(os.path.exists(os.path.join(d, f))
               for f in ("log", "benchA.json"))


def _latest_dir() -> str:
    cands = [d for d in glob.glob("/tmp/onchip/*") + ["/tmp/onchip"]
             if os.path.isdir(d) and _evidence_dir(d)]
    if not cands:
        sys.exit("no /tmp/onchip evidence directory found")
    return max(cands, key=os.path.getmtime)


def _read_verdict(path: str):
    """bench.py's one-JSON-line stdout verdict, or None/raw text."""
    try:
        with open(path) as f:
            txt = f.read().strip()
    except OSError:
        return None
    try:
        return json.loads(txt.splitlines()[-1])
    except (ValueError, IndexError):
        return txt  # unparseable; caller decides how to show it


def _bench_line(path: str) -> str:
    d = _read_verdict(path)
    if d is None:
        return "  (missing)"
    if isinstance(d, str):
        return f"  (unparseable: {d[-200:]!r})"
    keys = ("metric", "value", "unit", "vs_baseline", "median_mbps",
            "total_mb", "platform", "oracle_mbps", "stream_mbps",
            "stream_mb", "stream_parity",
            # PR-3 rows: the wire-independent HBM-resident kernel reps
            # (sort vs hash) and the framework row's native-sequential
            # oracle decomposition.
            "kernel_sort_mbps", "kernel_hash_mbps", "kernel_mb",
            "tfidf_mbps", "tfidf_parity",
            "native_oracle_mbps", "native_vs_python",
            "framework_mbps", "framework_vs_oracle", "framework_vs_native",
            # The streaming grep engine row (parity-gated vs the
            # host-grep oracle).
            "grep_mbps", "grep_mb", "grep_matched", "grep_oracle_mbps",
            "grep_vs_oracle", "grep_parity",
            # Checkpoint/restore cost keys riding the stream row
            # (dsi_tpu/ckpt), the cadence-1 sync-vs-async A/B:
            # sync-full overhead vs overlapped+incremental, the
            # full-vs-delta payload bytes, and the chain restore wall.
            "ckpt_overhead_pct", "ckpt_async_overhead_pct",
            "ckpt_every", "ckpt_saves", "ckpt_deltas",
            "ckpt_full_bytes_per_save", "ckpt_delta_bytes_per_save",
            "ckpt_barrier_s", "resume_gap_s", "resume_parity",
            # The plan-layer chained-vs-staged A/B (ISSUE 14): the
            # device-resident handoff against the host-materialization
            # baseline — the zero-copy evidence the on-chip sweep wants.
            "plan_mb", "plan_chained_mbps", "plan_staged_mbps",
            "plan_intermediate_bytes", "plan_staged_intermediate_bytes",
            "plan_zero_copy", "plan_parity",
            # The elastic pipelined arm (ISSUE 16): stage-overlap
            # execution of the same chain, with the attributed
            # overlap wall.
            "plan_pipelined_mbps", "plan_overlap_s",
            # The speculative-execution A/B (ISSUE 15): backup dispatch
            # against an injected slow shard, first-commit-wins gated.
            "spec_mb", "spec_backup_mbps", "spec_nobackup_mbps",
            "spec_backup_fired", "spec_duplicate_commits",
            "spec_exactly_once", "spec_resumed", "spec_parity",
            # The dynamic re-split arm (ISSUE 16): the straggler's
            # remaining range split across idle workers.
            "spec_resplit_mbps", "spec_resplits", "spec_subshards",
            # The network data plane A/B (ISSUE 17): shuffle over TCP
            # vs the shared-directory plane, with the line codec's wire
            # leverage and the locality-placement evidence.
            "net_mb", "net_shuffle_mbps", "net_fs_mbps", "net_ratio",
            "net_fetches", "net_local_reads", "locality_hits",
            "net_refetches", "net_parity",
            # The overlapped-shuffle A/B (ISSUE 18): pipelined vs
            # serial reduce-side fetches under injected serve latency,
            # with the overlap attribution.
            "net_pipe_mb", "net_pipelined_mbps", "net_serial_mbps",
            "net_overlap_s", "net_fetch_wait_s", "net_pipeline_parity",
            "tpu_error")
    parts = [f"{k}={d[k]}" for k in keys if k in d]
    phases = d.get("phases")
    if phases:
        parts.append("phases=" + json.dumps(phases))
    for k in ("stream_phases", "tfidf_phases", "grep_phases",
              # The per-phase SPAN rollups (dsi_tpu/obs): present when
              # the bench ran traced (DSI_BENCH_TRACE=1/DSI_TRACE_DIR) —
              # same measurements as the phases plus per-span counts/max.
              "stream_spans", "tfidf_spans", "grep_spans",
              # The plan row's per-stage wall decomposition.
              "plan_stage_walls"):
        if k in d:
            parts.append(f"{k}=" + json.dumps(d[k]))
    return "  " + "  ".join(parts)


def _harness(path: str) -> str:
    try:
        with open(path) as f:
            txt = f.read()
    except OSError:
        return "  (missing)"
    verdict = "PASS" if "PASS" in txt else ("FAIL" if "FAIL" in txt
                                            else "no verdict")
    m = re.search(r"^real\s+(\S+)", txt, re.M)
    wall = m.group(1) if m else "?"
    return f"  {verdict}  wall={wall}"


def _tail(path: str, n: int = 6) -> str:
    try:
        with open(path) as f:
            lines = [ln.rstrip() for ln in f if ln.strip()]
    except OSError:
        return "  (missing)"
    return "\n".join("  " + ln for ln in lines[-n:])


def _valid_tpu_verdict(v) -> bool:
    # bench.py's stdout verdict has no "parity" key; a failed or
    # parity-mismatched run ships metric=wc_tpu_throughput with value=0
    # and an "error" key, and an outage run switches the metric to
    # wc_cpu_fallback_throughput — exclude all of those.
    return (isinstance(v, dict) and v.get("metric") == "wc_tpu_throughput"
            and "error" not in v and "tpu_error" not in v
            and isinstance(v.get("value"), (int, float)) and v["value"] > 0)


def _window_samples(path: str) -> None:
    """Digest bench_window_loop.sh's congestion-window samples, if any."""
    rows, bad = [], 0
    try:
        with open(path) as f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    rows.append(json.loads(ln))
                except ValueError:  # truncated final line after a TERM
                    bad += 1
    except OSError:
        return
    good = [r for r in rows if _valid_tpu_verdict(r.get("verdict"))]
    good.sort(key=lambda r: r["verdict"]["value"])
    print(f"window samples ({path}): {len(rows)} total, "
          f"{len(good)} valid TPU verdicts"
          + (f", {bad} unparseable lines" if bad else ""))
    if good:
        best, med = good[-1], good[len(good) // 2]
        print(f"  best={best['verdict']['value']} MB/s  "
              f"median={med['verdict']['value']} MB/s  "
              f"worst={good[0]['verdict']['value']} MB/s")
        print(f"  best sample: ts={best['ts']} vs_baseline="
              f"{best['verdict'].get('vs_baseline')} median_mbps="
              f"{best['verdict'].get('median_mbps')}")


def _probe_rates(path: str) -> dict:
    """Parse probe_tunnel.py output into {label: MB/s}."""
    rates = {}
    try:
        with open(path) as f:
            for ln in f:
                m = re.match(r"\s*(H2D|D2H)\s+(.+?):\s+[\d.]+s\s+"
                             r"([\d.]+) MB/s", ln)
                if m:
                    rates[f"{m.group(1)} {m.group(2).strip()}"] = \
                        float(m.group(3))
    except OSError:
        pass
    return rates


def _machine_limit(out: str) -> None:
    """The VERDICT r3 task-1 fallback verdict: when the tunnel caps below
    the north star, report the bench number as a fraction of the measured
    wire ceiling.  The corpus must cross the wire once per run (H2D) and
    the position-coded result once back (~2 MB D2H), so the e2e ceiling
    for a CORPUS_MB corpus is CORPUS_MB / (CORPUS_MB/h2d + 2/d2h) even if
    the chip itself were infinitely fast."""
    verdicts = {b: _read_verdict(f"{out}/{b}.json")
                for b in ("benchA", "benchB", "benchC")}
    best = None
    for b, v in verdicts.items():
        if _valid_tpu_verdict(v) and (best is None or
                                      v["value"] > best[1]["value"]):
            best = (b, v)
    # Corpus size: prefer the bench artifact's own measurement; the env
    # default only covers artifacts from before bench.py emitted total_mb.
    corpus_mb = next((v["total_mb"] for v in verdicts.values()
                      if isinstance(v, dict) and "total_mb" in v),
                     None)
    mb_src = "bench artifact"
    if corpus_mb is None:
        corpus_mb = float(os.environ.get("DSI_BENCH_CORPUS_MB", "16.7"))
        mb_src = "DSI_BENCH_CORPUS_MB default"
    if corpus_mb <= 0:
        print("machine-limit analysis: corpus size unusable "
              f"({corpus_mb} MB from {mb_src})")
        return
    rates = _probe_rates(f"{out}/probe_tunnel.log")
    h2d = {k: v for k, v in rates.items() if k.startswith("H2D")}
    d2h = {k: v for k, v in rates.items() if k.startswith("D2H")}
    if not h2d:
        return
    bh_k, bh = max(h2d.items(), key=lambda kv: kv[1])
    bd = max(d2h.values(), default=None)
    if bh <= 0 or (bd is not None and bd <= 0):
        # A transfer slow enough to round to "0.0 MB/s" (the probe's
        # :8.1f format) has no usable rate; print what was seen and move
        # on rather than dividing by it.
        print("machine-limit analysis: probe rates too low to use "
              f"(best H2D {bh}, best D2H {bd})")
        return
    t = corpus_mb / bh + (2.0 / bd if bd else 0.0)
    ceil = corpus_mb / t
    print("machine-limit analysis (probe-measured wire ceiling):")
    print(f"  best H2D {bh} MB/s [{bh_k}]"
          + (f"  best D2H {bd} MB/s" if bd else "  (no D2H row parsed)"))
    print(f"  e2e ceiling for the {corpus_mb} MB corpus ({mb_src}): "
          f"{ceil:.2f} MB/s "
          + ("(one upload crossing + ~2 MB position-coded pull)" if bd
             else "(upload crossing only — D2H term unknown, so this "
                  "ceiling is an overestimate)"))
    if best:
        frac = 100.0 * best[1]["value"] / ceil
        print(f"  bench best ({best[0]}): {best[1]['value']} MB/s = "
              f"{frac:.0f}% of the wire ceiling "
              f"(vs_baseline {best[1].get('vs_baseline')})")


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else _latest_dir()
    print(f"== on-chip evidence: {out} ==")
    print("bench A (fresh process, warm cache):")
    print(_bench_line(f"{out}/benchA.json"))
    print("bench B (repeat):")
    print(_bench_line(f"{out}/benchB.json"))
    if os.path.exists(f"{out}/benchC.json"):
        print("bench C (full: transport probe + stream row):")
        print(_bench_line(f"{out}/benchC.json"))
    if os.path.isdir(f"{out}/done"):
        print("ladder steps done:", " ".join(sorted(os.listdir(f"{out}/done"))))
    print("wire probe (probe_tunnel.py tail):")
    print(_tail(f"{out}/probe_tunnel.log", 8))
    _machine_limit(out)
    for name in ("tpu_wc", "tpu_grep", "tpu_grep_literal", "tpu_grep_nfa",
                 "tpu_indexer", "tfidf"):
        print(f"harness {name}:{_harness(f'{out}/harness_{name}.log')}")
    print("wcstream --check (single-device mesh):")
    print(_tail(f"{out}/wcstream.log", 3))
    if os.path.exists(f"{out}/wcstream-dacc.log"):
        print("wcstream --device-accumulate (fold table, K-step pulls):")
        print(_tail(f"{out}/wcstream-dacc.log", 3))
    if os.path.exists(f"{out}/grepstream.log"):
        print("grepstream --check (streaming grep + on-device top-k/histogram):")
        print(_tail(f"{out}/grepstream.log", 5))
    if os.path.exists(f"{out}/wcstream-trace.log"):
        print("wcstream --trace-dir (unified obs trace, warmed dacc "
              "shapes):")
        print(_tail(f"{out}/wcstream-trace.log", 3))
    if os.path.exists(f"{out}/tracecat.log"):
        print("tracecat (flame summary + slowest steps + stragglers):")
        print(_tail(f"{out}/tracecat.log", 16))
    if os.path.exists(f"{out}/ckptstream.log"):
        print("wcstream crash-resume (DSI_FAULT_POINT kill + --resume "
              "--check):")
        print(_tail(f"{out}/ckptstream.log", 5))
    print("wcstream ~1 GB:")
    print(_tail(f"{out}/wcstream-1g.log", 4))
    print("chain log:")
    print(_tail(f"{out}/log", 30))
    # Window-loop samples: every OUT dir bench_window_loop.sh was run
    # with (default /tmp/rebench; operators may stamp their own).
    for p in sorted(glob.glob("/tmp/rebench*/samples.jsonl")):
        _window_samples(p)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # e.g. piped into `head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
