#!/usr/bin/env python
"""Summarize an on-chip evidence directory into a BASELINE.md-ready block.

`scripts/onchip_evidence.sh $OUT` leaves ~10 artifacts (two bench JSON
lines, a wire probe log, four harness logs, two wcstream logs plus a
token-count invariant).  This reads one such directory and prints the
compact, citable summary the round report needs — so the evidence write-up
is mechanical and nothing gets transcribed by hand.

Usage: python scripts/summarize_onchip.py [/tmp/onchip/<stamp>]
       (default: the newest directory under /tmp/onchip)
"""
import glob
import json
import os
import re
import sys


def _evidence_dir(d: str) -> bool:
    # A real evidence dir holds the chain log or a bench artifact; the
    # chain also creates workdirs (corpus/, wcstream-wd/, ...) under a
    # default-OUT run that must not win the newest-mtime pick.
    return any(os.path.exists(os.path.join(d, f))
               for f in ("log", "benchA.json"))


def _latest_dir() -> str:
    cands = [d for d in glob.glob("/tmp/onchip/*") + ["/tmp/onchip"]
             if os.path.isdir(d) and _evidence_dir(d)]
    if not cands:
        sys.exit("no /tmp/onchip evidence directory found")
    return max(cands, key=os.path.getmtime)


def _bench_line(path: str) -> str:
    try:
        with open(path) as f:
            txt = f.read().strip()
    except OSError:
        return "  (missing)"
    # bench.py prints exactly one JSON object on stdout
    try:
        d = json.loads(txt.splitlines()[-1])
    except (ValueError, IndexError):
        return f"  (unparseable: {txt[-200:]!r})"
    keys = ("metric", "value", "unit", "vs_baseline", "median_mbps",
            "platform", "oracle_mbps", "stream_mbps", "stream_mb",
            "stream_parity", "tpu_error")
    parts = [f"{k}={d[k]}" for k in keys if k in d]
    phases = d.get("phases")
    if phases:
        parts.append("phases=" + json.dumps(phases))
    return "  " + "  ".join(parts)


def _harness(path: str) -> str:
    try:
        with open(path) as f:
            txt = f.read()
    except OSError:
        return "  (missing)"
    verdict = "PASS" if "PASS" in txt else ("FAIL" if "FAIL" in txt
                                            else "no verdict")
    m = re.search(r"^real\s+(\S+)", txt, re.M)
    wall = m.group(1) if m else "?"
    return f"  {verdict}  wall={wall}"


def _tail(path: str, n: int = 6) -> str:
    try:
        with open(path) as f:
            lines = [ln.rstrip() for ln in f if ln.strip()]
    except OSError:
        return "  (missing)"
    return "\n".join("  " + ln for ln in lines[-n:])


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else _latest_dir()
    print(f"== on-chip evidence: {out} ==")
    print("bench A (fresh process, warm cache):")
    print(_bench_line(f"{out}/benchA.json"))
    print("bench B (repeat):")
    print(_bench_line(f"{out}/benchB.json"))
    print("wire probe (probe_tunnel.py tail):")
    print(_tail(f"{out}/probe_tunnel.log"))
    for name in ("tpu_wc", "tpu_grep", "tpu_grep_literal", "tpu_indexer",
                 "tfidf"):
        print(f"harness {name}:{_harness(f'{out}/harness_{name}.log')}")
    print("wcstream --check (single-device mesh):")
    print(_tail(f"{out}/wcstream.log", 3))
    print("wcstream ~1 GB:")
    print(_tail(f"{out}/wcstream-1g.log", 4))
    print("chain log:")
    print(_tail(f"{out}/log", 30))


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # e.g. piped into `head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
