#!/usr/bin/env python
"""TF-IDF GB-scale soak on the virtual mesh: one measured partition slice.

BASELINE.json's last config is TF-IDF over a 10 GB shard on a v5e-64; this
host has one core and a virtual mesh, so the honest reachable evidence is a
measured ~1 GB single-slice run (VERDICT r3 task 4): wall, throughput,
postings volume, and peak RSS, from which the 10 GB config's cost model is
extrapolated in BASELINE.md (device work repeats per slice; host memory
divides by the slice count — parallel/tfidf.py module docs).

Verification at this scale: full oracle parity would cost more than the
run (it is covered byte-for-byte at test scale, tests/test_tfidf.py), so
the soak checks structural invariants over everything plus exact posting
parity for the first --verify-docs documents (host recount).

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/tfidf_soak.py [--mb 1024] [--slice 5]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=1024)
    ap.add_argument("--doc-kb", type=int, default=1024)
    ap.add_argument("--slice", type=int, default=5,
                    help="accumulate the first N of --n-reduce partitions")
    ap.add_argument("--n-reduce", type=int, default=10)
    ap.add_argument("--verify-docs", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    help="in-flight wave window (default: "
                         "DSI_STREAM_PIPELINE_DEPTH or 2; 1 = the "
                         "synchronous lockstep walk)")
    ap.add_argument("--device-accumulate", action="store_true",
                    help="batch the wave walk's D2H through the "
                         "device-resident postings buffer (dsi_tpu/"
                         "device/postings.py)")
    ap.add_argument("--mesh-shards", type=int, default=None,
                    help="mesh-shard the postings buffer across N shards "
                         "(ihash %% N word routing inside the append; "
                         "implies --device-accumulate; default: "
                         "DSI_STREAM_MESH_SHARDS or 0 = off)")
    ap.add_argument("--sync-every", type=int, default=None,
                    help="waves between host pulls with "
                         "--device-accumulate (default: "
                         "DSI_STREAM_SYNC_EVERY or 8)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="enable crash-resume checkpoints (dsi_tpu/ckpt)")
    ap.add_argument("--checkpoint-every", type=int, default=None,
                    help="confirmed waves between checkpoints (default: "
                         "DSI_STREAM_CKPT_EVERY or 32)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the newest valid checkpoint in "
                         "--checkpoint-dir")
    ap.add_argument("--ckpt-async", action="store_true", default=None,
                    dest="ckpt_async",
                    help="overlap checkpoint commits with the wave walk "
                         "(env DSI_STREAM_CKPT_ASYNC)")
    ap.add_argument("--ckpt-delta", action="store_true", default=None,
                    dest="ckpt_delta",
                    help="incremental checkpoints, full re-base every "
                         "DSI_STREAM_CKPT_REBASE saves (env "
                         "DSI_STREAM_CKPT_DELTA)")
    ap.add_argument("--trace-dir", default=None,
                    help="write the soak's unified trace (dsi_tpu/obs): "
                         "Perfetto trace.json + trace.jsonl; render "
                         "with scripts/tracecat.py")
    ap.add_argument("--statusz-port", type=int, default=None,
                    help="serve live telemetry on 127.0.0.1:PORT — "
                         "/statusz + /metrics (0 = pick a free port; "
                         "default off, env DSI_STATUSZ_PORT); arms the "
                         "stall watchdog and the live.jsonl ring")
    args = ap.parse_args()
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")

    if args.trace_dir:
        from dsi_tpu.obs import configure_tracing

        configure_tracing(trace_dir=args.trace_dir)

    if args.statusz_port is not None or os.environ.get("DSI_STATUSZ_PORT"):
        from dsi_tpu.obs.live import start_from_args

        start_from_args(args.statusz_port, live_dir=args.trace_dir)

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from dsi_tpu.mr.worker import ihash
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.parallel.tfidf import FileDocs, tfidf_sharded
    from dsi_tpu.utils.corpus import ensure_corpus

    n_docs = max(1, (args.mb << 10) // args.doc_kb)
    doc_bytes = args.doc_kb << 10
    cdir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".bench", f"tfidf-soak-{args.mb}")
    t0 = time.perf_counter()
    paths = ensure_corpus(cdir, n_files=n_docs, file_size=doc_bytes)
    # Lazy docs + packed result (round 5): the corpus never sits resident
    # and the postings stay numpy — the r4 soak's 5.1 GB peak was mostly
    # the resident docs plus the pythonized result dict.
    docs = FileDocs(paths)
    gen_s = time.perf_counter() - t0
    total_mb = sum(docs.lengths) / 1e6
    print(f"corpus: {len(docs)} docs, {total_mb:.0f} MB "
          f"(gen {gen_s:.1f}s)", file=sys.stderr, flush=True)

    mesh = default_mesh(args.devices)
    partitions = set(range(args.slice)) if args.slice else None
    wave_stats: dict = {}
    t0 = time.perf_counter()
    res = tfidf_sharded(docs, mesh=mesh, n_reduce=args.n_reduce,
                        u_cap=1 << 15, partitions=partitions, packed=True,
                        depth=args.pipeline_depth,
                        device_accumulate=args.device_accumulate,
                        sync_every=args.sync_every,
                        mesh_shards=args.mesh_shards,
                        checkpoint_dir=args.checkpoint_dir,
                        checkpoint_every=args.checkpoint_every,
                        checkpoint_async=args.ckpt_async,
                        checkpoint_delta=args.ckpt_delta,
                        resume=args.resume,
                        wave_stats=wave_stats)
    wall = time.perf_counter() - t0
    assert res is not None, "tfidf fell back to host"
    if args.trace_dir:
        from dsi_tpu.obs import flush_tracing_report

        flush_tracing_report(args.trace_dir)

    # Structural invariants over the whole result (vectorized on the
    # packed tables).
    ppw = res.postings_per_word()
    assert len(ppw) == 0 or (1 <= ppw.min() and ppw.max() <= len(docs))
    if partitions is not None:
        assert np.isin(res.parts,
                       np.fromiter(partitions, np.uint32)).all()
    postings = res.n_postings

    # Exact parity for the first --verify-docs documents: every sampled
    # doc's (word -> tf) with an in-slice partition must appear verbatim.
    sample_ok = True
    for di in range(min(args.verify_docs, len(docs))):
        counts: dict = {}
        for w in re.findall(r"[A-Za-z]+", docs[di].decode()):
            counts[w] = counts.get(w, 0) + 1
        hits = res.lookup_many(counts.keys())
        for w, tf in counts.items():
            if partitions is not None and ihash(w) % args.n_reduce \
                    not in partitions:
                continue
            ent = hits.get(w)  # a missing word is a mismatch, not a crash
            got = dict(ent[1]).get(di) if ent else None
            if got != tf:
                print(f"sample mismatch: doc {di} word {w!r}: {got} != {tf}",
                      file=sys.stderr, flush=True)
                sample_ok = False

    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    # Per-phase attribution (tfidf_sharded wave_stats), mirroring the
    # stream row's stream_phases: says WHERE the soak's seconds went —
    # and whether the pipeline actually took check/pull off the critical
    # path (kernel_s = time blocked on deferred scalar checks).
    wave_phases = {
        k: (round(v, 3) if isinstance(v, float) else v)
        for k, v in wave_stats.items()
        if k.endswith("_s") or k in (
            "waves", "depth", "replays", "max_inflight_waves",
            "step_pulls", "appends", "append_overflows", "sync_pulls",
            "postings_widens", "sync_every", "device_accumulate")}
    print(json.dumps({
        "tfidf_mb": round(total_mb, 1), "wall_s": round(wall, 1),
        "mbps": round(total_mb / wall, 2), "n_docs": len(docs),
        "slice": f"{args.slice}/{args.n_reduce}" if partitions else "full",
        "uniques": len(res), "postings": postings,
        "sample_parity": sample_ok, "peak_rss_mb": round(rss_mb, 1),
        "wave_phases": wave_phases}))
    return 0 if sample_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
