#!/usr/bin/env python
"""Total-token invariant for a GB-scale stream run.

One host pass over the corpus counts ASCII-letter tokens and compares
against the sum of counts in the run's ``mr-out-*`` files — a cheap gross
miscount detector at sizes where full per-word parity is impractical
(per-word parity is covered at test scale by ``wcstream --check`` and the
differential suite).  Shared by scripts/warm_loop.sh step C4 and
scripts/onchip_evidence.sh so both collectors compute the SAME invariant.

Usage: python scripts/token_invariant.py <corpus_dir> <workdir>
Prints ``token-count invariant: corpus=N mr-out=M match=True|False``;
exit 0 iff they match.
"""
import glob
import re
import sys


def main() -> int:
    corpus_dir, workdir = sys.argv[1], sys.argv[2]
    tot = 0
    for p in sorted(glob.glob(f"{corpus_dir}/pg-*.txt")):
        with open(p, "rb") as f:
            tot += len(re.findall(rb"[A-Za-z]+", f.read()))
    got = 0
    for p in glob.glob(f"{workdir}/mr-out-*"):
        with open(p) as f:
            for line in f:
                if line.strip():
                    got += int(line.rsplit(" ", 1)[1])
    print(f"token-count invariant: corpus={tot} mr-out={got} "
          f"match={tot == got}", flush=True)
    return 0 if tot == got else 1


if __name__ == "__main__":
    raise SystemExit(main())
