#!/usr/bin/env python
"""Summarize a DSI_TRACE=1 event stream into a per-task timeline table.

The tracing layer (dsi_tpu/utils/tracing.py) emits one-line JSON events on
stderr: coordinator ``assign``/``complete``/``requeue``/
``duplicate_completion`` and worker ``span`` records.  This turns a captured
stream into a human-readable account of the job — the observability layer
the reference lacks entirely (SURVEY.md §5).

Usage:
    DSI_TRACE=1 python -m dsi_tpu.cli.mrrun --check wc inputs/pg-*.txt \
        2> trace.log
    python scripts/trace_timeline.py trace.log

For the unified subsystem (Perfetto trace.json, per-step engine spans,
flame/straggler rendering) use ``mrrun --trace-dir DIR`` +
``scripts/tracecat.py DIR`` instead — this script stays for quick
stderr-stream triage where no trace dir was configured.
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict


def parse(stream):
    events = []
    for line in stream:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "event" in rec and "t" in rec:
            events.append(rec)
    return sorted(events, key=lambda r: r["t"])


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    stream = open(argv[0]) if argv else sys.stdin
    events = parse(stream)
    if not events:
        print("no DSI_TRACE events found (run with DSI_TRACE=1, "
              "capture stderr)", file=sys.stderr)
        return 1
    t0 = events[0]["t"]

    spans = defaultdict(list)   # (kind, task) -> [seconds, ...]
    requeues = []
    dups = []
    for r in events:
        ev = r["event"]
        if ev == "span" and r.get("name", "").startswith("worker."):
            kind = r["name"].split(".", 1)[1]
            spans[(kind, r.get("task"))].append(r.get("seconds", 0.0))
        elif ev == "requeue":
            requeues.append(r)
        elif ev == "duplicate_completion":
            dups.append(r)

    print(f"{'when':>8}  event")
    for r in events:
        ev = r["event"]
        if ev == "span":
            name = r.get("name", "?")
            extra = f" task={r['task']}" if "task" in r else ""
            print(f"{r['t'] - t0:8.3f}  {name}{extra} "
                  f"({r.get('seconds', 0):.3f}s)")
        else:
            detail = {k: v for k, v in r.items() if k not in ("t", "event")}
            print(f"{r['t'] - t0:8.3f}  {ev} {detail}")

    print("\nper-task attempt counts (attempts > 1 ⇒ requeue/duplicate):")
    for (kind, task), secs in sorted(spans.items()):
        marks = ""
        if len(secs) > 1:
            marks = "  <-- executed by multiple workers"
        print(f"  {kind}[{task}]: {len(secs)} attempt(s), "
              f"{max(secs):.3f}s max{marks}")
    print(f"\n{len(requeues)} requeue(s), {len(dups)} duplicate "
          f"completion(s) absorbed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
