#!/usr/bin/env bash
# Collect the round's on-chip evidence, in order, assuming the AOT cache
# was just warmed (scripts/warm_loop.sh runs this automatically after a
# successful warm):
#
#   1. bench run A — a FRESH process: proves the AOT cache hits
#      (compile_s < 5, aot_loads >= 2) and records the north-star number
#      plus the streaming row (stream_mbps).
#   2. bench run B — repeatability / second sample of the tunnel.
#   3. scripts/probe_tunnel.py — the wire-ceiling measurement that turns
#      a below-north-star bench into 'machine limit reached' evidence.
#   4. scripts/test_mr.sh tpu_wc tpu — the full coordinator/worker/RPC
#      framework path on the real chip (VERDICT r2 task 3).
#   5. scripts/test_mr.sh tpu_grep tpu — class-pattern tier on-chip, then
#      a literal-tier run (both device grep kernels covered).
#   6. scripts/test_mr.sh tpu_indexer tpu — third app family on-chip.
#   7. scripts/test_mr.sh tfidf tpu — fourth app family (in-module
#      tpu_map; same warmed kernel shape as tpu_wc).
#   8. wcstream --check --aot — the bounded-memory streaming CLI on the
#      chip, loading the warmed executables.
#   9. wcstream --aot over a ~1 GB corpus (4 MiB chunks, warmed shapes) —
#      the GB-scale on-chip run VERDICT r3 missing #4 asks for.
#
# Everything logs under $OUT; nothing else may touch the chip while this
# runs (single-tenant tunnel).
#
# Bench outer timeout: 2700 s > the worst-case bench budget (2100 s TPU
# half + <=900 s deadline-bounded CPU fallback only when budget remains +
# oracle) so the always-emit-a-verdict contract can't be SIGKILLed away
# (ADVICE r3 medium).
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
OUT=${1:-/tmp/onchip}
mkdir -p "$OUT"
log() { echo "$(date -u +%H:%M:%S) $*" >> "$OUT/log"; }

# This script exists to measure the CHIP: a stale ambient platform pin
# (e.g. JAX_PLATFORMS=cpu left over from a soak run) would silently turn
# every step below into a host run with green-looking logs.
log "ambient pins before unset: JAX_PLATFORMS='${JAX_PLATFORMS:-}' DSI_JAX_PLATFORM='${DSI_JAX_PLATFORM:-}' DSI_GREP_PATTERN='${DSI_GREP_PATTERN:-}'"
# DSI_GREP_PATTERN leak would silently demote the class-pattern grep run
# to the literal kernel, leaving regexk.py with zero on-chip coverage.
unset JAX_PLATFORMS DSI_JAX_PLATFORM DSI_GREP_PATTERN

log "bench run A (fresh process, warm cache)"
DSI_CHILD_INIT_TIMEOUT=150 timeout -k 30s 2700s \
  python bench.py > "$OUT/benchA.json" 2> "$OUT/benchA.err"
log "benchA rc=$? $(cat "$OUT/benchA.json" 2>/dev/null | head -c 200)"

log "bench run B"
DSI_CHILD_INIT_TIMEOUT=150 timeout -k 30s 2700s \
  python bench.py > "$OUT/benchB.json" 2> "$OUT/benchB.err"
log "benchB rc=$? $(cat "$OUT/benchB.json" 2>/dev/null | head -c 200)"

log "tunnel wire-ceiling probe (H2D/D2H bandwidth + latency)"
# VERDICT r3 task 1: if the tunnel physically caps below the ~30 MB/s
# north star, the verdict must be 'machine limit reached' with the
# measured ceiling — record it right after the benches, alone on the
# single-tenant chip like every other step here.
timeout -k 30s 900s python scripts/probe_tunnel.py --mb 8 \
  > "$OUT/probe_tunnel.log" 2>&1
log "probe rc=$? $(tail -c 200 "$OUT/probe_tunnel.log" | tr '\n' ' ')"

log "harness tpu_wc --backend tpu (on-chip)"
{ time bash scripts/test_mr.sh tpu_wc tpu ; } \
  > "$OUT/harness_tpu_wc.log" 2>&1
log "tpu_wc rc=$? $(tail -c 120 "$OUT/harness_tpu_wc.log" | tr '\n' ' ')"

log "harness tpu_grep --backend tpu (on-chip, class pattern [Tt]he)"
{ time bash scripts/test_mr.sh tpu_grep tpu ; } \
  > "$OUT/harness_tpu_grep.log" 2>&1
log "tpu_grep rc=$? $(tail -c 120 "$OUT/harness_tpu_grep.log" | tr '\n' ' ')"

log "harness tpu_grep --backend tpu (on-chip, literal tier)"
# The class pattern above runs ops/regexk.py; this literal run keeps the
# tier-1 shifted-compare kernel (ops/grepk.py) covered by the harness too.
{ time DSI_GREP_PATTERN=the bash scripts/test_mr.sh tpu_grep tpu ; } \
  > "$OUT/harness_tpu_grep_literal.log" 2>&1
log "tpu_grep literal rc=$? $(tail -c 120 "$OUT/harness_tpu_grep_literal.log" | tr '\n' ' ')"

log "harness tpu_indexer --backend tpu (on-chip)"
{ time bash scripts/test_mr.sh tpu_indexer tpu ; } \
  > "$OUT/harness_tpu_indexer.log" 2>&1
log "tpu_indexer rc=$? $(tail -c 120 "$OUT/harness_tpu_indexer.log" | tr '\n' ' ')"

log "harness tfidf --backend tpu (on-chip, 4th app family)"
{ time bash scripts/test_mr.sh tfidf tpu ; } \
  > "$OUT/harness_tfidf.log" 2>&1
log "tfidf rc=$? $(tail -c 120 "$OUT/harness_tfidf.log" | tr '\n' ' ')"

log "wcstream --check on the chip (single-device mesh, AOT-cached programs)"
# Own corpus under $OUT: regenerating .bench here could desync it from
# the warm loop's oracle (bench.py owns that workdir and its env knobs).
python -c "from dsi_tpu.utils.corpus import ensure_corpus; \
           print(ensure_corpus('$OUT/corpus', n_files=4))" \
  > "$OUT/corpus.log" 2>&1
log "corpus rc=$?"
mkdir -p "$OUT/wcstream-wd"
# --u-cap 16384 + --aot MUST stay in lockstep with the rungs
# scripts/warm_kernels.py pre-compiles (warm_stream_aot caps=(1<<14,1<<16)):
# a drifting shape here misses the persistent cache and pays the 900s+
# cold axon compile inside this timeout — the exact hazard --aot avoids.
timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --check --devices 1 \
  --aot --u-cap 16384 \
  --workdir "$OUT/wcstream-wd" "$OUT"/corpus/pg-*.txt \
  > "$OUT/wcstream.log" 2>&1
log "wcstream rc=$? $(tail -c 160 "$OUT/wcstream.log" | tr '\n' ' ')"

log "wcstream --grouper hash on the chip (hash-grouper A/B vs the sort run above)"
# Same corpus and shapes as the sort-grouper step above, with the hash
# grouper env-selected (DSI_WC_GROUPER via --grouper): the ~1.8x kernel
# win measured on CPU (BASELINE r5) gets its on-chip verdict from the
# two runs' stream_phases kernel_s side by side.  The *_hg executables
# are pre-warmed by warm_kernels --phase stream (warm_groupers covers
# both variants), so this loads — never cold-compiles.  The benches
# above also carry kernel_sort_mbps / kernel_hash_mbps (the HBM-resident
# rep loop, DSI_BENCH_KERNEL_REPS), the wire-independent form of the
# same comparison.
mkdir -p "$OUT/wcstream-hg-wd"
timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --check --devices 1 \
  --aot --u-cap 16384 --grouper hash --stats \
  --workdir "$OUT/wcstream-hg-wd" "$OUT"/corpus/pg-*.txt \
  > "$OUT/wcstream-hg.log" 2>&1
log "wcstream-hg rc=$? $(tail -c 200 "$OUT/wcstream-hg.log" | tr '\n' ' ')"

log "wcstream --device-accumulate on the chip (fold table, K=${SYNC_EVERY:-8})"
# Same corpus and shapes as the step above, with the device-resident
# accumulator service folding confirmed steps on-chip and pulling only
# every K steps — --stats records the fold/sync/widen counters so
# BENCH_r06+ can put stream_phases with and without on-device folding
# side by side (the amortization story: step_pulls vs sync_pulls).  The
# fold shapes are pre-warmed by warm_kernels --phase stream
# (warm_stream_aot(device_accumulate=True)); a drifting --u-cap here
# would cold-compile a fold inside this timeout.
mkdir -p "$OUT/wcstream-dacc-wd"
timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --check --devices 1 \
  --aot --u-cap 16384 --device-accumulate --sync-every "${SYNC_EVERY:-8}" \
  --stats --workdir "$OUT/wcstream-dacc-wd" "$OUT"/corpus/pg-*.txt \
  > "$OUT/wcstream-dacc.log" 2>&1
log "wcstream-dacc rc=$? $(tail -c 200 "$OUT/wcstream-dacc.log" | tr '\n' ' ')"

log "wcstream traced run (--trace-dir: Perfetto trace + span rollups, dsi_tpu/obs)"
# Same warmed shapes as the wcstream-dacc step, with the unified tracer
# on: the trace.json answers the questions the on-chip sweep exists for
# — per-step upload/pull wall over the tunnel, widen/replay causality,
# fold/sync amortization — as a per-step timeline, not just totals.
# tracecat.log is the text rendering (flame summary + slowest steps +
# straggler table) summarize_onchip.py tails into the round report.
rm -rf "$OUT/wcstream-trace" "$OUT/wcstream-trace-ck"
mkdir -p "$OUT/wcstream-trace-wd"
timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --check --devices 1 \
  --aot --u-cap 16384 --device-accumulate --sync-every "${SYNC_EVERY:-8}" \
  --checkpoint-dir "$OUT/wcstream-trace-ck" --checkpoint-every 8 \
  --trace-dir "$OUT/wcstream-trace" --stats \
  --workdir "$OUT/wcstream-trace-wd" "$OUT"/corpus/pg-*.txt \
  > "$OUT/wcstream-trace.log" 2>&1
log "wcstream-trace rc=$? $(tail -c 200 "$OUT/wcstream-trace.log" | tr '\n' ' ')"
python scripts/tracecat.py "$OUT/wcstream-trace" > "$OUT/tracecat.log" 2>&1
log "tracecat rc=$? $(head -c 160 "$OUT/tracecat.log" | tr '\n' ' ')"

log "grepstream --check on the chip (streaming grep engine + on-device top-k/histogram)"
# Same corpus as the wcstream steps; the CLI's default --chunk-bytes
# (1 MiB) and pattern length 3 MUST stay in lockstep with the shapes
# scripts/warm_kernels.py --phase grep pre-compiles (both l_cap rungs +
# the top-k fold/snapshot and histogram fold programs) — a drifting
# shape here pays a cold axon compile inside this timeout.  --check runs
# the host-grep oracle over the same stream: the parity verdict is the
# step's PASS, and --stats records step_pulls vs sync_pulls/widens/
# topk_snapshots (the pull-amortization evidence for BENCH_r06+).
timeout -k 30s 3600s python -m dsi_tpu.cli.grepstream --check --devices 1 \
  --pattern the --device-accumulate --sync-every "${SYNC_EVERY:-8}" \
  --aot --stats "$OUT"/corpus/pg-*.txt \
  > "$OUT/grepstream.log" 2>&1
log "grepstream rc=$? $(tail -c 200 "$OUT/grepstream.log" | tr '\n' ' ')"

log "wcstream crash-resume on the chip (DSI_FAULT_POINT=mid-fold kill + --resume --check)"
# A REAL crash (os._exit 87, no teardown) injected mid-engine, then a
# fresh-process --resume over the same corpus with the parity oracle:
# the checkpoint subsystem's evidence is an actual process death on the
# chip, not a mock.  Shapes stay in lockstep with the warmed wcstream
# step above (--u-cap 16384, --aot), so neither run cold-compiles;
# --checkpoint-every 1 guarantees a checkpoint exists before the kill.
rm -rf "$OUT/ckptstream-ck"
mkdir -p "$OUT/ckptstream-wd"
DSI_FAULT_POINT=mid-fold DSI_FAULT_STEP=2 timeout -k 30s 3600s \
  python -m dsi_tpu.cli.wcstream --devices 1 --aot --u-cap 16384 \
  --checkpoint-dir "$OUT/ckptstream-ck" --checkpoint-every 1 \
  --workdir "$OUT/ckptstream-wd" "$OUT"/corpus/pg-*.txt \
  > "$OUT/ckptstream.log" 2>&1
log "ckptstream crash rc=$? (87 = injected fault fired)"
timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --devices 1 --aot \
  --u-cap 16384 --checkpoint-dir "$OUT/ckptstream-ck" --resume --check \
  --stats --workdir "$OUT/ckptstream-wd" "$OUT"/corpus/pg-*.txt \
  >> "$OUT/ckptstream.log" 2>&1
log "ckptstream resume rc=$? $(tail -c 200 "$OUT/ckptstream.log" | tr '\n' ' ')"

log "wcstream ~1 GB on the chip (GB-scale single-device stream)"
# 1024 x 1 MB generated files; --check would double the wall with a host
# oracle pass over 1 GB, so this step relies on wcstream's own exactness
# machinery (device counts are exact or the CLI falls back/fails loudly)
# and records wall time for the throughput story.  4 MiB chunks amortize
# the tunnel's per-step latency; the shapes are pre-warmed
# (scripts/warm_kernels.py) so no cold compile runs inside the timeout.
python -c "from dsi_tpu.utils.corpus import ensure_corpus; \
           ensure_corpus('$OUT/corpus-1g', n_files=1024, file_size=1048576)" \
  > "$OUT/corpus-1g.log" 2>&1
log "corpus-1g rc=$?"
mkdir -p "$OUT/wcstream-1g-wd"
# Stale outputs must not masquerade as this run's result (the invariant
# below would happily sum a previous round's files).
rm -f "$OUT/wcstream-1g-wd"/mr-out-*
{ time timeout -k 30s 3600s python -m dsi_tpu.cli.wcstream --devices 1 \
    --aot --u-cap 16384 --chunk-bytes 4194304 \
    --workdir "$OUT/wcstream-1g-wd" "$OUT"/corpus-1g/pg-*.txt ; } \
  > "$OUT/wcstream-1g.log" 2>&1
log "wcstream-1g rc=$? $(tail -c 160 "$OUT/wcstream-1g.log" | tr '\n' ' ')"
# Total-token invariant (full per-word parity is covered at test scale;
# this one-pass host count catches gross miscounts at 1 GB for ~1 min;
# shared helper so this and the warm_loop.sh ladder compute the SAME
# invariant):
python scripts/token_invariant.py "$OUT/corpus-1g" "$OUT/wcstream-1g-wd" \
  >> "$OUT/wcstream-1g.log" 2>&1
log "wcstream-1g invariant: $(tail -n 1 "$OUT/wcstream-1g.log")"

log "evidence collection done"
