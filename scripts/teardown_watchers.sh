#!/usr/bin/env bash
# End-of-round teardown: stop the warm/evidence automation so the
# driver's bench has the single-tenant chip to itself (BASELINE.md
# round-3 close ritual, now encoded).
#
# Kill discipline (the whole point of this script):
#   * supervisor + warm_loop shells: plain TERM, they hold no device state;
#   * a PRE-init bench child (no warm-result.json.init marker): blocked in
#     the jax.devices() C call where SIGTERM is deferred — SIGKILL is safe
#     (a polling pre-init client holds no claim);
#   * a POST-init child (marker present): actively holds the device claim —
#     SIGTERM + bounded wait so its handler can unwind the PJRT client (a
#     SIGKILL here wedges the chip for the driver's bench).
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
INIT_MARKER="$REPO/.bench/warm-result.json.init"

pids_of() { ps -eo pid,args | grep "$1" | grep -v grep | awk '{print $1}'; }

for pat in "[w]hile ! bash scripts/warm_loop.sh" "[w]arm_loop.sh /tmp"; do
  for pid in $(pids_of "$pat"); do
    echo "TERM shell $pid"
    kill "$pid" 2>/dev/null
  done
done

for pat in "[b]ench.py --tpu-child" "[w]arm_kernels.py" \
           "[o]nchip_evidence.sh" "[t]est_mr.sh" "[w]cstream"; do
  for pid in $(pids_of "$pat"); do
    if [ -f "$INIT_MARKER" ] || [ "$pat" != "[b]ench.py --tpu-child" ]; then
      echo "TERM $pid ($pat) + grace"
      kill "$pid" 2>/dev/null
      for _ in $(seq 1 25); do
        kill -0 "$pid" 2>/dev/null || break
        sleep 1
      done
      if kill -0 "$pid" 2>/dev/null; then
        echo "  still alive after 25s: KILL $pid (accepting wedge risk" \
             "over leaking a claim holder into the driver's window)"
        kill -9 "$pid" 2>/dev/null
      fi
    else
      echo "KILL pre-init child $pid (no claim held)"
      kill -9 "$pid" 2>/dev/null
    fi
  done
done

echo "teardown complete; remaining matching processes:"
ps -eo pid,args | grep -E "[w]arm_loop|[b]ench.py --tpu-child|[o]nchip" || true
