#!/usr/bin/env bash
# End-of-round teardown: stop the warm/evidence automation so the
# driver's bench has the single-tenant chip to itself (BASELINE.md
# round-3 close ritual, now encoded).
#
# Kill discipline (the whole point of this script):
#   * supervisor shells (warm_loop / device_watch / bench_window_loop):
#     plain TERM, they hold no device state — and they go FIRST, since a
#     live supervisor respawns a fresh bench the moment its current one
#     dies (device_watch.sh runs bench1 then bench2; warm_loop retries);
#   * a PRE-init bench child (no fresh .init marker): blocked in the
#     jax.devices() C call where SIGTERM is deferred — SIGKILL is safe
#     (a polling pre-init client holds no claim);
#   * a POST-init child (marker written after the process started):
#     actively holds the device claim — SIGTERM + bounded wait so its
#     handler can unwind the PJRT client (a SIGKILL here wedges the chip
#     for the driver's bench).
set -u

# True process start time in epoch seconds: boot time + starttime ticks.
# (/proc/<pid> dentry timestamps are NOT usable — they reflect the first
# lookup, often this very script's ps, not the process start.)  The comm
# field can contain spaces/parens, so strip through the last ')' first;
# starttime is overall field 22 = field 20 after pid+comm are removed.
proc_start_epoch() {  # $1 = pid; prints epoch or fails if process gone
  local btime rest ticks
  btime=$(awk '/^btime/{print $2}' /proc/stat)
  rest=$(sed 's/.*) //' "/proc/$1/stat" 2>/dev/null) || return 1
  ticks=$(echo "$rest" | awk '{print $20}')
  [ -n "$ticks" ] || return 1
  echo $(( btime + ticks / $(getconf CLK_TCK) ))
}

# Post-init = THIS child's own marker was written during its lifetime.
# A child's argv is "... bench.py --tpu-child <result_path>"; the marker
# is <result_path>.init, touched once jax.devices() returns.  Completed
# runs leave markers behind (cleared only at the next attempt's start),
# so existence alone proves nothing — mtime must be >= process start;
# and another child's marker (warm vs tpu result paths) must not vouch
# for this one.
post_init() {  # $1 = pid
  local started rpath m
  started=$(proc_start_epoch "$1") || return 0  # gone: TERM path, harmless
  rpath=$(tr '\0' '\n' < "/proc/$1/cmdline" 2>/dev/null | tail -n 1)
  case "$rpath" in
    */*) m="$rpath.init" ;;
    *)   return 0 ;;  # argv unreadable: assume claim held (safe side)
  esac
  [ -f "$m" ] && [ "$(stat -c %Y "$m")" -ge "$started" ]
}

pids_of() { ps -eo pid,args | grep "$1" | grep -v grep | awk '{print $1}'; }

# Phase 1 — supervisor/respawner shells, parents before anything else.
for pat in "[w]hile ! bash scripts/warm_loop.sh" "[w]arm_loop.sh /tmp" \
           "[d]evice_watch.sh" "[b]ench_window_loop.sh"; do
  for pid in $(pids_of "$pat"); do
    echo "TERM shell $pid ($pat)"
    kill "$pid" 2>/dev/null
  done
done

# Phase 2 — bench drivers before their children: a live `python bench.py`
# driver respawns a fresh tpu-child when its current one dies (bench.py
# retry loop), so killing children first would race a respawn past this
# scan.  The bounded wait below confirms each parent is gone before the
# child pattern runs.
# "[i]mport jax" catches device_watch.sh's standalone JAX probe
# (`timeout 300 python -c "import jax; ..."`); "[p]robe_tunnel.py"
# catches onchip_evidence.sh's wire probe — both hold a claim once init
# returns and match no other pattern here.
for pat in "[p]ython bench.py" "[b]ench.py --tpu-child" "[w]arm_kernels.py" \
           "[o]nchip_evidence.sh" "[t]est_mr.sh" "[w]cstream" \
           "[i]mport jax" "[p]robe_tunnel.py"; do
  for pid in $(pids_of "$pat"); do
    if [ "$pat" = "[b]ench.py --tpu-child" ] && ! post_init "$pid"; then
      echo "KILL pre-init child $pid (no claim held)"
      kill -9 "$pid" 2>/dev/null
    else
      echo "TERM $pid ($pat) + grace"
      kill "$pid" 2>/dev/null
      for _ in $(seq 1 25); do
        kill -0 "$pid" 2>/dev/null || break
        sleep 1
      done
      if kill -0 "$pid" 2>/dev/null; then
        echo "  still alive after 25s: KILL $pid (accepting wedge risk" \
             "over leaking a claim holder into the driver's window)"
        kill -9 "$pid" 2>/dev/null
      fi
    fi
  done
done

echo "teardown complete; remaining matching processes:"
ps -eo pid,args | grep -E \
  "[w]arm_loop|[d]evice_watch|[b]ench_window_loop|[b]ench.py|[o]nchip|[w]arm_kernels|[w]cstream|[i]mport jax|[p]robe_tunnel" \
  || true
