#!/usr/bin/env python
"""Probe the axon tunnel's transfer characteristics (the bench's real wall).

This is the consolidated form of the round-3 exploration that drove the
corpus_wc design; its findings (2026-07-29, single run each — the tunnel's
bandwidth varies >10x between moments):

* H2D: ~60-80 ms per-call latency at any size; single-shot bandwidth
  20-150 MB/s and noisy; MANY SMALL ASYNC PUTS PIPELINE (16 x 1 MiB
  observed at 1.2 GB/s once, 29 MB/s under congestion) — hence
  corpus_wc uploads the corpus as per-file 2 MiB pieces, all dispatched
  before any sync.
* D2H: ~20-25 MB/s sustained regardless of piecing or array rank, ~0.1 s
  latency per pull, plus a ~0.5-2.8 s one-time first-pull cost per
  process — hence corpus_wc returns ONE contiguous 1-D uint32 buffer of
  ~8 B per unique word (position-coded; the host re-slices spellings from
  its own corpus copy) and bench.py warms the D2H path before timing.
* np.asarray(dev_arr) caches the value on the array (jax _npy_value):
  measuring a second pull of the SAME array measures the cache, not the
  wire.  Every D2H sample here uses a fresh kernel output.
* Two concurrent clients wedge the device claim; a SIGKILLed client can
  leave it wedged for a long time.  NEVER run this while anything else
  (bench, another probe) is on the chip.

Usage: python scripts/probe_tunnel.py [--mb 8]
"""
import argparse
import time

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=8)
    args = ap.parse_args()
    n = args.mb << 20

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"devices={jax.devices()}", flush=True)
    incr = jax.jit(lambda x, c: x + c)

    # one-time D2H warm (first pull in a process pays ~0.5-2.8 s extra)
    w = incr(jax.device_put(np.arange(256, dtype=np.uint32), dev),
             jnp.uint32(1))
    t0 = time.perf_counter()
    np.asarray(w)
    print(f"first-D2H warm: {time.perf_counter() - t0:.3f}s", flush=True)

    # H2D single-shot vs pieced-async
    host = np.random.randint(0, 255, size=n, dtype=np.uint8)
    t0 = time.perf_counter()
    jax.device_put(host, dev).block_until_ready()
    t = time.perf_counter() - t0
    print(f"H2D {args.mb} MiB single: {t:.3f}s  {n / t / 1e6:8.1f} MB/s",
          flush=True)

    pieces = [host[i << 20:(i + 1) << 20] for i in range(args.mb)]
    t0 = time.perf_counter()
    ds = jax.device_put(pieces, dev)
    for d in ds:
        d.block_until_ready()
    t = time.perf_counter() - t0
    print(f"H2D {args.mb} x 1 MiB async: {t:.3f}s  {n / t / 1e6:8.1f} MB/s",
          flush=True)

    # Sequential (sync) piecing: one transfer in flight at a time.  On a
    # DEGRADED tunnel the async pipeline has measured 10x SLOWER than one
    # single-shot put (2026-07-31: 0.6 vs 5.8 MB/s) — concurrent streams
    # appear to thrash the constrained link; this row shows whether
    # serializing the pieces recovers the single-shot rate, which decides
    # if corpus_wc needs a probe-selected upload mode.
    t0 = time.perf_counter()
    for p in pieces:
        jax.device_put(p, dev).block_until_ready()
    t = time.perf_counter() - t0
    print(f"H2D {args.mb} x 1 MiB sync : {t:.3f}s  {n / t / 1e6:8.1f} MB/s",
          flush=True)

    # 2 MiB async pieces — corpus_wc's actual upload geometry (pack_pieces
    # caps piece_size at 1 << 21), so this row is the bench's real H2D rate.
    if args.mb >= 2:
        p2 = [host[i << 21:(i + 1) << 21] for i in range(args.mb // 2)]
        n2 = len(p2) << 21  # bytes actually transferred (odd --mb drops one)
        t0 = time.perf_counter()
        ds = jax.device_put(p2, dev)
        for d in ds:
            d.block_until_ready()
        t = time.perf_counter() - t0
        print(f"H2D {len(p2)} x 2 MiB async: {t:.3f}s  "
              f"{n2 / t / 1e6:8.1f} MB/s", flush=True)

    # D2H of a fresh kernel output (no _npy_value cache)
    src = jax.device_put(host[:n // 4].view(np.uint32), dev)
    src.block_until_ready()
    out = incr(src, jnp.uint32(3))
    out.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(out)
    t = time.perf_counter() - t0
    print(f"D2H {args.mb // 4} MiB kernel-out: {t:.3f}s  "
          f"{(n // 4) / t / 1e6:8.1f} MB/s", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
