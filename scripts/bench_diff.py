#!/usr/bin/env python
"""Diff two BENCH_r*.json verdicts against per-metric thresholds.

Perf work on this repo has been eyeball-audited across the BENCH_r0*
trajectory; this is the gate that makes a regression a table row
instead of an archaeology project.  No jax, no repo imports — it reads
the checked-in artifacts alone, so it runs in CI and on any laptop.

Inputs: two artifact paths, or ``--dir`` to auto-pick the two newest
``BENCH_r<NN>.json`` by round number (the matching ``MULTICHIP_r<NN>``
twins are diffed too when both exist).  Artifacts may be the driver's
wrapper (``{"parsed": {...}}``) or a raw bench verdict line.

Each shared top-level numeric key is classified by the GATES table:

* **higher-better** (throughputs, ratios): regress when the new value
  drops more than the threshold fraction below the old;
* **lower-better** (overheads, resume gap): regress when it RISES more
  than the threshold fraction;
* **bool** (parity, multichip ``ok``): regress on true→false;
* everything else is an **info** row — shown, never gated.

Missing keys compare as ``unknown``, never as a regression: rows are
added over time and old artifacts legitimately lack them
(backfill-tolerant by construction).  A zero/absent old value is also
``unknown`` — no division by a failed round.  Provenance blocks
(``bench.py`` stamps git sha / jax version / platform / hostname /
x64) are printed as attribution, not compared.

Exit: 0 when no gated metric regressed, 1 otherwise (CI runs this as
an advisory, non-failing step; a release gate can take the rc as-is).

Usage:
  python scripts/bench_diff.py [--dir .] [OLD.json NEW.json]
      [--threshold PATTERN=FRACTION ...] [--top N]
"""

from __future__ import annotations

import argparse
import fnmatch
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: (key pattern, kind, threshold-fraction).  First match wins — keep
#: specific patterns above general ones.  kind: "higher" = bigger is
#: better, "lower" = smaller is better, "bool" = regress on
#: true→false.
GATES: List[Tuple[str, str, float]] = [
    ("*_parity", "bool", 0.0),
    ("ok", "bool", 0.0),
    ("counts_exact", "bool", 0.0),
    ("value", "higher", 0.10),
    ("vs_baseline", "higher", 0.10),
    ("*_vs_oracle", "higher", 0.10),
    ("*_vs_native", "higher", 0.10),
    ("*_vs_python", "higher", 0.10),
    ("framework_vs_native", "higher", 0.10),
    ("*_mbps", "higher", 0.10),
    ("*_overhead_pct", "lower", 0.50),
    ("resume_gap_s", "lower", 1.00),
    # The serving daemon's amortized boot cost (ISSUE 11): the *_mbps
    # and *_parity patterns above already gate its throughput and
    # per-tenant parity keys; the warm cost gates lower-better here.
    ("serve_amortized_warm_s", "lower", 1.00),
    # Serving QoS (ISSUE 19): the packed-grep arm's tail latency is
    # THE tentpole number — it gates lower-better so a packing or
    # admission regression that doubles p99 fails the diff (the
    # *_parity pattern above already gates serve_lat_parity; the tmux
    # control arm and p50s ride ungated as context).
    ("serve_pack_p99_s", "lower", 1.00),
    # Compressed wire + parallel ingest (ISSUE 13): codec ratios and
    # the readahead hit rate regress when they DROP (a codec change
    # that stops shrinking the shuffle payload, a pool change that
    # stops running ahead), delta-checkpoint payload bytes when they
    # RISE (compression silently off, delta windows ballooning).  The
    # *_parity patterns above already gate wire/ingest correctness.
    ("wire_ratio", "higher", 0.10),
    ("wire_upload_ratio", "higher", 0.10),
    ("ckpt_compress_ratio", "higher", 0.10),
    ("readahead_hit_pct", "higher", 0.10),
    ("ckpt_delta_bytes*", "lower", 0.50),
    # Plan layer (ISSUE 14): the *_mbps/*_parity patterns above already
    # gate the chained-vs-staged throughputs and byte parity; the
    # device handoff's host-crossing bytes gate lower-better (a relay
    # regression quietly re-introducing host round-trips), and the
    # zero-copy invariant is boolean (old=0 bytes reads "unknown" under
    # the numeric rule, so the bool carries the gate).
    ("plan_zero_copy", "bool", 0.0),
    ("plan_intermediate_bytes", "lower", 0.50),
    # Speculative execution (ISSUE 15): the *_mbps/*_parity patterns
    # above already gate both arms' throughput and oracle parity.
    # Exactly-once is a BOOL gate (the plan_zero_copy precedent: the
    # healthy old duplicate-commit count is 0, which the numeric rule
    # reads as "unknown" and never gates — the bool regresses on
    # true→false, i.e. the first duplicate commit ever seen);
    # backup_fired/resumed regress when they stop happening at all
    # (1→0 = the dispatcher or the chain adoption went dark; a 2→1
    # count wobble stays under the 90% threshold).
    ("spec_exactly_once", "bool", 0.0),
    ("spec_backup_fired", "higher", 0.90),
    ("spec_resumed", "higher", 0.90),
    # Elastic dataflow (ISSUE 16): the *_mbps pattern above already
    # gates plan_pipelined_mbps and spec_resplit_mbps; the re-split
    # evidence counters regress when they stop happening at all
    # (1→0 = the trigger or the sub-shard dispatcher went dark — the
    # spec_backup_fired precedent).  plan_overlap_s stays info-only:
    # more overlap is better only relative to the stage walls, and the
    # pipelined throughput gate already owns that trade.
    ("spec_resplits", "higher", 0.90),
    ("spec_subshards", "higher", 0.90),
    # Network data plane (ISSUE 17): the *_mbps/*_parity patterns above
    # already gate net_shuffle_mbps/net_fs_mbps and net_parity.
    # net_ratio gates higher-better explicitly (it does not match the
    # wire_ratio patterns): a drop means shuffle payloads stopped
    # crossing the link through the line codec.  locality_hits
    # regresses when placement goes dark entirely (the
    # spec_backup_fired precedent: 1→0 gates, count wobble does not).
    ("net_ratio", "higher", 0.10),
    ("locality_hits", "higher", 0.90),
    # Overlapped shuffle (ISSUE 18): the *_mbps/*_parity patterns above
    # already gate net_pipelined_mbps/net_serial_mbps and
    # net_pipeline_parity.  net_overlap_s regresses when the prefetch
    # pool stops hiding wire time at all (the spec_backup_fired
    # precedent: >0 → ~0 gates, wobble under the 90% threshold does
    # not); net_fetch_wait_s stays info-only — the throughput gate
    # already owns that trade.
    ("net_overlap_s", "higher", 0.90),
    # Replicated control plane (ISSUE 20): the *_mbps/*_parity patterns
    # above already gate the single/group/chaos arm throughputs and
    # oracle parity, and *_overhead_pct gates the majority-commit cost.
    # The failover wall is THE tentpole number — lower-better, so an
    # election-timeout or log-replay regression that doubles the
    # leaderless window fails the diff.  Exactly-once across terms is a
    # BOOL gate (the spec_exactly_once precedent: the healthy old
    # duplicate count is 0, which the numeric rule reads as "unknown" —
    # the bool regresses on the first cross-term duplicate ever seen).
    ("replica_failover_s", "lower", 1.00),
    ("replica_exactly_once", "bool", 0.0),
]


def classify(key: str,
             overrides: List[Tuple[str, float]]) -> Tuple[str, float]:
    """(kind, threshold) for one metric KEY.  The gate DIRECTION always
    comes from the built-in table (matched against the key, never
    against an override pattern — an override must not silently flip a
    lower-better gate to higher-better); an override only replaces the
    threshold, and promotes an otherwise-info metric to higher-better."""
    kind, thr = "info", 0.0
    for pat, k, t in GATES:
        if fnmatch.fnmatch(key, pat):
            kind, thr = k, t
            break
    for pat, frac in overrides:
        if fnmatch.fnmatch(key, pat):
            if kind == "info":
                kind = "higher"
            thr = frac
            break
    return kind, thr


def load(path: str) -> Dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    return parsed if isinstance(parsed, dict) else doc


def _round_no(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def discover(d: str) -> Tuple[str, str]:
    files = sorted(glob.glob(os.path.join(d, "BENCH_r*.json")),
                   key=_round_no)
    files = [f for f in files if _round_no(f) >= 0]
    if len(files) < 2:
        sys.exit(f"bench_diff: need two BENCH_r*.json under {d}, "
                 f"found {len(files)}")
    return files[-2], files[-1]


def fmt(v) -> str:
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def diff_table(old: Dict, new: Dict, overrides, out) -> Tuple[int, int]:
    """Print the per-metric table; returns (regressions, gated)."""
    keys = [k for k in list(old) + [k for k in new if k not in old]
            if k not in ("provenance",)]
    rows = []
    regressions = gated = 0
    for k in keys:
        ov, nv = old.get(k), new.get(k)
        if not (isinstance(ov, (int, float, bool)) or
                isinstance(nv, (int, float, bool))):
            continue  # nested dicts (phases/spans), strings: not gated
        kind, thr = classify(k, overrides)
        if kind == "bool":
            if ov is None or nv is None:
                verdict, delta = "unknown", "?"
            elif bool(ov) and not bool(nv):
                verdict, delta = "REGRESS", "true->false"
                regressions += 1
                gated += 1
            else:
                verdict, delta = "ok", f"{ov}->{nv}"
                gated += 1
            rows.append((k, ov, nv, delta, "true", verdict))
            continue
        if not isinstance(ov, (int, float)) or \
                not isinstance(nv, (int, float)) or \
                isinstance(ov, bool) or isinstance(nv, bool):
            rows.append((k, ov, nv, "?", "-", "unknown"))
            continue
        delta = f"{100.0 * (nv - ov) / ov:+.1f}%" if ov else "?"
        if kind == "info":
            rows.append((k, ov, nv, delta, "-", "info"))
            continue
        if ov <= 0:
            # A zeroed old value is a failed round, not a baseline.
            rows.append((k, ov, nv, delta, "-", "unknown"))
            continue
        gated += 1
        if kind == "higher":
            bad = nv < ov * (1.0 - thr)
            gate = f">-{thr:.0%}"
        else:
            bad = nv > ov * (1.0 + thr)
            gate = f"<+{thr:.0%}"
        if bad:
            regressions += 1
        rows.append((k, ov, nv, delta, gate, "REGRESS" if bad else "ok"))
    print(f"  {'metric':<28} {'old':>10} {'new':>10} {'delta':>12} "
          f"{'gate':>8}  verdict", file=out)
    order = {"REGRESS": 0, "ok": 1, "info": 2, "unknown": 3}
    for k, ov, nv, delta, gate, verdict in sorted(
            rows, key=lambda r: (order.get(r[5], 9), r[0])):
        print(f"  {k:<28} {fmt(ov) if ov is not None else '?':>10} "
              f"{fmt(nv) if nv is not None else '?':>10} {delta:>12} "
              f"{gate:>8}  {verdict}", file=out)
    return regressions, gated


def _provenance_line(doc: Dict) -> Optional[str]:
    p = doc.get("provenance")
    if not isinstance(p, dict):
        return None
    return " ".join(f"{k}={p[k]}" for k in ("git_sha", "jax_version",
                                            "platform", "hostname",
                                            "x64", "utc") if k in p)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="OLD.json NEW.json (default: the two newest "
                         "BENCH_r*.json in --dir)")
    ap.add_argument("--dir", default=".",
                    help="artifact directory for auto-discovery "
                         "(default .)")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="PATTERN=FRACTION",
                    help="override a gate, e.g. stream_mbps=0.25; "
                         "repeatable; prepended to the built-in table")
    args = ap.parse_args(argv)

    overrides: List[Tuple[str, float]] = []
    for spec in args.threshold:
        pat, _, frac = spec.partition("=")
        if not frac:
            ap.error(f"--threshold wants PATTERN=FRACTION, got {spec!r}")
        overrides.append((pat, float(frac)))

    if len(args.paths) == 2:
        old_path, new_path = args.paths
    elif not args.paths:
        old_path, new_path = discover(args.dir)
    else:
        ap.error("give exactly two paths, or none with --dir")

    out = sys.stdout
    total_regressions = 0
    print(f"== bench_diff: {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)} ==", file=out)
    old, new = load(old_path), load(new_path)
    for tag, doc in (("old", old), ("new", new)):
        line = _provenance_line(doc)
        if line:
            print(f"  {tag} provenance: {line}", file=out)
    if old.get("metric") != new.get("metric"):
        print(f"  NOTE: metric changed "
              f"({old.get('metric')} -> {new.get('metric')}) — "
              f"numbers may not be like-for-like", file=out)
    r, g = diff_table(old, new, overrides, out)
    total_regressions += r
    print(f"  -> {'REGRESS' if r else 'PASS'} "
          f"({r} regressions over {g} gated metrics)", file=out)

    # The MULTICHIP twins of the same rounds, when both exist.
    ro, rn = _round_no(old_path), _round_no(new_path)
    d = os.path.dirname(os.path.abspath(old_path))
    mco = os.path.join(d, f"MULTICHIP_r{ro:02d}.json")
    mcn = os.path.join(d, f"MULTICHIP_r{rn:02d}.json")
    if ro >= 0 and rn >= 0 and os.path.exists(mco) and os.path.exists(mcn):
        print(f"\n== bench_diff: {os.path.basename(mco)} -> "
              f"{os.path.basename(mcn)} ==", file=out)
        r, g = diff_table(load(mco), load(mcn), overrides, out)
        total_regressions += r
        print(f"  -> {'REGRESS' if r else 'PASS'} "
              f"({r} regressions over {g} gated metrics)", file=out)

    return 1 if total_regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
