"""Measure compile + steady-state cost of the kernel's building blocks on
the real chip, to direct optimization (not part of the test suite)."""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jaxcache")
sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

print("devices", jax.devices(), flush=True)
rng = np.random.default_rng(0)


def bench(name, fn, *args):
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    print(f"{name}: compile {compile_s:.2f}s steady {min(times)*1e3:.1f}ms",
          flush=True)


for n in (1 << 18, 1 << 20):
    keys = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    pay = [jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
           for _ in range(5)]

    bench(f"sort1op n={n}", jax.jit(lambda x: lax.sort((x,), num_keys=1)),
          keys)
    bench(f"sort2op n={n}",
          jax.jit(lambda x, p: lax.sort((x, p), num_keys=1)), keys, pay[0])
    bench(f"sort6op n={n}",
          jax.jit(lambda x, *p: lax.sort((x,) + p, num_keys=4)), keys, *pay)
    bench(f"argsort n={n}", jax.jit(lambda x: jnp.argsort(x)), keys)

    mask = jnp.asarray(rng.random(n) < 0.3)
    bench(f"nonzero n={n}",
          jax.jit(lambda m: jnp.nonzero(m, size=n // 2, fill_value=0)), mask)
    bench(f"cumsum n={n}", jax.jit(lambda m: jnp.cumsum(m.astype(jnp.int32))),
          mask)

    idx = jnp.asarray(rng.integers(0, n, size=(n // 2, 16), dtype=np.int32))
    data = jnp.asarray(rng.integers(0, 255, size=n, dtype=np.uint8))
    bench(f"gather {n//2}x16", jax.jit(lambda d, i: d[i]), data, idx)

    seg = jnp.asarray(np.sort(rng.integers(0, n // 2, size=n,
                                           dtype=np.int32)))
    vals = jnp.asarray(rng.integers(0, 100, size=n, dtype=np.int32))
    bench(f"segsum n={n}",
          jax.jit(lambda v, s: jax.ops.segment_sum(v, s, num_segments=n // 2)),
          vals, seg)
