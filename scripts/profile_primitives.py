"""Measure compile + steady-state cost of the kernel's building blocks on
the real chip, to direct optimization (not part of the test suite)."""
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jaxcache")
sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

print("devices", jax.devices(), flush=True)
rng = np.random.default_rng(0)
N = 1 << 20


def bench(name, fn, *args):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    print(f"{name}: compile {compile_s:.2f}s steady {min(times)*1e3:.1f}ms",
          flush=True)


keys = jnp.asarray(rng.integers(0, 2**32, size=N, dtype=np.uint32))
pay = [jnp.asarray(rng.integers(0, 2**32, size=N, dtype=np.uint32))
       for _ in range(5)]
chunk = jnp.asarray(rng.integers(0, 128, size=2 * N, dtype=np.uint8))
idx = jnp.asarray(rng.integers(0, 2 * N, size=(N, 16), dtype=np.int32))
mask = jnp.asarray(rng.random(2 * N) < 0.3)

bench("sort1op 1M", jax.jit(lambda x: lax.sort((x,), num_keys=1)), keys)
bench("sort6op 1M",
      jax.jit(lambda x, *p: lax.sort((x,) + p, num_keys=4)), keys, *pay)
bench("gather 1Mx16", jax.jit(lambda d, i: d[i]), chunk, idx)
bench("nonzero 2M->1M",
      jax.jit(lambda m: jnp.nonzero(m, size=N, fill_value=0)), mask)
bench("cumsum 2M", jax.jit(lambda m: jnp.cumsum(m.astype(jnp.int32))), mask)

from dsi_tpu.ops.wordcount import count_words_kernel  # noqa: E402

bench("full kernel 2M chunk",
      lambda c: count_words_kernel(c, max_word_len=16, u_cap=1 << 17), chunk)
