#!/usr/bin/env bash
# Build the native runtime components (dsi_tpu/native/*.cpp) into build/.
# The framework works without them (pure-Python fallbacks); when present
# they accelerate the host-side data plane.
set -eu
REPO=$(cd "$(dirname "$0")/.." && pwd)
mkdir -p "$REPO/build"
# Build to a temp name + atomic rename: concurrent workers may trigger the
# lazy first-use build simultaneously, and no process may ever dlopen a
# half-written .so.
TMP="$REPO/build/.libkvcodec.$$.tmp"
g++ -O2 -Wall -shared -fPIC -std=c++17 \
    -o "$TMP" "$REPO/dsi_tpu/native/kvcodec.cpp" \
    "$REPO/dsi_tpu/native/wcjob.cpp"
mv -f "$TMP" "$REPO/build/libkvcodec.so"
echo "built $REPO/build/libkvcodec.so"
