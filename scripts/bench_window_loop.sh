#!/usr/bin/env bash
# Sample the bench across tunnel-congestion windows.
#
# The axon wire's bandwidth varies >10x between moments (probe_tunnel.py
# header; BASELINE.md).  One bench process = one ~minutes-long window, so a
# single run can land entirely inside a congested period and understate the
# machine.  This loop re-runs `python bench.py` every PERIOD seconds until
# DEADLINE, appending each JSON verdict (stamped) to $OUT/samples.jsonl —
# the round report then cites the best window alongside the distribution.
#
# Single-tenant discipline: start this ONLY when nothing else is on the
# chip (after scripts/onchip_evidence.sh completes), and tear it down
# before the driver's end-of-round bench (scripts/teardown_watchers.sh
# kills it: the pkill patterns there match bench.py and this script name).
# Each bench is TERM'd on timeout with a 30 s `-k` SIGKILL backstop — the
# backstop accepts the wedge risk over leaking a hung claim holder, same
# trade as warm_loop.sh; DSI_CHILD_INIT_TIMEOUT converts an init hang
# into a clean error verdict that the loop just records and sleeps past.
#
# Usage: bash scripts/bench_window_loop.sh [OUT=/tmp/rebench] [BUDGET_S=14400] [PERIOD_S=1200]
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO"
OUT=${1:-/tmp/rebench}
DEADLINE=$(( $(date +%s) + ${2:-14400} ))
PERIOD=${3:-1200}
mkdir -p "$OUT"
n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n + 1))
  start=$(date +%s)
  echo "$(date -u +%H:%M:%S) sample $n" >> "$OUT/log"
  line=$(DSI_CHILD_INIT_TIMEOUT=150 DSI_BENCH_STREAM_MB=0 \
         timeout -k 30s 2700s python bench.py 2>> "$OUT/err.log")
  rc=$?
  # A TERM'd bench can die with a partial (unflushed) stdout prefix —
  # only splice stdout in verbatim when it parses as JSON, else the
  # samples file itself stops being JSONL.
  if [ -n "$line" ] && echo "$line" | python -c \
      "import json,sys; json.loads(sys.stdin.read())" 2>/dev/null; then
    printf '{"ts":"%s","rc":%d,"sample":%d,"verdict":%s}\n' \
      "$(date -u +%FT%TZ)" "$rc" "$n" "$line" >> "$OUT/samples.jsonl"
  else
    printf '{"ts":"%s","rc":%d,"sample":%d,"verdict":null}\n' \
      "$(date -u +%FT%TZ)" "$rc" "$n" >> "$OUT/samples.jsonl"
  fi
  # Sleep out the remainder of the period (a long bench eats into it),
  # but never past the deadline — the loop must end on budget, not up to
  # a full idle period later.
  now=$(date +%s)
  rest=$(( PERIOD - (now - start) ))
  [ "$rest" -gt $(( DEADLINE - now )) ] && rest=$(( DEADLINE - now ))
  [ "$rest" -gt 0 ] && sleep "$rest"
done
echo "$(date -u +%H:%M:%S) done after $n samples" >> "$OUT/log"
