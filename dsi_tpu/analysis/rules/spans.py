"""span-discipline: spans are context managers with pinned names.

The tracer's accounting depends on two conventions PR 6 established and
nothing enforced:

* a span is opened ONLY as a ``with`` context manager — a bare
  ``span(...)`` call never closes, so its duration never lands in the
  buffer, the stats sink never accumulates, and the stage histogram
  silently under-counts (the exact bug class the span/stats
  reconciliation test can only catch for instrumented paths);
* span names (and explicit ``lane=`` tags) come from the pinned schema
  (``obs.trace.SPAN_NAMES`` / ``obs.trace.LANES``) — an off-schema
  name falls out of every rollup, tracecat table, and histogram.

Checked: calls to ``span``/``_span`` (the engines' import alias),
``<x>.span(...)`` on a tracer, and ``record_span`` name/lane literals.
Non-literal names are skipped (the ``utils/tracing`` mirror path
forwards variables by design).  ``obs/trace.py`` and
``obs/__init__.py`` — the definition sites whose helpers *return*
spans — are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted,
)
from dsi_tpu.obs.trace import LANES, SPAN_NAMES

_EXEMPT = ("dsi_tpu/obs/trace.py", "dsi_tpu/obs/__init__.py")


def _is_span_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name in ("span", "_span") or name.endswith(".span")


class SpanDisciplineRule(Rule):
    rule_id = "span-discipline"
    summary = "span not context-managed, or off-schema span/lane name"

    def applies(self, rel: str) -> bool:
        return not rel.endswith(_EXEMPT)

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        with_exprs: Set[int] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_exprs.add(id(item.context_expr))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            is_span = _is_span_call(node)
            is_record = (name == "record_span"
                         or name.endswith(".record_span"))
            if not is_span and not is_record:
                continue
            if is_span and id(node) not in with_exprs:
                yield Finding(
                    module.rel, node.lineno, node.col_offset,
                    self.rule_id,
                    "span opened outside a `with` statement — it never "
                    "closes, so its duration is lost to the trace, the "
                    "stats sink, and the stage histograms")
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                sname = node.args[0].value
                if sname not in SPAN_NAMES:
                    yield Finding(
                        module.rel, node.lineno, node.col_offset,
                        self.rule_id,
                        f"span name {sname!r} is not in the pinned "
                        f"schema (obs.trace.SPAN_NAMES) — add it there "
                        f"(a schema change) or use a pinned stage name")
            for kw in node.keywords:
                if kw.arg == "lane" and isinstance(kw.value,
                                                   ast.Constant) \
                        and isinstance(kw.value.value, str) \
                        and kw.value.value not in LANES:
                    yield Finding(
                        module.rel, node.lineno, node.col_offset,
                        self.rule_id,
                        f"lane {kw.value.value!r} is not in the pinned "
                        f"lane taxonomy (obs.trace.LANES)")
