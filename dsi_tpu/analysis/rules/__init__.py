"""The dsicheck rule catalogue (one module per invariant family).

Rule ids (the ``allow[...]`` vocabulary)::

    donation-after-use   a donated buffer read after the donating call
    raw-write            a write bypassing the atomicio durable path
    lock-guard           a guarded attribute mutated outside its lock
    span-discipline      spans not context-managed / off-schema names
    metric-schema        engine stat keys missing from the registry
    jit-purity           time/random/env reads inside jit bodies
"""

from typing import List

from dsi_tpu.analysis.core import Rule
from dsi_tpu.analysis.rules.donation import DonationAfterUseRule
from dsi_tpu.analysis.rules.jitpure import JitPurityRule
from dsi_tpu.analysis.rules.lockguard import LockGuardRule
from dsi_tpu.analysis.rules.rawwrite import RawWriteRule
from dsi_tpu.analysis.rules.schema import MetricSchemaRule
from dsi_tpu.analysis.rules.spans import SpanDisciplineRule


def all_rules() -> List[Rule]:
    return [
        DonationAfterUseRule(),
        RawWriteRule(),
        LockGuardRule(),
        SpanDisciplineRule(),
        MetricSchemaRule(),
        JitPurityRule(),
    ]
