"""metric-schema: engine stat keys come from the one registry schema.

Before PR 6 each engine grew its own stats spellings and every consumer
re-learned them; ``obs/registry.py`` unified the READ side, but nothing
stopped a new engine key from drifting in unregistered — the schema
docstring and ``test_bench_contract.py`` were two hand-maintained
lists.  Now the registry owns one machine-readable key set
(``SCHEMA_KEYS`` = phases + counters + legacy spellings) and this rule
closes the write side: every string literal used as a stats-scope key
anywhere in the engine/device/ckpt/serve modules must be in it.

A "stats scope write" is any of::

    stats["k"] = / += ...        stats.setdefault("k", ...)
    st["k"] ... self.stats["k"] ... self._stats[...]
    _span(..., stats=stats, key="k")

where the receiver is a registered scope by construction: a name
assigned from ``metrics_scope(...)``, a parameter/attribute named
``stats``/``_stats``/``st``/``pstats``/``wave_stats``/
``pipeline_stats``, or ``self.stats``/``self._stats``.  Adding an
engine key is therefore a one-line schema change in
``obs/registry.py`` — which is exactly where the contract test and
every consumer will see it.

Since ISSUE 19 the rule also closes the serving daemon's /metrics
surface: any string literal carrying a ``dsi_serve_`` token must name a
series in ``obs/registry.py SERVE_SERIES`` (a truncated f-string head —
``f"dsi_serve_tenant_{k}..."`` — passes when it is a prefix of a
registered series).  Emitting a new serving series without registering
it is the same drift the stats-key half guards against, with the same
one-edit fix.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted,
    self_attr,
)
from dsi_tpu.obs.registry import LEGACY_ALIASES, SCHEMA_KEYS, SERVE_SERIES

#: Identifier spellings that denote an engine stats scope.
_STATS_NAMES = {"stats", "_stats", "st", "pstats", "wave_stats",
                "pipeline_stats"}

_ALLOWED = frozenset(SCHEMA_KEYS) | frozenset(LEGACY_ALIASES)

#: A serving-series token inside any string constant; f-string constant
#: heads truncate at the first interpolation, so a token is judged as a
#: prefix (``dsi_serve_`` alone — docstrings' ``dsi_serve_*`` prose —
#: trivially prefixes every series and stays clean).
_SERVE_TOKEN = re.compile(r"dsi_serve_[a-z0-9_]*")


def _serve_token_ok(tok: str) -> bool:
    return any(s == tok or s.startswith(tok) for s in SERVE_SERIES)


def _is_stats_recv(node: ast.AST, scope_names: Set[str],
                   nonscope: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        if node.id in nonscope:
            return False
        return node.id in _STATS_NAMES or node.id in scope_names
    attr = self_attr(node)
    if attr is not None:
        return attr in _STATS_NAMES
    return False


class MetricSchemaRule(Rule):
    rule_id = "metric-schema"
    summary = "stats key not in the registry schema (obs/registry.py)"

    def applies(self, rel: str) -> bool:
        # The registry defines the schema; the analysis rules and the
        # aotcache's module-level counters are not engine scopes.
        return not rel.endswith(("obs/registry.py",))

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        # Names assigned from metrics_scope(...) anywhere in the module.
        scope_names: Set[str] = set()
        # Module-level dict-literal globals (aotcache's process-wide
        # cache counters) are NOT engine scopes even when they happen
        # to be spelled `stats` — scopes are created per-run via
        # metrics_scope().
        nonscope: Set[str] = set()
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict):
                nonscope.update(t.id for t in node.targets
                                if isinstance(t, ast.Name))
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                cn = dotted(node.value.func)
                if cn.endswith("metrics_scope"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            scope_names.add(tgt.id)
        nonscope -= scope_names

        def bad(key: str) -> bool:
            return key not in _ALLOWED

        for node in ast.walk(module.tree):
            # Serving /metrics series: every dsi_serve_* token in any
            # string constant (f-string heads included — JoinedStr
            # parts are Constant nodes) must match SERVE_SERIES.
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                for tok in _SERVE_TOKEN.findall(node.value):
                    if not _serve_token_ok(tok):
                        yield Finding(
                            module.rel, node.lineno, node.col_offset,
                            self.rule_id,
                            f"serving series {tok!r} is not in the "
                            f"registry's SERVE_SERIES — register it in "
                            f"obs/registry.py or rename to a registered "
                            f"series")
            # stats["k"] = / += / del  (Store/Del contexts only: reads
            # of foreign dicts named `st` must not be judged)
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    _is_stats_recv(node.value, scope_names, nonscope) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                key = node.slice.value
                if bad(key):
                    yield self._finding(module, node, key)
            elif isinstance(node, ast.Call):
                fn = node.func
                # stats.setdefault("k", ...)
                if isinstance(fn, ast.Attribute) and \
                        fn.attr == "setdefault" and \
                        _is_stats_recv(fn.value, scope_names, nonscope) and \
                        node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    key = node.args[0].value
                    if bad(key):
                        yield self._finding(module, node, key)
                # stats.update({"k": ..., ...})
                if isinstance(fn, ast.Attribute) and \
                        fn.attr == "update" and \
                        _is_stats_recv(fn.value, scope_names, nonscope) and \
                        node.args and isinstance(node.args[0], ast.Dict):
                    for k in node.args[0].keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str) and bad(k.value):
                            yield self._finding(module, k, k.value)
                # _span(..., stats=X, key="k")
                kws = {kw.arg: kw.value for kw in node.keywords}
                if "stats" in kws and "key" in kws and \
                        _is_stats_recv(kws["stats"], scope_names, nonscope) and \
                        isinstance(kws["key"], ast.Constant) and \
                        isinstance(kws["key"].value, str):
                    key = kws["key"].value
                    if bad(key):
                        yield self._finding(module, node, key)

    def _finding(self, module: SourceFile, node: ast.AST,
                 key: str) -> Finding:
        return Finding(
            module.rel, node.lineno, node.col_offset, self.rule_id,
            f"stats key {key!r} is not in the registry schema — add it "
            f"to obs/registry.py SCHEMA_KEYS (one source of truth) or "
            f"rename to a schema key")
