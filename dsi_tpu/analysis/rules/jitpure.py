"""jit-purity: no wall-clock / randomness / environment inside jit.

A jit-compiled body executes at TRACE time on abstract values and is
then replayed from the compiled executable forever after — a
``time.time()``, ``random.random()``, ``np.random...`` draw, or
``os.environ`` read inside one is evaluated ONCE at compile and baked
into the program as a constant.  With the persistent AOT cache the
constant then survives across processes and machines, which turns
"nondeterminism" into the worse failure: *stale* determinism that
changes whenever the cache misses.  (Host-side numpy RNG inside a jit
body is also a parity trap: the mesh A/B harness diffing two runs
bit-for-bit assumes the program text is the only input.)

The rule finds functions that are jit targets — decorated ``@jax.jit``
/ ``@partial(jax.jit, ...)``, or referenced by name in ``jax.jit(f)``
/ ``cached_compile("...", f, ...)`` / ``is_persisted("...", f, ...)``
calls (optionally wrapped in ``x64_scoped``) — and flags calls/reads
of: ``time.*``, ``random.*``, ``np.random.*``/``numpy.random.*``,
``os.environ``/``os.getenv``, ``datetime.now``/``utcnow``,
``uuid.*``, and ``open``/``input``.  Helper calls are not chased
(one level, documented); a deliberate exception is annotated
``# dsicheck: allow[jit-purity] <why>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted,
)

_JIT_CALLS = ("jax.jit", "jit")
_COMPILE_CALLS = ("cached_compile", "aotcache.cached_compile",
                  "is_persisted", "aotcache.is_persisted")
_WRAPPERS = ("x64_scoped", "jaxcompat.x64_scoped")

_BANNED_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.", "uuid.",
    "secrets.",
)
_BANNED_EXACT = ("os.getenv", "os.urandom", "datetime.now",
                 "datetime.utcnow", "datetime.datetime.now",
                 "datetime.datetime.utcnow", "open", "input")
_BANNED_ATTRS = ("os.environ",)


def _jit_target_names(tree: ast.Module) -> Set[str]:
    """Names of functions handed to jit/cached_compile in this module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name in _WRAPPERS and node.args:
            inner = node.args[0]
            if isinstance(inner, ast.Call):
                node = inner
                name = dotted(node.func)
        if name in _JIT_CALLS or name.endswith(
                tuple("." + j for j in _JIT_CALLS)):
            if node.args and isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
        elif name in _COMPILE_CALLS or name.endswith(
                tuple("." + c for c in _COMPILE_CALLS)):
            # cached_compile(name, fn, ...)
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Name):
                out.add(node.args[1].id)
    return out


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dotted(dec)
        if name in _JIT_CALLS or name.endswith((".jit",)):
            return True
        if isinstance(dec, ast.Call):
            cn = dotted(dec.func)
            if cn in _JIT_CALLS or cn.endswith((".jit",)):
                return True
            if cn in ("partial", "functools.partial") and dec.args:
                inner = dotted(dec.args[0])
                if inner in _JIT_CALLS or inner.endswith((".jit",)):
                    return True
    return False


class JitPurityRule(Rule):
    rule_id = "jit-purity"
    summary = "time/random/env read inside a jit-compiled body"

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        targets = _jit_target_names(module.tree)
        fns: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, []).append(node)
        checked: Set[int] = set()
        for fn_list in fns.values():
            for fn in fn_list:
                if id(fn) in checked:
                    continue
                if fn.name in targets or _is_jit_decorated(fn):
                    checked.add(id(fn))
                    yield from self._check_body(module, fn)

    def _check_body(self, module: SourceFile,
                    fn: ast.FunctionDef) -> Iterator[Finding]:
        for node in ast.walk(fn):
            bad = None
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in _BANNED_EXACT or \
                        name.startswith(_BANNED_PREFIXES):
                    bad = f"{name}()"
            elif isinstance(node, (ast.Attribute, ast.Subscript)):
                name = dotted(node if isinstance(node, ast.Attribute)
                              else node.value)
                if name in _BANNED_ATTRS:
                    bad = name
            if bad is not None:
                yield Finding(
                    module.rel, node.lineno, node.col_offset,
                    self.rule_id,
                    f"{bad} inside jit target `{fn.name}` — evaluated "
                    f"once at trace time and baked into the compiled "
                    f"(and AOT-persisted) program as a constant")
