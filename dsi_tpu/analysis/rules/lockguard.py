"""lock-guard: inferred GuardedBy checking for shared mutable state.

Six thread types mutate shared dicts/deques/counters in this repo
(serve scheduler, CommitWorker, pipeline producer, statusz sampler,
stall watchdog, RPC handler threads); the locking convention was only
in reviewers' heads.  This rule infers it and enforces it:

1. **Lock discovery** — a class attribute assigned ``threading.Lock()``
   / ``RLock()`` / ``Condition(...)`` is a lock; ``Condition(self.x)``
   is an *alias* of ``x`` (the daemon's ``_wake``/``_lock`` pair, the
   coordinator's ``_deadline_cv``/``mu`` pair acquire the same mutex).
2. **Guarded-set inference** — every ``self.x`` the class MUTATES
   inside a ``with self.<lock>`` block joins the lock's guarded set
   (mutation = assign / augassign / del, subscript store, a mutating
   method call like ``append``/``pop``/``setdefault``, or
   ``heapq.heappush(self.x, ...)``).
3. **Held-context inference** — a private method whose every
   intra-class call site is lock-held is analyzed as lock-held itself
   (fixpoint), which is exactly the repo's documented "caller holds the
   lock" convention (``ServeDaemon._admit``, ``Coordinator._touch``);
   ``__init__`` and private helpers reachable only from it are
   construction-time (no other thread can hold a reference yet) and
   exempt.
4. **Finding** — a mutation of a guarded attribute anywhere else.

The same inference runs at module level: a global assigned inside a
``with <module-lock>:`` block is guarded; a bare assignment to it
elsewhere (outside module top level) is a finding.

Reads are deliberately NOT checked (several hot paths publish racy
reads by design — the pipeline's in-flight deque, the histogram
snapshot — and flagging them would bury the real signal); the runtime
lock-order validator (``analysis/lockcheck.py``) covers the dynamic
half.  A deliberate unlocked mutation is annotated
``# dsicheck: allow[lock-guard] <why>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted,
    self_attr,
)
from dsi_tpu.analysis.core import scope_nodes as _core_scope_nodes

_LOCK_FACTORIES = ("threading.Lock", "threading.RLock",
                   "threading.Condition", "Lock", "RLock", "Condition")
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard",
}
_HEAPQ = {"heapq.heappush", "heapq.heappop", "heapq.heapify",
          "heappush", "heappop", "heapify"}


def _lock_factory(value: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, aliased_attr) when ``value`` constructs a lock: kind is
    the factory name; aliased_attr is the ``self.x`` a Condition wraps
    (None for a lock of its own)."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted(value.func)
    if name not in _LOCK_FACTORIES:
        return None
    alias = None
    if name.endswith("Condition") and value.args:
        alias = self_attr(value.args[0])
    return name, alias


class _Mutation:
    __slots__ = ("attr", "line", "col", "how")

    def __init__(self, attr: str, line: int, col: int, how: str):
        self.attr, self.line, self.col, self.how = attr, line, col, how


def _mutations_in(nodes: List[ast.AST]) -> List[_Mutation]:
    """self-attribute mutations among ``nodes`` (non-recursive: the
    caller hands a pre-pruned node list)."""
    out: List[_Mutation] = []
    for node in nodes:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                out.extend(_target_mutations(tgt))
        elif isinstance(node, ast.AugAssign):
            out.extend(_target_mutations(node.target))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                out.extend(_target_mutations(tgt))
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in _HEAPQ and node.args:
                attr = self_attr(node.args[0])
                if attr is not None:
                    out.append(_Mutation(attr, node.lineno,
                                         node.col_offset, name))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = self_attr(node.func.value)
                if attr is not None:
                    out.append(_Mutation(attr, node.lineno,
                                         node.col_offset,
                                         f".{node.func.attr}()"))
    return out


def _target_mutations(tgt: ast.AST) -> List[_Mutation]:
    out: List[_Mutation] = []
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for el in tgt.elts:
            out.extend(_target_mutations(el))
        return out
    if isinstance(tgt, ast.Starred):
        return _target_mutations(tgt.value)
    attr = self_attr(tgt)
    if attr is not None:
        out.append(_Mutation(attr, tgt.lineno, tgt.col_offset, "="))
        return out
    if isinstance(tgt, ast.Subscript):
        attr = self_attr(tgt.value)
        if attr is not None:
            out.append(_Mutation(attr, tgt.lineno, tgt.col_offset,
                                 "[...]="))
    return out


def _scope_nodes(scope: ast.AST):
    """Method-body nodes: the shared core walker, additionally pruning
    nested class bodies (a class defined inside a method owns its own
    lock discipline)."""
    return _core_scope_nodes(scope, skip_classes=True)


class _MethodInfo:
    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.name = fn.name
        # Nodes inside any `with self.<lock>` region, per lock attr.
        self.locked_nodes: Dict[str, List[ast.AST]] = {}
        self.unlocked_nodes: List[ast.AST] = []
        self.calls_self: List[Tuple[str, bool, Set[str]]] = []
        # (callee, under_lock, lock_names) for self.method() calls


class LockGuardRule(Rule):
    rule_id = "lock-guard"
    summary = "guarded attribute mutated outside its owning lock"

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        for cls in [n for n in ast.walk(module.tree)
                    if isinstance(n, ast.ClassDef)]:
            yield from self._check_class(module, cls)
        yield from self._check_module_globals(module)

    # ── class-level analysis ──

    def _check_class(self, module: SourceFile,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # 1. discover lock attrs + condition aliases.
        locks: Set[str] = set()
        alias_of: Dict[str, str] = {}
        for fn in methods:
            for node in _scope_nodes(fn):
                if not isinstance(node, ast.Assign):
                    continue
                got = _lock_factory(node.value)
                if got is None:
                    continue
                _kind, aliased = got
                for tgt in node.targets:
                    attr = self_attr(tgt)
                    if attr is None:
                        continue
                    locks.add(attr)
                    if aliased:
                        alias_of[attr] = aliased
        if not locks:
            return

        def canon(lock_attr: str) -> str:
            seen = set()
            while lock_attr in alias_of and lock_attr not in seen:
                seen.add(lock_attr)
                lock_attr = alias_of[lock_attr]
            return lock_attr

        # 2. split every method into locked/unlocked regions.
        infos: Dict[str, _MethodInfo] = {}
        for fn in methods:
            info = _MethodInfo(fn)
            self._split(fn, locks, canon, info)
            infos[fn.name] = info

        # 3. infer held/init-exempt methods (fixpoint).
        held, init_exempt = self._infer_contexts(infos)

        # 4. guarded sets from locked-region mutations.
        guarded: Dict[str, str] = {}  # attr -> lock
        for info in infos.values():
            regions = dict(info.locked_nodes)
            if info.name in held:
                regions.setdefault(held[info.name], []).extend(
                    info.unlocked_nodes)
            for lock, nodes in regions.items():
                for m in _mutations_in(nodes):
                    if m.attr not in locks:
                        guarded.setdefault(m.attr, lock)
        if not guarded:
            return

        # 5. findings: guarded-attr mutations in unlocked regions.
        for info in infos.values():
            if info.name == "__init__" or info.name in init_exempt \
                    or info.name in held:
                continue
            for m in _mutations_in(info.unlocked_nodes):
                lock = guarded.get(m.attr)
                if lock is None:
                    continue
                yield Finding(
                    module.rel, m.line, m.col, self.rule_id,
                    f"{cls.name}.{m.attr} is guarded by self.{lock} "
                    f"(mutated under it elsewhere) but mutated here "
                    f"({m.how}) in {info.name}() without holding it")
            # mutations under the WRONG lock
            for lock, nodes in info.locked_nodes.items():
                for m in _mutations_in(nodes):
                    want = guarded.get(m.attr)
                    if want is not None and want != lock:
                        yield Finding(
                            module.rel, m.line, m.col, self.rule_id,
                            f"{cls.name}.{m.attr} is guarded by "
                            f"self.{want} but mutated here under "
                            f"self.{lock}")

    def _split(self, fn: ast.AST, locks: Set[str], canon,
               info: _MethodInfo,
               current: Optional[str] = None) -> None:
        """Walk one method, assigning each node to its lock region.
        ``current`` is the canonical lock attr currently held."""
        for stmt in (fn.body if hasattr(fn, "body") else []):
            self._split_stmt(stmt, locks, canon, info, current)

    def _split_stmt(self, stmt, locks, canon, info, current) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        entered = current
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                attr = self_attr(item.context_expr)
                if attr is not None and attr in locks:
                    entered = canon(attr)
            self._bucket(stmt, info, current, items_only=True)
            for inner in stmt.body:
                self._split_stmt(inner, locks, canon, info, entered)
            return
        # Compound statements recurse so a with-block nested under an
        # if/try keeps its region.
        self._bucket(stmt, info, current, items_only=True)
        for name in ("body", "orelse", "finalbody"):
            for inner in getattr(stmt, name, []) or []:
                self._split_stmt(inner, locks, canon, info, current)
        for h in getattr(stmt, "handlers", []) or []:
            for inner in h.body:
                self._split_stmt(inner, locks, canon, info, current)

    def _bucket(self, stmt, info: _MethodInfo, current: Optional[str],
                items_only: bool = False) -> None:
        """File the statement's own (non-block) nodes into the current
        region and note intra-class calls."""
        nodes: List[ast.AST] = [stmt]
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                continue
            # ast.walk yields the child itself first — no re-append.
            nodes.extend(n for n in ast.walk(child)
                         if not isinstance(n, (ast.stmt,
                                               ast.ExceptHandler)))
        if current is not None:
            info.locked_nodes.setdefault(current, []).extend(nodes)
        else:
            info.unlocked_nodes.extend(nodes)
        for node in nodes:
            if isinstance(node, ast.Call):
                attr = self_attr(node.func)
                if attr is not None:
                    info.calls_self.append(
                        (attr, current is not None,
                         {current} if current else set()))

    def _infer_contexts(self, infos: Dict[str, _MethodInfo]):
        """(held, init_exempt): held maps a private method name to the
        lock every one of its call sites holds; init_exempt are private
        methods reachable only from __init__/other exempt methods."""
        # call sites per callee: (caller, under_lock, locks)
        sites: Dict[str, List[Tuple[str, bool, Set[str]]]] = {}
        for info in infos.values():
            for callee, under, lks in info.calls_self:
                if callee in infos:
                    sites.setdefault(callee, []).append(
                        (info.name, under, lks))
        init_exempt: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, info in infos.items():
                if name == "__init__" or name in init_exempt \
                        or not name.startswith("_") \
                        or name.startswith("__"):
                    continue
                callers = sites.get(name)
                if not callers:
                    continue
                if all(c == "__init__" or c in init_exempt
                       for c, _u, _l in callers):
                    init_exempt.add(name)
                    changed = True
        held: Dict[str, str] = {}
        changed = True
        while changed:
            changed = False
            for name, info in infos.items():
                if name in held or name == "__init__" \
                        or name in init_exempt \
                        or not name.startswith("_") \
                        or name.startswith("__"):
                    continue
                callers = sites.get(name)
                if not callers:
                    continue
                lock_votes: Set[str] = set()
                ok = True
                for caller, under, lks in callers:
                    if caller == "__init__" or caller in init_exempt:
                        continue  # construction-time call: no vote
                    if under:
                        lock_votes.update(lks)
                    elif caller in held:
                        lock_votes.add(held[caller])
                    else:
                        ok = False
                        break
                if ok and len(lock_votes) == 1:
                    held[name] = next(iter(lock_votes))
                    changed = True
        return held, init_exempt

    # ── module-level globals ──

    def _check_module_globals(self,
                              module: SourceFile) -> Iterator[Finding]:
        tree = module.tree
        # module-level lock names
        locks: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    _lock_factory(node.value) is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        locks.add(tgt.id)
        if not locks:
            return
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        guarded: Dict[str, str] = {}
        bare: List[Tuple[str, int, int]] = []
        for fn in funcs:
            declared = {n for node in _scope_nodes(fn)
                        if isinstance(node, ast.Global)
                        for n in node.names}
            if not declared:
                continue
            self._module_regions(fn, locks, declared, guarded, bare)
        for name, line, col in bare:
            lock = guarded.get(name)
            if lock is not None:
                yield Finding(
                    module.rel, line, col, self.rule_id,
                    f"module global `{name}` is guarded by `{lock}` "
                    f"(assigned under it elsewhere) but assigned here "
                    f"without holding it")

    def _module_regions(self, fn, locks, declared, guarded, bare,
                        current: Optional[str] = None) -> None:
        for stmt in (fn.body if hasattr(fn, "body") else []):
            entered = current
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    if isinstance(item.context_expr, ast.Name) and \
                            item.context_expr.id in locks:
                        entered = item.context_expr.id
                for inner in stmt.body:
                    self._module_stmt(inner, locks, declared, guarded,
                                      bare, entered)
                continue
            self._module_stmt(stmt, locks, declared, guarded, bare,
                              current)

    def _module_stmt(self, stmt, locks, declared, guarded, bare,
                     current) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            entered = current
            for item in stmt.items:
                if isinstance(item.context_expr, ast.Name) and \
                        item.context_expr.id in locks:
                    entered = item.context_expr.id
            for inner in stmt.body:
                self._module_stmt(inner, locks, declared, guarded, bare,
                                  entered)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id in declared:
                    if current is not None:
                        guarded.setdefault(tgt.id, current)
                    else:
                        bare.append((tgt.id, tgt.lineno,
                                     tgt.col_offset))
        for name in ("body", "orelse", "finalbody"):
            for inner in getattr(stmt, name, []) or []:
                self._module_stmt(inner, locks, declared, guarded, bare,
                                  current)
        for h in getattr(stmt, "handlers", []) or []:
            for inner in h.body:
                self._module_stmt(inner, locks, declared, guarded, bare,
                                  current)
