"""donation-after-use: the PR-8 silent-corruption shape, statically.

``aotcache.cached_compile(donate_argnums=...)`` / ``jax.jit(...,
donate_argnums=...)`` hand the named argument positions' buffers to the
compiled program — after the call the caller's array aliases freed (or
worse, recycled) device memory.  jax catches a re-DONATION ("Array has
been deleted"); it does NOT catch a plain host-side read of a donated
numpy buffer that the runtime already recycled — that is the
silent-count-corruption class behind the persisted-AOT heap flake
(CHANGES.md PR 8), which only a parity gate ever caught.

The rule, per function body:

1. find names bound to donating callables — ``f = cached_compile(...,
   donate_argnums=D)`` / ``jax.jit(..., donate_argnums=D)`` (optionally
   wrapped in ``x64_scoped``), where ``D`` is a literal tuple or a
   module-level constant (``_TABLE_DONATE`` style); ``self.x = ...``
   bindings are tracked class-wide the same way;
2. at each call through such a name, the arguments in donated positions
   that are plain names or ``self`` attributes become *consumed*;
3. any later read of a consumed name in the same function is a finding,
   unless an assignment re-bound it in between (the idiomatic
   ``table = fold(table, ...)`` re-binding is the expected kill).

Scope is one function body with statements in line order — the
analysis does not chase aliases, dict-stored callables, or
cross-function flows (the fixtures pin what it DOES catch; DESIGN.md
documents the blind spots).  A deliberate post-donation touch must be
annotated ``# dsicheck: allow[donation-after-use] <why it is safe>``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    dotted,
    literal,
    module_constants,
    self_attr,
)

#: Call targets that produce a donating callable when handed a
#: non-empty donate_argnums.
_FACTORIES = ("cached_compile", "aotcache.cached_compile", "jax.jit",
              "jit")
#: Transparent wrappers whose first argument is the real callable.
_WRAPPERS = ("x64_scoped", "jaxcompat.x64_scoped")


def _donate_positions(call: ast.Call,
                      consts: Dict[str, object]) -> Optional[Tuple[int, ...]]:
    """The donated argument indices of a factory call, resolved from a
    literal or a module-level constant; None when absent/empty or
    unresolvable."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        val = literal(kw.value)
        if val is None and isinstance(kw.value, ast.Name):
            val = consts.get(kw.value.id)
        if val is None:
            return None
        if isinstance(val, int):
            val = (val,)
        try:
            pos = tuple(int(v) for v in val)
        except (TypeError, ValueError):
            return None
        return pos or None
    return None


def _unwrap(call: ast.AST) -> Optional[ast.Call]:
    """The innermost factory call: looks through x64_scoped(...) and
    conditional expressions (``X if donate else ()`` stays on the
    caller)."""
    if not isinstance(call, ast.Call):
        return None
    name = dotted(call.func)
    if name.endswith(_WRAPPERS) and call.args:
        return _unwrap(call.args[0])
    if any(name == f or name.endswith("." + f) for f in _FACTORIES):
        return call
    return None


class _FnScan:
    """One function body's donating-call / consumed-name bookkeeping."""

    def __init__(self, donating: Dict[str, Tuple[int, ...]],
                 consts: Dict[str, object]):
        # name -> donated positions; names are 'x' or 'self.x'.
        self.donating = dict(donating)
        self.consts = consts
        # consumed name -> (line of the donating call)
        self.consumed: Dict[str, int] = {}
        self.findings: List[Tuple[int, int, str, str]] = []

    def _key(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        attr = self_attr(node)
        return f"self.{attr}" if attr is not None else None

    def kill(self, target: ast.AST) -> None:
        """An assignment target re-binds a name: it is fresh again."""
        for node in ast.walk(target):
            k = self._key(node)
            if k is not None and isinstance(getattr(node, "ctx", None),
                                            (ast.Store, ast.Del)):
                self.consumed.pop(k, None)

    def note_call(self, call: ast.Call) -> None:
        # A direct factory(...)(...) immediate call donates too.
        callee = self._key(call.func)
        pos: Optional[Tuple[int, ...]] = None
        if callee is not None and callee in self.donating:
            pos = self.donating[callee]
        else:
            inner = _unwrap(call.func)
            if inner is not None:
                pos = _donate_positions(inner, self.consts)
        if not pos:
            return
        for i in pos:
            if i < len(call.args):
                k = self._key(call.args[i])
                if k is not None:
                    self.consumed[k] = call.lineno

    def note_read(self, node: ast.AST) -> None:
        k = self._key(node)
        if k is None or not isinstance(getattr(node, "ctx", None),
                                       ast.Load):
            return
        at = self.consumed.get(k)
        if at is not None and node.lineno > at:
            self.findings.append(
                (node.lineno, node.col_offset, k,
                 f"`{k}` was donated to a compiled call on line {at} "
                 f"and read again here — donated buffers must not be "
                 f"reused (re-bind the name, copy before donating, or "
                 f"annotate why this read is safe)"))
            # one report per consumption, not per subsequent read
            self.consumed.pop(k, None)


class DonationAfterUseRule(Rule):
    rule_id = "donation-after-use"
    summary = ("a buffer passed in a donate_argnums position is read "
               "after the donating call")

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        consts = module_constants(module.tree)
        # Class-wide self.x -> donated positions (factory assigned to an
        # attribute in one method, called in another).
        class_donating: Dict[ast.ClassDef, Dict[str, Tuple[int, ...]]] = {}
        for cls in [n for n in ast.walk(module.tree)
                    if isinstance(n, ast.ClassDef)]:
            attrs: Dict[str, Tuple[int, ...]] = {}
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                inner = _unwrap(node.value)
                if inner is None:
                    continue
                pos = _donate_positions(inner, consts)
                if not pos:
                    continue
                for tgt in node.targets:
                    attr = self_attr(tgt)
                    if attr is not None:
                        attrs[f"self.{attr}"] = pos
            class_donating[cls] = attrs

        owner: Dict[ast.AST, ast.ClassDef] = {}
        for cls in class_donating:
            for node in ast.walk(cls):
                owner.setdefault(node, cls)

        for fn in [n for n in ast.walk(module.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            donating = dict(class_donating.get(owner.get(fn), {}) or {})
            # First pass: local names bound to donating factories.
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                inner = _unwrap(node.value)
                if inner is None:
                    continue
                pos = _donate_positions(inner, consts)
                if not pos:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donating[tgt.id] = pos
            if not donating and not any(
                    isinstance(n, ast.Call) and _unwrap(n.func)
                    for n in ast.walk(fn)):
                continue
            scan = _FnScan(donating, consts)
            self._walk_body(fn.body, scan)
            for line, col, _name, msg in scan.findings:
                yield Finding(module.rel, line, col, self.rule_id, msg)

    # Statement-ordered walk: reads are checked in source order, and
    # assignment targets kill consumption AFTER their value side was
    # checked (``x = f(x)`` donates then immediately re-binds — clean).
    def _walk_body(self, body, scan: _FnScan) -> None:
        for stmt in body:
            self._walk_stmt(stmt, scan)

    def _walk_stmt(self, stmt: ast.stmt, scan: _FnScan) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes analyzed on their own
        if isinstance(stmt, ast.Assign):
            self._walk_expr(stmt.value, scan)
            for tgt in stmt.targets:
                scan.kill(tgt)
            return
        if isinstance(stmt, ast.AugAssign):
            self._walk_expr(stmt.value, scan)
            scan.note_read(stmt.target)  # aug-assign READS the target
            scan.kill(stmt.target)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._walk_expr(stmt.value, scan)
            scan.kill(stmt.target)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                scan.kill(t)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._walk_expr(stmt.test, scan)
            self._walk_body(stmt.body, scan)
            self._walk_body(stmt.orelse, scan)
            return
        if isinstance(stmt, ast.For):
            self._walk_expr(stmt.iter, scan)
            scan.kill(stmt.target)
            self._walk_body(stmt.body, scan)
            self._walk_body(stmt.orelse, scan)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._walk_expr(item.context_expr, scan)
                if item.optional_vars is not None:
                    scan.kill(item.optional_vars)
            self._walk_body(stmt.body, scan)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, scan)
            for h in stmt.handlers:
                self._walk_body(h.body, scan)
            self._walk_body(stmt.orelse, scan)
            self._walk_body(stmt.finalbody, scan)
            return
        # Return/Expr/Raise/Assert/...: check every expression inside.
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._walk_expr(node, scan)

    def _walk_expr(self, expr: ast.expr, scan: _FnScan) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.Name, ast.Attribute)):
                scan.note_read(node)
        # Calls noted AFTER reads: the donating call's own arguments are
        # legitimate reads; consumption starts on the next line.
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                scan.note_call(node)
