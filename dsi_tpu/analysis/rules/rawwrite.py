"""raw-write: the durable-write discipline, statically.

Every byte that must survive a crash — checkpoint payloads/manifests
under ``--checkpoint-dir``, the serve spool journal, trace artifacts,
the control-plane journal — goes through ``utils/atomicio`` (temp +
fsync + rename + CRC sidecar + parent-dir fsync).  PR 5 built that
path precisely because bare ``open(..., 'wb')`` writes had already
shipped torn-file windows; this rule keeps the next subsystem from
re-introducing one.

Flagged, anywhere under ``dsi_tpu/`` except ``utils/atomicio.py``
itself:

* ``open(...)`` with a write-capable literal mode (any of ``w a x +``);
* ``np.save``/``np.savez``/``np.savez_compressed`` whose target is not
  provably an in-memory ``io.BytesIO`` (serializing into a buffer that
  is then committed durably is the checkpoint store's own idiom —
  since ISSUE 13 the store's compressed-delta path is
  ``np.savez_compressed(BytesIO)``, recognized the same way).  A
  BytesIO target is recognized as a plain/annotated/walrus-assigned
  local or the inline ``np.savez*(io.BytesIO(), ...)`` spelling.

The parallel ingest pool (``utils/ioread.py``) needs no exemption by
construction: it is mmap ``ACCESS_READ`` + read-mode fallbacks only —
there are no temp spools to lose.

A write that is *genuinely* non-durable — rebuildable caches, bounded
telemetry rings, best-effort markers — is annotated
``# dsicheck: allow[raw-write] <reason>`` at the call site, which is
exactly the reviewable inventory of "bytes we are allowed to lose"
(today: the AOT cache entry + its execfail marker, the live.jsonl
ring, the nfak cost cache, and the journal's append handle whose
durability comes from its own per-record fsync discipline).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from dsi_tpu.analysis.core import (
    Finding,
    Project,
    Rule,
    SourceFile,
    call_name,
    scope_nodes as _scope_nodes,
)

_WRITE_CHARS = set("wax+")
_NP_WRITERS = ("save", "savez", "savez_compressed")


def _mode_of(call: ast.Call) -> str:
    """The literal mode argument of an ``open()`` call ('' when absent
    or not a literal — absent means 'r', non-literal is not judged)."""
    mode_node = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and \
            isinstance(mode_node.value, str):
        return mode_node.value
    return ""


class RawWriteRule(Rule):
    rule_id = "raw-write"
    summary = "file write bypassing the atomicio durable-write path"

    def applies(self, rel: str) -> bool:
        # The discipline's implementation is the one legitimate home of
        # raw writes.
        return not rel.endswith("utils/atomicio.py")

    def check(self, module: SourceFile,
              project: Project) -> Iterator[Finding]:
        for fn_body, bytesio_names in _function_scopes(module.tree):
            for node in fn_body:
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name == "open":
                    mode = _mode_of(node)
                    if set(mode) & _WRITE_CHARS:
                        yield Finding(
                            module.rel, node.lineno, node.col_offset,
                            self.rule_id,
                            f"bare open(..., {mode!r}) — durable writes "
                            f"go through atomicio.write_bytes_durable/"
                            f"atomic_write; annotate genuinely "
                            f"non-durable writes")
                elif name.split(".")[-1] in _NP_WRITERS and \
                        name.split(".")[0] in ("np", "numpy"):
                    tgt = node.args[0] if node.args else None
                    if isinstance(tgt, ast.Name) and \
                            tgt.id in bytesio_names:
                        continue  # serialize-to-buffer: durable commit
                    if isinstance(tgt, ast.Call) and \
                            call_name(tgt) in ("io.BytesIO", "BytesIO"):
                        continue  # inline buffer: same idiom
                    yield Finding(
                        module.rel, node.lineno, node.col_offset,
                        self.rule_id,
                        f"direct {name}(...) to a path — serialize into "
                        f"io.BytesIO and commit via "
                        f"atomicio.write_bytes_durable")


def _function_scopes(tree: ast.Module):
    """Yield (nodes, bytesio_names) per function scope (plus the module
    top level), where bytesio_names are locals bound to
    ``io.BytesIO()`` — plain, annotated, or walrus assignment — the
    allowed np.savez targets."""
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        names: Set[str] = set()
        body_nodes = []

        def note(target, value):
            if isinstance(value, ast.Call) and \
                    call_name(value) in ("io.BytesIO", "BytesIO") and \
                    isinstance(target, ast.Name):
                names.add(target.id)

        for node in _scope_nodes(scope):
            body_nodes.append(node)
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    note(tgt, node.value)
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                note(node.target, node.value)
            elif isinstance(node, ast.NamedExpr):
                note(node.target, node.value)
        yield body_nodes, names
