"""The dsicheck rule engine: files, findings, suppressions, runner.

Deliberately dependency-free (stdlib ``ast`` only): the CI job that
gates on this runs with a bare interpreter, and the pass must stay
usable on a box where jax is mid-outage.  Rules are small classes with
a ``check(module, project)`` generator; the engine owns everything
rule-agnostic — parsing, the suppression ledger, ordering, rendering —
so a rule is only its invariant.

Suppression contract (the reviewed escape hatch): a finding on line N
is suppressed when line N *or line N-1* carries::

    # dsicheck: allow[<rule-id>] <reason>

``allow[all]`` suppresses every rule on that line.  The reason is not
optional in spirit — the clean-tree test counts suppressions, so a
bare allow is visible in review either way.  Suppressed findings are
still collected (``--json`` shows them); only unsuppressed ones fail
the build.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

_ALLOW_RE = re.compile(r"#\s*dsicheck:\s*allow\[([a-zA-Z0-9_,\- ]+)\]")


@dataclass(order=True)
class Finding:
    """One rule violation, anchored to a source line."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = field(default=False, compare=False)

    def render(self) -> str:
        sup = "  (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}{sup}")

    def as_json(self) -> Dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message,
                "suppressed": self.suppressed}


class SourceFile:
    """One parsed module: AST + raw lines + the allow-comment ledger."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel  # repo-relative, forward slashes — what rules match
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        #: line -> set of allowed rule ids ("all" = wildcard).
        self.allows: Dict[int, Set[str]] = {}
        for lineno, rules in _scan_allows(text):
            self.allows.setdefault(lineno, set()).update(rules)
            # A comment-only allow line annotates the next CODE line
            # (reason comments are encouraged to span several lines, so
            # the anchor walks past the rest of the comment block).
            if self._comment_only(lineno):
                ln = lineno + 1
                while ln <= len(self.lines) and self._comment_only(ln):
                    ln += 1
                if ln <= len(self.lines):
                    self.allows.setdefault(ln, set()).update(rules)

    def _comment_only(self, lineno: int) -> bool:
        text = (self.lines[lineno - 1]
                if 0 < lineno <= len(self.lines) else "")
        stripped = text.strip()
        return not stripped or stripped.startswith("#")

    def allowed(self, line: int, rule: str) -> bool:
        """True when ``line``, or a comment-only line/block ending
        above it, carries an allow comment matching ``rule``.  A
        trailing annotation on the previous CODE line does NOT leak
        onto this one — each violating line needs its own decision."""
        def match(ln: int) -> bool:
            got = self.allows.get(ln)
            return bool(got and (rule in got or "all" in got))

        if match(line):
            return True
        return self._comment_only(line - 1) and match(line - 1)


def _scan_allows(text: str) -> Iterator[tuple]:
    """Yield (lineno, [rule, ...]) for every dsicheck allow comment.
    Tokenize-based so a ``# dsicheck:`` inside a string literal (this
    engine's own source, the fixtures' docstrings) is not an
    annotation."""
    import io

    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if m:
                rules = [r.strip() for r in m.group(1).split(",")
                         if r.strip()]
                yield tok.start[0], rules
    except tokenize.TokenError:
        return


class Rule:
    """Base class: subclasses set ``rule_id``/``summary`` and implement
    ``check``.  ``applies`` scopes a rule off specific files (e.g. the
    raw-write rule exempts ``utils/atomicio.py`` — the implementation
    of the discipline cannot route through itself)."""

    rule_id: str = ""
    summary: str = ""

    def applies(self, rel: str) -> bool:
        return True

    def check(self, module: SourceFile,
              project: "Project") -> Iterator[Finding]:
        raise NotImplementedError


class Project:
    """The scanned file set plus cross-file context (pinned constants
    resolved from the obs schema modules)."""

    def __init__(self, root: str, files: Sequence[SourceFile]):
        self.root = root
        self.files = list(files)
        self._by_rel = {f.rel: f for f in self.files}

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)


def load_files(root: str, paths: Sequence[str]
               ) -> Tuple[List[SourceFile], List[Finding]]:
    """Collect ``.py`` files under each path (file or directory),
    skipping caches/build dirs, as SourceFiles.  Unparsable files are
    reported as ``parse-error`` findings (never suppressible — a file
    the engine cannot read is a file no rule inspected), not as an
    exception: the CI gate must fail with a file:line, not a
    traceback."""
    out: List[SourceFile] = []
    errors: List[Finding] = []
    seen: Set[str] = set()
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            cands = [ap]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            "build", ".aotcache")]
                cands.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for c in sorted(cands):
            if c in seen:
                continue
            seen.add(c)
            rel = os.path.relpath(c, root).replace(os.sep, "/")
            try:
                with open(c, encoding="utf-8") as f:
                    text = f.read()
                out.append(SourceFile(c, rel, text))
            except (SyntaxError, ValueError, UnicodeDecodeError,
                    OSError) as e:
                line = getattr(e, "lineno", None) or 1
                col = getattr(e, "offset", None) or 1
                errors.append(Finding(
                    rel, int(line), int(col), "parse-error",
                    f"file could not be parsed "
                    f"({type(e).__name__}: {e}) — no rule inspected "
                    f"it"))
    return out, errors


def default_rules() -> List[Rule]:
    from dsi_tpu.analysis.rules import all_rules

    return all_rules()


def run_project(root: str, paths: Sequence[str],
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run every rule over every file; findings come back sorted with
    suppression already applied (``.suppressed`` set, nothing
    dropped).  Unparsable files surface as ``parse-error`` findings."""
    if rules is None:
        rules = default_rules()
    files, findings = load_files(root, paths)
    project = Project(root, files)
    for mod in files:
        for rule in rules:
            if not rule.applies(mod.rel):
                continue
            for f in rule.check(mod, project):
                f.suppressed = mod.allowed(f.line, f.rule)
                findings.append(f)
    findings.sort()
    return findings


def render_human(findings: Sequence[Finding],
                 show_suppressed: bool = False) -> str:
    lines = []
    unsup = [f for f in findings if not f.suppressed]
    sup = [f for f in findings if f.suppressed]
    for f in unsup:
        lines.append(f.render())
    if show_suppressed:
        for f in sup:
            lines.append(f.render())
    lines.append(f"dsicheck: {len(unsup)} finding(s), "
                 f"{len(sup)} suppressed")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({
        "findings": [f.as_json() for f in findings if not f.suppressed],
        "suppressed": [f.as_json() for f in findings if f.suppressed],
    }, indent=1, sort_keys=True)


# ── shared AST helpers used by several rules ───────────────────────────

def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``open`` / ``np.savez`` /
    ``self._lock.acquire`` -> ``open`` / ``np.savez`` /
    ``self._lock.acquire`` (best effort; '' when not a plain name
    chain)."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def literal(node: ast.AST):
    """ast.literal_eval that answers None instead of raising."""
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None


def module_constants(tree: ast.Module) -> Dict[str, object]:
    """Top-level ``NAME = <literal>`` assignments — how rules resolve
    module-level donation tuples and pinned schema constants without
    importing (the scanned file may need jax; the scanner must not)."""
    out: Dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            val = literal(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


def scope_nodes(scope: ast.AST, skip_classes: bool = False):
    """Every node under ``scope`` without descending into nested
    function scopes (and, with ``skip_classes``, class bodies) — the
    one scope walker every rule shares, so scope-boundary semantics
    cannot drift between rules."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if skip_classes and isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None
