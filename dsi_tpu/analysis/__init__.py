"""dsi_tpu.analysis — the codebase-invariant analysis plane.

Eleven PRs grew this repo from the paper's single-threaded coordinator
loop into a system with six concurrent thread types, donated device
buffers on every hot path, and a crash-durability protocol whose
invariants were enforced only by reviewer memory.  This package encodes
those invariants as machine-checked rules — the Python moral equivalent
of 6.5840's ``go test -race`` grading gate:

* :mod:`~dsi_tpu.analysis.core` — the AST rule engine: per-file
  findings with ``file:line``, ``# dsicheck: allow[rule] <reason>``
  suppression comments, JSON + human output (``scripts/dsicheck.py``).
* :mod:`~dsi_tpu.analysis.rules` — the repo-specific rule catalogue:
  ``donation-after-use`` (a buffer passed into a ``donate_argnums``
  position must not be read afterwards — the PR-8 silent-corruption
  shape), ``raw-write`` (durable paths go through
  ``atomicio.write_bytes_durable``), ``lock-guard`` (attributes ever
  mutated under their owning lock must be mutated under it everywhere),
  ``span-discipline`` (spans are context managers with pinned
  stage-schema names), ``metric-schema`` (engine stat keys come from
  the one registry schema), ``jit-purity`` (no time/random/env reads
  inside jit-compiled bodies).
* :mod:`~dsi_tpu.analysis.lockcheck` — the RUNTIME lock-order
  validator (``DSI_LOCKCHECK=1``): wrapped ``threading.Lock`` factories
  maintain a per-thread held-set and a global acquisition-order graph,
  raising :class:`~dsi_tpu.analysis.lockcheck.LockOrderError` on a
  cycle — a scheduler×CommitWorker×sampler deadlock fails loudly
  instead of hanging the CI smoke.

The static pass runs clean on this tree (``tests/test_static_analysis
.py`` pins that), so any new finding is a regression, not noise.  No
third-party imports anywhere in this package: ``dsicheck`` must run in
a bare-Python CI job with no jax/numpy installed.
"""

from dsi_tpu.analysis.core import (  # noqa: F401
    Finding,
    Project,
    run_project,
)
