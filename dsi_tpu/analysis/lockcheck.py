"""DSI_LOCKCHECK=1 — the runtime lock-order validator.

The static ``lock-guard`` rule proves mutations happen *under* their
lock; it cannot prove two locks are always taken in the same ORDER.
With six thread types (serve scheduler, CommitWorker, pipeline
producer, statusz sampler, stall watchdog, RPC handlers) an ABBA
inversion deadlocks silently — the CI smoke would hang to its timeout
with nothing attributable.  This module is the lockdep-style dynamic
half:

* :func:`install` replaces ``threading.Lock``/``RLock`` factories with
  tracked wrappers (``threading.Condition(tracked_lock)`` composes —
  the wrapper exposes ``acquire``/``release``/``_is_owned``, which is
  the whole protocol Condition needs);
* every acquisition maintains a per-thread **held-list** and a global
  **acquisition-order graph** whose nodes are lock *creation sites*
  (``file:line`` — the lockdep "lock class": instances allocated at
  one site share ordering discipline, so an inversion between two
  instances of the same pair of classes is caught even when the exact
  instances differ across threads);
* an edge A→B is added when B is acquired while A is held; if B→…→A
  already exists the acquisition **raises** :class:`LockOrderError`
  *before blocking* — the deadlock becomes a loud traceback with both
  chains named instead of a hang.

Installed at import of :mod:`dsi_tpu` when ``DSI_LOCKCHECK=1`` (before
any repo module creates a lock), which is how the CI daemon smoke runs
it.  Same-site nesting (two instances of one lock class, e.g. paired
``LatencyHistogram.merge``) is recorded but not raised on — ordering
within a class needs an instance tiebreak the call sites own; the
limitation is documented in DESIGN.md.

Cost: one dict update + a bounded DFS per *novel* edge, a set lookup
per repeat edge — measurable but fine for smokes and soaks; never
enabled by default.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread
from typing import Dict, List, Optional, Set, Tuple

_real_allocate = _thread.allocate_lock


class LockOrderError(RuntimeError):
    """An acquisition that would complete a cycle in the global
    lock-order graph — i.e. a schedule exists where this line
    deadlocks."""


class _State:
    """The global validator state (its own RAW lock: the tracking
    machinery must never route through the wrappers it tracks)."""

    def __init__(self):
        self.mu = _real_allocate()
        #: site -> set of sites acquired while it was held
        self.edges: Dict[str, Set[str]] = {}
        #: edges already checked (skip the DFS on the hot path)
        self.seen: Set[Tuple[str, str]] = set()
        self.tls = threading.local()
        self.violations: List[str] = []
        self.raise_on_cycle = True

    def held(self) -> List:
        return getattr(self.tls, "held", [])

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A src→…→dst path in the edge graph, or None."""
        stack = [(src, [src])]
        visited = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self.edges.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def before_acquire(self, site: str) -> None:
        held = self.held()
        if not held:
            return
        for h in held:
            a = h._site
            if a == site:
                continue  # same lock class: documented blind spot
            with self.mu:
                if (a, site) in self.seen:
                    continue
                back = self._path(site, a)
                self.seen.add((a, site))
                self.edges.setdefault(a, set()).add(site)
            if back is not None:
                chain = " -> ".join(back)
                msg = (f"lock-order cycle: acquiring {site} while "
                       f"holding {a}, but the graph already has "
                       f"{chain} — an ABBA deadlock schedule exists "
                       f"(held here: "
                       f"{[x._site for x in held]})")
                with self.mu:
                    self.violations.append(msg)
                print(f"lockcheck: {msg}", file=sys.stderr, flush=True)
                if self.raise_on_cycle:
                    raise LockOrderError(msg)

    def note_acquired(self, lock) -> None:
        held = getattr(self.tls, "held", None)
        if held is None:
            held = self.tls.held = []
        held.append(lock)

    def note_released(self, lock) -> None:
        held = getattr(self.tls, "held", None)
        if held and lock in held:
            # remove the most recent occurrence (re-entrant RLocks pop
            # at count zero; out-of-order releases stay correct)
            for i in range(len(held) - 1, -1, -1):
                if held[i] is lock:
                    del held[i]
                    break


_state: Optional[_State] = None
_orig_lock = None
_orig_rlock = None


_THIS_FILE = os.path.abspath(__file__)


def _caller_site() -> str:
    """file:line of the frame that called the lock factory — the lock
    class identity (skips this module and threading's own frames)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE and \
                os.path.basename(fn) != "threading.py":
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "?:0"


class TrackedLock:
    """A ``threading.Lock`` stand-in that feeds the order graph."""

    _reentrant = False

    def __init__(self, site: Optional[str] = None):
        self._lock = _real_allocate()
        self._site = site or _caller_site()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = _state
        me = _thread.get_ident()
        if st is not None and not (self._reentrant
                                   and self._owner == me):
            st.before_acquire(self._site)
        got = self._lock.acquire(blocking, timeout)
        if got:
            first = self._count == 0 or self._owner != me
            self._owner = me
            self._count += 1
            if st is not None and first:
                st.note_acquired(self)
        return got

    def release(self):
        st = _state
        self._count -= 1
        if self._count <= 0:
            self._count = 0
            self._owner = None
            if st is not None:
                st.note_released(self)
        self._lock.release()

    # The protocol threading.Condition composes over.  _release_save /
    # _acquire_restore matter for REENTRANT locks: Condition's fallback
    # calls release() once, which on an RLock held at count > 1 leaves
    # the underlying lock held through the wait — the validator would
    # itself manufacture a deadlock that does not exist without it.
    def _is_owned(self) -> bool:
        return self._owner == _thread.get_ident()

    def _release_save(self):
        count, owner = self._count, self._owner
        self._count = 0
        self._owner = None
        st = _state
        if st is not None:
            st.note_released(self)
        for _ in range(count if self._reentrant else 1):
            self._lock.release()
        return count, owner

    def _acquire_restore(self, saved):
        count, owner = saved
        for _ in range(count if self._reentrant else 1):
            self._lock.acquire()
        self._count, self._owner = count, owner
        st = _state
        # Re-acquisition after a wait is not a NEW ordering decision
        # (Condition semantics: the caller logically held the lock all
        # along), so only the held-list is restored — no order edge.
        if st is not None:
            st.note_acquired(self)

    def locked(self) -> bool:
        return self._count > 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return (f"<{type(self).__name__} site={self._site} "
                f"locked={self.locked()}>")


class TrackedRLock(TrackedLock):
    _reentrant = True

    def __init__(self, site: Optional[str] = None):
        # bypass the parent's plain-lock constructor path
        self._lock = _thread.RLock()
        self._site = site or _caller_site()
        self._owner = None
        self._count = 0


def install(raise_on_cycle: bool = True) -> None:
    """Patch the ``threading`` lock factories.  Idempotent.  Locks
    created BEFORE install (interpreter-startup stdlib locks) stay
    untracked — which is why ``dsi_tpu/__init__`` installs on import
    when ``DSI_LOCKCHECK=1``, before any repo lock exists."""
    global _state, _orig_lock, _orig_rlock
    if _state is not None:
        _state.raise_on_cycle = raise_on_cycle
        return
    _state = _State()
    _state.raise_on_cycle = raise_on_cycle
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = TrackedLock  # type: ignore[misc,assignment]
    threading.RLock = TrackedRLock  # type: ignore[misc,assignment]


def uninstall() -> None:
    """Restore the real factories (tests).  Already-created tracked
    locks keep working — their tracking calls see ``_state is None``
    and degrade to plain locking."""
    global _state, _orig_lock, _orig_rlock
    if _state is None:
        return
    threading.Lock = _orig_lock  # type: ignore[misc]
    threading.RLock = _orig_rlock  # type: ignore[misc]
    _state = None
    _orig_lock = _orig_rlock = None


def installed() -> bool:
    return _state is not None


def violations() -> List[str]:
    """Messages of every cycle detected so far (also raised unless
    ``install(raise_on_cycle=False)``)."""
    if _state is None:
        return []
    with _state.mu:
        return list(_state.violations)


def order_graph() -> Dict[str, Set[str]]:
    """A copy of the acquisition-order graph (site -> successors)."""
    if _state is None:
        return {}
    with _state.mu:
        return {k: set(v) for k, v in _state.edges.items()}
