"""Execution backends for worker tasks.

The worker loop (``dsi_tpu/mr/worker.py``) executes tasks on the host by
default — reference semantics (``mr/worker.go:55-161``).  A backend is an
object with ``run_map``/``run_reduce`` methods passed as ``task_runner``;
the TPU backend routes app-declared device kernels through JAX while keeping
the wire protocol, file formats, and fault-tolerance semantics identical.
"""
