"""TPU task backend: runs map tasks through app-declared device kernels.

Reference scope: the worker's task execution bodies (``mr/worker.go:55-97``
map, ``:99-161`` reduce).  Everything around the execution — pull protocol,
intermediate file naming/format, atomic commit, missing-file tolerance,
completion RPCs — is untouched; this backend only swaps the *compute* inside
a task, which is exactly the boundary SURVEY.md §7 step 4 prescribes.

App contract (optional, duck-typed — the plugin boundary stays two-symbol
for portable apps):

* ``tpu_map(filename: str, raw: bytes) -> list[KeyValue] | None`` — device
  implementation of the map task.  Returning None means "this input needs
  the host path" (e.g. non-ASCII text); the runner then falls back to the
  app's ordinary ``Map`` — correctness never depends on the kernel.
* ``tpu_reduce(key, values) -> str`` — optional; defaults to the app's
  ``Reduce``.  For combiner-style apps the reduce phase is tiny (one record
  per unique key per split), so it stays on the host.
"""

from __future__ import annotations

from dsi_tpu.mr import worker as w
from dsi_tpu.mr.plugin import load_plugin_module


class TpuTaskRunner:
    """Backend object for ``worker_loop(task_runner=...)``."""

    def __init__(self, app_module):
        self.app = app_module
        self.tpu_map = getattr(app_module, "tpu_map", None)
        self.tpu_reduce = getattr(app_module, "tpu_reduce", None)
        if self.tpu_map is None and self.tpu_reduce is None:
            import sys

            print(
                f"mrworker: app {getattr(app_module, '__name__', app_module)} "
                "declares no tpu_map/tpu_reduce; --backend=tpu will run every "
                "task on the host path (use the tpu_wc app for the device "
                "word-count kernel)", file=sys.stderr)

    @classmethod
    def for_app(cls, name_or_path: str) -> "TpuTaskRunner":
        from dsi_tpu.utils.platformpin import pin_platform_from_env

        pin_platform_from_env()  # e.g. cpu for harness runs
        return cls(load_plugin_module(name_or_path))

    def run_map(self, mapf, filename: str, map_task: int, n_reduce: int,
                workdir: str = ".") -> None:
        with open(filename, "rb") as f:
            raw = f.read()
        kva = self.tpu_map(filename, raw) if self.tpu_map else None
        if kva is None:  # host fallback (worker.go:55-92 semantics)
            kva = mapf(filename, raw.decode("utf-8", errors="replace"))
        w.write_intermediates(kva, map_task, n_reduce, workdir)

    def run_reduce(self, reducef, reduce_task: int, n_map: int,
                   workdir: str = ".") -> None:
        w.run_reduce_task(self.tpu_reduce or reducef, reduce_task, n_map,
                          workdir)
