"""Persistent AOT executable cache: compile once per machine, ever.

Why this exists: JAX's own persistent compilation cache
(``JAX_COMPILATION_CACHE_DIR``) never produced a hit on this platform's
axon-tunneled TPU — round-2 profiling measured a 219.8 s re-compile in every
fresh process with a same-shape entry sitting in the cache directory
(VERDICT r2 weakness #1a).  The PJRT client *does* support executable
serialization (probed: ``serialize``/``deserialize_and_load`` round-trips in
milliseconds), so this module implements the cache one level up: serialized
compiled executables on disk, keyed by (platform fingerprint, function
identity, input avals, static params).

Usage::

    fn = cached_compile("corpus_wc", tokenize_fn, example_args,
                        static={"u_cap": 1 << 18})
    out = fn(*args)   # args must match example_args' shapes/dtypes

Every failure path (unserializable backend, corrupt entry, version drift)
falls back to plain ``jax.jit`` compilation — the cache is a pure
optimization, never a correctness dependency (the same discipline as the
kernel fallbacks in ``backends/tpu.py``).

The reference has no compilation step at all (Go builds AOT by nature);
this is the TPU-native moral equivalent of shipping compiled binaries
(``main/test-mr.sh:19-22`` builds once per run, not once per process).

Entry-name families (the human-readable prefix of each ``.aot`` file —
the key itself also hashes platform/source/shapes/statics/donation):
``wc_kernel*`` and ``corpus_wc*`` single-chunk programs,
``stream_step_*``/``stream_pack_*`` streaming programs,
``tfidf_wave_*`` the pipelined TF-IDF wave step, ``dacc_*`` the device
accumulator's fold/clear/pack.  Grouper variants append
``ops.wordcount.grouper_suffix``: bare names are the sort grouper,
``*_hg`` the hash grouper — both ride the warm ladder
(``scripts/warm_kernels.py``), so ``DSI_WC_GROUPER=hash`` runs load on
any platform.  Donation changes the key (aliasing config), so the
kernel-only bench row's non-donated ``stream_step_*`` entries coexist
with the pipeline's donated ones.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys
import threading
from typing import Any, Callable, Dict, Tuple

# Version tag: bump to invalidate every entry (e.g. after a kernel rewrite
# that changes semantics without changing shapes).
_CACHE_VERSION = "aot-v1"

_memo: Dict[str, Callable] = {}
_memo_lock = threading.Lock()

# Process-wide counters the bench reports (compile_s must be ~0 in any
# process that found a warm cache — VERDICT r2 task 1a's "done" criterion).
stats = {"compiled_s": 0.0, "compiles": 0, "loads": 0}


def cache_dir() -> str:
    d = os.environ.get("DSI_AOT_CACHE_DIR")
    if d:
        return d
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".aotcache")


def _platform_fingerprint() -> str:
    """Identity of the compile target: platform + its version string.

    ``platform_version`` on this stack includes the runtime build and
    serialization format version ("axon 0.1.0; SerializedExecutable v9;
    compile-cache v14; ..."), so executables cannot be loaded across
    incompatible runtime updates — a mismatch simply misses and recompiles.
    """
    import jax
    from jax._src import xla_bridge

    backend = xla_bridge.get_backend()
    return (f"{jax.__version__}|{backend.platform}|"
            f"{getattr(backend, 'platform_version', '?')}")


def _code_fingerprint(fn: Callable) -> str:
    """Hash the source files the compiled program's semantics depend on:
    the function's own module plus any modules it declares via a
    ``_aot_code_deps`` attribute.  A kernel edit therefore misses the cache
    and recompiles — a stale executable is never served (a comment-only
    edit also misses; that one-time recompile is the accepted price)."""
    import inspect

    h = hashlib.sha256()
    mods = [inspect.getmodule(fn)]
    mods += list(getattr(fn, "_aot_code_deps", ()))
    for mod in mods:
        try:
            src = inspect.getsource(mod)
        except (OSError, TypeError):
            code = getattr(fn, "__code__", None)
            src = repr(code.co_code if code else fn)
        h.update(src.encode())
    return h.hexdigest()[:16]


def _key(name: str, fn: Callable, example_args: Tuple[Any, ...],
         static: Dict[str, Any],
         donate_argnums: Tuple[int, ...] = ()) -> str:
    import jax

    parts = [_CACHE_VERSION, _platform_fingerprint(), name,
             _code_fingerprint(fn)]
    for a in example_args:
        parts.append(f"{jax.numpy.shape(a)}:{jax.numpy.result_type(a)}")
    for k in sorted(static):
        parts.append(f"{k}={static[k]!r}")
    if donate_argnums:
        # Donation changes the executable's aliasing config, not its math;
        # keyed only when requested so pre-existing entries keep their keys.
        parts.append(f"donate={tuple(donate_argnums)!r}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:32]


def _log(msg: str) -> None:
    if os.environ.get("DSI_AOT_QUIET") != "1":
        print(f"[aotcache] {msg}", file=sys.stderr, flush=True)


def is_persisted(name: str, fn: Callable, example_args: Tuple[Any, ...],
                 static: Dict[str, Any] | None = None,
                 donate_argnums: Tuple[int, ...] = ()) -> bool:
    """True when a compiled executable for exactly this (platform, source,
    shapes, static) key is already on disk.  Pure existence probe — no
    compile, no load, no device work beyond the platform fingerprint
    (which needs backends initialized, as every caller already has).

    Lets a time-boxed process decide whether touching a program is a
    millisecond load or a multi-minute remote compile BEFORE committing —
    on the axon platform a cold compile mid-bench can eat the whole
    attempt budget (BASELINE.md incident log).

    Mirrors cached_compile's LOAD policy, not just file existence: with
    the DSI_AOT_CACHE=0 kill switch, or in a multi-device process (where
    deserialized executables reject single-device args — see
    cached_compile), the entry on disk would never be loaded, so the
    honest answer is False."""
    import jax

    if os.environ.get("DSI_AOT_CACHE", "1") == "0":
        return False
    if len(jax.devices()) != 1:
        return False
    key = _key(name, fn, example_args, static or {}, donate_argnums)
    return os.path.exists(os.path.join(cache_dir(), f"{name}-{key}.aot"))


def cached_compile(name: str, fn: Callable, example_args: Tuple[Any, ...],
                   static: Dict[str, Any] | None = None,
                   persist: bool | None = None,
                   donate_argnums: Tuple[int, ...] = (),
                   x64: bool = False) -> Callable:
    """Return a compiled callable for ``fn`` at ``example_args``' avals.

    ``static`` are keyword arguments baked into the program (and the cache
    key).  The result accepts positional arrays with exactly the example
    shapes/dtypes.  Thread-safe; per-process memoized.  ``persist=False``
    keeps the in-process memo + compile-time accounting but never touches
    disk; the default honors the ``DSI_AOT_CACHE=0`` kill switch.
    ``donate_argnums`` marks input buffers the caller hands to the program
    (jax.jit semantics; the streaming pipeline donates its per-step chunk
    uploads so an in-flight window never doubles HBM residency) — callers
    must not reuse a donated argument after the call.  ``x64=True`` runs
    trace/lower/compile under the scoped x64 flag — required for programs
    whose bodies touch uint64 (utils/jaxcompat.x64_scoped rationale).
    """
    import jax

    if persist is None:
        persist = os.environ.get("DSI_AOT_CACHE", "1") != "0"
    static = static or {}
    key = _key(name, fn, example_args, static, donate_argnums)
    with _memo_lock:
        hit = _memo.get(key)
    if hit is not None:
        return hit

    path = os.path.join(cache_dir(), f"{name}-{key}.aot")
    jitted = jax.jit(fn, static_argnames=tuple(static or ()),
                     donate_argnums=donate_argnums)

    # Disk persistence is for the real chip (one device per process).  In a
    # multi-device process (the 8-virtual-CPU test mesh) a deserialized
    # executable comes back bound to every visible device and then rejects
    # single-device arguments — so compile in-process instead (still
    # memoized, still counted in stats).
    persist = persist and len(jax.devices()) == 1

    # DSI_AOT_FRESH=1 skips persisted LOADS (compiles fresh, still
    # saves): the mitigation for the known 1-device widen-shape
    # heap-corruption flake where a deserialized executable
    # intermittently corrupts the heap or the counts (CHANGES.md PR 8;
    # OPERATIONS.md runbook).  Loads stay attributable either way —
    # every load logs basename+digest+shapes and lands in the trace's
    # control lane as an ``aot_load`` event.
    fresh = os.environ.get("DSI_AOT_FRESH") == "1"
    loaded = _try_load(path) if (persist and not fresh) else None
    if loaded is None:
        compiled = _compile_with_retry(jitted, example_args, static, name,
                                       x64=x64)
        if persist:
            _try_save(path, compiled, name)
        loaded = compiled
    else:
        stats["loads"] += 1
        # Flake attribution (ISSUE 10): WHICH persisted entry, at WHICH
        # digest and shapes, was deserialized — so a later heap
        # corruption or silent count mismatch names its suspect instead
        # of "some aot entry".  Mirrored into the tracer's control lane
        # when tracing is on.
        shapes = ",".join(str(tuple(getattr(a, "shape", ())))
                          for a in example_args)
        _log(f"{name}: loaded from {os.path.basename(path)} "
             f"(digest={key} shapes={shapes})")
        try:
            from dsi_tpu.obs import get_tracer

            get_tracer().event(
                "aot_load", lane="control", name=name,
                file=os.path.basename(path), digest=key, shapes=shapes,
                bytes=os.path.getsize(path))
        except Exception:
            pass  # attribution must never break a load
        loaded = _verify_first_call(loaded, path, name, jitted,
                                    example_args, static, x64=x64,
                                    donate_argnums=donate_argnums)

    with _memo_lock:
        _memo[key] = loaded
    return loaded


#: Status substrings that mean "the tunnel blipped", not "this program
#: or entry is broken": retrying (compile) or re-raising to the caller's
#: outage machinery (first-call verify) is right; evicting or marking a
#: cache entry over one of these would trade a warm load for remote
#: recompiles.  Drawn from the outage log (BASELINE.md): UNAVAILABLE
#: ("Unexpected EOF" / "Connection refused"), plus the other transient
#: gRPC statuses the same transport surfaces.
_TRANSIENT = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED",
              "Socket closed")


def _is_transient(e: Exception) -> bool:
    return any(t in str(e) for t in _TRANSIENT)


def _tunnel_answers() -> bool:
    """2 s side-effect-free TCP probe of the stateless tunnel port (the
    one jax.devices() uses), so a compile retry can distinguish an RPC
    blip (retry is worth it) from a full outage (fail fast and let the
    caller's bounded-attempt machinery cycle).  ``DSI_TUNNEL_PROBE_PORT=0``
    disables the probe (always 'answers').

    Default: probe 8083 ONLY when this process targets the axon tunnel
    (decided from the platform-pin environment, NOT from
    ``get_backend()`` — a backend-initializing call here could itself
    hang on the outage this probe exists to sidestep); on any other
    platform a closed local port says nothing about the compile service,
    and failing the probe there would silently disable retries
    everywhere except the one machine the port exists on (ADVICE r4)."""
    import socket

    env = os.environ.get("DSI_TUNNEL_PROBE_PORT")
    if env is None:
        from jax._src import xla_bridge

        if xla_bridge._backends:
            # Backends already initialized (every current caller's case):
            # asking the live backend is free and authoritative.
            axon = "axon" in _platform_fingerprint()
            if not axon:
                return True
        else:
            # Pre-init: never trigger initialization from here — decide
            # from the platform-pin environment when it says anything.
            # With NO pin at all the environment is inconclusive:
            # fall through to the probe rather than assume non-axon —
            # that assumption answered "tunnel fine" during real outages
            # and disabled the fast-fail exactly where it matters
            # (ADVICE r5 item 4).  A pinned non-axon process (tests,
            # soaks set JAX_PLATFORMS=cpu) still skips the probe, so a
            # closed local 8083 cannot disable retries there.
            pins = (os.environ.get("JAX_PLATFORMS", "")
                    + os.environ.get("DSI_JAX_PLATFORM", ""))
            if pins and "axon" not in pins:
                return True
        port = 8083
    else:
        port = int(env)
    if port == 0:
        return True
    s = socket.socket()
    s.settimeout(2)
    try:
        s.connect(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def _compile_with_retry(jitted, example_args, static, name: str,
                        x64: bool = False):
    """lower+compile pinned to one device, with bounded transient retry.

    Pinning: under a multi-device process (e.g. the 8-virtual-CPU test
    mesh) an unpinned lower() targets every visible device and the
    executable then demands 8-sharded args; these are single-chunk
    kernels, one device by design.

    Retry: the axon remote-compile RPC has died mid-compile with
    UNAVAILABLE ("Unexpected EOF" / "Connection refused") after tens of
    minutes (BASELINE.md outages #3/#4).  Dying here forfeits the whole
    process — init, device claim, and any earlier warm loads — so a
    bounded retry (DSI_COMPILE_RETRIES, default 2) re-issues the compile
    in-process while the claim is still held.  Between attempts it
    pauses briefly and probes the tunnel port: a dead tunnel fails every
    retry in milliseconds, so raising immediately hands control back to
    the caller's outage machinery instead of burning the budget.
    Non-transient errors (OOM, lowering bugs) raise immediately."""
    import contextlib
    import time

    import jax

    from dsi_tpu.utils.jaxcompat import enable_x64

    retries = int(os.environ.get("DSI_COMPILE_RETRIES", "2"))
    t0 = time.perf_counter()
    x64_scope = enable_x64(True) if x64 else contextlib.nullcontext()
    with jax.default_device(jax.devices()[0]), x64_scope:
        for attempt in range(retries + 1):
            try:
                compiled = jitted.lower(*example_args, **static).compile()
                break
            except Exception as e:  # jax wraps XLA status in several
                if not _is_transient(e) or attempt == retries:
                    raise
                time.sleep(float(os.environ.get(
                    "DSI_COMPILE_RETRY_PAUSE_S", "10")))
                if not _tunnel_answers():
                    raise  # outage, not a blip — fail fast to the caller
                _log(f"{name}: compile attempt {attempt + 1} died "
                     f"transient ({str(e)[:120]}); tunnel answers, "
                     "retrying")
    dt = time.perf_counter() - t0
    stats["compiled_s"] += dt
    stats["compiles"] += 1
    _log(f"{name}: compiled in {dt:.1f}s")
    return compiled


def _verify_first_call(exe, path: str, name: str, jitted,
                       example_args, static, x64: bool = False,
                       donate_argnums: Tuple[int, ...] = ()) -> Callable:
    """Trust-but-verify wrapper for DESERIALIZED executables: a loaded
    entry can pass deserialization yet fail at EXECUTION (observed on
    this host 2026-07-31: XLA:CPU AOT loader warns of a machine-feature
    mismatch, then the first invocation dies with ``NOT_FOUND: Buffer
    Definition Event: Function ..._kernel not found``).  ``_try_load``
    cannot see that; this wrapper blocks on the first call's outputs so
    any execution-time failure surfaces HERE (async dispatch would defer
    it to the caller's D2H), evicts the poisoned entry, recompiles
    in-process, re-persists, and re-invokes.  After one verified call it
    delegates directly."""
    import jax

    state = {"exe": exe, "verified": False}

    def call(*args):
        if state["verified"]:
            return state["exe"](*args)
        backups = None
        if donate_argnums:
            # The first invocation DONATES (consumes) these inputs; the
            # evict-recompile-reinvoke recovery below re-runs with the
            # same args, which would hit 'Array has been deleted' instead
            # of recovering.  Keep device copies until the call verifies
            # — a one-time cost per loaded program, dropped on success.
            # Copy under the x64 scope when the program needs it: outside
            # it jnp.array canonicalizes a uint64 operand down to uint32,
            # and the recovery re-invoke would hand the recompiled
            # executable a wrong-dtype (truncated) argument.
            import contextlib

            import jax.numpy as jnp

            from dsi_tpu.utils.jaxcompat import enable_x64

            scope = enable_x64(True) if x64 else contextlib.nullcontext()
            with scope:
                backups = {i: jnp.array(args[i], copy=True)
                           for i in donate_argnums if i < len(args)}
        try:
            out = state["exe"](*args)
            jax.block_until_ready(out)
        except Exception as e:
            if _is_transient(e):
                # Tunnel hiccup, not a poisoned entry: let the caller's
                # outage machinery re-run; evicting or marking over a
                # blip would permanently trade a warm load for remote
                # recompiles.
                raise
            _log(f"{name}: loaded executable failed its first execution "
                 f"({type(e).__name__}: {str(e)[:120]}); evicting + "
                 "recompiling")
            try:
                os.remove(path)
            except OSError:
                pass
            if "NOT_FOUND" in str(e):
                # The observed poison class (missing kernel symbol after
                # deserialization: the SERIALIZATION of this program is
                # broken on this machine, a fresh recompile works).  The
                # sidecar marker makes future processes compile this
                # entry directly; a kernel edit changes the fingerprint
                # (and the marker path) and gets a fresh chance.
                try:
                    # dsicheck: allow[raw-write] best-effort poison
                    # marker: losing it to a crash only costs one
                    # retried load; tearing it is harmless (existence
                    # is the signal, content is diagnostic)
                    with open(path + ".execfail", "w") as f:
                        f.write(f"{type(e).__name__}: {str(e)[:200]}\n")
                except OSError:
                    pass
            compiled = _compile_with_retry(jitted, example_args, static,
                                           name, x64=x64)
            # Outside the poison class the entry bytes may simply have
            # been stale/corrupt — re-persist the fresh executable
            # (_try_save itself skips marked entries).
            _try_save(path, compiled, name)
            state["exe"] = compiled
            if backups:
                args = list(args)
                for i, b in backups.items():
                    args[i] = b
            out = state["exe"](*args)
        state["verified"] = True
        return out

    return call


def _try_load(path: str):
    if not os.path.exists(path):
        return None
    if os.path.exists(path + ".execfail"):
        # This entry deserialized but failed its first EXECUTION on this
        # machine before (see _verify_first_call); loading it again just
        # repeats the failure, so compile directly.
        _log(f"skipping {os.path.basename(path)}: previous load failed "
             "execution on this machine (.execfail marker)")
        return None
    try:
        from jax.experimental.serialize_executable import deserialize_and_load

        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        return deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:  # corrupt / version-drifted entry: recompile
        _log(f"load failed ({type(e).__name__}: {e}); recompiling")
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def _try_save(path: str, compiled, name: str) -> None:
    if os.path.exists(path + ".execfail"):
        return  # serialization of this program is broken on this machine
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        # dsicheck: allow[raw-write] cache entry: temp+rename keeps it
        # atomic; fsync durability is deliberately skipped (an entry
        # lost to power failure recompiles; _try_load discards a
        # corrupt one), and pickle streams too large to buffer twice
        with open(tmp, "wb") as f:
            pickle.dump((payload, in_tree, out_tree), f)
        os.replace(tmp, path)  # atomic: concurrent writers can't corrupt
        _log(f"{name}: saved {os.path.getsize(path)} bytes")
    except Exception as e:  # backend without serialization: plain compile
        _log(f"save failed ({type(e).__name__}: {e}); continuing uncached")
