"""Native task backend: runs task bodies through the C++ job kernels.

The reference's per-task compute is compiled Go (``mrapps/wc.go:21-44``,
``mr/worker.go:110-146``); the framework's default host path re-creates
those semantics in Python and pays interpreter costs per token/record.
This runner (``mrworker --backend native``) executes the whole task body
in one C++ call for apps that declare a supported ``native_kind``
(currently ``"wc_combine"`` — the word-count combiner family,
``apps/tpu_wc.py``), falling back to the exact host path whenever the
native side declines (non-ASCII input, JSON escapes, missing library) —
the same correctness-never-depends-on-the-kernel contract as the TPU
backend (``backends/tpu.py``).
"""

from __future__ import annotations

import os

from dsi_tpu.mr import worker as w
from dsi_tpu.mr.plugin import load_plugin_module
from dsi_tpu.utils.atomicio import atomic_write


def _wc_map(filename, n_reduce):
    from dsi_tpu import native

    return native.wc_map_file(filename, n_reduce)


def _wc_reduce(workdir, reduce_task, n_map):
    from dsi_tpu import native

    return native.wc_reduce(workdir, reduce_task, n_map)


def _idx_map(filename, n_reduce):
    from dsi_tpu import native

    # The host Map's document value is the filename argument verbatim
    # (apps/indexer.py Map).
    return native.idx_map_file(filename, filename, n_reduce)


def _idx_reduce(workdir, reduce_task, n_map):
    from dsi_tpu import native

    return native.idx_reduce(workdir, reduce_task, n_map)


def _grep_map(filename, n_reduce):
    from dsi_tpu import native

    # Same out-of-band pattern source as the app (apps/grep.py).
    pattern = os.environ.get("DSI_GREP_PATTERN", "")
    if not pattern:
        return None
    return native.grep_map_file(filename, pattern, n_reduce)


def _grep_reduce(workdir, reduce_task, n_map):
    from dsi_tpu import native

    return native.grep_reduce(workdir, reduce_task, n_map)


def _tfidf_map(filename, n_reduce):
    from dsi_tpu import native

    return native.tfidf_map_file(filename, filename, n_reduce)


#: native_kind -> (map body, reduce body); each returns None to decline.
#: A None reduce body means that phase always runs the Python path (the
#: tfidf reduce does float scoring whose formatting parity belongs to
#: the shared Python format_value).
_KINDS = {
    "wc_combine": (_wc_map, _wc_reduce),
    "indexer": (_idx_map, _idx_reduce),
    "grep_count": (_grep_map, _grep_reduce),
    "tfidf": (_tfidf_map, None),
}


class NativeTaskRunner:
    """Backend object for ``worker_loop(task_runner=...)``."""

    def __init__(self, app_module):
        self.app = app_module
        self.kind = getattr(app_module, "native_kind", None)
        if self.kind not in _KINDS:
            import sys

            print(
                f"mrworker: app {getattr(app_module, '__name__', app_module)}"
                " declares no supported native_kind; --backend=native will "
                f"run every task on the host path (supported:"
                f" {sorted(_KINDS)})", file=sys.stderr)
            self.kind = None

    @classmethod
    def for_app(cls, name_or_path: str) -> "NativeTaskRunner":
        return cls(load_plugin_module(name_or_path))

    def run_map(self, mapf, filename: str, map_task: int, n_reduce: int,
                workdir: str = ".") -> None:
        blobs = (_KINDS[self.kind][0](filename, n_reduce)
                 if self.kind else None)
        if blobs is None:  # host fallback (worker.go:55-92 semantics)
            w.run_map_task(mapf, filename, map_task, n_reduce, workdir)
            return
        for r, blob in enumerate(blobs):
            with atomic_write(w.intermediate_name(map_task, r, workdir),
                              mode="wb") as f:
                f.write(blob)

    def run_reduce(self, reducef, reduce_task: int, n_map: int,
                   workdir: str = ".") -> None:
        body = _KINDS[self.kind][1] if self.kind else None
        blob = body(workdir, reduce_task, n_map) if body else None
        if blob is None:
            w.run_reduce_task(reducef, reduce_task, n_map, workdir)
            return
        # Same commit + GC discipline as the host reduce (first-writer-
        # wins against re-queued duplicates; errors-ignored intermediate
        # GC — worker.go:148,151-154 with the duplicate-race fix).
        with atomic_write(w.output_name(reduce_task, workdir),
                          first_wins=True, mode="wb") as out:
            out.write(blob)
        for i in range(n_map):
            try:
                os.remove(w.intermediate_name(i, reduce_task, workdir))
            except OSError:
                pass
