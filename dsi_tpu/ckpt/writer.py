"""The capture/commit split: overlapped checkpoint commits.

PR 5's ``save_ckpt`` was synchronous end to end: pull complete device
images, serialize, CRC, fsync — all on the engine thread, stalling the
pipeline window for the whole durable write.  This module splits it:

* **capture** (engine thread, at the confirmed-step boundary): the
  device services dispatch their snapshot pulls without blocking
  (freshly packed buffers + ``copy_to_host_async`` — fresh outputs, so
  later folds that DONATE the live state cannot invalidate the
  capture), the host accumulators are snapshotted by reference (their
  merge tables are append-only: later adds create new buffers, never
  mutate captured ones) with small scalars copied — and the capture is
  handed to the writer.  Cost: flag flushes + dispatches, not wire.
* **commit** (writer thread): materialize the deferred pulls (the D2H
  has been draining under the next pipeline window), serialize, and run
  the existing ``CheckpointStore`` durable path.  Commits are strictly
  ordered (one worker — ``parallel/pipeline.CommitWorker``), so seq
  numbering and newest-valid-wins semantics are untouched.

The barrier rule: the engine blocks only when the NEXT save (or the
stream end) finds the previous commit still draining —
``submit``'s bounded queue — accounted in ``ckpt_barrier_s``.  With
async off the same capture/commit code runs inline on the engine
thread: bit-identical PR-5 behavior, one code path.

Fault points: ``mid-commit`` fires in the writer after materialize and
before the store write (a crash there must leave the previous chain
winning), and ``post-ckpt`` moves INTO the commit — it means "right
after a checkpoint manifest commits" and keeps meaning that when the
commit is asynchronous.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dsi_tpu.ckpt.delta import materialize_part
from dsi_tpu.ckpt.fault import fault_point
from dsi_tpu.ckpt.policy import checkpoint_rebase_default
from dsi_tpu.ckpt.store import CheckpointStore
from dsi_tpu.obs import span as _span
from dsi_tpu.parallel.pipeline import CommitWorker

#: A capture: ordered (prefix, part) pairs — part a ready dict or a
#: Deferred — exactly the arrays dict the engine used to build inline,
#: split so device pulls can finish in the writer.
CaptureParts = List[Tuple[str, object]]


class CheckpointWriter:
    """Commit captured snapshots through one ``CheckpointStore`` —
    inline when ``async_`` is off (the PR-5 path, bit-identical),
    through a :class:`~dsi_tpu.parallel.pipeline.CommitWorker`
    otherwise.  Also owns the delta-window state machine the engines
    share: :meth:`want_delta` says whether the next save may be
    incremental (a base exists, the re-base window isn't due), and
    every commit advances the window — one implementation instead of
    four per-engine copies.  ``stats`` receives
    ``ckpt_saves``/``ckpt_deltas``, ``ckpt_commit_s``/
    ``ckpt_barrier_s``, the ``ckpt_full_bytes``/``ckpt_delta_bytes``
    payload totals the bench's delta A/B reads, and the compression
    attribution (``ckpt_compress`` mode, ``ckpt_delta_raw_bytes``
    uncompressed denominator, ``ckpt_compress_s`` zlib wall — on the
    worker thread under async, exactly like ``ckpt_commit_s``)."""

    def __init__(self, store: CheckpointStore, stats: dict,
                 async_: bool = False, delta: bool = False,
                 rebase: Optional[int] = None):
        self.store = store
        self.stats = stats
        self.async_ = bool(async_)
        self.delta = bool(delta)
        #: Re-base window: every ``rebase``-th save is a full image
        #: (``DSI_STREAM_CKPT_REBASE``, default 8; 1 = every save full,
        #: deltas effectively disabled).
        self.rebase = (checkpoint_rebase_default() if rebase is None
                       else max(1, int(rebase)))
        self._since_full = -1  # saves since the last full; -1 = no base
        self._worker: Optional[CommitWorker] = None
        if self.async_:
            self._worker = CommitWorker(name="dsi-ckpt-writer")
        for key in ("ckpt_saves", "ckpt_deltas", "ckpt_full_bytes",
                    "ckpt_delta_bytes", "ckpt_delta_raw_bytes"):
            self.stats.setdefault(key, 0)
        for key in ("ckpt_commit_s", "ckpt_barrier_s",
                    "ckpt_compress_s"):
            self.stats.setdefault(key, 0.0)
        self.stats.setdefault("ckpt_compress", store.compress)

    def want_delta(self) -> bool:
        """True when the NEXT save may be incremental: delta mode is
        on, this run has already committed a base, and the chain has
        not reached the re-base window (``rebase - 1`` deltas per
        full — so ``rebase=1`` really is every-save-full).  The engine
        still falls back to a full save when its delta window is
        invalid (``take_delta()`` returned None)."""
        return self.delta and 0 <= self._since_full < self.rebase - 1

    def commit(self, parts: CaptureParts, meta: Dict,
               kind: str = "full") -> None:
        """Hand one capture to the commit path.  Async: returns as soon
        as a writer slot is free (blocking time → ``ckpt_barrier_s``);
        a previous commit's error re-raises HERE, on the engine
        thread.  Sync: commits before returning."""
        def do_commit():
            with _span("ckpt_commit", lane="ckpt", stats=self.stats,
                       key="ckpt_commit_s", kind=kind):
                arrays: Dict = {}
                for prefix, part in parts:
                    for k, v in materialize_part(part).items():
                        arrays[prefix + k] = v
                fault_point("mid-commit")
                if kind == "delta":
                    self.store.save_delta(arrays, meta)
                    self.stats["ckpt_deltas"] += 1
                    self.stats["ckpt_delta_bytes"] += \
                        self.store.last_payload_bytes
                    # The compression A/B's denominator: what this
                    # delta's arrays would have cost raw.
                    self.stats["ckpt_delta_raw_bytes"] += \
                        self.store.last_payload_raw_bytes
                else:
                    self.store.save(arrays, meta)
                    self.stats["ckpt_full_bytes"] += \
                        self.store.last_payload_bytes
                self.stats["ckpt_compress_s"] += self.store.last_compress_s
                self.stats["ckpt_saves"] += 1
            fault_point("post-ckpt")

        self._since_full = 0 if kind == "full" else self._since_full + 1
        if self._worker is None:
            do_commit()
        else:
            self.stats["ckpt_barrier_s"] += self._worker.submit(do_commit)

    def drain(self) -> None:
        """Block until every submitted commit is durable; re-raise the
        first commit error.  Engines call this before finalizing their
        result (and before reading save counters)."""
        if self._worker is not None:
            self.stats["ckpt_barrier_s"] += self._worker.drain()

    def shutdown(self) -> None:
        """Silent join for ``finally`` blocks (never masks an engine
        exception already unwinding; a stored commit error is simply
        dropped with the run)."""
        if self._worker is not None:
            self._worker.shutdown()
