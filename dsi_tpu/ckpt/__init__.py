"""Checkpoint/restore for the streaming engines.

The reference system's whole fault-tolerance story is re-execution: a
task that dies is re-run from its input files (10 s presumed-dead
timeout, ``mr/coordinator.go``), and the control-plane journal
(``mr/journal.py``) extends that to coordinator death.  The streaming
engines broke that model's assumption — their value IS the gigabytes of
cross-step state held on device (`dsi_tpu/device/`) with ``step_pulls=0``
— so a worker death lost the whole stream and the only recovery was a
full replay.  This package closes that gap:

* :mod:`~dsi_tpu.ckpt.policy` — :class:`CheckpointPolicy`, the cadence
  (every K confirmed steps and/or T seconds), mirroring
  ``device/policy.SyncPolicy``;
* :mod:`~dsi_tpu.ckpt.store` — :class:`CheckpointStore`, the durable
  versioned (payload, manifest) pairs with CRC sidecars, parent-dir
  fsync, newest-valid-wins loading and last-two retention;
* :mod:`~dsi_tpu.ckpt.fault` — :func:`fault_point`, the named
  kill-points (``DSI_FAULT_POINT``/``DSI_FAULT_STEP``) that let tests
  and ``onchip_evidence.sh`` prove resume against REAL crashes;
* :mod:`~dsi_tpu.ckpt.writer` — :class:`CheckpointWriter`, the
  capture/commit split (``--ckpt-async``: snapshot pulls overlap the
  next pipeline window, a background writer runs the durable path);
* :mod:`~dsi_tpu.ckpt.delta` — the incremental payload format
  (``--ckpt-delta``: a save ships only the confirmed step payloads
  appended since the previous one; the store chains ``delta-<seq>``
  manifests onto their base, restore = base + ordered deltas).

The consistency contract, owned here and honored by every engine
(``parallel/streaming.py``, ``parallel/grepstream.py``,
``parallel/tfidf.py``): a checkpoint is taken only at a CONFIRMED-step
boundary and contains (a) the host accumulators, (b) drain-free images
of every live device service (flushed of lagged flags, pulled but NOT
cleared), (c) the sticky dispatch-rung state, and (d) the input cursor
of the last confirmed step.  Steps in the in-flight window — dispatched
but with deferred checks unread — are deliberately EXCLUDED: their
outputs were never merged, so re-reading the input from the cursor and
re-processing them preserves exactly-once through the same
replay-at-sticky-rungs ladder that makes the pipelined engines
bit-identical to ``depth=1``.  Resume therefore yields bit-identical
final output to an uninterrupted run — the parity gate
tests/test_checkpoint.py enforces per engine, fault point, depth, and
device_accumulate mode.
"""

from dsi_tpu.ckpt.fault import (
    CHAOS_EXIT,
    FAULT_EXIT,
    FAULT_POINTS,
    FaultInjected,
    chaos_kill_point,
    fault_point,
    reset_chaos,
    reset_faults,
)
from dsi_tpu.ckpt.delta import (
    Deferred,
    DeltaSteps,
    HostDeltaLog,
    drain_packed_steps,
    drain_posting_steps,
    iter_delta_steps,
)
from dsi_tpu.ckpt.policy import (
    CheckpointPolicy,
    checkpoint_async_default,
    checkpoint_compress_default,
    checkpoint_delta_default,
    checkpoint_every_default,
    checkpoint_rebase_default,
    checkpoint_secs_default,
)
from dsi_tpu.ckpt.store import (
    CKPT_VERSION,
    CheckpointMismatch,
    CheckpointStore,
    skip_stream,
)
from dsi_tpu.ckpt.writer import CheckpointWriter

__all__ = [
    "CKPT_VERSION",
    "CheckpointMismatch",
    "CheckpointPolicy",
    "CheckpointStore",
    "CheckpointWriter",
    "Deferred",
    "DeltaSteps",
    "HostDeltaLog",
    "CHAOS_EXIT",
    "FAULT_EXIT",
    "FAULT_POINTS",
    "FaultInjected",
    "chaos_kill_point",
    "reset_chaos",
    "checkpoint_async_default",
    "checkpoint_compress_default",
    "checkpoint_delta_default",
    "checkpoint_every_default",
    "checkpoint_rebase_default",
    "checkpoint_secs_default",
    "drain_packed_steps",
    "drain_posting_steps",
    "fault_point",
    "iter_delta_steps",
    "reset_faults",
    "skip_stream",
]
