"""Versioned, CRC-guarded checkpoint store.

One checkpoint = one numpy payload (``state-<seq>.npz``: every array the
engine needs back, host accumulators and pulled device-service images
alike) plus one JSON manifest (``manifest-<seq>.json``: format version,
engine name, job identity, cursor and sticky-rung metadata, and the
payload's name + CRC32).  Both files go through the shared durable-write
path (``utils/atomicio.write_bytes_durable``: temp + fsync + rename +
CRC32 sidecar + parent-dir fsync) — the same discipline the control
plane's journal uses, so a crash at ANY instant leaves either a fully
valid checkpoint or recognisable garbage, never a half-truth:

* crash mid-payload: a ``.tmp-*`` orphan, no manifest — invisible to
  the loader, reaped by the next save (and by the bench's try/finally);
* crash between payload and manifest: a payload with no manifest —
  invisible;
* torn/corrupt file that somehow survives rename: the CRC sidecar (and
  the payload CRC recorded in the manifest) fails verification and the
  loader falls back to the previous checkpoint — which is why the last
  TWO checkpoints are retained and only older ones garbage-collected.

The manifest carries the job identity (engine name + the shape knobs
that change byte layout); resuming against a different job is refused
rather than silently corrupting state — the journal-header rule.

Payloads may be COMPRESSED (``DSI_STREAM_CKPT_COMPRESS``, ISSUE 13 —
default ``deltas``): the serialize step swaps ``np.savez`` for
``np.savez_compressed`` into the same ``BytesIO``, so the durable
commit path (CRC sidecar, tmp+fsync+rename) and every loader are
byte-for-byte unchanged — ``np.load`` reads both flavors, mixed
chains restore fine, and the mode is deliberately NOT part of the job
identity.  ``last_payload_raw_bytes``/``last_compress_s`` feed the
``ckpt_delta_raw_bytes``/``ckpt_compress_s`` attribution through the
writer.

## Delta chains (incremental snapshots)

A checkpoint may be INCREMENTAL: ``save_delta`` writes a
``delta-<seq>.npz`` payload whose manifest carries ``kind: "delta"`` and
``prev: <seq>`` — the checkpoint it extends.  A chain is one full image
(``state-<seq>.npz``, the base) plus the ordered deltas chained onto it;
restoring a chain = restore the base, then re-apply each delta's
increment oldest-first (the engines re-ingest the delta rows through
their host drain path, which is order-insensitive for count merges and
order-preserving for postings — the same argument the cross-degree
resume already rests on).  Newest-valid-wins generalizes to chains: the
loader walks manifests newest→oldest and returns the first seq whose
ENTIRE chain back to a base verifies; a torn middle delta invalidates
every seq above it and the walk falls back to the last complete chain
(ultimately the bare base).  GC is chain-aware: the last two restore
points are retained *with every chain member they reference*, so a
live delta chain can never lose its base to retention.
"""

from __future__ import annotations

import io
import json
import os
import re
import time
import zlib
from typing import Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from dsi_tpu.ckpt.policy import checkpoint_compress_default
from dsi_tpu.obs import trace_event as _trace_event
from dsi_tpu.utils.atomicio import (
    read_bytes_verified,
    reap_tmp_files,
    write_bytes_durable,
)

#: Bumped whenever the payload/manifest layout changes incompatibly; a
#: loader refuses versions it does not know instead of misreading them.
CKPT_VERSION = 1

_MANIFEST_RE = re.compile(r"^manifest-(\d{6})\.json$")


class CheckpointMismatch(RuntimeError):
    """A valid checkpoint exists but belongs to a different job."""


def _zlevel() -> int:
    """Deflate level for compressed payloads (``DSI_STREAM_CKPT_ZLEVEL``,
    default 1): on the 2-core boxes the CommitWorker shares with the
    engine, level 1 keeps ~85% of level 6's ratio at ~1/3 the CPU —
    cadence-1 overhead stays flat while the bytes still drop 2-5x."""
    try:
        return min(9, max(1, int(os.environ.get("DSI_STREAM_CKPT_ZLEVEL",
                                                "1"))))
    except ValueError:
        return 1


def _write_npz_compressed(buf, arrays: Dict[str, np.ndarray]) -> None:
    """``np.savez_compressed`` with a CHOSEN deflate level (numpy
    hardcodes the zlib default): same zip-of-.npy container, so
    ``np.load`` reads it identically and mixed chains stay readable."""
    import zipfile

    from numpy.lib import format as npformat

    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED,
                         compresslevel=_zlevel()) as zf:
        for k, v in arrays.items():
            with zf.open(k + ".npy", "w", force_zip64=True) as f:
                npformat.write_array(f, np.asarray(v),
                                     allow_pickle=False)


def skip_stream(blocks: Iterable[bytes], skip: int) -> Iterator[bytes]:
    """Drop the first ``skip`` bytes of a block stream — the resume
    seek.  The engines' batchers are pure functions of the byte stream,
    so feeding them the suffix from the confirmed cursor reproduces the
    uninterrupted run's remaining batches exactly."""
    remaining = int(skip)
    for b in blocks:
        if remaining:
            if len(b) <= remaining:
                remaining -= len(b)
                continue
            b = bytes(memoryview(b)[remaining:])
            remaining = 0
        yield b


class CheckpointStore:
    """Save/load numbered (payload, manifest) checkpoint pairs in one
    directory, newest-valid-wins, last two retained."""

    def __init__(self, directory: str, engine: str, job: Dict,
                 compress: Optional[str] = None):
        self.dir = directory
        self.engine = engine
        #: The identity a checkpoint must match to be resumable: every
        #: knob that changes byte layout or stream cutting (chunk size,
        #: mesh width, reduce count, pattern, ...).  JSON-normalised so
        #: tuple-vs-list spelling differences can't refuse a real match.
        self.job = json.loads(json.dumps(job))
        #: Payload-compression mode (``ckpt/policy.py
        #: checkpoint_compress_default``: off / deltas / all).  Purely a
        #: serialization choice — ``np.load`` reads both npz flavors, so
        #: mixed chains restore fine and the mode is NOT part of the job
        #: identity.
        self.compress = checkpoint_compress_default(compress)
        #: Serialized payload size of the most recent save — the bench's
        #: delta-vs-full bytes evidence rides this through the writer.
        self.last_payload_bytes = 0
        #: Raw array bytes behind the most recent payload (sum of
        #: ``nbytes`` — the compression ratio's denominator) and the
        #: seconds the compressing serialize spent (0.0 for a raw save);
        #: the writer maps these to ``ckpt_delta_raw_bytes`` /
        #: ``ckpt_compress_s``.
        self.last_payload_raw_bytes = 0
        self.last_compress_s = 0.0
        os.makedirs(self.dir, exist_ok=True)

    # ── paths ──

    def _manifest_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"manifest-{seq:06d}.json")

    def _payload_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"state-{seq:06d}.npz")

    def _delta_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"delta-{seq:06d}.npz")

    def _seqs(self) -> list[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _MANIFEST_RE.match(n)))

    # ── writing ──

    def reset(self) -> None:
        """Start a fresh lineage: remove every manifest/payload/sidecar
        (and orphan temp file) so a later ``--resume`` can never pick up
        a checkpoint from a PREVIOUS job's run that this run has since
        diverged from."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for n in names:
            if (n.startswith(("manifest-", "state-", "delta-", ".tmp-"))
                    and not os.path.isdir(os.path.join(self.dir, n))):
                try:
                    os.remove(os.path.join(self.dir, n))
                except OSError:
                    pass
        # Make the unlinks durable BEFORE the new lineage's first save:
        # without this, a crash after save() could resurrect a
        # higher-seq checkpoint of the PREVIOUS run (same job identity,
        # diverged state) and load_latest would prefer it.
        from dsi_tpu.utils.atomicio import fsync_dir

        fsync_dir(self.dir)

    def save(self, arrays: Dict[str, np.ndarray], meta: Dict) -> int:
        """Commit one FULL checkpoint (a chain base); returns its
        sequence number.  The payload lands durably BEFORE the manifest
        that names it, so the manifest's existence implies a complete
        payload."""
        return self._commit(arrays, meta, kind="full")

    def save_delta(self, arrays: Dict[str, np.ndarray], meta: Dict) -> int:
        """Commit one INCREMENTAL checkpoint chained onto the newest
        existing one (full or delta).  Refuses when the store is empty —
        a delta with nothing under it could never restore; the engines
        write a full base first (and re-base every
        ``DSI_STREAM_CKPT_REBASE`` saves)."""
        if not self._seqs():
            raise RuntimeError("delta checkpoint with no base: the first "
                               "save of a lineage must be full")
        return self._commit(arrays, meta, kind="delta")

    def _commit(self, arrays: Dict[str, np.ndarray], meta: Dict,
                kind: str) -> int:
        seqs = self._seqs()
        seq = (seqs[-1] + 1) if seqs else 1
        buf = io.BytesIO()
        compress = (self.compress == "all"
                    or (self.compress == "deltas" and kind == "delta"))
        if compress:
            # Same serialize-then-commit idiom, deflated payload; with
            # --ckpt-async this runs on the CommitWorker, so the
            # compression wall never lands on the engine thread.
            t0 = time.perf_counter()
            _write_npz_compressed(buf, arrays)
            self.last_compress_s = time.perf_counter() - t0
        else:
            np.savez(buf, **arrays)
            self.last_compress_s = 0.0
        self.last_payload_raw_bytes = sum(
            int(np.asarray(v).nbytes) for v in arrays.values())
        payload = buf.getvalue()
        path = (self._delta_path(seq) if kind == "delta"
                else self._payload_path(seq))
        crc = write_bytes_durable(path, payload)
        manifest = {
            "version": CKPT_VERSION,
            "engine": self.engine,
            "job": self.job,
            "seq": seq,
            "payload": os.path.basename(path),
            "payload_crc32": crc,
            "meta": meta,
        }
        if kind == "delta":
            manifest["kind"] = "delta"
            manifest["prev"] = seqs[-1]
        write_bytes_durable(
            self._manifest_path(seq),
            json.dumps(manifest, sort_keys=True).encode("utf-8"))
        self.last_payload_bytes = len(payload)
        self._gc()
        reap_tmp_files(self.dir)
        _trace_event("ckpt_save", lane="ckpt", engine=self.engine,
                     seq=seq, bytes=len(payload), kind=kind)
        return seq

    def _chain_members(self, seq: int) -> Tuple[set, bool]:
        """The seqs a restore at ``seq`` needs — ``seq`` itself plus,
        for a delta, everything down its ``prev`` links to the base —
        and whether the walk reached a full image.  Reads manifests
        WITHOUT CRC verification; an unreadable link ends the walk
        INCOMPLETE, and GC must then err toward retention: everything
        below the hole might be the complete chain the loader falls
        back to."""
        members = set()
        while seq not in members:
            members.add(seq)
            try:
                with open(self._manifest_path(seq), "rb") as f:
                    m = json.loads(f.read())
            except (OSError, ValueError):
                return members, False
            if m.get("kind") != "delta":
                return members, True
            seq = int(m.get("prev", seq))
        return members, False  # prev-link cycle: same retention rule

    def _gc(self) -> None:
        """Chain-aware last-two retention: keep the newest two restore
        points AND every chain member they reference (a live delta
        chain must never lose its base `state-<seq>.npz` to
        retention); remove everything else.  A chain walk that cannot
        reach its base (unreadable mid-chain manifest) protects every
        OLDER seq too — the loader's fallback could need any of them,
        and GC never reaps what the loader might still read."""
        seqs = self._seqs()
        protect: set = set()
        for seq in seqs[-2:]:
            members, complete = self._chain_members(seq)
            protect |= members
            if not complete:
                protect |= {s for s in seqs if s <= min(members)}
        for seq in seqs:
            if seq in protect:
                continue
            for path in (self._manifest_path(seq), self._payload_path(seq),
                         self._delta_path(seq)):
                for p in (path, path + ".crc32"):
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    # ── reading ──

    def _load_one(self, seq: int) -> Optional[Tuple[Dict,
                                                    Dict[str, np.ndarray]]]:
        """One verified (manifest, arrays) pair, or None when any check
        fails — manifest CRC, version, payload CRC.  A VALID manifest
        for a different job refuses loudly instead (silently starting
        fresh would overwrite a good lineage)."""
        raw = read_bytes_verified(self._manifest_path(seq))
        if raw is None:
            return None  # torn manifest
        try:
            manifest = json.loads(raw)
        except ValueError:
            return None
        if manifest.get("version") != CKPT_VERSION:
            return None
        if (manifest.get("engine") != self.engine
                or manifest.get("job") != self.job):
            raise CheckpointMismatch(
                f"checkpoint {self._manifest_path(seq)} belongs to a "
                f"different job (engine/job mismatch); refusing to "
                f"resume")
        payload = read_bytes_verified(
            os.path.join(self.dir, manifest["payload"]))
        if payload is None:
            return None
        if zlib.crc32(payload) != manifest["payload_crc32"]:
            return None
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: z[k] for k in z.files}
        return manifest, arrays

    def load_latest(self) -> Optional[Tuple[Dict, Dict[str, np.ndarray]]]:
        """Newest FULL checkpoint that passes every check, or None when
        no usable one exists.  A corrupt newest falls back to its
        predecessor (that is what last-two retention buys).  Delta
        manifests are skipped — a delta alone is not restorable; chain
        consumers use :meth:`load_latest_chain`."""
        for seq in reversed(self._seqs()):
            raw = read_bytes_verified(self._manifest_path(seq))
            if raw is None:
                continue
            try:
                if json.loads(raw).get("kind") == "delta":
                    continue  # manifest-only skip: no payload read for
                    # a delta this view can never return
            except ValueError:
                continue
            loaded = self._load_one(seq)
            if loaded is None:
                continue
            manifest, arrays = loaded
            _trace_event("ckpt_restore", lane="ckpt",
                         engine=self.engine, seq=seq)
            return manifest["meta"], arrays
        return None

    def load_latest_chain(self) -> Optional[Tuple[
            Dict, Dict[str, np.ndarray], list]]:
        """Newest restore point whose ENTIRE chain verifies, as
        ``(base_meta, base_arrays, deltas)`` with ``deltas`` the ordered
        ``[(delta_meta, delta_arrays), ...]`` oldest-first (empty for a
        bare full checkpoint — then this is exactly
        :meth:`load_latest`).  A chain torn anywhere — missing middle
        delta, corrupt base — invalidates every seq above the tear and
        the walk falls back to the last complete chain."""
        for seq in reversed(self._seqs()):
            chain = []
            seen = set()
            s = seq
            ok = True
            while True:
                if s in seen:  # corrupt prev link: never walk a cycle
                    ok = False
                    break
                seen.add(s)
                loaded = self._load_one(s)
                if loaded is None:
                    ok = False
                    break
                manifest, arrays = loaded
                chain.append((manifest, arrays))
                if manifest.get("kind") != "delta":
                    break
                s = int(manifest["prev"])
            if not ok:
                continue
            chain.reverse()
            base_manifest, base_arrays = chain[0]
            deltas = [(m["meta"], a) for m, a in chain[1:]]
            _trace_event("ckpt_restore", lane="ckpt", engine=self.engine,
                         seq=seq, deltas=len(deltas))
            return base_manifest["meta"], base_arrays, deltas
        return None
