"""Versioned, CRC-guarded checkpoint store.

One checkpoint = one numpy payload (``state-<seq>.npz``: every array the
engine needs back, host accumulators and pulled device-service images
alike) plus one JSON manifest (``manifest-<seq>.json``: format version,
engine name, job identity, cursor and sticky-rung metadata, and the
payload's name + CRC32).  Both files go through the shared durable-write
path (``utils/atomicio.write_bytes_durable``: temp + fsync + rename +
CRC32 sidecar + parent-dir fsync) — the same discipline the control
plane's journal uses, so a crash at ANY instant leaves either a fully
valid checkpoint or recognisable garbage, never a half-truth:

* crash mid-payload: a ``.tmp-*`` orphan, no manifest — invisible to
  the loader, reaped by the next save (and by the bench's try/finally);
* crash between payload and manifest: a payload with no manifest —
  invisible;
* torn/corrupt file that somehow survives rename: the CRC sidecar (and
  the payload CRC recorded in the manifest) fails verification and the
  loader falls back to the previous checkpoint — which is why the last
  TWO checkpoints are retained and only older ones garbage-collected.

The manifest carries the job identity (engine name + the shape knobs
that change byte layout); resuming against a different job is refused
rather than silently corrupting state — the journal-header rule.
"""

from __future__ import annotations

import io
import json
import os
import re
import zlib
from typing import Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from dsi_tpu.obs import trace_event as _trace_event
from dsi_tpu.utils.atomicio import (
    read_bytes_verified,
    reap_tmp_files,
    write_bytes_durable,
)

#: Bumped whenever the payload/manifest layout changes incompatibly; a
#: loader refuses versions it does not know instead of misreading them.
CKPT_VERSION = 1

_MANIFEST_RE = re.compile(r"^manifest-(\d{6})\.json$")


class CheckpointMismatch(RuntimeError):
    """A valid checkpoint exists but belongs to a different job."""


def skip_stream(blocks: Iterable[bytes], skip: int) -> Iterator[bytes]:
    """Drop the first ``skip`` bytes of a block stream — the resume
    seek.  The engines' batchers are pure functions of the byte stream,
    so feeding them the suffix from the confirmed cursor reproduces the
    uninterrupted run's remaining batches exactly."""
    remaining = int(skip)
    for b in blocks:
        if remaining:
            if len(b) <= remaining:
                remaining -= len(b)
                continue
            b = bytes(memoryview(b)[remaining:])
            remaining = 0
        yield b


class CheckpointStore:
    """Save/load numbered (payload, manifest) checkpoint pairs in one
    directory, newest-valid-wins, last two retained."""

    def __init__(self, directory: str, engine: str, job: Dict):
        self.dir = directory
        self.engine = engine
        #: The identity a checkpoint must match to be resumable: every
        #: knob that changes byte layout or stream cutting (chunk size,
        #: mesh width, reduce count, pattern, ...).  JSON-normalised so
        #: tuple-vs-list spelling differences can't refuse a real match.
        self.job = json.loads(json.dumps(job))
        os.makedirs(self.dir, exist_ok=True)

    # ── paths ──

    def _manifest_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"manifest-{seq:06d}.json")

    def _payload_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"state-{seq:06d}.npz")

    def _seqs(self) -> list[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _MANIFEST_RE.match(n)))

    # ── writing ──

    def reset(self) -> None:
        """Start a fresh lineage: remove every manifest/payload/sidecar
        (and orphan temp file) so a later ``--resume`` can never pick up
        a checkpoint from a PREVIOUS job's run that this run has since
        diverged from."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for n in names:
            if (n.startswith(("manifest-", "state-", ".tmp-"))
                    and not os.path.isdir(os.path.join(self.dir, n))):
                try:
                    os.remove(os.path.join(self.dir, n))
                except OSError:
                    pass
        # Make the unlinks durable BEFORE the new lineage's first save:
        # without this, a crash after save() could resurrect a
        # higher-seq checkpoint of the PREVIOUS run (same job identity,
        # diverged state) and load_latest would prefer it.
        from dsi_tpu.utils.atomicio import fsync_dir

        fsync_dir(self.dir)

    def save(self, arrays: Dict[str, np.ndarray], meta: Dict) -> int:
        """Commit one checkpoint; returns its sequence number.  The
        payload lands durably BEFORE the manifest that names it, so the
        manifest's existence implies a complete payload."""
        seqs = self._seqs()
        seq = (seqs[-1] + 1) if seqs else 1
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        payload = buf.getvalue()
        crc = write_bytes_durable(self._payload_path(seq), payload)
        manifest = {
            "version": CKPT_VERSION,
            "engine": self.engine,
            "job": self.job,
            "seq": seq,
            "payload": os.path.basename(self._payload_path(seq)),
            "payload_crc32": crc,
            "meta": meta,
        }
        write_bytes_durable(
            self._manifest_path(seq),
            json.dumps(manifest, sort_keys=True).encode("utf-8"))
        self._gc(keep_from=seq - 1)
        reap_tmp_files(self.dir)
        _trace_event("ckpt_save", lane="ckpt", engine=self.engine,
                     seq=seq, bytes=len(payload))
        return seq

    def _gc(self, keep_from: int) -> None:
        """Remove checkpoints older than ``keep_from`` (last-two
        retention: the newest may be the one a concurrent crash tore,
        the one before it is the fallback)."""
        for seq in self._seqs():
            if seq >= keep_from:
                continue
            for path in (self._manifest_path(seq), self._payload_path(seq)):
                for p in (path, path + ".crc32"):
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    # ── reading ──

    def load_latest(self) -> Optional[Tuple[Dict, Dict[str, np.ndarray]]]:
        """Newest checkpoint that passes every check — manifest CRC,
        version, job identity, payload CRC — or None when no usable
        checkpoint exists.  A corrupt newest falls back to its
        predecessor (that is what last-two retention buys); a VALID
        manifest for a different job refuses loudly instead, because
        silently starting fresh would overwrite a good lineage."""
        for seq in reversed(self._seqs()):
            raw = read_bytes_verified(self._manifest_path(seq))
            if raw is None:
                continue  # torn manifest: fall back to the previous
            try:
                manifest = json.loads(raw)
            except ValueError:
                continue
            if manifest.get("version") != CKPT_VERSION:
                continue
            if (manifest.get("engine") != self.engine
                    or manifest.get("job") != self.job):
                raise CheckpointMismatch(
                    f"checkpoint {self._manifest_path(seq)} belongs to a "
                    f"different job (engine/job mismatch); refusing to "
                    f"resume")
            payload = read_bytes_verified(
                os.path.join(self.dir, manifest["payload"]))
            if payload is None:
                continue
            if zlib.crc32(payload) != manifest["payload_crc32"]:
                continue
            with np.load(io.BytesIO(payload)) as z:
                arrays = {k: z[k] for k in z.files}
            _trace_event("ckpt_restore", lane="ckpt",
                         engine=self.engine, seq=seq)
            return manifest["meta"], arrays
        return None
