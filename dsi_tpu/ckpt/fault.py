"""Fault injection: kill the process at named engine points.

The crash-resume guarantee is only evidence if the crashes are real —
a mocked "restore from dict" test cannot catch a snapshot that forgot
to fsync, a manifest torn mid-rename, or device state that was captured
while a fold was still in flight.  This module lets the test grid and
``scripts/onchip_evidence.sh`` kill a live engine at the points where
those bugs would hide:

* ``post-dispatch`` — right after a step/wave kernel is dispatched (the
  in-flight window holds unconfirmed work that a checkpoint must NOT
  contain);
* ``mid-fold``     — right after a confirmed step's merge/fold is
  issued, before the cursor advances (the classic torn-update point);
* ``pre-sync``     — immediately before a device-service drain/sync
  pull (host and device state maximally divergent);
* ``post-ckpt``    — right after a checkpoint manifest commits (resume
  must pick THIS checkpoint, and replay exactly the uncheckpointed
  tail);
* ``mid-capture``  — inside an async save, after the snapshot's device
  pulls are dispatched but before the capture is handed to the commit
  writer (nothing of this save may be visible to a resume);
* ``mid-commit``   — in the commit writer, after the capture
  materialized but before the payload/manifest pair lands (a
  half-written delta or image must LOSE to the previous complete
  chain — the newest-valid-wins walk's async edge);
* ``mid-serve``    — in the partition server, after the FIRST chunk of
  a streamed fetch hits the socket (the consumer sees a half-sent
  payload and a dead peer — the network data plane's re-fetch-from-
  replacement trigger, ISSUE 17).

Knobs (all read per call, so a subprocess inherits them from its env):

* ``DSI_FAULT_POINT`` — one of the names above; unset = disabled.
* ``DSI_FAULT_STEP``  — fire on the n-th occurrence of that point in
  this process (default 1).
* ``DSI_FAULT_MODE``  — ``exit`` (default): ``os._exit(FAULT_EXIT)``,
  a real crash with no teardown, no atexit, no flushes — exactly what
  a SIGKILL'd worker looks like; ``raise``: raise
  :class:`FaultInjected` instead, for the in-process parity grid
  (tests/test_checkpoint.py) where spawning an interpreter per grid
  cell would not fit the tier-1 budget.  The subprocess tests and the
  CI/evidence smoke steps use ``exit`` — real crashes, not mocks.
"""

from __future__ import annotations

import os
import sys
from typing import Dict

#: The injected-crash exit code — distinct from every code the CLIs use
#: (0/1/2) and from SIGKILL's 137, so a harness can assert "the fault
#: fired" rather than "something died".
FAULT_EXIT = 87

#: The chaos-kill exit code (``DSI_CHAOS_WORKER_KILL``) — distinct from
#: FAULT_EXIT so a grid can tell a scripted point-kill from a random
#: boundary-kill in the same run.
CHAOS_EXIT = 88

#: The ENGINE-level points — the crash-resume parity grid
#: (tests/test_checkpoint.py) parametrizes over exactly this tuple, so
#: points that fire outside an engine run (``mid-serve`` in the
#: partition server, the plan layer's ``plan-stage<i>-advance``) are
#: deliberately not listed; they fire by name through
#: :func:`fault_point` all the same.
FAULT_POINTS = ("post-dispatch", "mid-fold", "pre-sync", "post-ckpt",
                "mid-capture", "mid-commit")

_counters: Dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised instead of exiting under ``DSI_FAULT_MODE=raise``."""


def reset_faults() -> None:
    """Forget per-point occurrence counts (in-process test isolation)."""
    _counters.clear()


def fault_point(point: str) -> None:
    """Note one occurrence of ``point``; crash if the env says so.

    Free when ``DSI_FAULT_POINT`` is unset (one env read); the per-point
    counter only advances for the armed point, so unrelated engines in
    the same process don't consume the budget.
    """
    armed = os.environ.get("DSI_FAULT_POINT")
    if not armed or armed != point:
        return
    n = _counters.get(point, 0) + 1
    _counters[point] = n
    try:
        at = int(os.environ.get("DSI_FAULT_STEP", "1"))
    except ValueError:
        at = 1
    if n != max(1, at):
        return
    if os.environ.get("DSI_FAULT_MODE") == "raise":
        raise FaultInjected(f"injected fault at {point} #{n}")
    print(f"FAULT: injected crash at {point} #{n}", file=sys.stderr,
          flush=True)
    # Commit the trace buffer BEFORE dying: the tracer flush rides the
    # same atomicio durable-write path as the checkpoints, so a traced
    # crash leaves a complete, loadable trace.json — the observability
    # half of the crash-resume evidence.  Never let tracing break the
    # fault itself.
    try:
        from dsi_tpu.obs import trace as _obs_trace

        tracer = _obs_trace.get_tracer()
        tracer.event("fault", point=point, n=n)
        tracer.flush()
    except Exception:
        pass
    # A real crash: no interpreter unwind, no atexit, no buffered-IO
    # flush — anything the checkpoint path did not make durable BEFORE
    # this instant is gone, which is the whole point.
    os._exit(FAULT_EXIT)


# ── chaos injection (ISSUE 15 satellite) ──────────────────────────────
#
# ``DSI_CHAOS_WORKER_KILL=p[,seed]`` makes a worker ``os._exit`` with
# probability ``p`` at task boundaries — the scriptable kill/recovery
# grid knob.  Determinism: the per-process RNG is seeded from (seed,
# ``DSI_CHAOS_WORKER_INDEX``) — the spawner stamps each worker with its
# fleet index — so a grid re-run draws the SAME kill sequence per
# worker regardless of pids or wall time.  Same discipline as
# ``fault_point``: trace-flush before the exit, then a real
# ``os._exit`` with no unwind.

_chaos_rng = None
_chaos_key = None


def parse_chaos_spec(spec: str):
    """``"p"`` or ``"p,seed"`` → ``(p, seed)``; malformed specs read as
    disabled (0.0, 0) — chaos must never crash the worker by itself."""
    try:
        parts = spec.split(",")
        p = float(parts[0])
        seed = int(parts[1]) if len(parts) > 1 and parts[1].strip() else 0
    except (ValueError, IndexError):
        return 0.0, 0
    return (p, seed) if 0.0 < p <= 1.0 else (0.0, 0)


def chaos_decision(p: float, seed: int, index: str, draw: int) -> bool:
    """Whether the ``draw``-th boundary of worker ``index`` under
    (p, seed) dies — a pure function, so grids are predictable and the
    unit tests can pin the schedule without spawning processes."""
    import random

    rng = random.Random(f"{seed}:{index}")
    hit = False
    for _ in range(draw):
        hit = rng.random() < p
    return hit


def chaos_kill_point(boundary: str = "task") -> None:
    """Note one task boundary; die with probability p when
    ``DSI_CHAOS_WORKER_KILL`` is armed.  Free when unset (one env
    read)."""
    global _chaos_rng, _chaos_key
    spec = os.environ.get("DSI_CHAOS_WORKER_KILL")
    if not spec:
        return
    p, seed = parse_chaos_spec(spec)
    if p <= 0.0:
        return
    import random

    index = os.environ.get("DSI_CHAOS_WORKER_INDEX", "0")
    key = (spec, index)
    if _chaos_rng is None or _chaos_key != key:
        _chaos_rng = random.Random(f"{seed}:{index}")
        _chaos_key = key
    if _chaos_rng.random() >= p:
        return
    print(f"CHAOS: killing worker (index={index}) at {boundary} "
          f"boundary (p={p})", file=sys.stderr, flush=True)
    try:  # same trace-flush-then-die discipline as fault_point
        from dsi_tpu.obs import trace as _obs_trace

        tracer = _obs_trace.get_tracer()
        tracer.event("chaos_kill", boundary=boundary, index=index)
        tracer.flush()
    except Exception:
        pass
    os._exit(CHAOS_EXIT)


def reset_chaos() -> None:
    """Forget the per-process chaos RNG (in-process test isolation)."""
    global _chaos_rng, _chaos_key
    _chaos_rng = None
    _chaos_key = None
