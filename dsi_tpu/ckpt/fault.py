"""Fault injection: kill the process at named engine points.

The crash-resume guarantee is only evidence if the crashes are real —
a mocked "restore from dict" test cannot catch a snapshot that forgot
to fsync, a manifest torn mid-rename, or device state that was captured
while a fold was still in flight.  This module lets the test grid and
``scripts/onchip_evidence.sh`` kill a live engine at the points where
those bugs would hide:

* ``post-dispatch`` — right after a step/wave kernel is dispatched (the
  in-flight window holds unconfirmed work that a checkpoint must NOT
  contain);
* ``mid-fold``     — right after a confirmed step's merge/fold is
  issued, before the cursor advances (the classic torn-update point);
* ``pre-sync``     — immediately before a device-service drain/sync
  pull (host and device state maximally divergent);
* ``post-ckpt``    — right after a checkpoint manifest commits (resume
  must pick THIS checkpoint, and replay exactly the uncheckpointed
  tail);
* ``mid-capture``  — inside an async save, after the snapshot's device
  pulls are dispatched but before the capture is handed to the commit
  writer (nothing of this save may be visible to a resume);
* ``mid-commit``   — in the commit writer, after the capture
  materialized but before the payload/manifest pair lands (a
  half-written delta or image must LOSE to the previous complete
  chain — the newest-valid-wins walk's async edge).

Knobs (all read per call, so a subprocess inherits them from its env):

* ``DSI_FAULT_POINT`` — one of the names above; unset = disabled.
* ``DSI_FAULT_STEP``  — fire on the n-th occurrence of that point in
  this process (default 1).
* ``DSI_FAULT_MODE``  — ``exit`` (default): ``os._exit(FAULT_EXIT)``,
  a real crash with no teardown, no atexit, no flushes — exactly what
  a SIGKILL'd worker looks like; ``raise``: raise
  :class:`FaultInjected` instead, for the in-process parity grid
  (tests/test_checkpoint.py) where spawning an interpreter per grid
  cell would not fit the tier-1 budget.  The subprocess tests and the
  CI/evidence smoke steps use ``exit`` — real crashes, not mocks.
"""

from __future__ import annotations

import os
import sys
from typing import Dict

#: The injected-crash exit code — distinct from every code the CLIs use
#: (0/1/2) and from SIGKILL's 137, so a harness can assert "the fault
#: fired" rather than "something died".
FAULT_EXIT = 87

FAULT_POINTS = ("post-dispatch", "mid-fold", "pre-sync", "post-ckpt",
                "mid-capture", "mid-commit")

_counters: Dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised instead of exiting under ``DSI_FAULT_MODE=raise``."""


def reset_faults() -> None:
    """Forget per-point occurrence counts (in-process test isolation)."""
    _counters.clear()


def fault_point(point: str) -> None:
    """Note one occurrence of ``point``; crash if the env says so.

    Free when ``DSI_FAULT_POINT`` is unset (one env read); the per-point
    counter only advances for the armed point, so unrelated engines in
    the same process don't consume the budget.
    """
    armed = os.environ.get("DSI_FAULT_POINT")
    if not armed or armed != point:
        return
    n = _counters.get(point, 0) + 1
    _counters[point] = n
    try:
        at = int(os.environ.get("DSI_FAULT_STEP", "1"))
    except ValueError:
        at = 1
    if n != max(1, at):
        return
    if os.environ.get("DSI_FAULT_MODE") == "raise":
        raise FaultInjected(f"injected fault at {point} #{n}")
    print(f"FAULT: injected crash at {point} #{n}", file=sys.stderr,
          flush=True)
    # Commit the trace buffer BEFORE dying: the tracer flush rides the
    # same atomicio durable-write path as the checkpoints, so a traced
    # crash leaves a complete, loadable trace.json — the observability
    # half of the crash-resume evidence.  Never let tracing break the
    # fault itself.
    try:
        from dsi_tpu.obs import trace as _obs_trace

        tracer = _obs_trace.get_tracer()
        tracer.event("fault", point=point, n=n)
        tracer.flush()
    except Exception:
        pass
    # A real crash: no interpreter unwind, no atexit, no buffered-IO
    # flush — anything the checkpoint path did not make durable BEFORE
    # this instant is gone, which is the whole point.
    os._exit(FAULT_EXIT)
