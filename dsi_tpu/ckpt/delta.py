"""Capture-side deferreds and the delta payload format.

The async capture/commit split (``ckpt/writer.py``) needs snapshot
pieces that are CHEAP to take at the confirmed-step boundary and
materialize later in the commit writer: a device service dispatches its
snapshot pulls (``copy_to_host_async`` on freshly packed buffers) and
hands back a :class:`Deferred` whose ``materialize()`` blocks only on
transfers that have been draining while the pipeline kept stepping.

The delta payload format is shared by every engine: a delta checkpoint
is the ordered list of confirmed per-step device payloads retained
since the previous save — the rows APPENDED to the services, trimmed to
their occupied prefix — serialized as ``<prefix>d<i>_rows`` /
``<prefix>d<i>_nus`` array pairs (``rows[d, :nus[d]]`` are device
``d``'s valid rows; the key width is recoverable from the row shape, so
mixed-width chains survive a mid-stream re-key).  Restore re-ingests
each step through the engine's host drain path — ``PackedCounts``/
``KeyCounts`` merges are order-insensitive sums and the postings sink
preserves wave order, which is the same argument the cross-degree
resume (``DeviceTable.drain_image``) already rests on — so
``base + ordered deltas`` reproduces the uninterrupted accumulator
content exactly.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Iterator, List, Tuple

import numpy as np

_DELTA_KEY_RE = re.compile(r"^(.*)d(\d+)_rows$")


class Deferred:
    """A snapshot piece whose arrays materialize later (in the commit
    writer): wraps a zero-argument callable returning the final
    ``{name: np.ndarray}`` dict.  A plain dict is also accepted
    anywhere a Deferred is — ``materialize_part`` normalizes."""

    def __init__(self, fn: Callable[[], Dict[str, np.ndarray]]):
        self._fn = fn

    def materialize(self) -> Dict[str, np.ndarray]:
        return self._fn()


def materialize_part(part) -> Dict[str, np.ndarray]:
    """A capture part is either a ready dict (host accumulators —
    references to append-only tables, copied-on-capture scalars) or a
    :class:`Deferred` (device images with in-flight pulls)."""
    if hasattr(part, "materialize"):
        return part.materialize()
    return dict(part)


class HostDeltaLog:
    """Host-merge-path twin of the device services' delta log
    (``DeviceTable.enable_delta``): bounded retained window, overflow
    invalidates THIS window only — ``take()`` then returns None and the
    engine falls back to a full save, exactly the device rule.  Entries
    are ``(rows, nus)`` pairs; ``append`` trims ``rows`` to the
    occupied prefix AND copies (an AOT-shaped pull is full capacity,
    and a slice view would pin the whole buffer) so the retained bytes
    track the step's payload, not its capacity rung."""

    def __init__(self, max_steps: int = 64):
        self.max_steps = max(1, int(max_steps))
        self._log: List[Tuple[np.ndarray, np.ndarray]] = []
        self._invalid = False

    def append(self, rows, nus) -> None:
        if self._invalid:
            return  # dead window: nothing retained, no pointless copy
        if len(self._log) >= self.max_steps:
            self._invalid = True
            self._log.clear()
            return
        rows = np.asarray(rows)
        nus = np.asarray(nus, dtype=np.int64)
        mp = max(1, min(int(nus.max(initial=0)), int(rows.shape[1])))
        self._log.append((rows[:, :mp].copy(), nus.copy()))

    def take(self):
        """The retained steps since the last save — or None when this
        window overflowed (the full-save fallback signal); always
        re-arms the log."""
        if self._invalid:
            self._invalid = False
            self._log.clear()
            return None
        out = self._log[:]
        self._log.clear()
        return out

    def reset(self) -> None:
        """A full save landed: everything recorded so far is inside its
        image, so the window starts clean and valid."""
        self._log.clear()
        self._invalid = False


class DeltaSteps:
    """Deferred serializer for one delta's retained step payloads.

    ``entries`` is the ordered list of ``(rows, nus)`` pairs a service's
    ``take_delta()`` (or an engine-side host log) produced — ``rows``
    either a numpy array or a jax device array whose D2H was already
    kicked; ``materialize`` turns them into the shared
    ``d<i>_rows``/``d<i>_nus`` payload arrays."""

    def __init__(self, entries: List[Tuple]):
        self.entries = list(entries)

    def materialize(self) -> Dict[str, np.ndarray]:
        arrays: Dict[str, np.ndarray] = {}
        for i, (rows, nus) in enumerate(self.entries):
            arrays[f"d{i:03d}_rows"] = np.asarray(rows)
            arrays[f"d{i:03d}_nus"] = np.asarray(nus, dtype=np.int64)
        return arrays


def iter_delta_steps(arrays: Dict[str, np.ndarray],
                     prefix: str = "") -> Iterator[Tuple[np.ndarray,
                                                         np.ndarray]]:
    """The ``(rows, nus)`` pairs of one delta payload under ``prefix``,
    in step order — the restore-side inverse of :class:`DeltaSteps`."""
    idxs = []
    for k in arrays:
        m = _DELTA_KEY_RE.match(k)
        if m and m.group(1) == prefix:
            idxs.append(int(m.group(2)))
    for i in sorted(idxs):
        yield (arrays[f"{prefix}d{i:03d}_rows"],
               arrays[f"{prefix}d{i:03d}_nus"])


def drain_packed_steps(acc, arrays: Dict[str, np.ndarray],
                       prefix: str = "") -> int:
    """Re-ingest a delta's packed table steps (``shuffle._slice_pack``
    layout: kk key lanes + len/count/part columns) into a host
    accumulator — the same per-device ``acc.add`` walk
    ``DeviceTable._pull_merge`` and ``drain_image`` perform.  Returns
    the number of steps applied."""
    n = 0
    for rows, nus in iter_delta_steps(arrays, prefix):
        kk = int(rows.shape[2]) - 3
        for d in range(rows.shape[0]):
            nu = int(nus[d])
            if nu:
                r = rows[d, :nu]
                acc.add(r[:, :kk], r[:, kk],
                        r[:, kk + 1].astype(np.int64), r[:, kk + 2])
        n += 1
    return n


def drain_posting_steps(sink, arrays: Dict[str, np.ndarray],
                        prefix: str = "") -> int:
    """Re-ingest a delta's posting-row steps through the engine's sink
    (one ``[n, width]`` block per device, device order within a step,
    steps oldest-first) — per-word posting order is preserved because a
    word's rows within one wave come from exactly one source device,
    the invariant ``DevicePostings.drain_image`` documents."""
    n = 0
    for rows, nus in iter_delta_steps(arrays, prefix):
        for d in range(rows.shape[0]):
            nu = int(nus[d])
            if nu:
                sink(rows[d, :nu])
        n += 1
    return n
