"""Checkpoint cadence for the streaming engines.

Mirrors ``device/policy.py``'s :class:`SyncPolicy` exactly in shape: one
place decides what "checkpoint every K confirmed steps" means and where
the knobs live, so the word-count stream, the grep stream, and the wave
walks cannot read them differently.  Two triggers, OR-combined:

* every ``every`` CONFIRMED steps (``--checkpoint-every`` /
  ``DSI_STREAM_CKPT_EVERY``, default 32) — confirmed, not dispatched:
  a checkpoint is only consistent at a confirmed-step boundary, where
  every merged/folded step has passed its deferred exactness check and
  nothing in the accumulators is provisional;
* every ``secs`` wall seconds (``DSI_STREAM_CKPT_SECS``, default off) —
  the cap on how much wall-clock a crash can lose on a slow stream
  (steps can take minutes each on a congested tunnel).

The policy is deliberately trivial because the *correctness* story
never depends on it: a missed checkpoint costs replay work after a
crash, never data — the engines re-read the input from the last durable
cursor and the exactly-once merge discipline does the rest.
"""

from __future__ import annotations

import os
import time

_CKPT_EVERY_ENV = "DSI_STREAM_CKPT_EVERY"
_CKPT_SECS_ENV = "DSI_STREAM_CKPT_SECS"
_CKPT_ASYNC_ENV = "DSI_STREAM_CKPT_ASYNC"
_CKPT_DELTA_ENV = "DSI_STREAM_CKPT_DELTA"
_CKPT_REBASE_ENV = "DSI_STREAM_CKPT_REBASE"
#: Delta saves between full rebases: long chains cost restore work
#: (base + every delta re-applied) and pin every chain member against
#: GC, so the store periodically compacts by writing a fresh full image.
_CKPT_REBASE_DEFAULT = 8
#: 32 confirmed steps at the bench's 2 MiB chunks is ~64 MB of replay
#: exposure — small against a GB-scale stream, large enough that the
#: snapshot pulls (capacity-sized D2H per live service) stay well under
#: the 5% overhead target.
_CKPT_EVERY_DEFAULT = 32


def checkpoint_every_default(every: int | None = None) -> int:
    """Resolve K: an explicit value wins, else ``DSI_STREAM_CKPT_EVERY``
    (default 32), floored at 1 (checkpoint after every confirmed step —
    the degenerate cadence the crash-resume tests lean on)."""
    if every is None:
        try:
            every = int(os.environ.get(_CKPT_EVERY_ENV,
                                       str(_CKPT_EVERY_DEFAULT)))
        except ValueError:
            every = _CKPT_EVERY_DEFAULT
    return max(1, every)


def checkpoint_secs_default(secs: float | None = None) -> float:
    """Resolve T (0 = disabled): explicit wins, else
    ``DSI_STREAM_CKPT_SECS`` (default 0)."""
    if secs is None:
        try:
            secs = float(os.environ.get(_CKPT_SECS_ENV, "0"))
        except ValueError:
            secs = 0.0
    return max(0.0, secs)


def _bool_env(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "on",
                                                        "yes")


def checkpoint_async_default(flag: bool | None = None) -> bool:
    """Resolve the async-commit switch: explicit wins, else
    ``DSI_STREAM_CKPT_ASYNC`` (default off — off is bit-identical PR-5
    behavior: capture + commit inline at the confirmed-step boundary)."""
    if flag is None:
        return _bool_env(_CKPT_ASYNC_ENV)
    return bool(flag)


def checkpoint_delta_default(flag: bool | None = None) -> bool:
    """Resolve the incremental-snapshot switch: explicit wins, else
    ``DSI_STREAM_CKPT_DELTA`` (default off — every save a full image,
    the PR-5 shape)."""
    if flag is None:
        return _bool_env(_CKPT_DELTA_ENV)
    return bool(flag)


_CKPT_COMPRESS_ENV = "DSI_STREAM_CKPT_COMPRESS"
#: Which checkpoint payload kinds are zlib-compressed
#: (``np.savez_compressed`` through the store's BytesIO
#: serialize-then-commit idiom — the durable path is untouched).
#: Default ``deltas``: delta payloads are written at cadence (every
#: save on a delta chain) and their packed word tables compress 2-5x,
#: while full images are the latency-sensitive sync-save path, so they
#: stay raw unless ``all`` is asked for.
_CKPT_COMPRESS_DEFAULT = "deltas"
_CKPT_COMPRESS_MODES = ("off", "deltas", "all")


def checkpoint_compress_default(mode: str | None = None) -> str:
    """Resolve the payload-compression mode — one of ``off`` (every
    payload raw npz, the pre-ISSUE-13 bytes), ``deltas`` (default:
    ``delta-<seq>.npz`` compressed, full images raw), ``all``: explicit
    wins, else ``DSI_STREAM_CKPT_COMPRESS`` with the historical bool
    spellings accepted (``0``/``off``/``false`` → off, ``1``/``on`` →
    deltas)."""
    if mode is None:
        mode = os.environ.get(_CKPT_COMPRESS_ENV,
                              _CKPT_COMPRESS_DEFAULT)
    m = str(mode).strip().lower()
    if m in ("0", "off", "false", "no", "none"):
        return "off"
    if m in ("1", "on", "true", "yes", "delta", "deltas"):
        return "deltas"
    if m in ("2", "all", "full"):
        return "all"
    return _CKPT_COMPRESS_DEFAULT


def checkpoint_rebase_default(every: int | None = None) -> int:
    """Resolve the rebase cadence — every Nth save is a full image,
    i.e. up to ``N - 1`` deltas chain between fulls: explicit wins,
    else ``DSI_STREAM_CKPT_REBASE`` (default 8), floored at 1
    (= every save full, deltas effectively disabled)."""
    if every is None:
        try:
            every = int(os.environ.get(_CKPT_REBASE_ENV,
                                       str(_CKPT_REBASE_DEFAULT)))
        except ValueError:
            every = _CKPT_REBASE_DEFAULT
    return max(1, every)


class CheckpointPolicy:
    """Fire every ``every`` confirmed steps and/or every ``secs``
    seconds.  Counts CONFIRMED steps (the caller notes a step only after
    its merge/fold committed), so ``due()`` is only ever consulted at a
    consistent boundary."""

    def __init__(self, every: int | None = None,
                 secs: float | None = None):
        self.every = checkpoint_every_default(every)
        self.secs = checkpoint_secs_default(secs)
        self._since = 0
        self._last = time.monotonic()

    def note_step(self) -> None:
        self._since += 1

    def due(self) -> bool:
        if self._since >= self.every:
            return True
        return bool(self.secs) and self._since > 0 \
            and time.monotonic() - self._last >= self.secs

    def reset(self) -> None:
        self._since = 0
        self._last = time.monotonic()
