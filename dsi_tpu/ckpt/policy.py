"""Checkpoint cadence for the streaming engines.

Mirrors ``device/policy.py``'s :class:`SyncPolicy` exactly in shape: one
place decides what "checkpoint every K confirmed steps" means and where
the knobs live, so the word-count stream, the grep stream, and the wave
walks cannot read them differently.  Two triggers, OR-combined:

* every ``every`` CONFIRMED steps (``--checkpoint-every`` /
  ``DSI_STREAM_CKPT_EVERY``, default 32) — confirmed, not dispatched:
  a checkpoint is only consistent at a confirmed-step boundary, where
  every merged/folded step has passed its deferred exactness check and
  nothing in the accumulators is provisional;
* every ``secs`` wall seconds (``DSI_STREAM_CKPT_SECS``, default off) —
  the cap on how much wall-clock a crash can lose on a slow stream
  (steps can take minutes each on a congested tunnel).

The policy is deliberately trivial because the *correctness* story
never depends on it: a missed checkpoint costs replay work after a
crash, never data — the engines re-read the input from the last durable
cursor and the exactly-once merge discipline does the rest.
"""

from __future__ import annotations

import os
import time

_CKPT_EVERY_ENV = "DSI_STREAM_CKPT_EVERY"
_CKPT_SECS_ENV = "DSI_STREAM_CKPT_SECS"
#: 32 confirmed steps at the bench's 2 MiB chunks is ~64 MB of replay
#: exposure — small against a GB-scale stream, large enough that the
#: snapshot pulls (capacity-sized D2H per live service) stay well under
#: the 5% overhead target.
_CKPT_EVERY_DEFAULT = 32


def checkpoint_every_default(every: int | None = None) -> int:
    """Resolve K: an explicit value wins, else ``DSI_STREAM_CKPT_EVERY``
    (default 32), floored at 1 (checkpoint after every confirmed step —
    the degenerate cadence the crash-resume tests lean on)."""
    if every is None:
        try:
            every = int(os.environ.get(_CKPT_EVERY_ENV,
                                       str(_CKPT_EVERY_DEFAULT)))
        except ValueError:
            every = _CKPT_EVERY_DEFAULT
    return max(1, every)


def checkpoint_secs_default(secs: float | None = None) -> float:
    """Resolve T (0 = disabled): explicit wins, else
    ``DSI_STREAM_CKPT_SECS`` (default 0)."""
    if secs is None:
        try:
            secs = float(os.environ.get(_CKPT_SECS_ENV, "0"))
        except ValueError:
            secs = 0.0
    return max(0.0, secs)


class CheckpointPolicy:
    """Fire every ``every`` confirmed steps and/or every ``secs``
    seconds.  Counts CONFIRMED steps (the caller notes a step only after
    its merge/fold committed), so ``due()`` is only ever consulted at a
    consistent boundary."""

    def __init__(self, every: int | None = None,
                 secs: float | None = None):
        self.every = checkpoint_every_default(every)
        self.secs = checkpoint_secs_default(secs)
        self._since = 0
        self._last = time.monotonic()

    def note_step(self) -> None:
        self._since += 1

    def due(self) -> bool:
        if self._since >= self.every:
            return True
        return bool(self.secs) and self._since > 0 \
            and time.monotonic() - self._last >= self.secs

    def reset(self) -> None:
        self._since = 0
        self._last = time.monotonic()
