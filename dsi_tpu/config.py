"""Typed configuration for the framework.

The reference has no config system: nReduce is the literal 10
(``main/mrcoordinator.go:23``), the straggler timeout 10 s
(``mr/coordinator.go:71,100``), the done-poll and exit-grace 1 s
(``main/mrcoordinator.go:25,28``), and the socket path a constant
(``mr/rpc.go:37-41``).  SURVEY.md §5 calls for a small typed config with
those values as defaults — this is it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os


def default_socket_path(workdir: str | None = None) -> str:
    """Unix-domain socket path for the coordinator.

    Reference: ``coordinatorSock()`` returns ``/var/tmp/824-mr-<uid>``
    (``mr/rpc.go:37-41``).  That per-UID name prevents concurrent jobs on one
    machine (noted in ``main/test-mr-many.sh:10-11``); we additionally hash the
    working directory into the name so independent jobs (and parallel test
    sandboxes) never collide.  Overridable via ``DSI_MR_SOCKET``.
    """
    env = os.environ.get("DSI_MR_SOCKET")
    if env:
        return env
    wd = os.path.abspath(workdir or os.getcwd())
    tag = hashlib.md5(wd.encode()).hexdigest()[:8]
    return f"/var/tmp/dsi-mr-{os.getuid()}-{tag}"


@dataclasses.dataclass(frozen=True)
class JobConfig:
    """Everything the coordinator + workers need for one MapReduce job."""

    # Number of reduce partitions.  Reference default: 10
    # (main/mrcoordinator.go:23).
    n_reduce: int = 10

    # Straggler re-queue threshold, seconds.  Reference: 10 s goroutine sleep
    # (mr/coordinator.go:71,100).
    task_timeout_s: float = 10.0

    # Coordinator Done() poll interval and post-done grace, seconds
    # (main/mrcoordinator.go:25,28).
    done_poll_s: float = 1.0
    exit_grace_s: float = 1.0

    # Worker sleep when told "waiting" (TaskStatus=2).  The reference worker
    # busy-polls with no backoff (no case 2 in mr/worker.go:54-162) — SURVEY.md
    # §3.3 flags this as a defect to fix; output is unaffected.
    wait_sleep_s: float = 0.2

    # Directory where mr-X-Y and mr-out-Y files live.  Reference: the cwd.
    workdir: str = "."

    # Execution backend for map/reduce tasks: "host" (reference semantics,
    # pure Python) or "tpu" (JAX kernels for TPU-aware apps).
    backend: str = "host"

    # Coordinator socket path ("" -> default_socket_path(workdir)).
    socket_path: str = ""

    # Coordinator checkpoint journal ("" = disabled, reference behavior —
    # coordinator death kills the job, SURVEY.md §5).  When set, unique task
    # completions are journaled and a restarted coordinator resumes the job.
    journal_path: str = ""

    # ── streaming-shard jobs (mr/shards.py) ──

    # Attempt presumed-dead silence, seconds: a shard attempt that has not
    # sent a progress RPC for this long is marked dead and the shard is
    # re-queued with a resume hint.  Progress-based, unlike task_timeout_s
    # (shards are long-running; assignment-age timeouts would kill every
    # healthy big shard).
    shard_timeout_s: float = 10.0

    # Speculative backup dispatch (Dean & Ghemawat §3.6).  An idle worker
    # asking for work when no shard is untouched may be handed a BACKUP
    # attempt of a shard whose newest attempt has been silent longer than
    # max(spec_k * p99(that worker's contact gaps), spec_floor_s) — the
    # percentile-aware straggler_suspects() signal.  First commit wins.
    spec_backup: bool = True
    spec_k: float = 2.0
    spec_floor_s: float = 2.0

    # Setup grace: an attempt that has not yet sent its first progress
    # RPC is still constructing its engine (jax init + first compiles,
    # seconds of legitimate silence) — the silence trigger waits at
    # least this long for such attempts so fresh attempts don't attract
    # spurious backups.
    spec_setup_s: float = 8.0

    # Dynamic re-split (the elastic-dataflow half of §3.5/§3.6): when
    # the straggler triggers fire on a splittable shard, split the slow
    # attempt's REMAINING cursor range (from its live confirmed cursor)
    # into newline-aligned sub-shards for idle workers instead of
    # racing one whole-range backup.  First commit wins PER SUB-RANGE;
    # the straggler keeps running and still wins the whole shard if it
    # commits before every sub-range has.
    spec_resplit: bool = False
    # How many ways the remaining range is split.
    spec_resplit_ways: int = 2
    # Remainders smaller than this fall back to a plain backup — a
    # sub-shard must amortize one engine setup.
    spec_resplit_min_bytes: int = 1 << 16

    # Worker-side progress-RPC cadence while driving a shard, seconds.
    shard_progress_s: float = 0.5

    # Total attempts allowed per shard (primaries + backups + takeovers)
    # before the job is declared failed — bounds a poisoned shard.
    shard_max_attempts: int = 8

    # ── network data plane (dsi_tpu/net, ISSUE 17) ──

    # Worker-served shuffle: workers spool partitions to a PRIVATE local
    # dir and serve them over TCP; reducers/consumers fetch via
    # net/fetch.py instead of reading a shared directory.  Off = the
    # reference's shared-filesystem data plane.
    net_shuffle: bool = False

    # Partition-server bind address for this worker ("" = tcp:127.0.0.1:0,
    # an OS-assigned loopback port; multi-host fleets set a real host and
    # DSI_MR_SECRET).  Env override: DSI_NET_BIND.
    net_bind: str = ""

    # Shuffle payloads cross the wire through the PR-13 line codec
    # (ops/wirecodec.pack_kv) when it shrinks them; raw otherwise.
    net_codec: bool = True

    # Fetch dial/stream timeout, seconds (per fetch attempt; the dial
    # itself retries transient errors through dial_backoff_schedule).
    net_fetch_timeout_s: float = 30.0

    # Reduce-side prefetch window (ISSUE 18): how many partition fetches
    # may be in flight or buffered-unconsumed while the consumer decodes.
    # 1 = the serial fetch→decode loop, bit-identically.  Env override:
    # DSI_NET_FETCH_WINDOW.
    net_fetch_window: int = 4

    # Spool entries untouched this long are aged out at partition-server
    # boot (dead-task spools from kill-9'd predecessors; the serve
    # daemon's retention discipline).
    net_spool_retention_s: float = 3600.0

    def sock(self) -> str:
        return self.socket_path or default_socket_path(self.workdir)
