"""dsi_tpu — a TPU-native MapReduce framework.

A from-scratch rebuild of the capability surface of
``aatmiyasilwal/Distributed-Systems-Implemented`` (a Go MapReduce framework in the
MIT 6.5840 style; see SURVEY.md), redesigned TPU-first:

* Control plane: a pull-based coordinator/worker protocol over a Unix-domain
  socket (reference: ``mr/coordinator.go``, ``mr/worker.go``, ``mr/rpc.go``),
  implemented as host-side Python with the same task state machine, 10 s
  straggler re-queue, and atomic temp-file-rename commit discipline.
* Data plane: for the host backend, hash-partitioned intermediate files on a
  shared filesystem (reference: ``mr-X-Y`` JSON files, ``mr/worker.go:81-92``);
  for the TPU backend, on-chip tokenize/hash/bucket/segment-reduce kernels
  (JAX/XLA) with ``jax.lax.all_to_all`` over the device mesh replacing the
  file shuffle when more than one device is present.
* Apps: the two-symbol ``Map``/``Reduce`` plugin contract
  (reference: ``mrapps/wc.go:21,41``, loader ``main/mrworker.go:34-51``),
  loaded from Python modules instead of Go ``.so`` plugins.

Package layout:
  mr/        core framework: coordinator, worker, rpc, sequential oracle
  apps/      application plugins (wc, grep, indexer, crash, ...)
  ops/       single-device TPU kernels (tokenize, hash, segment reduce)
  parallel/  device mesh, shard_map all_to_all shuffle, multi-chip pipeline
  device/    device-resident accumulator services (fold table, top-k,
             histogram, postings buffer)
  ckpt/      checkpoint/restore for the streaming engines: cadence policy,
             CRC'd durable manifest store, crash fault injection
  backends/  host (reference-semantics) and tpu execution backends
  utils/     config, corpus generation, atomic IO, codecs, tracing
  cli/       process entry points (mrcoordinator, mrworker, mrsequential)
"""

__version__ = "0.1.0"

import os as _os

# DSI_LOCKCHECK=1: install the runtime lock-order validator BEFORE any
# repo module creates a lock (they all import dsi_tpu first), so every
# threading.Lock/RLock/Condition in the process feeds the acquisition-
# order graph and an ABBA inversion raises instead of deadlocking.
# See dsi_tpu/analysis/lockcheck.py and OPERATIONS.md.
if _os.environ.get("DSI_LOCKCHECK") == "1":
    from dsi_tpu.analysis.lockcheck import install as _lockcheck_install

    _lockcheck_install()

from dsi_tpu.mr.types import KeyValue  # noqa: F401
