// Native decoder for the intermediate-file data plane.
//
// The reference's reduce path decodes NMap JSON-lines files of
// {"Key": ..., "Value": ...} records per reduce task (mr/worker.go:102-121)
// — the host-side hot loop of the distributed data plane.  This implements
// that decode natively: one call parses a whole file into a length-prefixed
// arena the Python side slices into records, replacing a per-line
// json.loads + dict + KeyValue round trip.
//
// Semantics mirror the reference decoder exactly: parsing stops silently at
// the first malformed record (the Go json.Decoder `break` on error,
// worker.go:117 — a torn tail from a crashed writer is ignored), and a
// missing file is the *caller's* tolerated case (worker.go:106-108).
//
// Arena layout (little-endian): u32 n_records, u32 complete_flag, then per
// record u32 klen, u32 vlen, key bytes, value bytes.  Strings are UTF-8;
// JSON escapes including \uXXXX surrogate pairs are decoded.
// complete_flag=1 means the parse reached EOF cleanly; 0 means this strict
// parser stopped early — the Python wrapper then re-decodes the file with
// the (more lenient) reference-semantics decoder so native vs pure-Python
// runs can never diverge.
//
// Build: scripts/build_native.sh (g++ -O2 -shared -fPIC).  The Python
// wrapper (dsi_tpu/native/__init__.py) falls back to the pure-Python
// decoder whenever the library is unavailable or declines an input.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parser {
  const char* p;
  const char* end;

  bool skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    return p < end;
  }

  bool lit(const char* s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  // Append one UTF-8 encoded code point.
  static void put_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back((char)cp);
    } else if (cp < 0x800) {
      out.push_back((char)(0xC0 | (cp >> 6)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back((char)(0xE0 | (cp >> 12)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else {
      out.push_back((char)(0xF0 | (cp >> 18)));
      out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t* out) {
    if (end - p < 4) return false;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= (uint32_t)(c - '0');
      else if (c >= 'a' && c <= 'f') v |= (uint32_t)(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= (uint32_t)(c - 'A' + 10);
      else return false;
    }
    p += 4;
    *out = v;
    return true;
  }

  // Parse a JSON string (opening quote consumed by caller? no: consumes it).
  bool str(std::string& out) {
    if (!skip_ws() || *p != '"') return false;
    p++;
    out.clear();
    while (p < end) {
      unsigned char c = (unsigned char)*p;
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                uint32_t lo;
                if (!hex4(&lo)) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  put_utf8(out, cp);  // unpaired; emit both as-is
                  cp = lo;
                }
              }
            }
            put_utf8(out, cp);
            break;
          }
          default:
            return false;
        }
      } else if (c < 0x20) {
        // Raw control characters are invalid inside JSON strings — Python's
        // strict json.loads rejects them too; staying equally strict keeps
        // native and pure-Python torn-file behavior identical.
        return false;
      } else {
        out.push_back((char)c);
        p++;
      }
    }
    return false;
  }

  // One {"Key": k, "Value": v} record (field order fixed — both this
  // framework's writer and Go's struct encoder emit Key then Value).
  bool record(std::string& k, std::string& v) {
    if (!skip_ws() || *p != '{') return false;
    p++;
    if (!skip_ws() || !lit("\"Key\"")) return false;
    if (!skip_ws() || *p != ':') return false;
    p++;
    if (!str(k)) return false;
    if (!skip_ws() || *p != ',') return false;
    p++;
    if (!skip_ws() || !lit("\"Value\"")) return false;
    if (!skip_ws() || *p != ':') return false;
    p++;
    if (!str(v)) return false;
    if (!skip_ws() || *p != '}') return false;
    p++;
    skip_ws();
    return true;
  }
};

}  // namespace

extern "C" {

// Parse a JSON-lines KV file into an arena (see header comment).
// Returns nullptr only on IO/allocation failure; malformed content yields
// the records parsed before the first bad line (reference break semantics).
uint8_t* kv_decode_file(const char* path, size_t* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
  long sz = ftell(f);
  if (sz < 0) { fclose(f); return nullptr; }
  rewind(f);
  std::string buf;
  buf.resize((size_t)sz);
  if (sz > 0 && fread(&buf[0], 1, (size_t)sz, f) != (size_t)sz) {
    fclose(f);
    return nullptr;
  }
  fclose(f);

  std::string arena;
  arena.resize(8);  // n_records + complete_flag, patched at the end
  uint32_t n = 0, complete = 1;
  std::string k, v;
  const char* p = buf.data();
  const char* bend = buf.data() + buf.size();
  while (p < bend) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(bend - p));
    const char* line_end = nl ? nl : bend;
    Parser ws{p, line_end};
    ws.skip_ws();
    if (ws.p != line_end) {  // non-blank line (blank lines are tolerated)
      Parser ps{p, line_end};
      if (!ps.record(k, v) || ps.p != line_end) {
        complete = 0;  // strict parse stopped early: wrapper re-decodes
        break;
      }
      uint32_t kl = (uint32_t)k.size(), vl = (uint32_t)v.size();
      arena.append((const char*)&kl, 4);
      arena.append((const char*)&vl, 4);
      arena.append(k);
      arena.append(v);
      n++;
    }
    if (!nl) break;
    p = nl + 1;
  }
  memcpy(&arena[0], &n, 4);
  memcpy(&arena[4], &complete, 4);

  uint8_t* out = (uint8_t*)malloc(arena.size());
  if (!out) return nullptr;
  memcpy(out, arena.data(), arena.size());
  *out_len = arena.size();
  return out;
}

void kv_arena_free(uint8_t* p) { free(p); }

// Map-side encoder: partition + serialize a whole map task's output in one
// native pass.  Replaces three Python hot loops (per-byte FNV-1a ihash,
// json.dumps per record, per-bucket appends — mr/worker.go:33-37,74-92
// semantics).
//
// Input: n_records packed as (u32 klen, u32 vlen, key bytes, value bytes)*.
// Output arena: u32 n_reduce, then per partition u32 blob_len + blob bytes,
// where each blob is JSON-lines {"Key": k, "Value": v} records in input
// order.  Partition = fnv1a32(key) & 0x7fffffff % n_reduce, bit-identical
// to the reference's ihash.  Strings are written as raw UTF-8 with only
// the JSON-mandatory escapes (quote, backslash, control chars) — valid
// JSON that both this file's decoder and Python's json.loads accept.
// Returns nullptr on malformed input or allocation failure (caller falls
// back to the Python writer).

namespace {

void json_escape_append(std::string& out, const char* s, uint32_t n) {
  out.push_back('"');
  for (uint32_t i = 0; i < n; i++) {
    unsigned char c = (unsigned char)s[i];
    switch (c) {
      case '"': out.append("\\\""); break;
      case '\\': out.append("\\\\"); break;
      case '\b': out.append("\\b"); break;
      case '\f': out.append("\\f"); break;
      case '\n': out.append("\\n"); break;
      case '\r': out.append("\\r"); break;
      case '\t': out.append("\\t"); break;
      default:
        if (c < 0x20) {
          char hex[8];
          snprintf(hex, sizeof hex, "\\u%04x", c);
          out.append(hex);
        } else {
          out.push_back((char)c);
        }
    }
  }
  out.push_back('"');
}

}  // namespace

uint8_t* kv_encode_partitions(const uint8_t* recs, size_t recs_len,
                              uint32_t n_records, uint32_t n_reduce,
                              size_t* out_len) {
  if (n_reduce == 0 || n_reduce > 1u << 20) return nullptr;
  std::vector<std::string> blobs(n_reduce);
  const uint8_t* p = recs;
  const uint8_t* end = recs + recs_len;
  for (uint32_t i = 0; i < n_records; i++) {
    if ((size_t)(end - p) < 8) return nullptr;
    uint32_t kl, vl;
    memcpy(&kl, p, 4);
    memcpy(&vl, p + 4, 4);
    p += 8;
    if ((size_t)(end - p) < (size_t)kl + vl) return nullptr;
    const char* k = (const char*)p;
    const char* v = (const char*)(p + kl);
    p += (size_t)kl + vl;

    uint32_t h = 2166136261u;  // FNV-1a 32 offset (mr/worker.go:33-37)
    for (uint32_t j = 0; j < kl; j++) {
      h ^= (uint8_t)k[j];
      h *= 16777619u;
    }
    std::string& blob = blobs[(h & 0x7fffffffu) % n_reduce];
    blob.append("{\"Key\": ");
    json_escape_append(blob, k, kl);
    blob.append(", \"Value\": ");
    json_escape_append(blob, v, vl);
    blob.append("}\n");
  }
  if (p != end) return nullptr;  // trailing garbage: refuse, Python path

  size_t total = 4;
  for (auto& b : blobs) {
    if (b.size() > UINT32_MAX) return nullptr;  // length field would wrap
    total += 4 + b.size();
  }
  uint8_t* out = (uint8_t*)malloc(total);
  if (!out) return nullptr;
  uint8_t* w = out;
  memcpy(w, &n_reduce, 4);
  w += 4;
  for (auto& b : blobs) {
    uint32_t bl = (uint32_t)b.size();
    memcpy(w, &bl, 4);
    w += 4;
    memcpy(w, b.data(), bl);
    w += bl;
  }
  *out_len = total;
  return out;
}

}  // extern "C"
