// Native decoder for the intermediate-file data plane.
//
// The reference's reduce path decodes NMap JSON-lines files of
// {"Key": ..., "Value": ...} records per reduce task (mr/worker.go:102-121)
// — the host-side hot loop of the distributed data plane.  This implements
// that decode natively: one call parses a whole file into a length-prefixed
// arena the Python side slices into records, replacing a per-line
// json.loads + dict + KeyValue round trip.
//
// Semantics mirror the reference decoder exactly: parsing stops silently at
// the first malformed record (the Go json.Decoder `break` on error,
// worker.go:117 — a torn tail from a crashed writer is ignored), and a
// missing file is the *caller's* tolerated case (worker.go:106-108).
//
// Arena layout (little-endian): u32 n_records, u32 complete_flag, then per
// record u32 klen, u32 vlen, key bytes, value bytes.  Strings are UTF-8;
// JSON escapes including \uXXXX surrogate pairs are decoded.
// complete_flag=1 means the parse reached EOF cleanly; 0 means this strict
// parser stopped early — the Python wrapper then re-decodes the file with
// the (more lenient) reference-semantics decoder so native vs pure-Python
// runs can never diverge.
//
// Build: scripts/build_native.sh (g++ -O2 -shared -fPIC).  The Python
// wrapper (dsi_tpu/native/__init__.py) falls back to the pure-Python
// decoder whenever the library is unavailable or declines an input.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parser {
  const char* p;
  const char* end;

  bool skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    return p < end;
  }

  bool lit(const char* s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  // Append one UTF-8 encoded code point.
  static void put_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back((char)cp);
    } else if (cp < 0x800) {
      out.push_back((char)(0xC0 | (cp >> 6)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back((char)(0xE0 | (cp >> 12)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else {
      out.push_back((char)(0xF0 | (cp >> 18)));
      out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t* out) {
    if (end - p < 4) return false;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= (uint32_t)(c - '0');
      else if (c >= 'a' && c <= 'f') v |= (uint32_t)(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= (uint32_t)(c - 'A' + 10);
      else return false;
    }
    p += 4;
    *out = v;
    return true;
  }

  // Parse a JSON string (opening quote consumed by caller? no: consumes it).
  bool str(std::string& out) {
    if (!skip_ws() || *p != '"') return false;
    p++;
    out.clear();
    while (p < end) {
      unsigned char c = (unsigned char)*p;
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                uint32_t lo;
                if (!hex4(&lo)) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else {
                  put_utf8(out, cp);  // unpaired; emit both as-is
                  cp = lo;
                }
              }
            }
            put_utf8(out, cp);
            break;
          }
          default:
            return false;
        }
      } else if (c < 0x20) {
        // Raw control characters are invalid inside JSON strings — Python's
        // strict json.loads rejects them too; staying equally strict keeps
        // native and pure-Python torn-file behavior identical.
        return false;
      } else {
        out.push_back((char)c);
        p++;
      }
    }
    return false;
  }

  // One {"Key": k, "Value": v} record (field order fixed — both this
  // framework's writer and Go's struct encoder emit Key then Value).
  bool record(std::string& k, std::string& v) {
    if (!skip_ws() || *p != '{') return false;
    p++;
    if (!skip_ws() || !lit("\"Key\"")) return false;
    if (!skip_ws() || *p != ':') return false;
    p++;
    if (!str(k)) return false;
    if (!skip_ws() || *p != ',') return false;
    p++;
    if (!skip_ws() || !lit("\"Value\"")) return false;
    if (!skip_ws() || *p != ':') return false;
    p++;
    if (!str(v)) return false;
    if (!skip_ws() || *p != '}') return false;
    p++;
    skip_ws();
    return true;
  }
};

}  // namespace

extern "C" {

// Parse a JSON-lines KV file into an arena (see header comment).
// Returns nullptr only on IO/allocation failure; malformed content yields
// the records parsed before the first bad line (reference break semantics).
uint8_t* kv_decode_file(const char* path, size_t* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
  long sz = ftell(f);
  if (sz < 0) { fclose(f); return nullptr; }
  rewind(f);
  std::string buf;
  buf.resize((size_t)sz);
  if (sz > 0 && fread(&buf[0], 1, (size_t)sz, f) != (size_t)sz) {
    fclose(f);
    return nullptr;
  }
  fclose(f);

  std::string arena;
  arena.resize(8);  // n_records + complete_flag, patched at the end
  uint32_t n = 0, complete = 1;
  std::string k, v;
  const char* p = buf.data();
  const char* bend = buf.data() + buf.size();
  while (p < bend) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(bend - p));
    const char* line_end = nl ? nl : bend;
    Parser ws{p, line_end};
    ws.skip_ws();
    if (ws.p != line_end) {  // non-blank line (blank lines are tolerated)
      Parser ps{p, line_end};
      if (!ps.record(k, v) || ps.p != line_end) {
        complete = 0;  // strict parse stopped early: wrapper re-decodes
        break;
      }
      uint32_t kl = (uint32_t)k.size(), vl = (uint32_t)v.size();
      arena.append((const char*)&kl, 4);
      arena.append((const char*)&vl, 4);
      arena.append(k);
      arena.append(v);
      n++;
    }
    if (!nl) break;
    p = nl + 1;
  }
  memcpy(&arena[0], &n, 4);
  memcpy(&arena[4], &complete, 4);

  uint8_t* out = (uint8_t*)malloc(arena.size());
  if (!out) return nullptr;
  memcpy(out, arena.data(), arena.size());
  *out_len = arena.size();
  return out;
}

void kv_arena_free(uint8_t* p) { free(p); }

}  // extern "C"
