"""Native runtime components (C++, ctypes-bound), with Python fallbacks.

The reference has no native code (SURVEY.md §2: pure Go stdlib), but its
compiled-Go host runtime is the moral bar for this framework's host paths.
This package provides natively-accelerated pieces of the host data plane —
currently the intermediate-file decoder used by every reduce task
(``mr/worker.go:102-121`` semantics) — built by ``scripts/build_native.sh``
and loaded lazily.  Every entry point degrades to the pure-Python
implementation when the library is missing (``DSI_NO_NATIVE=1`` forces
that), and the C parser defers to Python on any input it cannot prove it
parsed completely, so native and pure runs can never diverge.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import sys
import threading
from typing import List, Optional

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None = not tried, False = absent

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SO_PATH = os.path.join(_REPO, "build", "libkvcodec.so")


def _load():
    """Load (building on first use if a toolchain exists) or mark absent."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib or None
        if os.environ.get("DSI_NO_NATIVE") == "1":
            _lib = False
            return None
        here = os.path.dirname(os.path.abspath(__file__))
        srcs = [os.path.join(here, "kvcodec.cpp"),
                os.path.join(here, "wcjob.cpp")]
        stale = (not os.path.exists(_SO_PATH)
                 or any(os.path.exists(s)
                        and os.path.getmtime(s) > os.path.getmtime(_SO_PATH)
                        for s in srcs))
        if stale:
            script = os.path.join(_REPO, "scripts", "build_native.sh")
            try:
                subprocess.run(["bash", script], check=True,
                               capture_output=True, timeout=120)
            except Exception as e:  # no compiler / build failure: fall back
                if os.path.exists(_SO_PATH):
                    pass  # stale-but-working library beats no library
                else:
                    print(f"dsi_tpu.native: build unavailable ({e}); "
                          "using pure-Python data plane", file=sys.stderr)
                    _lib = False
                    return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            lib.kv_decode_file.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.kv_decode_file.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(ctypes.c_size_t)]
            lib.kv_arena_free.restype = None
            lib.kv_arena_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
            lib.kv_encode_partitions.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.kv_encode_partitions.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.POINTER(ctypes.c_size_t)]
            lib.wc_map_file.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.wc_map_file.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                        ctypes.POINTER(ctypes.c_size_t)]
            lib.wc_reduce.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.wc_reduce.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                      ctypes.c_uint32,
                                      ctypes.POINTER(ctypes.c_size_t)]
            lib.idx_map_file.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.idx_map_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_uint32,
                                         ctypes.POINTER(ctypes.c_size_t)]
            lib.idx_reduce.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.idx_reduce.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                       ctypes.c_uint32,
                                       ctypes.POINTER(ctypes.c_size_t)]
            lib.grep_map_file.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.grep_map_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                          ctypes.c_uint32,
                                          ctypes.POINTER(ctypes.c_size_t)]
            lib.grep_reduce.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.grep_reduce.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                        ctypes.c_uint32,
                                        ctypes.POINTER(ctypes.c_size_t)]
            lib.tfidf_map_file.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.tfidf_map_file.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_size_t)]
            _lib = lib
        except (OSError, AttributeError) as e:
            # AttributeError: a stale .so predating a symbol and a failed
            # rebuild (no toolchain) — pure-Python fallback, never crash.
            print(f"dsi_tpu.native: load failed ({e}); "
                  "using pure-Python data plane", file=sys.stderr)
            _lib = False
        return _lib or None


def available() -> bool:
    return _load() is not None


def decode_kv_file(path: str) -> Optional[List[tuple]]:
    """Decode one mr-X-Y intermediate file natively.

    Returns a list of (key, value) string pairs, or None when the caller
    must use the Python decoder (library unavailable, IO error — including
    the tolerated missing-file case — or the strict parser stopped early).
    """
    lib = _load()
    if lib is None:
        return None
    out_len = ctypes.c_size_t()
    ptr = lib.kv_decode_file(path.encode(), ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        arena = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.kv_arena_free(ptr)
    n, complete = struct.unpack_from("<II", arena, 0)
    if not complete:
        return None  # lenient Python decoder takes over (never diverge)
    out: List[tuple] = []
    off = 8
    try:
        for _ in range(n):
            klen, vlen = struct.unpack_from("<II", arena, off)
            off += 8
            key = arena[off:off + klen].decode("utf-8")
            off += klen
            val = arena[off:off + vlen].decode("utf-8")
            off += vlen
            out.append((key, val))
    except (UnicodeDecodeError, struct.error):
        # e.g. a lone-surrogate \uXXXX escape: json.dumps emits it, strict
        # UTF-8 rejects it.  Never diverge — let the Python decoder decide.
        return None
    return out


def encode_partitions(kva, n_reduce: int) -> Optional[List[bytes]]:
    """Partition + serialize a map task's output natively.

    One C pass computes the reference partitioner (``fnv1a32(key) &
    0x7fffffff % n_reduce``, mr/worker.go:33-37,76) and renders each
    partition's JSON-lines blob — the three host hot loops of the map side
    (per-byte hash, json.dumps per record, bucket appends) fused.

    Returns ``n_reduce`` byte blobs, or None when the caller must use the
    Python writer (library unavailable, or a key/value that strict UTF-8
    cannot encode — e.g. surrogates from decode errors)."""
    lib = _load()
    if lib is None:
        return None
    kva = list(kva)
    pack = struct.Struct("<II").pack
    parts: List[bytes] = []
    try:
        for kv in kva:
            kb = kv.key.encode("utf-8")
            vb = kv.value.encode("utf-8")
            parts.append(pack(len(kb), len(vb)))
            parts.append(kb)
            parts.append(vb)
    except (UnicodeEncodeError, struct.error):
        # Surrogates (json.dumps can represent them, raw UTF-8 can't) or a
        # >=4 GiB string (length field would not fit): Python writer path.
        return None
    buf = b"".join(parts)
    out_len = ctypes.c_size_t()
    ptr = lib.kv_encode_partitions(buf, len(buf), len(kva), n_reduce,
                                   ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        arena = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.kv_arena_free(ptr)
    return _unpack_blobs(arena, n_reduce)


def _unpack_blobs(arena: bytes, want: int) -> Optional[List[bytes]]:
    (n,) = struct.unpack_from("<I", arena, 0)
    if n != want:
        return None
    out: List[bytes] = []
    off = 4
    for _ in range(n):
        (bl,) = struct.unpack_from("<I", arena, off)
        off += 4
        out.append(arena[off:off + bl])
        off += bl
    return out


def _call_arena(symbol: str, args: tuple, want: int) -> Optional[List[bytes]]:
    """Shared call shape for every wcjob.cpp entry point: load, call,
    copy the arena out, ALWAYS free it, unpack the blob framing.  One
    place owns the arena-free-on-any-path invariant."""
    lib = _load()
    if lib is None:
        return None
    out_len = ctypes.c_size_t()
    ptr = getattr(lib, symbol)(*args, ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        arena = ctypes.string_at(ptr, out_len.value)
    finally:
        lib.kv_arena_free(ptr)
    return _unpack_blobs(arena, want)


def wc_map_file(path: str, n_reduce: int) -> Optional[List[bytes]]:
    """Whole word-count COMBINER map task natively (dsi_tpu/native/
    wcjob.cpp): tokenize + count-per-unique + reference partition hash +
    JSON-lines render in one C++ pass.  Returns the n_reduce partition
    blobs, or None when the split needs the host path (non-ASCII bytes,
    IO failure, or no library)."""
    return _call_arena("wc_map_file", (path.encode(), n_reduce), n_reduce)


def wc_reduce(workdir: str, reduce_task: int, n_map: int) -> Optional[bytes]:
    """Whole word-count SUM reduce task natively: parse + per-key sum +
    bytewise sort + "key sum\\n" render.  Returns the mr-out-<r> blob, or
    None when the Python reduce (the app's own Reduce) must own the task
    (escapes/non-ASCII/malformed records, overflow, or no library)."""
    blobs = _call_arena("wc_reduce", (workdir.encode(), reduce_task, n_map),
                        1)
    return None if blobs is None else blobs[0]


def idx_map_file(path: str, docname: str,
                 n_reduce: int) -> Optional[List[bytes]]:
    """Whole inverted-index map task natively (distinct words +
    partition + render); None -> host path (non-ASCII split, docname
    needing JSON escapes, or no library)."""
    try:
        args = (path.encode(), docname.encode("ascii"), n_reduce)
    except UnicodeEncodeError:
        return None
    return _call_arena("idx_map_file", args, n_reduce)


def idx_reduce(workdir: str, reduce_task: int, n_map: int) -> Optional[bytes]:
    """Whole inverted-index reduce task natively ("<count> <docs,...>"
    over sorted deduplicated documents); None -> Python reduce."""
    blobs = _call_arena("idx_reduce", (workdir.encode(), reduce_task, n_map),
                        1)
    return None if blobs is None else blobs[0]


def grep_map_file(path: str, pattern: str,
                  n_reduce: int) -> Optional[List[bytes]]:
    """Whole literal-grep map task natively (byte-level substring search
    per line + partition + render); None -> host re path (regex
    metacharacters, non-ASCII split/pattern, rare control bytes)."""
    try:
        args = (path.encode(), pattern.encode("ascii"), n_reduce)
    except UnicodeEncodeError:
        return None
    return _call_arena("grep_map_file", args, n_reduce)


def grep_reduce(workdir: str, reduce_task: int,
                n_map: int) -> Optional[bytes]:
    """Whole occurrence-count grep reduce task natively; None -> Python
    reduce (escapes beyond the map's minimal set, non-ASCII keys)."""
    blobs = _call_arena("grep_reduce", (workdir.encode(), reduce_task,
                                        n_map), 1)
    return None if blobs is None else blobs[0]


def tfidf_map_file(path: str, docname: str,
                   n_reduce: int) -> Optional[List[bytes]]:
    """Whole TF-IDF map task natively (distinct words x in-doc counts,
    value "<doc>\\t<tf>"); None -> host path.  The reduce (float
    scoring) always runs on the Python path."""
    try:
        args = (path.encode(), docname.encode("ascii"), n_reduce)
    except UnicodeEncodeError:
        return None
    return _call_arena("tfidf_map_file", args, n_reduce)
