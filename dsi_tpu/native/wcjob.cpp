// Native word-count job bodies for the host data plane.
//
// The reference's whole per-task compute is compiled Go
// (mrapps/wc.go:21-44 map, mr/worker.go:110-146 reduce); the Python host
// path re-creates the semantics but pays interpreter costs per token and
// per record — on a 1-core box that caps the distributed N-worker run
// below the sequential oracle.  This file implements the word-count
// COMBINER app's task bodies natively (apps/tpu_wc.py semantics: Map
// emits one {word, count} record per unique word per split; Reduce sums
// counts), with the same exactness escapes as every native piece here:
// anything the C++ cannot prove it handled byte-identically returns NULL
// and the Python path serves the task (dsi_tpu/native/__init__.py
// contract — native and pure runs can never diverge).
//
// wc_map_file:  read a split, tokenize maximal [A-Za-z] runs (== Go
//   strings.FieldsFunc(!unicode.IsLetter) on ASCII; ANY byte >= 0x80
//   declines the split), count per unique word, partition by the
//   reference hash (fnv1a32(word) & 0x7fffffff % n_reduce,
//   mr/worker.go:33-37,76), and render each partition's JSON-lines blob
//   ({"Key": "w", "Value": "<count>"} — the exact record format the
//   Python writer and both decoders use).
//   Arena: u32 n_blobs, then per blob u32 len + bytes.
//
// wc_reduce: parse the n_map intermediate files of one reduce partition
//   (missing files tolerated, worker.go:106-108), sum integer Values per
//   Key, sort keys bytewise (== Python str sort for the ASCII keys this
//   parser accepts), render "key sum\n" lines (worker.go:144 "%v %v\n").
//   Declines (NULL) on: any JSON escape, any non-ASCII byte, any
//   non-integer value, or any malformed record — the Python reduce
//   (which applies the app's own Reduce) then owns the task.
//
// Build: scripts/build_native.sh links this into libkvcodec.so alongside
// the codec.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

inline bool is_letter(unsigned char c) {
  return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z');
}

inline uint32_t fnv1a32(const char* s, size_t n) {
  uint32_t h = 0x811C9DC5u;
  for (size_t i = 0; i < n; i++) {
    h ^= (unsigned char)s[i];
    h *= 0x01000193u;
  }
  return h;
}

// Read a whole file; false on open failure (caller's tolerated case).
bool read_file(const char* path, std::string& out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (n < 0) { fclose(f); return false; }
  out.resize((size_t)n);
  size_t got = n ? fread(&out[0], 1, (size_t)n, f) : 0;
  fclose(f);
  if (got != (size_t)n) return false;
  return true;
}

uint8_t* pack_blobs(const std::vector<std::string>& blobs, size_t* out_len) {
  size_t total = 4;
  for (const auto& b : blobs) {
    if (b.size() > UINT32_MAX) return nullptr;  // u32 framing would wrap
    total += 4 + b.size();
  }
  uint8_t* arena = (uint8_t*)malloc(total);
  if (!arena) return nullptr;
  uint32_t n = (uint32_t)blobs.size();
  memcpy(arena, &n, 4);
  size_t off = 4;
  for (const auto& b : blobs) {
    uint32_t len = (uint32_t)b.size();
    memcpy(arena + off, &len, 4);
    off += 4;
    memcpy(arena + off, b.data(), b.size());
    off += b.size();
  }
  *out_len = total;
  return arena;
}

// Byte-span key for per-unique-word hash tables over a split buffer.
// The table hash is FNV-1a 64 (table use only — the partition hash is
// always the reference's exact 32-bit variant, fnv1a32 above).
struct SV {
  const char* p;
  uint32_t n;
};
struct SVHash {
  size_t operator()(const SV& s) const {
    uint64_t h = 1469598103934665603ull;
    for (uint32_t i = 0; i < s.n; i++) {
      h ^= (unsigned char)s.p[i];
      h *= 1099511628211ull;
    }
    return (size_t)h;
  }
};
struct SVEq {
  bool operator()(const SV& a, const SV& b) const {
    return a.n == b.n && memcmp(a.p, b.p, a.n) == 0;
  }
};

// Tokenize maximal [A-Za-z] runs into a per-unique-word count table.
void count_tokens(const std::string& data,
                  std::unordered_map<SV, uint64_t, SVHash, SVEq>& counts) {
  const char* p = data.data();
  const char* end = p + data.size();
  while (p < end) {
    while (p < end && !is_letter((unsigned char)*p)) p++;
    const char* s = p;
    while (p < end && is_letter((unsigned char)*p)) p++;
    if (p > s) counts[SV{s, (uint32_t)(p - s)}]++;
  }
}

// Shared strict record parser for every native reduce body: one
// {"Key": "...", "Value": "..."} record per line, matching the exact
// shape both writers (this file and Python json.dumps) emit.  Returns
// 1 on a parsed record, 0 at clean end-of-data, -1 when the file must
// defer to the Python decoder (escapes — unless `unescape_key` handles
// the minimal set —, non-ASCII/control bytes, concatenated records,
// malformed shapes).  Acceptance here implies the Python decoder agrees
// on the record sequence, which is what lets the native reduce's output
// be byte-identical by construction.
int parse_record(const char*& p, const char* end, SV* key, SV* val,
                 std::string* unescape_key) {
  while (p < end && (*p == '\n' || *p == '\r' || *p == ' ')) p++;
  if (p >= end) return 0;
  auto expect = [&](const char* s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  };
  auto plain_span = [&](SV* out) {
    if (p >= end || *p != '"') return false;
    p++;
    const char* s = p;
    while (p < end && *p != '"') {
      unsigned char c = (unsigned char)*p;
      if (c == '\\' || c >= 0x80 || c < 0x20) return false;
      p++;
    }
    if (p >= end) return false;
    out->p = s;
    out->n = (uint32_t)(p - s);
    p++;
    return true;
  };
  auto escaped_span = [&](std::string* out) {
    if (p >= end || *p != '"') return false;
    p++;
    out->clear();
    while (p < end && *p != '"') {
      unsigned char c = (unsigned char)*p;
      if (c >= 0x80 || c < 0x20) return false;
      if (c == '\\') {
        if (p + 1 >= end) return false;
        char n = p[1];
        if (n == '"') out->push_back('"');
        else if (n == '\\') out->push_back('\\');
        else if (n == 't') out->push_back('\t');
        else if (n == 'r') out->push_back('\r');
        else if (n == '/') out->push_back('/');
        else return false;  // \uXXXX etc: Python owns it
        p += 2;
      } else {
        out->push_back((char)c);
        p++;
      }
    }
    if (p >= end) return false;
    p++;
    return true;
  };
  if (!expect("{\"Key\": ")) return -1;
  if (unescape_key ? !escaped_span(unescape_key) : !plain_span(key))
    return -1;
  if (!expect(", \"Value\": ") || !plain_span(val) || !expect("}"))
    return -1;
  // Strictly one record per line (the Python decoder json.loads's each
  // LINE and breaks on trailing garbage; kvcodec.cpp enforces the same).
  while (p < end && (*p == ' ' || *p == '\r')) p++;
  if (p < end && *p != '\n') return -1;
  if (p < end) p++;
  return 1;
}

}  // namespace

extern "C" {

// NULL when the split needs the host path (non-ASCII byte) or on IO/OOM.
uint8_t* wc_map_file(const char* path, uint32_t n_reduce, size_t* out_len) {
  std::string data;
  if (!read_file(path, data) || n_reduce == 0) return nullptr;
  for (unsigned char c : data)
    if (c >= 0x80) return nullptr;  // Unicode: host tokenizer owns it

  std::unordered_map<SV, uint64_t, SVHash, SVEq> counts;
  counts.reserve(1 << 15);
  count_tokens(data, counts);

  std::vector<std::string> blobs(n_reduce);
  char line[96];
  for (const auto& it : counts) {
    uint32_t part = (fnv1a32(it.first.p, it.first.n) & 0x7FFFFFFFu) % n_reduce;
    std::string& b = blobs[part];
    // {"Key": "word", "Value": "count"}\n — ASCII letters need no JSON
    // escaping; format matches the Python json.dumps writer.
    b += "{\"Key\": \"";
    b.append(it.first.p, it.first.n);
    int m = snprintf(line, sizeof line, "\", \"Value\": \"%llu\"}\n",
                     (unsigned long long)it.second);
    b.append(line, (size_t)m);
  }
  return pack_blobs(blobs, out_len);
}

// NULL => the Python reduce owns the task.  Arena: one blob (the rendered
// mr-out-<r> contents) in pack_blobs framing with n_blobs == 1.
uint8_t* wc_reduce(const char* workdir, uint32_t reduce_task, uint32_t n_map,
                   size_t* out_len) {
  std::unordered_map<std::string, uint64_t> sums;
  sums.reserve(1 << 15);
  std::string data;
  char path[4096];
  for (uint32_t i = 0; i < n_map; i++) {
    snprintf(path, sizeof path, "%s/mr-%u-%u", workdir, i, reduce_task);
    data.clear();
    if (!read_file(path, data)) continue;  // tolerated: worker.go:106-108
    const char* p = data.data();
    const char* end = p + data.size();
    SV key, val;
    int rc;
    while ((rc = parse_record(p, end, &key, &val, nullptr)) == 1) {
      if (val.n == 0 || val.n > 18) return nullptr;
      uint64_t v = 0;
      for (uint32_t j = 0; j < val.n; j++) {
        if (val.p[j] < '0' || val.p[j] > '9') return nullptr;
        v = v * 10 + (uint64_t)(val.p[j] - '0');
      }
      uint64_t& slot = sums[std::string(key.p, key.n)];
      if (slot > UINT64_MAX - v) return nullptr;  // Python sums exactly
      slot += v;
    }
    if (rc < 0) return nullptr;  // unexpected shape/escape: Python decides
  }
  std::vector<const std::pair<const std::string, uint64_t>*> rows;
  rows.reserve(sums.size());
  for (const auto& kv : sums) rows.push_back(&kv);
  std::sort(rows.begin(), rows.end(),
            [](const auto* a, const auto* b) { return a->first < b->first; });
  std::string out;
  out.reserve(rows.size() * 16);
  char tail[32];
  for (const auto* kv : rows) {
    out += kv->first;
    int m = snprintf(tail, sizeof tail, " %llu\n",
                     (unsigned long long)kv->second);
    out.append(tail, (size_t)m);
  }
  std::vector<std::string> blobs{out};
  return pack_blobs(blobs, out_len);
}

// TF-IDF map body (apps/tfidf.py semantics, native_kind "tfidf"): Map
// emits one {word, "<doc>\t<tf>"} record per DISTINCT word per
// document (tf = in-document count); the reduce (df/idf float scoring)
// stays on the Python path, whose decoder reads the \t escape this
// renders.  Same decline discipline as the other bodies.
extern "C" uint8_t* tfidf_map_file(const char* path, const char* docname,
                                   uint32_t n_reduce, size_t* out_len) {
  if (n_reduce == 0) return nullptr;
  for (const char* c = docname; *c; c++) {
    unsigned char u = (unsigned char)*c;
    if (u < 0x20 || u >= 0x7F || u == '"' || u == '\\')
      return nullptr;  // would need wider escaping: Python writer owns it
  }
  std::string data;
  if (!read_file(path, data)) return nullptr;
  for (unsigned char c : data)
    if (c >= 0x80) return nullptr;

  std::unordered_map<SV, uint64_t, SVHash, SVEq> counts;
  counts.reserve(1 << 14);
  count_tokens(data, counts);

  std::vector<std::string> blobs(n_reduce);
  char tail[96];
  for (const auto& it : counts) {
    uint32_t part = (fnv1a32(it.first.p, it.first.n) & 0x7FFFFFFFu) % n_reduce;
    std::string& b = blobs[part];
    b += "{\"Key\": \"";
    b.append(it.first.p, it.first.n);
    b += "\", \"Value\": \"";
    b += docname;
    int m = snprintf(tail, sizeof tail, "\\t%llu\"}\n",
                     (unsigned long long)it.second);
    b.append(tail, (size_t)m);
  }
  return pack_blobs(blobs, out_len);
}

// Distributed-grep app bodies (apps/grep.py semantics, native_kind
// "grep_count"): Map emits one {line, ""} record per line containing
// the LITERAL pattern (regex patterns decline to the host's re path);
// Reduce counts occurrences.  ASCII-only (a split or pattern with any
// byte >= 0x80 declines — the host path owns Unicode), with the minimal
// JSON escape set lines need (\" \\ \t \r; other control bytes
// decline).  For pure-ASCII literal patterns, byte-level substring
// search over 0x0A-split lines is exactly re.search over the
// utf-8-decoded text's lines.

static bool grep_escape_line(const char* s, size_t n, std::string& out) {
  for (size_t i = 0; i < n; i++) {
    unsigned char c = (unsigned char)s[i];
    if (c >= 0x80) return false;
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if (c < 0x20) return false;  // rare ctrl chars: Python owns them
        out.push_back((char)c);
    }
  }
  return true;
}

extern "C" uint8_t* grep_map_file(const char* path, const char* pattern,
                                  uint32_t n_reduce, size_t* out_len) {
  if (n_reduce == 0) return nullptr;
  size_t plen = strlen(pattern);
  if (plen == 0) return nullptr;
  for (const char* c = pattern; *c; c++) {
    unsigned char u = (unsigned char)*c;
    if (u >= 0x80 || u < 0x20) return nullptr;
    // Only LITERAL patterns: any regex metacharacter defers to re.
    if (strchr("\\^$.|?*+()[]{}", *c)) return nullptr;
  }
  std::string data;
  if (!read_file(path, data)) return nullptr;
  for (unsigned char c : data)
    if (c >= 0x80) return nullptr;

  std::vector<std::string> blobs(n_reduce);
  const char* p = data.data();
  const char* end = p + data.size();
  std::string esc;
  while (p <= end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* e = nl ? nl : end;
    if ((size_t)(e - p) >= plen &&
        memmem(p, (size_t)(e - p), pattern, plen) != nullptr) {
      esc.clear();
      if (!grep_escape_line(p, (size_t)(e - p), esc)) return nullptr;
      uint32_t part =
          (fnv1a32(p, (size_t)(e - p)) & 0x7FFFFFFFu) % n_reduce;
      std::string& b = blobs[part];
      b += "{\"Key\": \"";
      b += esc;
      b += "\", \"Value\": \"\"}\n";
    }
    if (!nl) break;
    p = nl + 1;
  }
  return pack_blobs(blobs, out_len);
}

extern "C" uint8_t* grep_reduce(const char* workdir, uint32_t reduce_task,
                                uint32_t n_map, size_t* out_len) {
  // Count records per key; keys unescape to raw bytes before grouping
  // and sorting (bytewise == Python str sort for the ASCII lines this
  // parser accepts; \uXXXX or unknown escapes decline).
  std::unordered_map<std::string, uint64_t> counts;
  std::string data, key;
  char path[4096];
  for (uint32_t i = 0; i < n_map; i++) {
    snprintf(path, sizeof path, "%s/mr-%u-%u", workdir, i, reduce_task);
    data.clear();
    if (!read_file(path, data)) continue;  // tolerated: worker.go:106-108
    const char* p = data.data();
    const char* end = p + data.size();
    SV val;
    int rc;
    // Key with the minimal escape set unescaped; the value's content is
    // ignored (the app's Reduce counts records) but still parses
    // strictly so acceptance implies the Python decoder agrees on the
    // record sequence.
    while ((rc = parse_record(p, end, nullptr, &val, &key)) == 1)
      counts[key]++;
    if (rc < 0) return nullptr;
  }
  std::vector<const std::pair<const std::string, uint64_t>*> rows;
  rows.reserve(counts.size());
  for (const auto& kv : counts) rows.push_back(&kv);
  std::sort(rows.begin(), rows.end(),
            [](const auto* a, const auto* b) { return a->first < b->first; });
  std::string out;
  char tail[32];
  for (const auto* kv : rows) {
    out += kv->first;
    int m = snprintf(tail, sizeof tail, " %llu\n",
                     (unsigned long long)kv->second);
    out.append(tail, (size_t)m);
  }
  std::vector<std::string> blobs{out};
  return pack_blobs(blobs, out_len);
}

// Inverted-index app bodies (apps/indexer.py semantics, native_kind
// "indexer"): Map emits one {word, document} record per DISTINCT word
// per split; Reduce renders "<count> <doc1>,<doc2>,..." over the sorted
// deduplicated documents.  Same decline discipline as the wc bodies.

// NULL when the split/docname needs the host path.
uint8_t* idx_map_file(const char* path, const char* docname,
                      uint32_t n_reduce, size_t* out_len) {
  if (n_reduce == 0) return nullptr;
  for (const char* c = docname; *c; c++) {
    unsigned char u = (unsigned char)*c;
    if (u < 0x20 || u >= 0x7F || u == '"' || u == '\\')
      return nullptr;  // would need JSON escaping: Python writer owns it
  }
  std::string data;
  if (!read_file(path, data)) return nullptr;
  for (unsigned char c : data)
    if (c >= 0x80) return nullptr;  // Unicode: host tokenizer owns it

  std::unordered_set<std::string> words;
  words.reserve(1 << 14);
  const char* p = data.data();
  const char* end = p + data.size();
  while (p < end) {
    while (p < end && !is_letter((unsigned char)*p)) p++;
    const char* s = p;
    while (p < end && is_letter((unsigned char)*p)) p++;
    if (p > s) words.emplace(s, (size_t)(p - s));
  }

  std::vector<std::string> blobs(n_reduce);
  for (const auto& w : words) {
    uint32_t part = (fnv1a32(w.data(), w.size()) & 0x7FFFFFFFu) % n_reduce;
    std::string& b = blobs[part];
    b += "{\"Key\": \"";
    b += w;
    b += "\", \"Value\": \"";
    b += docname;
    b += "\"}\n";
  }
  return pack_blobs(blobs, out_len);
}

// NULL => the Python reduce (the app's own Reduce) owns the task.
uint8_t* idx_reduce(const char* workdir, uint32_t reduce_task,
                    uint32_t n_map, size_t* out_len) {
  // std::set gives bytewise order == Python str sort for the ASCII
  // strings this parser accepts.
  std::unordered_map<std::string, std::set<std::string>> docs;
  std::string data;
  char path[4096];
  for (uint32_t i = 0; i < n_map; i++) {
    snprintf(path, sizeof path, "%s/mr-%u-%u", workdir, i, reduce_task);
    data.clear();
    if (!read_file(path, data)) continue;  // tolerated: worker.go:106-108
    const char* p = data.data();
    const char* end = p + data.size();
    SV key, val;
    int rc;
    while ((rc = parse_record(p, end, &key, &val, nullptr)) == 1)
      docs[std::string(key.p, key.n)].emplace(val.p, val.n);
    if (rc < 0) return nullptr;
  }
  std::vector<const std::pair<const std::string,
                              std::set<std::string>>*> rows;
  rows.reserve(docs.size());
  for (const auto& kv : docs) rows.push_back(&kv);
  std::sort(rows.begin(), rows.end(),
            [](const auto* a, const auto* b) { return a->first < b->first; });
  std::string out;
  char num[16];
  for (const auto* kv : rows) {
    out += kv->first;
    int m = snprintf(num, sizeof num, " %zu ", kv->second.size());
    out.append(num, (size_t)m);
    bool first = true;
    for (const auto& d : kv->second) {
      if (!first) out += ',';
      first = false;
      out += d;
    }
    out += '\n';
  }
  std::vector<std::string> blobs{out};
  return pack_blobs(blobs, out_len);
}

}  // extern "C"
