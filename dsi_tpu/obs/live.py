"""The live telemetry plane: sampler ring, ``/statusz``, ``/metrics``.

PR 6's tracer is post-hoc — you learn where the wall went after the
run flushes.  This module is the *in-flight* half the ROADMAP's
serving-daemon and speculative-execution items need (the paper ships a
live status page as a first-class framework feature, Dean & Ghemawat
§4.8): a running engine answers "what step are you on, what do your
stage latencies look like, is anything stalled" over HTTP while it
runs, and a bounded ``live.jsonl`` ring survives a crash for post-hoc
"what was it doing right before".

Default OFF = zero threads, zero overhead: nothing here is imported
until a CLI passes ``--statusz-port`` (or sets ``DSI_STATUSZ_PORT``),
and the span path's only cost stays the one module-attribute check in
``obs/trace.py``.  When ON:

* :class:`LiveTelemetry` binds a localhost-only HTTP server
  (``127.0.0.1`` — this is an operator peephole, not a public
  surface; port 0 picks a free port, printed to stderr) serving

  - ``/statusz`` — plain text: per-pipeline in-flight window (current
    step ordinal, oldest in-flight age), per-engine counters, the
    stage latency percentile table, heartbeat ages, stalls;
  - ``/metrics`` — Prometheus text format: the same data as
    ``dsi_*`` gauges/summaries, scrape-ready;
  - ``/healthz`` — ``{"ok": true}``.

  Both endpoints build their answer ON DEMAND from the metrics
  registry, the stage histograms, and the live-pipeline registry
  (``obs/hist.py``) — always current, no staleness window.

* a sampler thread snapshots the same state every
  ``DSI_STATUSZ_INTERVAL_S`` (default 1 s) into a bounded ring
  (``DSI_LIVE_RING`` samples, default 256) and — when a directory is
  known (the run's ``--trace-dir``) — rewrites ``live.jsonl`` with the
  ring's contents via temp+rename, so the file is bounded and never
  torn mid-line.  The first sample is taken at start, so even a run
  that crashes in device init leaves one.

Starting the plane activates the stage histograms with a *hold*
(``hist.hold``): a bench toggling its in-memory tracer off cannot drop
the sampler's percentiles mid-serve.
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from dsi_tpu.obs import hist as _hist
from dsi_tpu.obs.registry import get_registry
from dsi_tpu.obs.trace import get_tracer


_env_float = _hist.env_float


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


_METRIC_SANE = re.compile(r"[^a-zA-Z0-9_]")


def _mname(s: str) -> str:
    return _METRIC_SANE.sub("_", str(s))


# ── pluggable sections (the serving daemon's tenant table) ─────────────
#
# A resident process with state of its own (``dsi_tpu/serve``'s
# per-tenant table) registers a section here: ``statusz_fn`` returns the
# section's plain-text body (one indented line per row), ``metrics_fn``
# (optional) returns ready Prometheus lines.  Both are called on demand
# under the same no-staleness rule as the built-in sections; a provider
# that raises is skipped, never kills the scrape.

_sections_lock = threading.Lock()
_sections: Dict[str, tuple] = {}


def register_section(name: str, statusz_fn, metrics_fn=None) -> None:
    """Add (or replace) a named /statusz section + optional /metrics
    lines provider."""
    with _sections_lock:
        _sections[name] = (statusz_fn, metrics_fn)


def unregister_section(name: str) -> None:
    with _sections_lock:
        _sections.pop(name, None)


def _section_items() -> list:
    with _sections_lock:
        return sorted(_sections.items())


class LiveTelemetry:
    """One live telemetry server + sampler (module docstring)."""

    def __init__(self, port: int = 0, live_dir: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 ring: Optional[int] = None):
        self.port = int(port)
        self.live_dir = live_dir
        self.interval_s = (interval_s if interval_s is not None
                           else _env_float("DSI_STATUSZ_INTERVAL_S", 1.0))
        self.ring: "collections.deque" = collections.deque(
            maxlen=max(1, ring if ring is not None
                       else _env_int("DSI_LIVE_RING", 256)))
        self.samples = 0
        self._t0 = time.time()
        self._stop = threading.Event()
        self._srv: Optional[ThreadingHTTPServer] = None
        self._threads: list = []

    # ── state assembly (shared by /statusz, /metrics, the ring) ──

    def snapshot(self) -> Dict:
        """One JSON-ready sample of everything live: registry scopes +
        gauges + histograms, per-pipeline in-flight state, tracer
        counters.  Built on demand — this IS the statusz answer."""
        tr = get_tracer()
        pipes = []
        for p in _hist.live_pipelines():
            try:
                pipes.append(p.live_state())
            except Exception:  # a pipeline mid-teardown: skip, not die
                pass
        snap = {"ts": round(time.time(), 3),
                "uptime_s": round(time.time() - self._t0, 3),
                "pid": os.getpid(),
                "pipelines": pipes,
                "counters": tr.counters_snapshot(),
                "dropped_events": tr.dropped}
        snap.update(get_registry().snapshot())
        return snap

    # ── renderers ──

    def statusz_text(self) -> str:
        s = self.snapshot()
        out = [f"dsi statusz  pid={s['pid']} "
               f"uptime={s['uptime_s']:.1f}s "
               f"interval={self.interval_s}s samples={self.samples}"]
        out.append("-- pipelines (in flight) --")
        if not s["pipelines"]:
            out.append("  (none running)")
        for p in s["pipelines"]:
            out.append(
                f"  {p['engine'] or '?'}: dispatched={p['dispatched']} "
                f"finished={p['finished']} inflight={p['inflight']} "
                f"depth={p['depth']} step={p['step']} "
                f"oldest_age_s={p['oldest_age_s']}")
        out.append("-- engines --")
        engines = s.get("engines") or {}
        if not engines:
            out.append("  (none yet)")
        for eng, ph in sorted(engines.items()):
            kv = " ".join(
                f"{k}={round(v, 3) if isinstance(v, float) else v}"
                for k, v in sorted(ph.items())
                if isinstance(v, (int, float)))
            out.append(f"  {eng}: {kv}")
        out.append("-- stage latency (ms) --")
        hists = s.get("histograms") or {}
        if not hists:
            out.append("  (no samples yet)")
        else:
            out.append(f"  {'stage':<12} {'count':>8} {'p50':>10} "
                       f"{'p90':>10} {'p99':>10} {'max':>10}")
            for stage in _hist.HIST_STAGES:
                h = hists.get(stage)
                if not h:
                    continue
                out.append(f"  {stage:<12} {h['count']:>8} "
                           f"{h['p50_ms']:>10.3f} {h['p90_ms']:>10.3f} "
                           f"{h['p99_ms']:>10.3f} {h['max_ms']:>10.3f}")
        gauges = s.get("gauges") or {}
        hb = gauges.get("mr_worker_heartbeat_age_s")
        out.append("-- heartbeats --")
        if hb:
            out.append("  " + "  ".join(f"{w}={a}s"
                                        for w, a in sorted(hb.items())))
        else:
            out.append("  (no workers)")
        stall = gauges.get("pipeline_stall")
        if stall:
            out.append(f"-- last stall --\n  {stall}")
        if s["counters"]:
            out.append(f"-- counters --\n  {s['counters']}")
        for name, (status_fn, _metrics_fn) in _section_items():
            try:
                body = status_fn()
            except Exception:
                continue  # a broken provider must not kill the scrape
            out.append(f"-- {name} --")
            out.append(body.rstrip("\n") if body else "  (empty)")
        return "\n".join(out) + "\n"

    def metrics_text(self) -> str:
        s = self.snapshot()
        L = [f"dsi_up 1",
             f"dsi_uptime_seconds {s['uptime_s']}",
             f"dsi_live_samples_total {self.samples}",
             f"dsi_trace_dropped_events {s['dropped_events']}"]
        hists = s.get("histograms") or {}
        if hists:
            L.append("# TYPE dsi_stage_latency_seconds summary")
        for stage, h in sorted(hists.items()):
            lab = f'stage="{_mname(stage)}"'
            for q, key in (("0.5", "p50_ms"), ("0.9", "p90_ms"),
                           ("0.99", "p99_ms")):
                L.append(f"dsi_stage_latency_seconds{{{lab},"
                         f'quantile="{q}"}} {h[key] / 1e3:.6g}')
            L.append(f"dsi_stage_latency_seconds_sum{{{lab}}} "
                     f"{h['total_s']}")
            L.append(f"dsi_stage_latency_seconds_count{{{lab}}} "
                     f"{h['count']}")
            L.append(f"dsi_stage_latency_seconds_max{{{lab}}} "
                     f"{h['max_ms'] / 1e3:.6g}")
        for p in s["pipelines"]:
            lab = f'engine="{_mname(p["engine"] or "unknown")}"'
            L.append(f"dsi_pipeline_step{{{lab}}} {p['step']}")
            L.append(f"dsi_pipeline_inflight{{{lab}}} {p['inflight']}")
            L.append(f"dsi_pipeline_oldest_age_seconds{{{lab}}} "
                     f"{p['oldest_age_s']}")
        for eng, ph in sorted((s.get("engines") or {}).items()):
            lab_e = _mname(eng)
            for k, v in sorted(ph.items()):
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    L.append(f'dsi_engine_stat{{engine="{lab_e}",'
                             f'key="{_mname(k)}"}} {v}')
        gauges = s.get("gauges") or {}
        hb = gauges.get("mr_worker_heartbeat_age_s") or {}
        for w, a in sorted(hb.items()):
            L.append(f'dsi_worker_heartbeat_age_seconds'
                     f'{{worker="{_mname(w)}"}} {a}')
        for name, v in sorted(s["counters"].items()):
            L.append(f'dsi_counter{{name="{_mname(name)}"}} {v}')
        for name, (_status_fn, metrics_fn) in _section_items():
            if metrics_fn is None:
                continue
            try:
                extra = metrics_fn()
            except Exception:
                continue
            if extra:
                L.append(extra.rstrip("\n"))
        return "\n".join(L) + "\n"

    # ── sampler ──

    def _sample_once(self) -> None:
        try:
            line = json.dumps(self.snapshot(), sort_keys=True,
                              default=str)
        except Exception:
            return
        self.ring.append(line)
        self.samples += 1
        if not self.live_dir:
            return
        try:
            path = os.path.join(self.live_dir, "live.jsonl")
            tmp = f"{path}.tmp-{os.getpid()}"
            # dsicheck: allow[raw-write] bounded live ring, rewritten
            # every sample: temp+rename keeps readers untorn; fsync on
            # a 1 Hz telemetry loop would tax the engine for bytes
            # that are stale one interval later by design
            with open(tmp, "w", encoding="utf-8") as f:
                f.write("\n".join(self.ring) + "\n")
            os.replace(tmp, path)  # atomic: readers never see a torn file
        except OSError:
            pass  # a full disk must not kill the engine

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample_once()

    # ── lifecycle ──

    def start(self) -> "LiveTelemetry":
        _hist.hold()
        live = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no per-request stderr spam
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path in ("/", "/statusz"):
                    body, ctype = live.statusz_text(), "text/plain"
                elif path == "/metrics":
                    body, ctype = (live.metrics_text(),
                                   "text/plain; version=0.0.4")
                elif path == "/healthz":
                    body, ctype = '{"ok": true}\n', "application/json"
                else:
                    self.send_error(404)
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._srv = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        if self.live_dir:
            os.makedirs(self.live_dir, exist_ok=True)
        self._sample_once()  # a crash in device init still leaves one
        t_srv = threading.Thread(target=self._srv.serve_forever,
                                 name="dsi-statusz-server", daemon=True)
        t_smp = threading.Thread(target=self._sample_loop,
                                 name="dsi-live-sampler", daemon=True)
        self._threads = [t_srv, t_smp]
        t_srv.start()
        t_smp.start()
        print(f"statusz: serving on http://127.0.0.1:{self.port}/statusz "
              f"(metrics: /metrics"
              + (f"; ring: {os.path.join(self.live_dir, 'live.jsonl')}"
                 if self.live_dir else "") + ")",
              file=sys.stderr, flush=True)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        _hist.release()


# ── the process-global instance (one peephole per process) ─────────────

_live_lock = threading.Lock()
_live: Optional[LiveTelemetry] = None


def start_live(port: int, live_dir: Optional[str] = None) -> LiveTelemetry:
    """Start (or return) the process's live telemetry plane.  ``port``
    0 binds a free port; the chosen one is printed to stderr and
    available as ``.port``."""
    global _live
    with _live_lock:
        if _live is None:
            _live = LiveTelemetry(port=port, live_dir=live_dir).start()
        return _live


def stop_live() -> None:
    global _live
    with _live_lock:
        if _live is not None:
            _live.stop()
            _live = None


def start_from_args(port_arg: Optional[int],
                    live_dir: Optional[str] = None
                    ) -> Optional[LiveTelemetry]:
    """The CLIs' one-liner: an explicit ``--statusz-port`` wins (0 =
    pick a free port), else ``DSI_STATUSZ_PORT`` > 0 enables, else the
    plane stays off (None returned, zero threads)."""
    if port_arg is None:
        env = _env_int("DSI_STATUSZ_PORT", 0)
        if env <= 0:
            return None
        port_arg = env
    return start_live(int(port_arg), live_dir=live_dir)
