"""The one metrics registry behind every engine's phase dict.

Before this module each engine grew its own stats dict with its own
spellings — ``pipeline_stats``/``stream_phases`` (word count),
``wave_phases`` (TF-IDF), the grep variants — and bench.py, the CLIs,
and ``scripts/summarize_onchip.py`` each re-learned every shape.  Now an
engine's stats dict IS a :class:`MetricsScope` registered here under the
engine's name, and every consumer reads one documented schema.

## The unified key schema

Phase wall-seconds (suffix ``_s``; a key is present when the engine has
that phase):

* ``materialize_s``      — building host-side step items (batch slicing,
  wave chunk assembly); in the producer thread at depth > 1
* ``materialize_wait_s`` — consumer starvation on the producer queue
* ``upload_s``           — H2D puts of step inputs
* ``kernel_s``           — time blocked on a step's deferred scalar/flag
  check (the device-compute wall the window failed to hide)
* ``pull_s``             — D2H result pulls
* ``merge_s``            — host-side accumulation of pulled results
* ``replay_s``           — exactness-ladder replays of overflowed steps
* ``fold_s`` / ``append_s`` / ``hist_s`` — device-service folds
* ``sync_s`` / ``drain_s``               — device-service pulls/drains
* ``widen_s``            — drain→realloc→re-fold recoveries
* ``ckpt_s``             — checkpoint snapshot + durable write (with
  async commits, only the boundary-side work: capture + any barrier)
* ``ckpt_capture_s``     — the capture half of a save: flag flushes,
  snapshot-pull dispatches, host snapshot-by-reference (engine thread)
* ``ckpt_commit_s``      — the commit half: materialize the in-flight
  pulls, serialize, durable write (the background writer thread under
  ``--ckpt-async``, inline otherwise)
* ``ckpt_barrier_s``     — engine-thread stalls on the commit writer
  (the NEXT save or the end-of-stream drain found a commit in flight)

Counters / gauges: ``steps`` (or ``waves``), ``depth``, ``replays``,
``step_pulls``, ``sync_pulls``, ``widens``, ``folds``,
``fold_overflows``, ``appends``, ``append_overflows``,
``postings_widens``, ``topk_snapshots``, ``hist_folds``, ``hist_pulls``,
``table_cap``, ``l_cap``, ``sync_every``, ``max_inflight``,
``buffer_allocs``, ``ckpt_saves``, ``ckpt_every``, ``resume_gap_s``,
``resume_cursor``/``resume_wave``, ``device_accumulate``.

Async/incremental checkpoint keys (``dsi_tpu/ckpt`` writer/delta —
present when checkpointing is on): ``ckpt_async``/``ckpt_delta`` (the
mode flags), ``ckpt_deltas`` (incremental saves among ``ckpt_saves``),
``ckpt_full_bytes``/``ckpt_delta_bytes`` (serialized payload totals by
kind — the bench's delta-vs-full evidence).

Compressed wire + ingest keys (ISSUE 13): ``ingest_readers``/
``ingest_blocks``/``readahead_hit_pct`` and the ``ingest_wait_s``
phase come from the parallel reader pool (``utils/ioread.py``, folded
into the engine scope at release by ``parallel/pipeline.py
fold_source_stats``); ``wire_upload`` (the chunk-codec mode flag),
``wire_steps``/``wire_raw_steps`` (packed vs raw-fallback uploads),
``wire_packed_bytes``, ``wire_ratio`` (raw/packed upload bytes) and
the ``decode_s`` phase (host encode + decode-prologue dispatch) come
from the chunk-upload codec (``ops/wirecodec.py``);
``ckpt_compress``/``ckpt_delta_raw_bytes`` and the
``ckpt_compress_s`` phase are the compressed-checkpoint attribution
(``ckpt/store.py`` via the writer).

Plan-layer keys (``dsi_tpu/plan`` — the "plan" scope of a multi-stage
chain run): ``plan_stages`` (stage count), ``plan_handoff``
(``device``/``host`` — which relay flavor carried the intermediates),
``plan_intermediate_bytes`` (bytes that crossed the host on the
inter-stage handoff path: 0 on an unspilled device-relay chain, the
full materialization on the staged baseline), ``plan_handoff_bytes``
(total intermediate content the relays carried — the saved-bytes
denominator), ``plan_relay_buffers`` /
``plan_spilled_bytes`` / ``plan_restored_bytes`` (relay residency
accounting), ``plan_commit_bytes`` (durable stage-manifest payloads —
durability cost, deliberately NOT handoff bytes),
``plan_resumed_stages`` (stages skipped by a resume from stage
manifests), ``plan_stage_walls`` (per-stage wall seconds, keyed by
stage name), plus the ``plan_s`` / ``stage_commit_s`` phases.

Mesh-sharded service keys (``mesh_shards`` > 0, the shuffle-fold path
— ``device/table.py``): ``mesh_shards`` (the sharding degree),
``pull_bytes`` (total D2H drain payload, counted in BOTH modes — the
bench A/B's evidence), ``shard_widens`` (per-shard widen counts, a
length-``n_dev`` list whose sum tracks the per-shard drain→realloc→
re-fold recoveries), ``shard_imbalance`` (max/mean shard occupancy
after the last confirmed fold; ~1.0 under FNV routing), and
``resharded_resume`` (set when a resume crossed sharding degrees via
the drain path; its value is the checkpoint's OLD degree, which is
legitimately 0 resuming a host-merge image into a mesh run — key
presence, not truthiness, is the signal).  Fold spans land in the tracer's ``shuffle`` lane in
mesh mode; span totals still reconcile with ``fold_s`` — the span IS
the stats accumulator.

## Live telemetry keys (``obs/hist.py`` + ``obs/live.py``)

When the telemetry plane is active (tracing enabled, or a
``--statusz-port`` live sampler running), :meth:`MetricsRegistry.
snapshot` additionally carries ``histograms`` — one log-bucketed
latency distribution per hot stage (the pinned ``hist.HIST_STAGES``:
kernel/upload/pull/finish/fold/sync/ckpt_commit), each under the
pinned ``hist.HIST_SNAPSHOT_KEYS`` (``count``/``total_s``/``p50_ms``/
``p90_ms``/``p99_ms``/``max_ms``).  The stall watchdog
(``parallel/pipeline.py``) adds the ``stalls`` counter to an engine's
scope and publishes the ``pipeline_stall`` gauge (engine, step, age,
threshold of the most recent stall); the coordinator publishes
``mr_worker_heartbeat_age_s`` (current ages) and
``mr_worker_heartbeat_hist`` (per-worker contact-gap histogram
snapshots — the percentile-aware requeue signal) gauges.

Engines keep their historical spellings inside the scope (external
consumers — tests, soaks, BENCH artifacts — read those keys today);
:meth:`MetricsScope.unified` maps the legacy spellings onto the schema
above, which is the view new consumers (``scripts/tracecat.py``, the
trace-file registry snapshot, the schema contract test) use.  The
aliases below are the complete drift list — adding an engine key that
needs a NEW alias is a schema change and belongs in this table.

Since ISSUE 12 the prose above is backed by ONE machine-readable
tuple: :data:`SCHEMA_KEYS` (= :data:`PHASE_KEYS` +
:data:`COUNTER_KEYS`).  The ``metric-schema`` dsicheck rule gates
every stats-scope write against it and the bench contract test pins
every engine's unified view inside it, so adding an engine key is
exactly one edit here — and forgetting that edit fails both the static
gate and tier-1.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from dsi_tpu.obs.hist import active_histograms as _active_histograms

#: Legacy engine-specific spellings → unified schema names.  The
#: streaming word-count/grep engines predate the schema ("batch" for the
#: materialize phase, per-engine inflight names); everything else
#: already matches.
LEGACY_ALIASES = {
    "batch_s": "materialize_s",
    "batch_wait_s": "materialize_wait_s",
    "max_inflight_chunks": "max_inflight",
    "max_inflight_waves": "max_inflight",
    "batch_allocs": "buffer_allocs",
}

#: The canonical phase keys (module docstring) — what the schema
#: contract test pins.
PHASE_KEYS = (
    "materialize_s", "materialize_wait_s", "upload_s", "kernel_s",
    "pull_s", "merge_s", "replay_s", "fold_s", "append_s", "hist_s",
    "sync_s", "drain_s", "widen_s", "ckpt_s", "ckpt_capture_s",
    "ckpt_commit_s", "ckpt_barrier_s",
    # compressed wire + ingest (ISSUE 13)
    "decode_s", "ingest_wait_s", "ckpt_compress_s",
    # plan layer (ISSUE 14): per-stage walls + stage-commit writes
    "plan_s", "stage_commit_s",
    # elastic dataflow (ISSUE 16): wall spent with two adjacent stages
    # advancing concurrently (seal-driven pipelining)
    "plan_overlap_s",
    # overlapped shuffle (ISSUE 18): consumer time blocked on the
    # prefetch pool vs dialer wire time hidden behind the decode
    "net_fetch_wait_s", "net_overlap_s",
)

#: The canonical counter/gauge keys (module docstring) — previously
#: prose; now machine-readable because the ``metric-schema`` dsicheck
#: rule and the bench contract test both read THIS tuple, so the
#: docstring, the static gate, and the test cannot drift apart.
COUNTER_KEYS = (
    # pipeline / engine counters
    "steps", "waves", "depth", "replays", "step_pulls", "sync_pulls",
    "widens", "folds", "fold_overflows", "appends", "append_overflows",
    "postings_widens", "topk_snapshots", "hist_folds", "hist_pulls",
    "table_cap", "l_cap", "sync_every", "max_inflight",
    "buffer_allocs", "device_accumulate", "donate_chunks", "stalls",
    "upload_mode",
    # checkpoint/restore
    "ckpt_saves", "ckpt_every", "ckpt_async", "ckpt_delta",
    "ckpt_deltas", "ckpt_full_bytes", "ckpt_delta_bytes",
    "resume_gap_s", "resume_cursor", "resume_wave",
    # mesh-sharded services
    "mesh_shards", "pull_bytes", "shard_widens", "shard_imbalance",
    "resharded_resume",
    # compressed wire + parallel ingest (ISSUE 13): reader-pool fold
    # (utils/ioread.py ingest_stats → fold_source_stats) and the
    # chunk-upload codec's attribution (ops/wirecodec.py)
    "ingest_readers", "ingest_blocks", "readahead_hit_pct",
    "wire_upload", "wire_steps", "wire_raw_steps", "wire_packed_bytes",
    "wire_ratio", "ckpt_delta_raw_bytes", "ckpt_compress",
    # serving daemon (the "serve"/"serve_grep" scopes, serve/pack.py):
    # rung_widens counts grep lanes sticky-widened to the hard-bound
    # l_cap rung (the per-tenant AOT rung-affinity move, ISSUE 19)
    "packed_steps", "packed_rows", "max_tenants_per_step",
    "host_fallbacks", "rung_widens",
    # plan layer (the "plan" scope, dsi_tpu/plan + device/relay.py):
    # multi-stage chain accounting — handoff bytes vs commit bytes is
    # the zero-host-round-trip evidence
    "plan_stages", "plan_handoff", "plan_handoff_bytes",
    "plan_intermediate_bytes", "plan_commit_bytes",
    "plan_relay_buffers", "plan_spilled_bytes", "plan_restored_bytes",
    "plan_resumed_stages", "plan_stage_walls",
    # elastic dataflow (ISSUE 16): pipelined pair + stage-shard fan-out
    "plan_pipelined", "plan_stage_shards",
    # network data plane (ISSUE 17, the "net" scope, dsi_tpu/net):
    # worker-served shuffle attribution — raw vs wire bytes is the
    # codec's evidence, locality_hits the placement policy's, and
    # net_refetches the re-fetch-from-replacement machinery's
    "net_fetches", "net_local_reads", "net_bytes_raw", "net_bytes_wire",
    "net_ratio", "net_fetch_failures", "net_refetches", "locality_hits",
    # overlapped shuffle (ISSUE 18): the effective prefetch window
    # (gauge — 1 means the serial path ran)
    "net_prefetch_window",
    # replicated control plane (ISSUE 20, dsi_tpu/replica): the Raft
    # node's status surface — log-application progress and leadership
    # churn per replica (group_status / the failover harness read them)
    "applied_index", "failovers",
)

#: THE schema: every key an engine scope may carry, under its unified
#: spelling.  Legacy spellings (LEGACY_ALIASES keys) are additionally
#: accepted at write sites; ``unified()`` maps them here.
SCHEMA_KEYS = PHASE_KEYS + COUNTER_KEYS

#: The engine names the four streaming engines register under.
ENGINES = ("stream", "tfidf", "grep", "indexer")

#: Every ``dsi_serve_*`` series name the daemon may emit on
#: ``/metrics`` (``serve/daemon.py _metrics_section``).  Pinned the same
#: way SCHEMA_KEYS is: the ``metric-schema`` dsicheck rule requires any
#: ``dsi_serve_``-prefixed string literal in the tree to name (or be a
#: truncated f-string head of) a series listed here, and the bench
#: contract test asserts the daemon's emission stays inside this set —
#: so the serving surface cannot grow an unregistered series, and its
#: cardinality stays bounded by construction (per-tenant series are
#: emitted for the top ``DSI_SERVE_METRICS_TENANTS`` tenants only).
SERVE_SERIES = (
    "dsi_serve_jobs_total", "dsi_serve_queued", "dsi_serve_resident",
    "dsi_serve_tenants_total", "dsi_serve_queue_depth",
    "dsi_serve_shed_total", "dsi_serve_rate_limited_total",
    "dsi_serve_evictions_p99_total", "dsi_serve_evictions_quota_total",
    "dsi_serve_packed_steps", "dsi_serve_packed_rows",
    "dsi_serve_grep_packed_steps", "dsi_serve_grep_packed_rows",
    "dsi_serve_grep_rung_widens",
    "dsi_serve_tenant_steps", "dsi_serve_tenant_rows",
    "dsi_serve_tenant_evictions", "dsi_serve_tenant_resumes",
    "dsi_serve_tenant_done",
    "dsi_serve_tenant_resume_gap_seconds",
    "dsi_serve_tenant_p99_ms",
)

#: Every ``dsi_replica_*`` gauge the replicated control plane
#: (``dsi_tpu/replica/node.py``) publishes — pinned alongside
#: SERVE_SERIES for the same reason: the failover evidence surface
#: (``scripts/tracecat.py`` replica lane, the tier-1 replication smoke)
#: keys on these names, so growing one is a schema change that starts
#: here.
REPLICA_SERIES = (
    "dsi_replica_term", "dsi_replica_elections",
    "dsi_replica_applied_index",
)


class MetricsScope(dict):
    """One engine's stats dict, registered in the registry at creation.
    Behaves exactly like the plain dict it replaces (engines mutate it
    with ``+=``/``setdefault``/``update``); :meth:`unified` is the
    schema-normalized read view."""

    def __init__(self, engine: str):
        super().__init__()
        self.engine = engine

    def unified(self) -> Dict:
        """The scope under the documented schema: legacy spellings
        renamed, everything else passed through."""
        return {LEGACY_ALIASES.get(k, k): v for k, v in self.items()}


class MetricsRegistry:
    """Process-global map of live engine scopes + named gauges.  An
    engine re-registers its scope per run (latest wins) — the registry
    answers "what did the most recent <engine> run report", which is
    what bench rows, the CLIs' ``--stats``, and the trace-file snapshot
    all want."""

    def __init__(self):
        self._lock = threading.Lock()
        self._scopes: Dict[str, MetricsScope] = {}
        self._gauges: Dict[str, object] = {}

    def scope(self, engine: str) -> MetricsScope:
        """A fresh scope for one engine run, registered as the engine's
        current phase dict."""
        sc = MetricsScope(engine)
        with self._lock:
            self._scopes[engine] = sc
        return sc

    def phases(self, engine: str) -> Optional[MetricsScope]:
        """The engine's current phase dict (None before its first run)."""
        with self._lock:
            return self._scopes.get(engine)

    def engines(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._scopes))

    def set_gauge(self, name: str, value) -> None:
        """Publish a named gauge (e.g. the coordinator's per-worker
        heartbeat ages) — read back via :meth:`gauge`/:meth:`snapshot`;
        the speculative-execution hook consumes these."""
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str, default=None):
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> Dict:
        """JSON-ready dump: every engine's unified view + the gauges —
        embedded in trace files by ``obs/trace.py`` at flush — plus the
        stage latency histograms whenever the live telemetry plane is
        active (``obs/hist.py``)."""
        with self._lock:
            scopes = dict(self._scopes)
            gauges = dict(self._gauges)
        out = {"engines": {e: sc.unified() for e, sc in scopes.items()},
               "gauges": gauges}
        hs = _active_histograms()
        if hs is not None:
            out["histograms"] = hs.snapshot()
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def metrics_scope(engine: str) -> MetricsScope:
    """Shorthand: a fresh registered scope on the global registry — the
    one-liner every engine calls where it used to build ``stats = {}``."""
    return _REGISTRY.scope(engine)
