"""dsi_tpu.obs — unified tracing + metrics across every runtime layer.

Four parts, one subsystem:

* :mod:`~dsi_tpu.obs.trace` — the :class:`Tracer`: nested spans,
  instant events, counters, buffered in memory and flushed durably as a
  JSONL event log plus a Chrome/Perfetto ``trace.json`` (one lane per
  pipeline stage, plus device-service and control-plane lanes).
  Enabled by ``DSI_TRACE_DIR`` or the CLIs' ``--trace-dir``; ~free
  when disabled (``DSI_TRACE=1`` stays the stderr event stream's knob).
* :mod:`~dsi_tpu.obs.registry` — the :class:`MetricsRegistry` every
  engine's phase dict registers into, with the single documented key
  schema that subsumes ``pipeline_stats``/``stream_phases``/
  ``wave_phases``/``grep_phases``.

* :mod:`~dsi_tpu.obs.hist` — log-bucketed stage latency histograms
  (p50/p90/p99/max, HDR-style constant memory), recorded at span close
  for the pinned hot stages whenever the plane is active; plus the
  live-pipeline registry the sampler and stall watchdog read.
* :mod:`~dsi_tpu.obs.live` — the live telemetry plane: a sampler
  thread with a bounded ``live.jsonl`` ring and localhost ``/statusz``
  + ``/metrics`` endpoints (``--statusz-port`` / ``DSI_STATUSZ_PORT``;
  default off = zero threads).

Render a trace with ``scripts/tracecat.py``; open the ``trace.json`` at
https://ui.perfetto.dev.  DESIGN.md "Observability" and "Live
telemetry" document the span taxonomy, lane map, and sampler design.
"""

import sys

from dsi_tpu.obs.hist import (
    HIST_SNAPSHOT_KEYS,
    HIST_STAGES,
    LatencyHistogram,
    StageHistograms,
    active_histograms,
)
from dsi_tpu.obs.registry import (
    COUNTER_KEYS,
    ENGINES,
    LEGACY_ALIASES,
    PHASE_KEYS,
    SCHEMA_KEYS,
    MetricsRegistry,
    MetricsScope,
    get_registry,
    metrics_scope,
)
from dsi_tpu.obs.trace import (
    LANES,
    SPAN_NAMES,
    Tracer,
    configure,
    count,
    event,
    flush,
    get_tracer,
    span,
)

#: CLI-facing aliases (the engine modules import ``span``/``event``
#: directly; the CLIs read better with the explicit names).
configure_tracing = configure
flush_tracing = flush
trace_event = event


def flush_tracing_report(trace_dir: str, prog: str = "") -> None:
    """Flush the global tracer and print the canonical
    where-is-my-trace line — the one exit block every single-process
    ``--trace-dir`` entry point (wcstream/grepstream/the soaks) shares,
    so the wording cannot drift per CLI."""
    paths = flush()
    if paths:
        tag = f"{prog}: " if prog else ""
        print(f"{tag}trace written to {paths[1]} "
              f"(render: python scripts/tracecat.py {trace_dir})",
              file=sys.stderr)

__all__ = [
    "ENGINES",
    "HIST_SNAPSHOT_KEYS",
    "HIST_STAGES",
    "LANES",
    "LatencyHistogram",
    "StageHistograms",
    "active_histograms",
    "COUNTER_KEYS",
    "LEGACY_ALIASES",
    "PHASE_KEYS",
    "SCHEMA_KEYS",
    "MetricsRegistry",
    "MetricsScope",
    "SPAN_NAMES",
    "Tracer",
    "configure",
    "configure_tracing",
    "count",
    "event",
    "flush",
    "flush_tracing",
    "flush_tracing_report",
    "get_registry",
    "get_tracer",
    "metrics_scope",
    "span",
    "trace_event",
]
