"""Log-bucketed latency histograms — the live tail-latency view.

The paper's coordinator reacts to stragglers with a flat 10-second
timeout because it has no distributional view of task latency; Dean &
Ghemawat §3.6 make backup-task dispatch a *tail-latency* decision.  This
module is the distribution: an HDR-style histogram whose buckets are
log-spaced (4 sub-buckets per power of two over microseconds), so

* memory is constant (one small int array) however long the run,
* any duration from 1 µs to hours lands in O(1) with one ``frexp``,
* a reported percentile is within one sub-bucket (≤ ~12% relative) of
  the true value — plenty for "is this step 4× its p99" decisions,
* two histograms merge by adding bucket counts (the property the
  hypothesis test pins), so per-process histograms roll up exactly.

:data:`HIST_STAGES` pins which span names are recorded: the hot stages
of the pipeline (``obs/trace.py`` feeds every closing span through
:func:`active_histograms`; non-hot names cost one dict miss).  The
whole plane is OFF by default — ``_active`` is ``None`` until tracing
is enabled or the live sampler (``obs/live.py``) starts, and the
disabled check is a single module-attribute load on the span path.

This module is also the neutral ground for the live plane's shared
state: the pipeline registry (:func:`register_pipeline`) that lets the
sampler and the stall watchdog see in-flight step state without
``parallel/pipeline.py`` importing the HTTP half.
"""

from __future__ import annotations

import math
import os
import threading
import weakref
from typing import Dict, Iterable, List, Optional


def env_float(name: str, default: float) -> float:
    """Float env knob with a default — the live plane's one parser
    (the watchdog, the sampler, and the endpoints all read knobs)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default

#: The span names recorded into stage histograms — the pipeline's hot
#: stages.  Pinned: the registry schema contract test asserts this exact
#: set, and ``/statusz``, ``/metrics``, trace meta, and tracecat's
#: percentile table all key on it.
HIST_STAGES = ("kernel", "upload", "pull", "finish", "fold", "sync",
               "ckpt_commit")

#: The keys every histogram snapshot carries — pinned like HIST_STAGES.
HIST_SNAPSHOT_KEYS = ("count", "total_s", "p50_ms", "p90_ms", "p99_ms",
                      "max_ms")

_SUB = 4                     # sub-buckets per power of two
_NBUCKETS = 64 * _SUB        # 1 µs .. 2^64 µs — covers any real span


class LatencyHistogram:
    """One log-bucketed duration distribution (module docstring).

    ``record`` is the hot path: one ``frexp``, one list increment,
    under a lock (recording happens from the engine thread, the
    producer thread, and the commit worker at once).  Everything else
    is read-side and cheap.
    """

    __slots__ = ("_counts", "count", "total_s", "max_s", "_lock")

    def __init__(self):
        self._counts: List[int] = [0] * _NBUCKETS
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    @staticmethod
    def bucket_of(seconds: float) -> int:
        """Bucket index for a duration: 4 linear sub-buckets per power
        of two of microseconds; everything under 1 µs is bucket 0."""
        v = seconds * 1e6
        if v < 1.0:
            return 0
        m, e = math.frexp(v)          # v = m * 2^e, m in [0.5, 1)
        b = (e - 1) * _SUB + int((m - 0.5) * (2 * _SUB))
        return b if b < _NBUCKETS else _NBUCKETS - 1

    @staticmethod
    def bucket_mid_s(b: int) -> float:
        """The bucket's midpoint in seconds — what a percentile
        reports (max relative error: half a sub-bucket)."""
        octave, k = divmod(b, _SUB)
        return (2.0 ** octave) * (1.0 + (k + 0.5) / _SUB) / 1e6

    def record(self, seconds: float) -> None:
        b = self.bucket_of(seconds)
        with self._lock:
            self._counts[b] += 1
            self.count += 1
            self.total_s += seconds
            if seconds > self.max_s:
                self.max_s = seconds

    def merge(self, other: "LatencyHistogram") -> None:
        """Fold ``other`` into this histogram (bucket-exact: merging
        equals having recorded every sample here)."""
        with other._lock:
            oc = list(other._counts)
            on, ot, om = other.count, other.total_s, other.max_s
        with self._lock:
            for b, c in enumerate(oc):
                if c:
                    self._counts[b] += c
            self.count += on
            self.total_s += ot
            if om > self.max_s:
                self.max_s = om

    def percentile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) in seconds, to bucket
        resolution; 0.0 when empty.  The top bucket answers with the
        exact observed max rather than a bucket midpoint above it."""
        with self._lock:
            n = self.count
            counts = list(self._counts)
            mx = self.max_s
        if n == 0:
            return 0.0
        target = max(1, math.ceil(q * n))
        cum = 0
        for b, c in enumerate(counts):
            if not c:
                continue
            cum += c
            if cum >= target:
                return min(self.bucket_mid_s(b), mx)
        return mx

    def snapshot(self) -> Dict:
        """JSON-ready summary under the pinned HIST_SNAPSHOT_KEYS."""
        return {
            "count": self.count,
            "total_s": round(self.total_s, 4),
            "p50_ms": round(1e3 * self.percentile(0.50), 4),
            "p90_ms": round(1e3 * self.percentile(0.90), 4),
            "p99_ms": round(1e3 * self.percentile(0.99), 4),
            "max_ms": round(1e3 * self.max_s, 4),
        }


class KeyedHistograms:
    """A bounded map of :class:`LatencyHistogram` per dynamic key — the
    serving daemon's per-TENANT step-latency view (``serve/daemon.py``
    feeds every packed-step wall to each participating tenant's
    histogram, and tail-driven eviction asks "whose p99 hurts the pack
    most").  Unlike :class:`StageHistograms` the key set is unbounded
    input (tenant ids), so memory is capped: past ``max_keys`` the
    least-recently-RECORDED key is dropped — a tenant idle long enough
    to be displaced by thousands of newer ones has no live tail worth
    evicting on, and a dropped tenant simply re-enters cold.
    """

    def __init__(self, max_keys: int = 4096):
        self._max = max(1, int(max_keys))
        self._h: Dict[str, LatencyHistogram] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._h)

    def record(self, key: str, seconds: float) -> None:
        with self._lock:
            h = self._h.pop(key, None)
            if h is None:
                h = LatencyHistogram()
                while len(self._h) >= self._max:
                    # dicts iterate in insertion order; re-inserting on
                    # every record makes the first key the LRU one.
                    self._h.pop(next(iter(self._h)))
            self._h[key] = h
        h.record(seconds)

    def get(self, key: str) -> Optional[LatencyHistogram]:
        with self._lock:
            return self._h.get(key)

    def drop(self, key: str) -> None:
        """Forget one key (a tenant whose jobs are all done)."""
        with self._lock:
            self._h.pop(key, None)

    def p99_ms(self, key: str) -> float:
        h = self.get(key)
        return round(1e3 * h.percentile(0.99), 4) if h is not None \
            else 0.0

    def top(self, n: int) -> List[tuple]:
        """The ``n`` keys with the worst p99, as ``(key, p99_seconds,
        count)`` tuples sorted worst-first — the eviction policy's and
        the bounded /metrics emission's read side."""
        with self._lock:
            items = list(self._h.items())
        rows = [(k, h.percentile(0.99), h.count) for k, h in items]
        rows.sort(key=lambda r: (-r[1], r[0]))
        return rows[:n]


class StageHistograms:
    """One :class:`LatencyHistogram` per hot stage; ``record`` drops
    non-hot names with a single dict miss."""

    def __init__(self, stages: Iterable[str] = HIST_STAGES):
        self._h: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram() for s in stages}

    def record(self, name: str, seconds: float) -> None:
        h = self._h.get(name)
        if h is not None:
            h.record(seconds)

    def get(self, name: str) -> Optional[LatencyHistogram]:
        return self._h.get(name)

    def snapshot(self) -> Dict[str, Dict]:
        """Non-empty stages only — an idle stage would read as
        "measured zero" when it was never exercised."""
        return {s: h.snapshot() for s, h in self._h.items() if h.count}


# ── activation: the one switch the span path checks ────────────────────

_lock = threading.Lock()
_active: Optional[StageHistograms] = None
_holds = 0  # live-sampler holds: tracing toggles cannot deactivate these


def activate() -> StageHistograms:
    """Turn stage-histogram recording on (idempotent; keeps whatever
    was already recorded).  Called when tracing is enabled and when the
    live sampler starts."""
    global _active
    with _lock:
        if _active is None:
            _active = StageHistograms()
        return _active


def deactivate(force: bool = False) -> None:
    """Turn recording off and drop the histograms — unless a live
    sampler still holds the plane (``force`` overrides, for tests)."""
    global _active, _holds
    with _lock:
        if _holds > 0 and not force:
            return
        if force:
            _holds = 0
        _active = None


def hold() -> StageHistograms:
    """Activate with a hold: the live sampler's entry — a tracer being
    switched off mid-run must not drop the sampler's histograms."""
    global _holds
    with _lock:
        _holds += 1
    return activate()


def release() -> None:
    """Drop one hold (the sampler stopping); recording stays on until
    an explicit deactivate (a still-enabled tracer keeps feeding it)."""
    global _holds
    with _lock:
        _holds = max(0, _holds - 1)


def active_histograms() -> Optional[StageHistograms]:
    """The live stage histograms, or None when the plane is off — THE
    check the span-close path and the pipeline watchdog make."""
    return _active


# ── live pipeline registry (read by the sampler + watchdog) ────────────

_pipelines: "weakref.WeakSet" = weakref.WeakSet()
_pipelines_lock = threading.Lock()


def register_pipeline(pipe) -> None:
    """Track a running ``StepPipeline`` so ``/statusz`` can report its
    in-flight window.  Weak: a pipeline that ends (or errors) without
    unregistering just vanishes.  Locked against the reader — an HTTP
    scrape iterating while an engine thread registers must not die."""
    with _pipelines_lock:
        _pipelines.add(pipe)


def unregister_pipeline(pipe) -> None:
    with _pipelines_lock:
        _pipelines.discard(pipe)


def live_pipelines() -> list:
    with _pipelines_lock:
        try:
            return list(_pipelines)
        except RuntimeError:  # a GC weakref callback mid-iteration
            return []
