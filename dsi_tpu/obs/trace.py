"""Unified tracer: nested spans, counters, Perfetto/JSONL export.

The paper ships counters and a live status page as first-class framework
features (Dean & Ghemawat §4.7–4.8); until this module the repo's
equivalent was four ad-hoc stats dicts with no per-step timeline and no
control-plane visibility.  :class:`Tracer` is the one timeline every
layer writes into:

* **spans** — ``with tracer.span("upload", step=n): ...`` times a named
  region.  Spans nest (a per-thread depth counter rides each event), are
  thread-safe (the buffer append is the only shared write, under one
  lock), and are ~free when tracing is disabled: a pure span returns a
  shared no-op singleton (zero allocation), and a span carrying a
  ``stats``/``key`` sink degenerates to exactly the two
  ``perf_counter`` calls the engines' hand-rolled phase timing already
  paid — the sink write IS the phase accounting, so the span totals and
  the ``stream_phases``-style registry values cannot disagree.
* **events** — ``tracer.event("requeue", ...)`` instant records (the
  control-plane lane).
* **counters** — ``tracer.count("steps")`` monotonic counters, emitted
  as Chrome ``"C"`` samples.

Everything buffers in memory (bounded by ``DSI_TRACE_BUFFER_EVENTS``,
drops counted — a silent cap would read as "covered everything") and
:meth:`Tracer.flush` writes two artifacts through
``utils/atomicio.write_bytes_durable`` (temp + fsync + rename + CRC32
sidecar — the checkpoint store's torn-write discipline, so a trace
survives the same crashes the checkpoints do):

* ``<basename>.jsonl`` — one JSON record per event, head record carries
  process metadata, counters, and the metrics-registry snapshot;
* ``<basename>.json``  — Chrome/Perfetto ``traceEvents``: one lane
  (tid) per pipeline stage (materialize/upload/dispatch/kernel/pull/
  merge/replay/fold/sync/widen/ckpt) plus the control-plane lane; load
  it at https://ui.perfetto.dev or chrome://tracing.

The process-global tracer (:func:`get_tracer`) is enabled by
``DSI_TRACE_DIR=<dir>`` (buffer + durable flush at exit — how
``mrrun --trace-dir`` reaches its child coordinator/workers) or by
:func:`configure` (the CLIs' ``--trace-dir``; ``enabled=True`` alone is
the bench's in-memory rollup mode).  Buffering without a consumer is a
pure memory cost, so ``DSI_TRACE=1`` keeps its historical stderr-only
meaning (``utils/tracing.log_event``) and does NOT enable the buffer.
``ckpt/fault.py`` flushes it right before ``os._exit``, so traces
survive injected crashes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import dsi_tpu.obs.hist as _hist

#: Span names recorded into the live stage histograms when the
#: telemetry plane is active (obs/hist.py owns the pinned set).
_HOT_STAGES = frozenset(_hist.HIST_STAGES)

#: The lane taxonomy: every span/event lands in one of these Perfetto
#: lanes (a span's lane defaults to its name).  Pipeline stages first in
#: display order, then the device-service lanes, then the control plane.
LANES = (
    "materialize", "upload", "dispatch", "kernel", "pull", "merge",
    "replay", "shuffle", "fold", "sync", "widen", "ckpt", "plan",
    "net", "replica", "control", "counters",
)

#: The pinned span-name schema: every span opened anywhere in the repo
#: draws its name from this set (lanes double as span names for the
#: simple stages; the rest are the documented sub-stages).  The
#: ``span-discipline`` rule in ``dsi_tpu/analysis`` enforces it
#: statically, and ``scripts/tracecat.py``'s flame/straggler tables key
#: on these names — an off-schema span would silently fall out of every
#: rollup, so adding one is a schema change and belongs here first.
SPAN_NAMES = frozenset(LANES) | frozenset((
    "wait", "finish", "drain", "append", "hist_fold", "hist_pull",
    "ckpt_capture", "ckpt_commit", "ckpt_save", "ckpt_restore", "task",
    "decode", "stage_commit", "resplit", "stage_overlap",
))

_BUFFER_ENV = "DSI_TRACE_BUFFER_EVENTS"
_BUFFER_DEFAULT = 500_000


class _NoopSpan:
    """The disabled-mode fast path: one shared instance, no allocation,
    no clock reads."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """One live span.  ``tr`` is None when only the stats sink is wanted
    (tracing disabled but the engine still needs its phase seconds)."""

    __slots__ = ("_tr", "name", "lane", "_stats", "_key", "_fields",
                 "_t0", "_depth", "elapsed_s")

    def __init__(self, tr: Optional["Tracer"], name: str, lane: str,
                 stats: Optional[dict], key: Optional[str],
                 fields: Optional[dict]):
        self._tr = tr
        self.name = name
        self.lane = lane
        self._stats = stats
        self._key = key
        self._fields = fields
        self.elapsed_s = 0.0

    def __enter__(self) -> "_Span":
        tr = self._tr
        if tr is not None:
            tls = tr._tls
            self._depth = getattr(tls, "depth", 0)
            tls.depth = self._depth + 1
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        self.elapsed_s = dur
        if self._stats is not None:
            self._stats[self._key] = self._stats.get(self._key, 0.0) + dur
        # Stage histogram recording at span close (the tentpole of the
        # live telemetry plane): one module-attribute load when the
        # plane is off, one dict lookup + O(1) bucket bump when on.
        hs = _hist._active
        if hs is not None:
            hs.record(self.name, dur)
        tr = self._tr
        if tr is not None:
            tr._tls.depth = self._depth
            tr._record("X", self.name, self.lane, self._t0, dur,
                       self._depth, self._fields)
        return False


class Tracer:
    """Buffered span/event/counter recorder with durable Perfetto export
    (module docstring for the full contract)."""

    def __init__(self, enabled: bool = False,
                 trace_dir: Optional[str] = None, basename: str = "trace",
                 buffer_cap: Optional[int] = None):
        self._lock = threading.Lock()
        self._tls = threading.local()
        #: (ph, name, lane, t_perf, dur_s, depth, fields) tuples.
        self._events: List[Tuple] = []
        self.dropped = 0
        self.counters: Dict[str, float] = {}
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        # Construction never DEactivates the histogram plane (another
        # tracer may be feeding it); only an explicit ``enabled=False``
        # assignment does — see the property setter.
        self._enabled = bool(enabled)
        if self._enabled:
            _hist.activate()
        self.trace_dir: Optional[str] = None
        self.basename = basename
        if buffer_cap is None:
            try:
                buffer_cap = int(os.environ.get(_BUFFER_ENV,
                                                str(_BUFFER_DEFAULT)))
            except ValueError:
                buffer_cap = _BUFFER_DEFAULT
        self.buffer_cap = max(1, buffer_cap)
        if trace_dir:
            self.set_trace_dir(trace_dir, basename)

    # ── configuration ──

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, v) -> None:
        """Enabling tracing also activates the stage-histogram plane
        (hot spans record their close latency); disabling deactivates
        it UNLESS the live sampler holds it — statusz must keep its
        percentiles when a bench toggles its in-memory tracer off."""
        self._enabled = bool(v)
        if self._enabled:
            _hist.activate()
        else:
            _hist.deactivate()

    def set_trace_dir(self, trace_dir: str,
                      basename: Optional[str] = None) -> None:
        """Enable tracing with durable flush into ``trace_dir``.  Reaps
        orphans from a previous writer killed mid-commit — the
        checkpoint store's startup discipline — but only THIS process's
        basename: mrrun's children share one trace dir, and a blanket
        reap could delete a sibling's in-flight temp mid-commit."""
        from dsi_tpu.utils.atomicio import reap_tmp_files

        os.makedirs(trace_dir, exist_ok=True)
        if basename:
            self.basename = basename
        reap_tmp_files(trace_dir, prefix=f".tmp-{self.basename}.")
        self.trace_dir = trace_dir
        self.enabled = True

    # ── recording ──

    def span(self, name: str, /, *, lane: Optional[str] = None,
             stats: Optional[dict] = None, key: Optional[str] = None,
             **fields):
        """A context manager timing one region.  With ``stats``/``key``
        the elapsed seconds are ALSO added to ``stats[key]`` (the
        engines' phase dicts — one measurement, two consumers).
        Disabled and sink-less returns the shared no-op singleton —
        unless the live histogram plane is active and the span is a hot
        stage, which still needs its close latency recorded (statusz-
        without-tracing mode)."""
        if not self.enabled:
            if stats is not None:
                return _Span(None, name, "", stats,
                             key or (name + "_s"), None)
            if _hist._active is not None and name in _HOT_STAGES:
                return _Span(None, name, "", None, None, None)
            return _NOOP_SPAN
        return _Span(self, name, lane or name, stats,
                     (key or (name + "_s")) if stats is not None else None,
                     fields or None)

    def event(self, name: str, /, *, lane: str = "control",
              **fields) -> None:
        """Record one instant event (control-plane lane by default)."""
        if not self.enabled:
            return
        self._record("I", name, lane, time.perf_counter(), 0.0,
                     getattr(self._tls, "depth", 0), fields or None)

    def record_span(self, name: str, dur_s: float, /, *,
                    lane: str = "control", **fields) -> None:
        """Record an already-timed region ending now — for measurements
        taken elsewhere (the worker's task ``Span``s mirror through
        here), so they land as real spans, not instants.  The start is
        clamped to the tracer's epoch: the global tracer is built
        lazily, so the first mirrored span may have BEGUN before ``_t0``
        and would otherwise export a negative timestamp."""
        if not self.enabled:
            return
        hs = _hist._active
        if hs is not None:
            hs.record(name, dur_s)
        self._record("X", name, lane,
                     max(self._t0, time.perf_counter() - dur_s),
                     dur_s, 0, fields or None)

    def count(self, name: str, /, n: float = 1, *,
              lane: str = "counters") -> None:
        """Bump a monotonic counter; emits a Chrome counter sample."""
        if not self.enabled:
            return
        with self._lock:
            v = self.counters.get(name, 0) + n
            self.counters[name] = v
        self._record("C", name, lane, time.perf_counter(), 0.0, 0,
                     {"value": v})

    def _record(self, ph: str, name: str, lane: str, t_perf: float,
                dur_s: float, depth: int, fields: Optional[dict]) -> None:
        with self._lock:
            if len(self._events) >= self.buffer_cap:
                self.dropped += 1
                return
            self._events.append((ph, name, lane, t_perf - self._t0,
                                 dur_s, depth, fields))

    # ── reading back ──

    def mark(self) -> int:
        """Current buffer position — pass to :meth:`rollup` to scope a
        rollup to the events recorded since."""
        with self._lock:
            return len(self._events)

    def counters_snapshot(self) -> Dict[str, float]:
        """A consistent copy of the counters — readers on other
        threads (the statusz endpoints, the live sampler) must not
        iterate the live dict while :meth:`count` inserts into it."""
        with self._lock:
            return dict(self.counters)

    def rollup(self, since: int = 0) -> Dict[str, dict]:
        """Per-span-name totals over the buffered events:
        ``{name: {"total_s", "count", "max_s", "p50_ms", "p99_ms"}}`` —
        the per-phase span rollup the bench rows publish.  The
        percentiles are EXACT over the buffered durations (the buffer
        holds every one), scoped by ``since`` like the totals — so a
        bench row's rollup carries its own latency distribution, not
        the whole process's."""
        with self._lock:
            evs = self._events[since:]
        out: Dict[str, dict] = {}
        durs: Dict[str, list] = {}
        for ph, name, lane, ts, dur, depth, fields in evs:
            if ph != "X":
                continue
            r = out.setdefault(name, {"total_s": 0.0, "count": 0,
                                      "max_s": 0.0})
            r["total_s"] += dur
            r["count"] += 1
            if dur > r["max_s"]:
                r["max_s"] = dur
            durs.setdefault(name, []).append(dur)
        for name, r in out.items():
            d = sorted(durs[name])
            n = len(d)
            # Nearest-rank percentiles, index ceil(q*n)-1 — the same
            # rank rule as LatencyHistogram.percentile, so the rollup
            # and the live histograms cannot disagree on definition
            # (p99 of 100 samples is the 99th, NOT the max).
            r["p50_ms"] = round(1e3 * d[(n + 1) // 2 - 1], 4)
            r["p99_ms"] = round(1e3 * d[(99 * n + 99) // 100 - 1], 4)
            r["total_s"] = round(r["total_s"], 4)
            r["max_s"] = round(r["max_s"], 4)
        return out

    # ── export ──

    def _meta(self, counters: Dict, dropped: int) -> dict:
        meta = {"pid": os.getpid(), "wall0": round(self._wall0, 3),
                "basename": self.basename, "dropped_events": dropped,
                "counters": counters}
        try:
            from dsi_tpu.obs.registry import get_registry

            meta["registry"] = get_registry().snapshot()
        except Exception:
            pass
        return meta

    def flush(self) -> Optional[Tuple[str, str]]:
        """Write ``<basename>.jsonl`` + ``<basename>.json`` durably into
        the trace dir; returns their paths, or None when no dir is
        configured (in-memory tracing: :meth:`rollup` is the consumer).
        Idempotent — each call rewrites the full buffer, so a fault-point
        flush followed by nothing still leaves complete artifacts."""
        if not self.enabled or self.trace_dir is None:
            return None
        from dsi_tpu.utils.atomicio import write_bytes_durable

        with self._lock:
            evs = list(self._events)
            counters = dict(self.counters)
            dropped = self.dropped
        meta = self._meta(counters, dropped)

        lines = [json.dumps({"type": "meta", **meta}, sort_keys=True)]
        for ph, name, lane, ts, dur, depth, fields in evs:
            rec = {"ph": ph, "name": name, "lane": lane,
                   "ts": round(ts, 6), "dur": round(dur, 6),
                   "depth": depth}
            if fields:
                rec.update(fields)
            lines.append(json.dumps(rec, sort_keys=True, default=str))
        jsonl_path = os.path.join(self.trace_dir, self.basename + ".jsonl")
        write_bytes_durable(jsonl_path,
                            ("\n".join(lines) + "\n").encode("utf-8"))

        pid = os.getpid()
        lanes = [l for l in LANES if any(e[2] == l for e in evs)]
        lanes += sorted({e[2] for e in evs} - set(lanes))
        tid_of = {l: i for i, l in enumerate(lanes)}
        tev: List[dict] = [{"name": "process_name", "ph": "M", "pid": pid,
                            "tid": 0,
                            "args": {"name": f"dsi {self.basename}"}}]
        for lane, tid in tid_of.items():
            tev.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": lane}})
            tev.append({"name": "thread_sort_index", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"sort_index": tid}})
        for ph, name, lane, ts, dur, depth, fields in evs:
            ev = {"name": name, "cat": lane, "pid": pid,
                  "tid": tid_of[lane], "ts": round(ts * 1e6, 3)}
            if ph == "X":
                ev.update(ph="X", dur=round(dur * 1e6, 3))
            elif ph == "C":
                ev.update(ph="C")
            else:
                ev.update(ph="i", s="t")
            if fields:
                ev["args"] = fields
            tev.append(ev)
        doc = {"traceEvents": tev, "displayTimeUnit": "ms",
               "otherData": meta}
        json_path = os.path.join(self.trace_dir, self.basename + ".json")
        write_bytes_durable(json_path,
                            json.dumps(doc, default=str).encode("utf-8"))
        return jsonl_path, json_path


# ── the process-global tracer ──────────────────────────────────────────

_global_lock = threading.Lock()
_global: Optional[Tracer] = None
_atexit_registered = False


def _register_atexit() -> None:
    """Flush at interpreter exit when a trace dir is configured — how an
    env-inherited child (mrrun's coordinator/workers) commits its
    ``trace-<pid>.json`` without any CLI plumbing of its own."""
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True
    import atexit

    def _flush():
        try:
            if _global is not None:
                _global.flush()
        except Exception:
            pass

    atexit.register(_flush)


def get_tracer() -> Tracer:
    """The process-global tracer, lazily built from the env:
    ``DSI_TRACE_DIR`` enables buffering with a per-process durable
    flush target (``trace-<pid>.*``).  ``DSI_TRACE=1`` alone does NOT
    enable it — buffered events with no flush target are dead weight on
    long runs, and that knob's stderr stream is ``utils/tracing``'s."""
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                env_dir = os.environ.get("DSI_TRACE_DIR")
                t = Tracer(enabled=bool(env_dir))
                if env_dir:
                    t.set_trace_dir(env_dir,
                                    basename=f"trace-{os.getpid()}")
                _global = t
                if env_dir:
                    _register_atexit()
    return _global


def configure(trace_dir: Optional[str] = None, basename: str = "trace",
              enabled: Optional[bool] = None) -> Tracer:
    """Configure the global tracer (the CLIs' ``--trace-dir`` entry):
    with ``trace_dir`` the process writes ``trace.json``/``trace.jsonl``
    there at flush; ``enabled=True`` alone turns on in-memory buffering
    (the bench's rollup mode)."""
    t = get_tracer()
    if trace_dir:
        t.set_trace_dir(trace_dir, basename)
        _register_atexit()
    if enabled is not None:
        t.enabled = bool(enabled)
    return t


def span(name: str, /, **kw):
    return get_tracer().span(name, **kw)


def event(name: str, /, **kw) -> None:
    get_tracer().event(name, **kw)


def count(name: str, /, n: float = 1, **kw) -> None:
    get_tracer().count(name, n, **kw)


def flush() -> Optional[Tuple[str, str]]:
    return get_tracer().flush()
