"""MapReduce-as-a-service: the resident serving daemon.

Everything else in this repo is a one-shot CLI that pays process start,
jax init, and AOT warm per job — the wrong shape for the ROADMAP's
"heavy traffic from millions of users", which is many SMALL jobs, not
one big one.  Dean & Ghemawat ran MapReduce as a shared service behind
a job-submission control plane (OSDI'04 §3; the status page of §4.8);
this package is that shape for the device mesh:

* :mod:`~dsi_tpu.serve.pack` — the multi-tenant packed step engine:
  many tenants' chunks ride ONE compiled wave dispatch, demuxed by the
  per-row tenant lane, so K tenants cost ~1 dispatch instead of K;
* :mod:`~dsi_tpu.serve.daemon` — the long-lived ``mrserve`` process:
  owns the warmed executables, accepts submissions over the repo's own
  framed-JSON pull-RPC control plane (``mr/rpc.py``, the 6.5840 idiom),
  journals jobs durably, packs/schedules tenants, evicts idle or
  over-quota tenants to delta-checkpoint chains, and resumes every
  in-flight tenant after a crash;
* :mod:`~dsi_tpu.serve.client` — the no-jax client library behind the
  ``mrsubmit`` CLI.

The resumable step objects (``parallel/stepobj.py``) are the substrate:
non-packable apps run as suspendable engine state machines the daemon
multiplexes, and eviction/resume is the checkpoint subsystem's
suspend/restore primitive (PR 8) at serving cadence.
"""

from dsi_tpu.serve.client import (
    default_socket,
    ping,
    shutdown,
    status,
    submit,
    wait,
)

__all__ = [
    "default_socket",
    "ping",
    "shutdown",
    "status",
    "submit",
    "wait",
]
