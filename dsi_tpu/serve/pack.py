"""Multi-tenant step packing: K tenants' chunks in ONE device dispatch.

The serving daemon's whole economic argument is amortization: a small
word-count job costs one or two device steps, so running each tenant's
job through its own engine pays a full dispatch (and, on a tunneled
accelerator, ~0.1 s of wire latency) per tenant per step.  This module
batches them: up to ``n_dev`` pending chunks from DIFFERENT tenants
fill the rows of one ``[n_dev, chunk_bytes]`` batch and run through one
compiled program, so K tenants cost ~1 dispatch instead of K.

The demux problem — and why the packed step is the TF-IDF wave
program.  The word-count step (``shuffle.mapreduce_step``) shuffles
rows across devices INSIDE the kernel (map → all_to_all → reduce), so
a device's output table mixes words from every input row: two tenants
sharing a batch would sum their counts for a shared word, and nothing
in the output says whose count is whose.  The wave program
(``tfidf._wave_fn``) already solved this for documents: every shuffled
row carries a ``doc`` payload lane.  Packing therefore treats each
tenant's chunk as a *document* — the doc lane IS the tenant lane — and
the host demuxes the pulled rows by that column into per-tenant
accumulators.  The ``tf`` payload is the word's in-chunk count and the
``part`` payload its reduce partition, so a demuxed row drops straight
into the tenant's :class:`~dsi_tpu.parallel.merge.PackedCounts` in the
packed-table layout the delta-checkpoint format already speaks
(``ckpt/delta.py``).  Counts are content-sums, independent of chunking,
so per-tenant output is byte-identical to the tenant running alone —
the parity bar the daemon's tests and bench row enforce.

Exactness discipline: the shared sticky rung (capacity / word window /
grouper / token frac) widens for the whole batch exactly as the wave
walk's ladder does — a replay re-runs the batch, every lane benefits,
and the cleared rung sticks.  Per-lane failures do NOT abort the batch:
a lane whose chunk carries non-ASCII bytes (or a >64-byte word) is
marked for the host path, its row zeroed, and the batch re-dispatched —
the surviving lanes' rows are demuxed normally and the dead tenant's
whole job re-runs on the host oracle path (correctness never depends on
the kernel, the ``backends/tpu.py`` contract).

Per-tenant state is host-side and checkpointable at every confirmed
packed step: the accumulator snapshot plus the input-byte cursor, saved
through the engines' own :class:`~dsi_tpu.ckpt.CheckpointWriter` as a
delta CHAIN (``HostDeltaLog`` of demuxed step payloads, periodic full
re-base) — which is what makes tenant eviction cheap and a daemon
``kill -9`` resumable with byte-identical output.

Grep packing (ISSUE 19) is the EASY demux case: the grep step program
(``parallel/grepstream._grep_step_device``) runs per device row under
``shard_map`` with no collectives, and each row carries its OWN pattern
operand — so K tenants' rows never mix and each output row (histogram
extension, top-k candidates, scalars) already belongs to exactly one
lane.  :class:`PackedGrepScheduler` therefore groups runnable
:class:`GrepLane` s by ``(pattern length, l_cap rung)`` — rows sharing a
compiled shape — and fills one ``[n_dev, chunk_bytes]`` dispatch
round-robin across the group's tenants.  The rung is per-TENANT sticky
AOT affinity: a lane whose row overflows rung 0's line capacity is
replayed at the hard-bound rung (``ops/grepk.line_cap_rungs``) and
STAYS there (persisted in its checkpoint meta), migrating between pack
groups instead of widening everyone — one tenant's pathological input
never cold-compiles, or re-runs, the rest of the pack.  Exactness is
per-ROW: a step confirms each lane's clean prefix of rows (cursor order
is byte-range order) and requeues the overflowed row and everything
after it for the lane's next (wider) dispatch; per-lane line-number
bases are assigned host-side at row-take time, so requeued rows keep
exact global line numbers and per-tenant output stays byte-identical to
the tenant running alone — the same parity bar the wc lanes carry.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Deque, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from dsi_tpu.ckpt import (
    CheckpointPolicy,
    CheckpointStore,
    CheckpointWriter,
    DeltaSteps,
    HostDeltaLog,
    drain_packed_steps,
    fault_point,
    skip_stream,
)
from dsi_tpu.obs import metrics_scope, span as _span
from dsi_tpu.ops.wordcount import grouper_ladder, rung0_cap
from dsi_tpu.parallel.merge import PackedCounts
from dsi_tpu.parallel.shuffle import write_partitioned_output


def host_wordcount(files, n_reduce: int) -> Dict[str, tuple]:
    """The host-path word count (the ``wcstream`` fallback semantics):
    ``apps.wc.Map`` tokens + ``ihash %% n_reduce`` partitions — the same
    result the device path produces, by the oracle's definition."""
    from dsi_tpu.apps import wc
    from dsi_tpu.mr.worker import ihash

    counts: Dict[str, int] = {}
    for f in files:
        with open(f, "rb") as fh:
            text = fh.read().decode("utf-8", errors="replace")
        for kv in wc.Map(f, text):
            counts[kv.key] = counts.get(kv.key, 0) + 1
    return {w: (c, ihash(w) % n_reduce) for w, c in counts.items()}


class TenantLane:
    """One tenant job's lane in the packed scheduler: a row stream cut
    from its input files, a host accumulator, and a per-tenant
    delta-checkpoint chain.

    ``resume=True`` (the default the daemon uses) loads the newest
    valid chain when one exists — a fresh job's empty directory simply
    starts fresh, so admission and crash-resume are the same code.
    """

    def __init__(self, job: Dict, chunk_bytes: int, ckpt_dir: str,
                 checkpoint_every: Optional[int] = None,
                 resume: bool = True, delta: bool = True):
        from dsi_tpu.parallel.streaming import batch_stream, stream_files

        self.job = job
        self.tenant = job["tenant"]
        self.n_reduce = int(job["n_reduce"])
        self.chunk_bytes = int(chunk_bytes)
        self.acc = PackedCounts()
        self.offsets: List[int] = []
        self.rows_taken = 0
        self.confirmed_rows = 0
        self.steps = 0                # confirmed packed steps ridden
        self.steps_since_resume = 0   # the eviction-quota clock
        self.hostpath = False
        self.input_done = False
        self.resume_gap_s = 0.0
        self.stats: Dict = {}
        self._pending: List[int] = []  # end offsets of unconfirmed rows
        ident = {"tenant": self.tenant,
                 "files": [[os.path.basename(f), os.path.getsize(f)]
                           for f in job["files"]],
                 "n_reduce": self.n_reduce,
                 "chunk_bytes": self.chunk_bytes}
        self.store = CheckpointStore(ckpt_dir, "serve-wc", ident)
        self.writer = CheckpointWriter(self.store, self.stats,
                                       async_=False, delta=delta)
        self.policy = CheckpointPolicy(checkpoint_every)
        self.delta_log = HostDeltaLog()
        start = 0
        if resume:
            t0 = time.perf_counter()
            loaded = self.store.load_latest_chain()
            if loaded is not None:
                meta, arrays, deltas = loaded
                eff = deltas[-1][0] if deltas else meta
                start = int(eff["cursor"])
                self.confirmed_rows = int(eff["rows"])
                self.acc.restore({k[4:]: v for k, v in arrays.items()
                                  if k.startswith("acc_")})
                for _, darr in deltas:
                    # Ordered deltas re-ingest through the host drain
                    # path — content-exact, the chain-restore argument.
                    drain_packed_steps(self.acc, darr)
                self.resume_gap_s = round(time.perf_counter() - t0, 4)
        else:
            self.store.reset()
        self.start_offset = start
        self.cursor = start
        blocks = stream_files(job["files"])
        feed = skip_stream(blocks, start) if start else blocks
        # One row per "batch": the lane's chunk stream is its document
        # stream — the packer assigns each row a doc id (= its batch
        # slot) and demuxes by it after the shuffle.
        self._rows = batch_stream(feed, 1, self.chunk_bytes,
                                  offsets=self.offsets)

    # ── the packer-facing surface ──

    @property
    def runnable(self) -> bool:
        return not (self.hostpath or self.input_done)

    def take_row(self) -> Optional[np.ndarray]:
        """The next ``[chunk_bytes]`` row, pending until
        :meth:`confirm_step` (or abandoned on a host-path flip).  None
        at end of input or when a >row-wide token forces the host
        path."""
        from dsi_tpu.parallel.streaming import _TokenTooLong

        try:
            batch = next(self._rows)
        except StopIteration:
            self.input_done = True
            return None
        except _TokenTooLong:
            self.to_hostpath()
            return None
        off = self.start_offset + self.offsets[self.rows_taken]
        self.rows_taken += 1
        self._pending.append(off)
        return batch[0]

    def to_hostpath(self) -> None:
        """This tenant's input needs the host path: the lane leaves the
        device batch (its rows are excluded at demux) and the whole job
        re-runs on the host oracle at finalize."""
        self.hostpath = True
        self._pending.clear()

    def merge_rows(self, rows: np.ndarray, kk: int) -> None:
        """One packed step's demuxed rows for this tenant, in the
        packed-table layout (kk key lanes + len/count/part)."""
        if not len(rows):
            return
        self.acc.add(rows[:, :kk], rows[:, kk],
                     rows[:, kk + 1].astype(np.int64), rows[:, kk + 2])
        self.delta_log.append(rows[None], np.array([len(rows)],
                                                   dtype=np.int64))

    def confirm_step(self) -> None:
        """Every pending row of this lane was confirmed by one packed
        step: advance the durable cursor, count, maybe checkpoint."""
        if self._pending:
            self.cursor = self._pending[-1]
            self.confirmed_rows += len(self._pending)
            self._pending.clear()
        self.steps += 1
        self.steps_since_resume += 1
        self.policy.note_step()
        if self.policy.due():
            self.save_ckpt()
            self.policy.reset()

    def save_ckpt(self) -> None:
        """One snapshot at the current confirmed boundary: a delta of
        the demuxed step payloads since the last save when the chain
        allows it, else a full accumulator image (the engines'
        want_delta/re-base discipline, one writer)."""
        meta = {"cursor": self.cursor, "rows": self.confirmed_rows}
        kind, parts = "full", None
        if self.writer.want_delta():
            entries = self.delta_log.take()
            if entries is not None:
                parts, kind = [("", DeltaSteps(entries))], "delta"
        if parts is None:
            self.delta_log.reset()
            parts = [("acc_", self.acc.snapshot())]
        self.writer.commit(parts, meta, kind=kind)

    def suspend(self) -> None:
        """Evict: one forced durable snapshot; the object is dead after
        (a fresh construction resumes the chain)."""
        if not self.hostpath:
            self.save_ckpt()
        self.writer.drain()
        self.writer.shutdown()

    def finalize(self) -> Dict[str, tuple]:
        """Job complete: the exact result (host path for a hostpath
        lane), ``mr-out-<r>`` files written to the job's out dir."""
        if self.hostpath:
            res = host_wordcount(self.job["files"], self.n_reduce)
        else:
            res = self.acc.finalize()
        out_dir = self.job["out_dir"]
        os.makedirs(out_dir, exist_ok=True)
        write_partitioned_output(res, self.n_reduce, out_dir)
        self.writer.drain()
        self.writer.shutdown()
        return res


class PackedWcScheduler:
    """Shared device-step packer over :class:`TenantLane` rows (module
    docstring).  One instance per daemon — it owns the sticky dispatch
    rung and the warmed wave executables; :meth:`step` is one shared
    dispatch over every runnable lane."""

    def __init__(self, mesh=None, chunk_bytes: int = 1 << 16,
                 n_reduce: int = 10, u_cap: int = 1 << 12):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dsi_tpu.parallel.shuffle import AXIS, default_mesh

        if mesh is None:
            mesh = default_mesh()
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        # The wave program's size contract: a power of two, >= 256.
        self.chunk_bytes = 1 << max(8, int(chunk_bytes - 1).bit_length())
        self.n_reduce = int(n_reduce)
        self.groupers = grouper_ladder()
        self.state = {"cap": rung0_cap(self.chunk_bytes, u_cap),
                      "mwl": 16, "grouper": self.groupers[0], "frac": 4}
        self.stats = metrics_scope("serve")
        self.stats.update({"packed_steps": 0, "packed_rows": 0,
                           "replays": 0, "upload_s": 0.0, "kernel_s": 0.0,
                           "pull_s": 0.0, "merge_s": 0.0,
                           "max_tenants_per_step": 0})
        self._sh_chunk = NamedSharding(mesh, P(AXIS, None))
        self._sh_ids = NamedSharding(mesh, P(AXIS))
        self._jax = jax

    def warm(self) -> None:
        """Compile (or load the persisted executable of) the
        sticky-rung wave program from shape structs — the daemon's
        boot-time warm, paid once for every tenant after it."""
        import jax
        import jax.numpy as jnp

        from dsi_tpu.parallel.tfidf import _wave_fn

        sds = jax.ShapeDtypeStruct
        examples = (sds((self.n_dev, self.chunk_bytes), jnp.uint8),
                    sds((self.n_dev,), jnp.int32))
        _wave_fn(examples, n_dev=self.n_dev, n_reduce=self.n_reduce,
                 max_word_len=self.state["mwl"], u_cap=self.state["cap"],
                 size=self.chunk_bytes, mesh=self.mesh,
                 t_cap_frac=self.state["frac"],
                 grouper=self.state["grouper"])

    # ── one packed step ──

    def _wave_call(self, chunk_np, ids_np, mwl, cap, frac, g):
        from dsi_tpu.device.table import _quiet_unusable_donation
        from dsi_tpu.parallel.tfidf import _wave_fn

        with _span("upload", stats=self.stats, key="upload_s"):
            chunk = self._jax.device_put(chunk_np, self._sh_chunk)
            ids = self._jax.device_put(ids_np, self._sh_ids)
        fn = _wave_fn((chunk, ids), n_dev=self.n_dev,
                      n_reduce=self.n_reduce, max_word_len=mwl,
                      u_cap=cap, size=self.chunk_bytes, mesh=self.mesh,
                      t_cap_frac=frac, grouper=g)
        with _quiet_unusable_donation():
            return fn(chunk, ids)

    def _dispatch_ladder(self, chunk_np, ids_np, picks):
        """The synchronous exactness ladder for ONE packed batch — the
        wave walk's replay discipline, with per-lane host-path
        attribution instead of rung aborts: a poisoned lane (non-ASCII,
        or a >64-byte word at the widest rung) is marked, its row
        zeroed, and the batch re-dispatched, so the other lanes'
        exactness flags are judged on clean input."""
        state = self.state
        cap, mwl = state["cap"], state["mwl"]
        while True:
            for g in self.groupers:
                for frac in (4, 2):
                    with _span("kernel", stats=self.stats,
                               key="kernel_s"):
                        rows, scal = self._wave_call(chunk_np, ids_np,
                                                     mwl, cap, frac, g)
                        scal_np = np.asarray(scal)
                    if not scal_np[:, 4].any():
                        break
                if not scal_np[:, 4].any():
                    break
            dead = [int(d) for d in np.flatnonzero(scal_np[:, 3])
                    if int(d) < len(picks) and not picks[int(d)].hostpath]
            if int(scal_np[:, 2].max()) > 64:
                dead += [int(d) for d in np.flatnonzero(scal_np[:, 2] > 64)
                         if int(d) < len(picks)
                         and not picks[int(d)].hostpath]
            if dead:
                for d in dead:
                    picks[d].to_hostpath()
                    chunk_np[d, :] = 0
                self.stats["replays"] += 1
                continue
            if int(scal_np[:, 2].max()) > mwl:
                mwl = 64  # a word overflowed the packed window: widen
                self.stats["replays"] += 1
                continue
            if int(scal_np[:, 1].max()) > cap:
                cap *= 4  # uniques <= tokens <= size/2: terminates
                self.stats["replays"] += 1
                continue
            break
        state.update(cap=cap, mwl=mwl, grouper=g, frac=frac)
        return rows, scal_np, mwl // 4

    def step(self, lanes: List[TenantLane]) -> List[TenantLane]:
        """Pack up to ``n_dev`` pending rows from ``lanes`` (round-robin
        across tenants; a lone tenant may fill every row, so
        single-tenant throughput matches the engine path) into ONE wave
        dispatch, demux by the doc lane, merge per tenant, confirm.
        Returns the lanes whose rows were confirmed."""
        from dsi_tpu.parallel.shuffle import occupied_prefix

        picks: List[TenantLane] = []
        chunk_np = np.zeros((self.n_dev, self.chunk_bytes), np.uint8)
        while len(picks) < self.n_dev:
            progressed = False
            for lane in list(lanes):
                if len(picks) >= self.n_dev:
                    break
                if not lane.runnable:
                    continue
                row = lane.take_row()
                if row is None:
                    continue
                chunk_np[len(picks), :] = row
                picks.append(lane)
                progressed = True
            if not progressed:
                break
        if not picks:
            return []
        # Doc id = batch slot: rides every shuffled row, so the pull
        # demuxes exactly.  Idle rows are all-zero chunks (no tokens).
        ids_np = np.arange(self.n_dev, dtype=np.int32)
        rows, scal_np, kk = self._dispatch_ladder(chunk_np, ids_np, picks)
        fault_point("post-dispatch")
        m = int(scal_np[:, 0].max())
        if m:
            with _span("pull", stats=self.stats, key="pull_s"):
                mp = occupied_prefix(m, rows.shape[1])
                rows_np = np.asarray(rows[:, :mp])
            with _span("merge", stats=self.stats, key="merge_s"):
                for d in range(self.n_dev):
                    nr = int(scal_np[d, 0])
                    if not nr:
                        continue
                    r = rows_np[d, :nr]
                    doc = r[:, kk + 2]
                    for slot, lane in enumerate(picks):
                        if lane.hostpath:
                            continue  # dead lane: its rows are dropped
                        sub = r[doc == slot]
                        if len(sub):
                            # Drop the doc column: kk keys + len + tf
                            # + part, the packed-table layout.
                            arr = np.concatenate(
                                [sub[:, :kk + 2], sub[:, kk + 3:kk + 4]],
                                axis=1)
                            lane.merge_rows(arr, kk)
        fault_point("mid-fold")
        confirmed = []
        for lane in dict.fromkeys(picks):
            if lane.hostpath:
                continue
            lane.confirm_step()
            confirmed.append(lane)
        self.stats["packed_steps"] += 1
        self.stats["packed_rows"] += len(picks)
        n_tenants = len({ln.tenant for ln in picks})
        if n_tenants > self.stats["max_tenants_per_step"]:
            self.stats["max_tenants_per_step"] = n_tenants
        return confirmed


# ── grep lanes (module docstring: the easy demux case) ─────────────────


class _GrepRow(NamedTuple):
    """One taken-but-unconfirmed lane row: the bytes, their valid
    length, the host line count, the stream offset just past the row,
    and the GLOBAL number of its first line.  Assigned once at take
    time, carried verbatim through requeues — which is why a replayed
    row's line numbers (the top-k key) cannot drift."""

    row: np.ndarray
    dlen: int
    n_lines: int
    end_off: int
    base: int


class GrepLane:
    """One tenant grep job's lane in :class:`PackedGrepScheduler`: a
    newline-aligned row stream cut from its input files, host-side
    whole-stream accumulators (totals, histogram, exact top-k), a
    sticky ``l_cap`` rung, and a per-tenant checkpoint chain.

    The accumulators fold per-ROW kernel outputs, so they are
    snapshot-small (``bins`` ints + ``topk`` pairs): checkpoints are
    full images, no delta log needed.  A non-literal pattern flips the
    lane to the host path at construction — the daemon finalizes it on
    :func:`~dsi_tpu.parallel.grepstream.grep_host_oracle` without the
    lane ever joining a pack.
    """

    def __init__(self, job: Dict, chunk_bytes: int, ckpt_dir: str,
                 checkpoint_every: Optional[int] = None,
                 resume: bool = True, bins: Optional[int] = None,
                 topk: Optional[int] = None):
        from dsi_tpu.ops.grepk import is_literal_pattern, line_cap_rungs
        from dsi_tpu.parallel.grepstream import (DEFAULT_TOPK, GREP_BINS,
                                                 batch_lines)
        from dsi_tpu.parallel.streaming import stream_files

        self.job = job
        self.tenant = job["tenant"]
        self.pattern = str(job["pattern"])
        self.pat = self.pattern.encode("ascii", errors="replace")
        self.m = len(self.pat)
        self.chunk_bytes = int(chunk_bytes)
        self.bins = int(bins if bins is not None else GREP_BINS)
        self.topk = int(topk if topk is not None else DEFAULT_TOPK)
        self.rungs = line_cap_rungs(self.chunk_bytes)
        self.rung = 0                 # sticky per-tenant AOT affinity
        self.lines = 0
        self.matched = 0
        self.occurrences = 0
        self.hist = [0] * self.bins
        self.cands: List[Tuple[int, int]] = []
        self.offsets: List[int] = []
        self.rows_taken = 0           # index into self.offsets
        self.confirmed_rows = 0
        self.steps = 0
        self.steps_since_resume = 0
        self.hostpath = not (self.m and is_literal_pattern(self.pattern)
                             and self.m <= self.chunk_bytes)
        self.input_done = False
        self.resume_gap_s = 0.0
        self.stats: Dict = {}
        self._held: Deque[_GrepRow] = deque()
        self._next_base = 0
        ident = {"tenant": self.tenant, "pattern": self.pattern,
                 "files": [[os.path.basename(f), os.path.getsize(f)]
                           for f in job["files"]],
                 "chunk_bytes": self.chunk_bytes,
                 "bins": self.bins, "topk": self.topk}
        self.store = CheckpointStore(ckpt_dir, "serve-grep", ident)
        self.writer = CheckpointWriter(self.store, self.stats,
                                       async_=False, delta=False)
        self.policy = CheckpointPolicy(checkpoint_every)
        start = 0
        if resume and not self.hostpath:
            t0 = time.perf_counter()
            loaded = self.store.load_latest_chain()
            if loaded is not None:
                meta, arrays, _deltas = loaded   # full images: no deltas
                start = int(meta["cursor"])
                self.lines = int(meta["lines"])
                self.matched = int(meta["matched"])
                self.occurrences = int(meta["occurrences"])
                self.rung = min(int(meta["rung"]), len(self.rungs) - 1)
                self.confirmed_rows = int(meta["rows"])
                self.hist = [int(v) for v in arrays["g_hist"]]
                self.cands = [(int(r[0]), int(r[1]))
                              for r in arrays["g_cand"]]
                self._next_base = self.lines
                self.resume_gap_s = round(time.perf_counter() - t0, 4)
        elif not resume:
            self.store.reset()
        self.start_offset = start
        self.cursor = start
        blocks = stream_files(job["files"])
        feed = skip_stream(blocks, start) if start else blocks
        self._rows = batch_lines(feed, 1, self.chunk_bytes,
                                 offsets=self.offsets)

    # ── the packer-facing surface ──

    @property
    def runnable(self) -> bool:
        if self.hostpath:
            return False
        return bool(self._held) or not self.input_done

    @property
    def l_cap(self) -> int:
        return self.rungs[self.rung]

    def take_row(self) -> Optional[_GrepRow]:
        """The next unconfirmed row — a requeued one first, else one
        pulled (and base-numbered) from the stream.  None at end of
        input or on a host-path flip (a line wider than one row)."""
        from dsi_tpu.parallel.grepstream import _LineTooLong

        if self._held:
            return self._held.popleft()
        try:
            batch, lens, row_lines = next(self._rows)
        except StopIteration:
            self.input_done = True
            return None
        except _LineTooLong:
            self.to_hostpath()
            return None
        end = self.start_offset + self.offsets[self.rows_taken]
        self.rows_taken += 1
        info = _GrepRow(batch[0], int(lens[0]), int(row_lines[0]), end,
                        self._next_base)
        self._next_base += info.n_lines
        return info

    def requeue(self, rows: List[_GrepRow]) -> None:
        """Give back a step's unconfirmed suffix, order preserved —
        the rows the lane's next (wider) dispatch serves first."""
        self._held.extendleft(reversed(rows))

    def to_hostpath(self) -> None:
        self.hostpath = True
        self._held.clear()

    def widen(self) -> bool:
        """Sticky-escalate to the next ``l_cap`` rung; False at the
        hard bound (``chunk_bytes + 1`` lines cannot overflow)."""
        if self.rung + 1 >= len(self.rungs):
            return False
        self.rung += 1
        return True

    def confirm_row(self, info: _GrepRow, hist_row: np.ndarray,
                    cand_pairs: List[Tuple[int, int]], matched: int,
                    occurrences: int) -> None:
        """Fold one clean (non-overflowed) row's kernel outputs and
        advance the durable cursor to the row's end offset."""
        from dsi_tpu.parallel.grepstream import merge_topk

        self.cursor = info.end_off
        self.confirmed_rows += 1
        self.lines += info.n_lines
        self.matched += int(matched)
        self.occurrences += int(occurrences)
        for b in range(self.bins):
            self.hist[b] += int(hist_row[b])
        if cand_pairs:
            self.cands = list(merge_topk(self.cands + cand_pairs,
                                         self.topk))

    def note_step(self) -> None:
        """One packed step confirmed rows for this lane: count it and
        maybe checkpoint (the wc lanes' cadence discipline)."""
        self.steps += 1
        self.steps_since_resume += 1
        self.policy.note_step()
        if self.policy.due():
            self.save_ckpt()
            self.policy.reset()

    def save_ckpt(self) -> None:
        meta = {"cursor": self.cursor, "lines": self.lines,
                "matched": self.matched,
                "occurrences": self.occurrences,
                "rung": self.rung, "rows": self.confirmed_rows}
        cand = np.array(self.cands or np.zeros((0, 2)), dtype=np.int64)
        parts = [("g_", {"hist": np.array(self.hist, dtype=np.int64),
                         "cand": cand.reshape(-1, 2)})]
        self.writer.commit(parts, meta, kind="full")

    def suspend(self) -> None:
        """Evict: one forced durable snapshot (held rows are simply
        re-read from the cursor on resume); dead after."""
        if not self.hostpath:
            self.save_ckpt()
        self.writer.drain()
        self.writer.shutdown()

    def finalize(self):
        """Job complete: the exact :class:`GrepStreamResult` (host
        oracle for a hostpath lane — correctness never depends on the
        kernel)."""
        from dsi_tpu.parallel.grepstream import (GrepStreamResult,
                                                 grep_host_oracle,
                                                 merge_topk)
        from dsi_tpu.parallel.streaming import stream_files

        if self.hostpath:
            res = grep_host_oracle(stream_files(self.job["files"]),
                                   self.pattern, bins=self.bins,
                                   topk=self.topk)
        else:
            res = GrepStreamResult(self.lines, self.matched,
                                   self.occurrences, tuple(self.hist),
                                   merge_topk(self.cands, self.topk))
        self.writer.drain()
        self.writer.shutdown()
        return res


class PackedGrepScheduler:
    """Shared grep-step packer over :class:`GrepLane` rows (module
    docstring).  One instance per daemon; :meth:`step` is one shared
    dispatch over ONE ``(pattern length, rung)`` group — groups take
    turns round-robin, so mixed pattern lengths interleave fairly
    instead of the shortest length starving the rest."""

    def __init__(self, mesh=None, chunk_bytes: int = 1 << 16,
                 bins: Optional[int] = None, topk: Optional[int] = None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dsi_tpu.parallel.grepstream import DEFAULT_TOPK, GREP_BINS
        from dsi_tpu.parallel.shuffle import AXIS, default_mesh

        if mesh is None:
            mesh = default_mesh()
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.chunk_bytes = int(chunk_bytes)
        self.bins = int(bins if bins is not None else GREP_BINS)
        self.topk = int(topk if topk is not None else DEFAULT_TOPK)
        self.stats = metrics_scope("serve_grep")
        self.stats.update({"packed_steps": 0, "packed_rows": 0,
                           "replays": 0, "rung_widens": 0,
                           "host_fallbacks": 0, "upload_s": 0.0,
                           "kernel_s": 0.0, "pull_s": 0.0,
                           "merge_s": 0.0, "max_tenants_per_step": 0})
        self._sh_chunk = NamedSharding(mesh, P(AXIS, None))
        self._sh_row = NamedSharding(mesh, P(AXIS))
        self._rr = 0
        self._jax = jax

    def warm(self, m: int, rung: int = 0) -> None:
        """Compile (or load) one pack shape ahead of need — the boot
        warm for the common pattern length; every other ``(m, rung)``
        pays its cold compile once, persisted."""
        from dsi_tpu.ops.grepk import line_cap_rungs
        from dsi_tpu.parallel.grepstream import grep_pack_fn

        grep_pack_fn(self.n_dev, self.chunk_bytes, int(m),
                     line_cap_rungs(self.chunk_bytes)[rung],
                     bins=self.bins, k=self.topk, mesh=self.mesh)

    # ── one packed step ──

    def _pick_group(self, lanes: List[GrepLane]) -> List[GrepLane]:
        """The next ``(m, rung)`` group, round-robin over the sorted
        group keys — a deterministic turn order under churn."""
        groups: Dict[tuple, List[GrepLane]] = {}
        for lane in lanes:
            if lane.runnable:
                groups.setdefault((lane.m, lane.rung), []).append(lane)
        if not groups:
            return []
        keys = sorted(groups)
        key = keys[self._rr % len(keys)]
        self._rr += 1
        return groups[key]

    def _dispatch(self, chunk_np, pats_np, lens_np, bases_np, m, l_cap):
        from dsi_tpu.device.table import _quiet_unusable_donation
        from dsi_tpu.parallel.grepstream import grep_pack_fn
        from dsi_tpu.utils.jaxcompat import enable_x64

        with _span("upload", stats=self.stats, key="upload_s"):
            chunk = self._jax.device_put(chunk_np, self._sh_chunk)
            pats = self._jax.device_put(pats_np, self._sh_chunk)
            lens = self._jax.device_put(lens_np, self._sh_row)
            with enable_x64(True):   # keep the u64 bases u64 through it
                bases = self._jax.device_put(
                    bases_np.astype(np.uint64), self._sh_row)
        fn = grep_pack_fn(self.n_dev, self.chunk_bytes, m, l_cap,
                          bins=self.bins, k=self.topk, mesh=self.mesh)
        with _span("kernel", stats=self.stats, key="kernel_s"):
            with _quiet_unusable_donation():
                hist_ext, cand, scal = fn(chunk, pats, lens, bases)
        with _span("pull", stats=self.stats, key="pull_s"):
            return (np.asarray(hist_ext), np.asarray(cand),
                    np.asarray(scal))

    def step(self, lanes: List[GrepLane]) -> List[GrepLane]:
        """Pack up to ``n_dev`` pending rows from ONE shape group
        (round-robin across its tenants; a lone tenant may fill every
        row) into one dispatch; demux per row, confirm each lane's
        clean prefix, requeue + sticky-widen on overflow.  Returns the
        lanes that confirmed rows."""
        group = self._pick_group(lanes)
        if not group:
            return []
        m, rung = group[0].m, group[0].rung
        l_cap = group[0].l_cap
        picks: List[Tuple[GrepLane, _GrepRow]] = []
        while len(picks) < self.n_dev:
            progressed = False
            for lane in group:
                if len(picks) >= self.n_dev:
                    break
                if not lane.runnable:
                    continue
                info = lane.take_row()
                if info is None:
                    if lane.hostpath:
                        self.stats["host_fallbacks"] += 1
                    continue
                picks.append((lane, info))
                progressed = True
            if not progressed:
                break
        if not picks:
            return []
        chunk_np = np.zeros((self.n_dev, self.chunk_bytes), np.uint8)
        pats_np = np.zeros((self.n_dev, m), np.uint8)
        lens_np = np.zeros(self.n_dev, dtype=np.int32)
        bases_np = np.zeros(self.n_dev, dtype=np.int64)
        # Idle rows carry slot-0's pattern over an all-zero chunk: a
        # printable-ASCII pattern cannot match zero padding, so they
        # contribute nothing (the kernel's padding argument).
        pats_np[:] = np.frombuffer(picks[0][0].pat, dtype=np.uint8)
        for slot, (lane, info) in enumerate(picks):
            chunk_np[slot, :len(info.row)] = info.row
            pats_np[slot] = np.frombuffer(lane.pat, dtype=np.uint8)
            lens_np[slot] = info.dlen
            bases_np[slot] = info.base
        hist_np, cand_np, scal_np = self._dispatch(
            chunk_np, pats_np, lens_np, bases_np, m, l_cap)
        fault_point("post-dispatch")
        # Per-lane demux: slots in take order ARE byte-range order, so
        # each lane confirms its clean prefix and requeues the rest.
        by_lane: Dict[int, List[tuple]] = {}
        order: List[GrepLane] = []
        for slot, (lane, info) in enumerate(picks):
            if id(lane) not in by_lane:
                by_lane[id(lane)] = []
                order.append(lane)
            by_lane[id(lane)].append((slot, info))
        confirmed: List[GrepLane] = []
        with _span("merge", stats=self.stats, key="merge_s"):
            for lane in order:
                slots = by_lane[id(lane)]
                n_ok = 0
                for slot, _info in slots:
                    if int(scal_np[slot, 2]):
                        break
                    n_ok += 1
                for slot, info in slots[:n_ok]:
                    n_cand = int(scal_np[slot, 0])
                    pairs = [((int(cand_np[slot, i, 0]) << 32)
                              | int(cand_np[slot, i, 1]),
                              int(cand_np[slot, i, 3]))
                             for i in range(n_cand)]
                    lane.confirm_row(info, hist_np[slot],
                                     pairs, int(scal_np[slot, 3]),
                                     int(scal_np[slot, 4]))
                if n_ok < len(slots):
                    lane.requeue([info for _s, info in slots[n_ok:]])
                    self.stats["replays"] += 1
                    if lane.widen():
                        self.stats["rung_widens"] += 1
                if n_ok:
                    confirmed.append(lane)
        fault_point("mid-fold")
        for lane in confirmed:
            lane.note_step()
        self.stats["packed_steps"] += 1
        self.stats["packed_rows"] += len(picks)
        n_tenants = len({ln.tenant for ln, _i in picks})
        if n_tenants > self.stats["max_tenants_per_step"]:
            self.stats["max_tenants_per_step"] = n_tenants
        return confirmed
