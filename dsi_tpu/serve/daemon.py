"""The resident serving daemon behind ``mrserve``.

One long-lived process owns the device mesh, the warmed AOT
executables, and a spool directory; tenants submit jobs over the
repo's framed-JSON pull-RPC control plane (``mr/rpc.py`` — the 6.5840
idiom the reference's coordinator already speaks) and the daemon:

* **admits by priority** (``serve/qos.py``): three strict FIFO lanes
  (``mrsubmit --priority``), per-tenant token-bucket rate limits, and a
  bounded queue — an over-rate or over-bound submission is SHED with a
  typed backpressure error carrying a retry-after hint (the client's
  bounded-retry contract), BEFORE any journal write, so shedding never
  loses an accepted job;
* **journals** every accepted submission durably
  (``spool/jobs/<id>.json`` through ``atomicio.write_bytes_durable``)
  BEFORE acking it, so a ``kill -9`` at any instant loses no accepted
  job;
* **packs** word-count tenants into shared device steps
  (``serve/pack.py``: K tenants ≈ 1 dispatch) AND grep tenants into
  shared lane-isolated dispatches (``PackedGrepScheduler``: rows
  grouped by pattern length, per-tenant sticky ``l_cap`` rung so one
  tenant's widen never cold-compiles the rest) — everything else runs
  as resumable step objects (``parallel/stepobj.py``) on one scheduler
  thread; a single thread owns all jax work;
* **evicts by tail latency**: when the resident set is full and jobs
  wait, the victim is the tenant whose p99 packed-step wall
  (``obs/hist.KeyedHistograms`` fed every step) hurts the pack most —
  the step-quota rule stays as the fallback when no tenant has a
  meaningful tail yet.  Parked tenants resume from their
  delta-checkpoint chains on their next turn (or next submission, which
  re-prioritizes their parked jobs within their own priority lane);
* **resumes after a crash**: on boot every journaled job not marked
  done re-enters the queue with ``resume=True``; per-tenant chains
  restore the accumulators and cursors, and the re-run output is
  byte-identical to an uninterrupted run (the CI smoke kills the
  daemon with ``kill -9`` mid-job and diffs against the sequential
  oracle);
* **reports**: a ``tenants`` section on ``/statusz`` and labeled
  ``dsi_serve_*`` series on ``/metrics`` via the live-telemetry
  section hooks (``obs/live.py``).  Every emitted series is registered
  in ``obs/registry.SERVE_SERIES`` (the dsicheck metric-schema rule
  enforces it), and per-tenant series are CAPPED at
  ``DSI_SERVE_METRICS_TENANTS`` worst-p99 tenants, so a
  thousands-of-tenants soak keeps /metrics bounded.

Spool hygiene at boot: ``.tmp-*`` orphans are reaped across the spool
(``atomicio.reap_tmp_files``), and checkpoint chains of tenants whose
jobs are all done age out after ``retention_s`` — a live (unfinished)
job's chain is never touched, and within a live chain the store's own
chain-aware GC (PR 8) keeps retention safe.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Dict, List, Optional

from dsi_tpu.mr.rpc import RpcServer
from dsi_tpu.obs.hist import KeyedHistograms
from dsi_tpu.serve import qos
from dsi_tpu.serve.client import default_socket
from dsi_tpu.utils.atomicio import (
    read_bytes_verified,
    reap_tmp_files,
    write_bytes_durable,
)

#: Apps the daemon serves.  ``wc`` rides the packed wave scheduler;
#: ``grep`` rides the packed grep scheduler (lane-isolated rows — see
#: serve/pack.py) unless ``pack_grep`` is off, in which case it runs as
#: a time-multiplexed resumable step object (the bench's control arm).
SERVE_APPS = ("wc", "grep")

_JOB_FIELDS = ("job_id", "tenant", "app", "files", "n_reduce", "out_dir",
               "pattern", "priority", "state", "submitted_ts", "done_ts",
               "error", "stats")

#: Tenant ids become path components (journal names, chain dirs): a
#: plain slug, no separators, no leading dot.
_TENANT_RE = re.compile(r"[A-Za-z0-9_-][A-Za-z0-9._-]{0,63}")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ServeDaemon:
    """One ``mrserve`` process (module docstring)."""

    def __init__(self, spool: str, socket_path: Optional[str] = None,
                 n_reduce: int = 10, chunk_bytes: int = 1 << 16,
                 devices: Optional[int] = None,
                 max_resident: int = 8, quota_steps: int = 64,
                 checkpoint_every: Optional[int] = 8,
                 retention_s: float = 14 * 86400.0,
                 warm: bool = True,
                 max_queue: int = 1024,
                 rate_limit: Optional[float] = None,
                 rate_burst: int = 4,
                 pack_grep: Optional[bool] = None,
                 evict_min_samples: int = 8,
                 metrics_tenants: Optional[int] = None,
                 clock=time.monotonic,
                 admit_hook=None):
        self.spool = os.path.abspath(spool)
        self.jobs_dir = os.path.join(self.spool, "jobs")
        self.tenants_dir = os.path.join(self.spool, "tenants")
        self.out_dir = os.path.join(self.spool, "out")
        for d in (self.spool, self.jobs_dir, self.tenants_dir,
                  self.out_dir):
            os.makedirs(d, exist_ok=True)
        self.socket_path = socket_path or default_socket(self.spool)
        self.n_reduce = int(n_reduce)
        # One chunk-width truth: the packer rounds to a pow2 >= 256 (the
        # wave program's size contract), so the lanes must cut rows at
        # exactly that width or the batch fill would shape-mismatch.
        self.chunk_bytes = 1 << max(8, int(chunk_bytes - 1).bit_length())
        self.devices = devices
        self.max_resident = max(1, int(max_resident))
        self.quota_steps = max(1, int(quota_steps))
        self.checkpoint_every = checkpoint_every
        self.retention_s = float(retention_s)
        self.warm = warm
        self.max_queue = max(1, int(max_queue))
        self.rate_limit = rate_limit
        self.rate_burst = max(1, int(rate_burst))
        if pack_grep is None:
            pack_grep = os.environ.get("DSI_SERVE_PACK_GREP", "1") != "0"
        self.pack_grep = bool(pack_grep)
        self.evict_min_samples = max(1, int(evict_min_samples))
        if metrics_tenants is None:
            metrics_tenants = _env_int("DSI_SERVE_METRICS_TENANTS", 32)
        self.metrics_tenants = max(1, int(metrics_tenants))
        self._clock = clock
        # Replicated control plane (dsi_tpu/replica): called with the
        # persisted job record BEFORE the local journal write and the
        # ack — it blocks until the admission is majority-replicated,
        # or raises, in which case the submission is NOT admitted (no
        # spool state, typed error to the client).  None = single-node.
        self.admit_hook = admit_hook

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = threading.Event()
        self.ready = threading.Event()
        self._jobs: Dict[str, Dict] = {}
        self._queue = qos.PriorityQueue()
        self._resident: Dict[str, Dict] = {}
        self._tenants: Dict[str, Dict] = {}
        self._buckets: Dict[str, qos.TokenBucket] = {}
        # Admission/eviction counters.  A plain dict, not an engine
        # metrics scope: these are control-plane events, surfaced as
        # dsi_serve_* series (SERVE_SERIES), not step-pipeline stats.
        self._qos = {"shed": 0, "rate_limited": 0, "evict_p99": 0,
                     "evict_quota": 0}
        # Per-tenant packed-step wall distributions — the eviction
        # policy's evidence and the bounded /metrics tenant selector.
        self._hist = KeyedHistograms()
        # Job-completion gap distribution (separate instance: _hist is
        # keyed by tenant and drives EVICTION — a pseudo-key there
        # would become an eviction candidate).  Feeds the measured
        # drain rate behind the queue-full retry-after hint.
        self._drain_hist = KeyedHistograms()
        self._last_done_ts: Optional[float] = None
        self._seq = 0
        self.packer = None
        self.grep_packer = None
        self.boot_reaped = 0
        self.boot_gc_chains = 0

        self._boot_hygiene()
        self._load_journal()
        self._rpc = RpcServer(self.socket_path, {
            "Submit": self._rpc_submit,
            "Status": self._rpc_status,
            "Ping": self._rpc_ping,
            "Shutdown": self._rpc_shutdown,
        })
        self._thread = threading.Thread(target=self._scheduler,
                                        name="dsi-mrserve-scheduler",
                                        daemon=True)

    # ── boot ──

    def _boot_hygiene(self) -> None:
        """Satellite: reap ``.tmp-*`` orphans everywhere a crashed run
        can leave them, and age out dead tenants' checkpoint chains."""
        n = 0
        roots = [self.spool, self.jobs_dir, self.out_dir,
                 self.tenants_dir]
        trace_dir = os.environ.get("DSI_TRACE_DIR")
        if trace_dir:
            roots.append(trace_dir)
        for t in list(os.listdir(self.tenants_dir)):
            tdir = os.path.join(self.tenants_dir, t)
            if os.path.isdir(tdir):
                roots.append(tdir)
                roots.extend(os.path.join(tdir, j)
                             for j in os.listdir(tdir)
                             if os.path.isdir(os.path.join(tdir, j)))
        for d in roots:
            try:
                n += reap_tmp_files(d)
            except OSError:
                pass
        self.boot_reaped = n

    def _gc_aged_chains(self) -> None:
        """Delete whole per-job chain dirs whose job is done (or
        unknown) and untouched past the retention age.  A live chain is
        never a candidate — its base stays protected — and within live
        chains the store's chain-aware GC already bounds growth."""
        now = time.time()
        live = {jid for jid, j in self._jobs.items()
                if j["state"] != "done"}
        for t in list(os.listdir(self.tenants_dir)):
            tdir = os.path.join(self.tenants_dir, t)
            if not os.path.isdir(tdir):
                continue
            for jid in list(os.listdir(tdir)):
                jdir = os.path.join(tdir, jid)
                if not os.path.isdir(jdir) or jid in live:
                    continue
                try:
                    mtimes = [os.path.getmtime(os.path.join(jdir, f))
                              for f in os.listdir(jdir)] or \
                             [os.path.getmtime(jdir)]
                    if now - max(mtimes) > self.retention_s:
                        shutil.rmtree(jdir, ignore_errors=True)
                        self.boot_gc_chains += 1
                except OSError:
                    continue

    def _load_journal(self) -> None:
        """Re-enter every journaled job; unfinished ones re-queue with
        their chains — the crash-resume half of the daemon contract."""
        for name in sorted(os.listdir(self.jobs_dir)):
            if not name.endswith(".json"):
                continue
            raw = read_bytes_verified(os.path.join(self.jobs_dir, name))
            if raw is None:
                continue  # torn journal entry: the submit never acked
            try:
                job = json.loads(raw)
            except ValueError:
                continue
            job.setdefault("priority", qos.DEFAULT_PRIORITY)
            job.setdefault("done_ts", None)
            self._jobs[job["job_id"]] = job
            self._tenant(job["tenant"])["jobs"] += 1
            try:
                self._seq = max(self._seq,
                                int(job["job_id"].rsplit("-", 1)[1]) + 1)
            except (IndexError, ValueError):
                pass
            if job["state"] == "done":
                self._tenant(job["tenant"])["done"] += 1
            elif job["state"] == "failed":
                pass
            else:
                job["state"] = "queued"
                self._queue.push(job["job_id"], job["priority"])
        self._gc_aged_chains()

    # ── bookkeeping ──

    def _tenant(self, tenant: str) -> Dict:
        return self._tenants.setdefault(tenant, {
            "jobs": 0, "done": 0, "steps": 0, "rows": 0,
            "evictions": 0, "resumes": 0, "resume_gap_s": 0.0,
            "hostpath": 0})

    def _persist(self, job: Dict) -> None:
        rec = {k: job.get(k) for k in _JOB_FIELDS}
        write_bytes_durable(
            os.path.join(self.jobs_dir, f"{job['job_id']}.json"),
            json.dumps(rec, sort_keys=True).encode("utf-8"))

    def _drain_jobs_per_sec(self) -> float:
        """The measured service rate behind ``qos.shed_retry_after``:
        the median completion gap inverted (KeyedHistograms evidence,
        same instrument the eviction policy trusts).  0.0 until at
        least two jobs finished — callers fall back to the cold-start
        linear hint."""
        h = self._drain_hist.get("gap")
        if h is None or h.count < 2:
            return 0.0
        p50 = h.percentile(0.5)
        return 1.0 / p50 if p50 > 0.0 else 0.0

    # ── RPC handlers (no jax; scheduler owns the device) ──

    def _rpc_submit(self, args: dict) -> dict:
        tenant = str(args.get("tenant") or "default")
        # The tenant id is spliced into journal filenames and chain
        # paths: a separator or dot-dot would write outside the spool
        # (and dodge the hygiene walks), so the id must be a slug.
        if not _TENANT_RE.fullmatch(tenant):
            return {"error": f"invalid tenant {tenant!r}: want "
                             f"[A-Za-z0-9._-]{{1,64}} with no leading "
                             f"dot"}
        app = str(args.get("app") or "wc")
        files = [os.path.abspath(f) for f in (args.get("files") or [])]
        if app not in SERVE_APPS:
            return {"error": f"unknown app {app!r} (have {SERVE_APPS})"}
        if not files:
            return {"error": "no input files"}
        missing = [f for f in files if not os.path.isfile(f)]
        if missing:
            return {"error": f"missing input files: {missing}"}
        n_reduce = int(args.get("n_reduce") or self.n_reduce)
        if n_reduce != self.n_reduce:
            # The packed step computes partitions on device with the
            # daemon's n_reduce; a per-job degree cannot share it.
            return {"error": f"n_reduce {n_reduce} != daemon's "
                             f"{self.n_reduce} (packing shares one "
                             f"partition degree)"}
        pattern = args.get("pattern")
        if app == "grep" and not pattern:
            return {"error": "grep needs a pattern"}
        priority = args.get("priority")
        if priority is None:
            priority = qos.DEFAULT_PRIORITY
        try:
            priority = int(priority)
        except (TypeError, ValueError):
            return {"error": f"invalid priority {priority!r}"}
        if priority not in qos.PRIORITIES:
            return {"error": f"invalid priority {priority} "
                             f"(want one of {qos.PRIORITIES})"}
        with self._wake:
            # Admission policy, BEFORE the journal write: a shed or
            # rate-limited submission leaves no spool state, so
            # backpressure can never manufacture a lost accepted job.
            if self.rate_limit is not None:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = qos.TokenBucket(self.rate_limit,
                                             self.rate_burst,
                                             clock=self._clock)
                    self._buckets[tenant] = bucket
                hint = bucket.take()
                if hint > 0.0:
                    self._qos["rate_limited"] += 1
                    return qos.backpressure_reply(
                        f"tenant {tenant!r} over submit rate "
                        f"({self.rate_limit}/s, burst "
                        f"{self.rate_burst})", hint)
            queued = len(self._queue)
            if queued >= self.max_queue:
                self._qos["shed"] += 1
                # Deeper backlog → longer hint, scaled by the MEASURED
                # drain rate (qos.shed_retry_after): the hint predicts
                # when a slot plausibly opens, not a fixed slope.
                hint = qos.shed_retry_after(queued,
                                            self._drain_jobs_per_sec())
                return qos.backpressure_reply(
                    f"queue full ({queued} >= {self.max_queue})", hint)
            jid = f"{tenant}-{self._seq:06d}"
            self._seq += 1
            job = {"job_id": jid, "tenant": tenant, "app": app,
                   "files": files, "n_reduce": n_reduce,
                   "out_dir": os.path.join(self.out_dir, jid),
                   "pattern": pattern, "priority": priority,
                   "state": "queued",
                   "submitted_ts": round(time.time(), 3),
                   "done_ts": None, "error": None, "stats": {}}
            if self.admit_hook is not None:
                # Replicated admission (dsi_tpu/replica): majority-
                # commit the record BEFORE any local state, so a leader
                # cut off from its group cannot ack a job the group
                # never heard of.  Raises on failure — caught by the
                # replica node's typed-reply wrapper; no spool state
                # was created, same shed contract as above.
                self.admit_hook({k: job.get(k) for k in _JOB_FIELDS})
            self._persist(job)  # durable BEFORE the ack
            self._jobs[jid] = job
            self._tenant(tenant)["jobs"] += 1
            # "Resume on the next submission": the tenant's PARKED jobs
            # move to the front of their own priority lanes, then the
            # new one joins its lane's tail.  Parked only — and never
            # across lanes, so a parked batch job cannot cut ahead of
            # the interactive lane.
            parked = [j for j in self._queue
                      if self._jobs[j]["tenant"] == tenant
                      and self._jobs[j]["state"] == "parked"]
            for j in parked:
                self._queue.remove(j)
            for j in reversed(parked):
                self._queue.push_front(j, self._jobs[j]["priority"])
            self._queue.push(jid, priority)
            self._wake.notify_all()
        return {"job_id": jid, "out_dir": job["out_dir"]}

    def _rpc_status(self, args: dict) -> dict:
        jid = args.get("job_id")
        tenant = args.get("tenant")
        with self._lock:
            if jid:
                job = self._jobs.get(jid)
                if job is None:
                    return {"error": f"no such job {jid!r}"}
                return {"job": {k: job.get(k) for k in _JOB_FIELDS}}
            jobs = [{k: j.get(k) for k in _JOB_FIELDS}
                    for j in self._jobs.values()
                    if tenant is None or j["tenant"] == tenant]
            return {"jobs": jobs,
                    "tenants": {t: dict(s)
                                for t, s in self._tenants.items()}}

    def _rpc_ping(self, args: dict) -> dict:
        with self._lock:
            out = {"ok": True, "pid": os.getpid(),
                   "ready": self.ready.is_set(),
                   "queued": len(self._queue),
                   "resident": len(self._resident),
                   "shed": self._qos["shed"],
                   "rate_limited": self._qos["rate_limited"]}
            if self.grep_packer is not None:
                out["grep_packed_steps"] = \
                    self.grep_packer.stats["packed_steps"]
            return out

    def _rpc_shutdown(self, args: dict) -> dict:
        self.stop()
        return {"ok": True}

    # ── statusz / metrics section (obs/live.py hooks) ──

    def _statusz_section(self) -> str:
        with self._lock:
            depths = self._queue.depths()
            lines = [f"  queued={len(self._queue)} "
                     f"depths={'/'.join(map(str, depths))} "
                     f"resident={len(self._resident)} "
                     f"jobs={len(self._jobs)} "
                     f"shed={self._qos['shed']} "
                     f"rate_limited={self._qos['rate_limited']} "
                     f"evict_p99={self._qos['evict_p99']} "
                     f"evict_quota={self._qos['evict_quota']}"]
            if self.packer is not None:
                st = self.packer.stats
                lines.append(
                    f"  wc packed_steps={st['packed_steps']} "
                    f"packed_rows={st['packed_rows']} "
                    f"max_tenants_per_step={st['max_tenants_per_step']} "
                    f"replays={st['replays']}")
            if self.grep_packer is not None:
                st = self.grep_packer.stats
                lines.append(
                    f"  grep packed_steps={st['packed_steps']} "
                    f"packed_rows={st['packed_rows']} "
                    f"max_tenants_per_step={st['max_tenants_per_step']} "
                    f"rung_widens={st['rung_widens']} "
                    f"host_fallbacks={st['host_fallbacks']}")
            for jid, rec in sorted(self._resident.items()):
                job = self._jobs[jid]
                if rec["kind"] in ("wc", "grep"):
                    lane = rec["lane"]
                    live = (f"steps={lane.steps} "
                            f"rows={lane.confirmed_rows} "
                            f"cursor={lane.cursor}")
                else:
                    live = f"steps={rec['advanced']}"
                lines.append(f"  tenant={job['tenant']} job={jid} "
                             f"app={job['app']} "
                             f"prio={job.get('priority')} {live}")
            # The tenant table is capped like /metrics: worst tails
            # first, then the rest in name order up to the cap.
            for t in self._emit_tenants():
                s = self._tenants[t]
                kv = " ".join(f"{k}={v}" for k, v in sorted(s.items()))
                p99 = self._hist.p99_ms(t)
                lines.append(f"  tenant={t} p99_ms={p99} {kv}")
            omitted = len(self._tenants) - \
                len(self._emit_tenants())
            if omitted > 0:
                lines.append(f"  ... {omitted} more tenants (cap "
                             f"{self.metrics_tenants})")
        return "\n".join(lines)

    def _emit_tenants(self) -> List[str]:
        """The capped tenant set for /statusz and /metrics: worst-p99
        tenants first (the ones an operator is hunting), filled with
        the rest in name order up to ``metrics_tenants``.  Caller holds
        the lock."""
        cap = self.metrics_tenants
        picked = [t for t, _p, _n in self._hist.top(cap)
                  if t in self._tenants]
        if len(picked) < cap:
            seen = set(picked)
            for t in sorted(self._tenants):
                if t not in seen:
                    picked.append(t)
                    if len(picked) >= cap:
                        break
        return picked

    def _metrics_section(self) -> str:
        from dsi_tpu.obs.live import _mname

        with self._lock:
            L = [f"dsi_serve_jobs_total {len(self._jobs)}",
                 f"dsi_serve_queued {len(self._queue)}",
                 f"dsi_serve_resident {len(self._resident)}",
                 f"dsi_serve_tenants_total {len(self._tenants)}",
                 f"dsi_serve_shed_total {self._qos['shed']}",
                 f"dsi_serve_rate_limited_total "
                 f"{self._qos['rate_limited']}",
                 f"dsi_serve_evictions_p99_total "
                 f"{self._qos['evict_p99']}",
                 f"dsi_serve_evictions_quota_total "
                 f"{self._qos['evict_quota']}"]
            for p, d in zip(qos.PRIORITIES, self._queue.depths()):
                L.append(f'dsi_serve_queue_depth{{priority="{p}"}} {d}')
            if self.packer is not None:
                st = self.packer.stats
                L.append(f"dsi_serve_packed_steps {st['packed_steps']}")
                L.append(f"dsi_serve_packed_rows {st['packed_rows']}")
            if self.grep_packer is not None:
                st = self.grep_packer.stats
                L.append(f"dsi_serve_grep_packed_steps "
                         f"{st['packed_steps']}")
                L.append(f"dsi_serve_grep_packed_rows "
                         f"{st['packed_rows']}")
                L.append(f"dsi_serve_grep_rung_widens "
                         f"{st['rung_widens']}")
            for t in self._emit_tenants():
                s = self._tenants[t]
                lab = f'tenant="{_mname(t)}"'
                for k in ("steps", "rows", "evictions", "resumes",
                          "done"):
                    L.append(f"dsi_serve_tenant_{k}{{{lab}}} {s[k]}")
                L.append(f"dsi_serve_tenant_resume_gap_seconds{{{lab}}} "
                         f"{s['resume_gap_s']}")
                L.append(f"dsi_serve_tenant_p99_ms{{{lab}}} "
                         f"{self._hist.p99_ms(t)}")
        return "\n".join(L)

    # ── scheduler (the one thread that touches jax) ──

    def _admit(self) -> bool:
        """Move queued jobs into the resident set (resuming from their
        chains), highest priority first; returns whether anything was
        admitted.  Caller holds the lock."""
        admitted = False
        while len(self._queue) and \
                len(self._resident) < self.max_resident:
            jid = self._queue.pop()
            job = self._jobs[jid]
            try:
                rec = self._make_runner(job)
            except Exception as e:  # noqa: BLE001 — job fails, daemon lives
                job["state"] = "failed"
                job["error"] = f"{type(e).__name__}: {e}"
                job["done_ts"] = round(time.time(), 3)
                self._persist(job)
                continue
            was_parked = job["state"] == "parked"
            job["state"] = "running"
            self._persist(job)
            self._resident[jid] = rec
            ts = self._tenant(job["tenant"])
            if was_parked or rec.get("resume_cursor", 0):
                ts["resumes"] += 1
                ts["resume_gap_s"] = round(
                    ts["resume_gap_s"] + rec.get("resume_gap_s", 0.0), 4)
            admitted = True
        return admitted

    def _make_runner(self, job: Dict) -> Dict:
        ckpt_dir = os.path.join(self.tenants_dir, job["tenant"],
                                job["job_id"])
        if job["app"] == "wc":
            from dsi_tpu.serve.pack import TenantLane

            lane = TenantLane(job, self.chunk_bytes, ckpt_dir,
                              checkpoint_every=self.checkpoint_every,
                              resume=True)
            return {"kind": "wc", "lane": lane,
                    "resume_gap_s": lane.resume_gap_s,
                    "resume_cursor": lane.start_offset}
        if self.pack_grep:
            # grep as a packed lane: rows join shared dispatches keyed
            # by (pattern length, rung) — the ISSUE-19 tentpole.
            from dsi_tpu.serve.pack import GrepLane

            lane = GrepLane(job, self.chunk_bytes, ckpt_dir,
                            checkpoint_every=self.checkpoint_every,
                            resume=True)
            return {"kind": "grep", "lane": lane,
                    "resume_gap_s": lane.resume_gap_s,
                    "resume_cursor": lane.start_offset}
        # grep as a resumable step object, time-multiplexed (the
        # packed-vs-tmux bench row's control arm).
        from dsi_tpu.parallel.grepstream import GrepStep
        from dsi_tpu.parallel.streaming import stream_files

        stats: Dict = {}
        step = GrepStep(stream_files(job["files"]), job["pattern"],
                        mesh=self._mesh, checkpoint_dir=ckpt_dir,
                        checkpoint_every=self.checkpoint_every,
                        checkpoint_delta=True, resume=True,
                        pipeline_stats=stats)
        info = step.restore()
        return {"kind": "step", "step": step, "stats": stats,
                "advanced": 0,
                "resume_gap_s": info.get("resume_gap_s", 0.0),
                "resume_cursor": info.get("resume_cursor", 0)}

    def _finish_job(self, jid: str, rec: Dict) -> None:
        """Finalize one retired runner.  Called WITHOUT the daemon lock
        held: the heavy half (host-path recomputation, durable output
        writes) must not freeze the control plane mid-multi-GB job —
        only the final job/tenant bookkeeping takes the lock."""
        job = self._jobs[jid]
        hostpath = False
        stats: Dict = {}
        error = None
        try:
            if rec["kind"] == "wc":
                lane = rec["lane"]
                lane.finalize()
                hostpath = lane.hostpath
                stats = {"steps": lane.steps,
                         "rows": lane.confirmed_rows,
                         "hostpath": lane.hostpath,
                         "resume_gap_s": lane.resume_gap_s}
            elif rec["kind"] == "grep":
                lane = rec["lane"]
                result = lane.finalize()
                hostpath = lane.hostpath
                self._write_grep_result(job, result)
                stats = {"steps": lane.steps,
                         "rows": lane.confirmed_rows,
                         "hostpath": lane.hostpath,
                         "rung": lane.rung,
                         "resume_gap_s": lane.resume_gap_s}
            else:
                step = rec["step"]
                result = step.close()
                if result is None:
                    # Host path: the oracle semantics, same output file.
                    from dsi_tpu.parallel.grepstream import \
                        grep_host_oracle
                    from dsi_tpu.parallel.streaming import stream_files

                    result = grep_host_oracle(stream_files(job["files"]),
                                              job["pattern"])
                    hostpath = True
                self._write_grep_result(job, result)
                stats = {"steps": rec["advanced"]}
        except Exception as e:  # noqa: BLE001 — job fails, daemon lives
            error = f"{type(e).__name__}: {e}"
        with self._lock:
            job["stats"] = stats
            job["state"] = "done" if error is None else "failed"
            job["error"] = error
            job["done_ts"] = round(time.time(), 3)
            ts = self._tenant(job["tenant"])
            if hostpath:
                ts["hostpath"] += 1
            if error is None:
                ts["done"] += 1
                ts["steps"] += int(stats.get("steps") or 0)
                ts["rows"] += int(stats.get("rows") or 0)
            # Drain-rate evidence: the gap between consecutive job
            # completions (any outcome — a failed job still drained a
            # queue slot) feeds the queue-full retry-after hint.
            now = self._clock()
            if self._last_done_ts is not None:
                self._drain_hist.record("gap",
                                        max(1e-6, now - self._last_done_ts))
            self._last_done_ts = now
        self._persist(job)

    @staticmethod
    def _write_grep_result(job: Dict, result) -> None:
        """One spelling of the grep output file — the packed lane, the
        step object, and the host path must serialize identically (the
        per-tenant byte-parity bar)."""
        os.makedirs(job["out_dir"], exist_ok=True)
        payload = json.dumps(
            {"lines": result.lines, "matched": result.matched,
             "occurrences": result.occurrences,
             "hist": list(result.hist),
             "topk": [list(r) for r in result.topk]},
            sort_keys=True).encode("utf-8")
        write_bytes_durable(
            os.path.join(job["out_dir"], "grep.json"), payload)

    def _rec_steps(self, rec: Dict) -> int:
        return (rec["lane"].steps_since_resume
                if rec["kind"] in ("wc", "grep") else rec["advanced"])

    def _evict_one(self) -> None:
        """Park one resident job so a queued tenant gets a turn —
        checkpoint to its delta chain, drop the runner, re-queue in its
        own priority lane.  Victim choice is TAIL-DRIVEN: among
        residents past a minimum residency, the tenant whose p99
        packed-step wall is worst (its rows stall every pack it rides).
        The step-quota rule is the fallback when no resident has a
        meaningful tail yet.  Caller holds the lock."""
        victim = None
        worst = 0.0
        min_steps = min(self.quota_steps, self.evict_min_samples)
        for jid, rec in self._resident.items():
            if self._rec_steps(rec) < min_steps:
                continue  # too fresh: let it earn a tail first
            h = self._hist.get(self._jobs[jid]["tenant"])
            if h is None or h.count < self.evict_min_samples:
                continue
            p99 = h.percentile(0.99)
            if p99 > worst:
                victim, worst = jid, p99
        reason = "evict_p99"
        if victim is None:
            # Fallback: the original furthest-past-quota rule.
            most = -1
            for jid, rec in self._resident.items():
                steps = self._rec_steps(rec)
                if steps >= self.quota_steps and steps > most:
                    victim, most = jid, steps
            reason = "evict_quota"
        if victim is None:
            return
        rec = self._resident.pop(victim)
        job = self._jobs[victim]
        try:
            if rec["kind"] in ("wc", "grep"):
                rec["lane"].suspend()
            else:
                rec["step"].suspend()
        except Exception as e:  # noqa: BLE001
            job["state"] = "failed"
            job["error"] = f"evict: {type(e).__name__}: {e}"
            job["done_ts"] = round(time.time(), 3)
            self._persist(job)
            return
        job["state"] = "parked"
        self._persist(job)
        self._queue.push(victim, job.get("priority",
                                         qos.DEFAULT_PRIORITY))
        self._tenant(job["tenant"])["evictions"] += 1
        self._qos[reason] += 1

    def _fail_lanes(self, pairs, e: Exception, what: str) -> None:
        """Fail the jobs riding a packer that threw — the packer error
        takes out its participants, never the daemon."""
        with self._wake:
            for jid, _ln in pairs:
                rec = self._resident.pop(jid, None)
                if rec is None:
                    continue
                job = self._jobs[jid]
                job["state"] = "failed"
                job["error"] = f"{what}: {type(e).__name__}: {e}"
                job["done_ts"] = round(time.time(), 3)
                self._persist(job)

    def _scheduler(self) -> None:
        from dsi_tpu.parallel.shuffle import default_mesh
        from dsi_tpu.serve.pack import (PackedGrepScheduler,
                                        PackedWcScheduler)

        self._mesh = default_mesh(self.devices)
        self.packer = PackedWcScheduler(self._mesh, self.chunk_bytes,
                                        self.n_reduce)
        if self.pack_grep:
            self.grep_packer = PackedGrepScheduler(self._mesh,
                                                   self.chunk_bytes)
        if self.warm:
            self.packer.warm()
        self.ready.set()
        while not self._stop.is_set():
            with self._wake:
                self._admit()
                if len(self._queue):
                    self._evict_one()
                    self._admit()
                resident = dict(self._resident)
            worked = False
            # One packed step across every runnable wc lane.  A packer
            # error fails the participating jobs, never the daemon.
            # The step wall feeds every participant tenant's histogram
            # — the eviction policy's evidence.
            wc_lanes = [(jid, rec["lane"])
                        for jid, rec in resident.items()
                        if rec["kind"] == "wc" and rec["lane"].runnable]
            if wc_lanes:
                t0 = time.perf_counter()
                try:
                    confirmed = self.packer.step(
                        [ln for _, ln in wc_lanes])
                    wall = time.perf_counter() - t0
                    for ln in confirmed:
                        self._hist.record(ln.tenant, wall)
                    worked = bool(confirmed) or any(
                        not ln.runnable for _, ln in wc_lanes)
                except Exception as e:  # noqa: BLE001
                    self._fail_lanes(wc_lanes, e, "packed step")
                    worked = True
            # One packed grep step over ONE (pattern length, rung)
            # group — groups rotate across scheduler iterations.
            grep_lanes = [(jid, rec["lane"])
                          for jid, rec in resident.items()
                          if rec["kind"] == "grep"
                          and rec["lane"].runnable]
            if grep_lanes:
                t0 = time.perf_counter()
                try:
                    confirmed = self.grep_packer.step(
                        [ln for _, ln in grep_lanes])
                    wall = time.perf_counter() - t0
                    for ln in confirmed:
                        self._hist.record(ln.tenant, wall)
                    worked = worked or bool(confirmed) or any(
                        not ln.runnable for _, ln in grep_lanes)
                except Exception as e:  # noqa: BLE001
                    self._fail_lanes(grep_lanes, e, "packed grep step")
                    worked = True
            # A bounded slice of every step-object job — the same
            # ``advance_slice`` primitive the shard workers drive their
            # cursor-range shards with (parallel/stepobj.py).
            for jid, rec in resident.items():
                if rec["kind"] != "step":
                    continue
                step = rec["step"]
                t0 = time.perf_counter()
                try:
                    took = step.advance_slice(8)
                    rec["advanced"] += took
                    if took:
                        self._hist.record(self._jobs[jid]["tenant"],
                                          time.perf_counter() - t0)
                    worked = worked or took > 0
                except Exception as e:  # noqa: BLE001
                    with self._wake:
                        if self._resident.pop(jid, None) is not None:
                            job = self._jobs[jid]
                            job["state"] = "failed"
                            job["error"] = f"{type(e).__name__}: {e}"
                            job["done_ts"] = round(time.time(), 3)
                            self._persist(job)
                    worked = True
            # Retire finished runners: pop under the lock, finalize
            # outside it (the heavy half must not block the RPC plane).
            retired = []
            with self._wake:
                for jid, rec in list(self._resident.items()):
                    finished = (not rec["lane"].runnable
                                if rec["kind"] in ("wc", "grep")
                                else rec["step"].phase != "running")
                    if finished:
                        del self._resident[jid]
                        retired.append((jid, rec))
            for jid, rec in retired:
                self._finish_job(jid, rec)
                worked = True
            with self._wake:
                if not worked and not len(self._queue):
                    self._wake.wait(timeout=0.2)
        # Graceful stop: park every resident job so a restart resumes
        # from fresh chains instead of replaying from the last cadence.
        with self._wake:
            for jid, rec in list(self._resident.items()):
                job = self._jobs[jid]
                try:
                    if rec["kind"] in ("wc", "grep"):
                        rec["lane"].suspend()
                    else:
                        rec["step"].suspend()
                    job["state"] = "parked"
                except Exception as e:  # noqa: BLE001
                    job["state"] = "failed"
                    job["error"] = f"stop: {type(e).__name__}: {e}"
                    job["done_ts"] = round(time.time(), 3)
                self._persist(job)
            self._resident.clear()

    # ── lifecycle ──

    def start(self) -> "ServeDaemon":
        from dsi_tpu.obs import live as _live

        _live.register_section("serve tenants", self._statusz_section,
                               self._metrics_section)
        self._rpc.start()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout=timeout)

    def close(self) -> None:
        from dsi_tpu.obs import live as _live

        self.stop()
        self.join(timeout=60.0)
        self._rpc.close()
        _live.unregister_section("serve tenants")
