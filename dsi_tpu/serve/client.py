"""No-jax client library for the serving daemon (``mrsubmit``'s guts).

Every call is one framed-JSON RPC over the daemon's Unix socket
(``mr/rpc.py`` — dial per call, the 6.5840 idiom), so the client stays
import-light: submitting a job from a test, the bench's serve row, or a
shell never pays a jax init.

Backpressure (ISSUE 19): a shed or rate-limited submission comes back
as a TYPED error — ``error_type == "backpressure"`` with a
``retry_after_s`` hint — raised here as :class:`ServeBusy` so callers
can tell "the daemon is protecting itself, try later" from a real
rejection.  :func:`submit` optionally honors the hint itself with a
bounded, jittered retry loop (``retries``), which is what the soak's
thousands of submitting clients use.
"""

from __future__ import annotations

import os
import random
import time
from typing import Dict, List, Optional

from dsi_tpu.mr.rpc import CoordinatorGone, call
from dsi_tpu.replica.client import group_call


class ServeBusy(RuntimeError):
    """The daemon shed the request (queue full or tenant over its
    submit rate).  ``retry_after_s`` is the daemon's drain-proportional
    hint — retry after roughly that long (with jitter)."""

    def __init__(self, msg: str, retry_after_s: float = 0.5):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


def default_socket(spool: str) -> str:
    """The daemon's default control socket inside its spool."""
    return os.path.join(os.path.abspath(spool), "mrserve.sock")


def _call(socket_path: str, method: str, args: dict,
          timeout: float = 30.0) -> dict:
    if "," in socket_path:
        # A replica-group spec (mrserve --replicas): ride the
        # leader-tracking group dialer, which hides NotLeader redirects
        # and mid-election retries.  Backpressure still surfaces below.
        ok, reply = group_call(socket_path, method, args,
                               timeout=timeout)
    else:
        ok, reply = call(socket_path, method, args, timeout=timeout)
    if not ok or not isinstance(reply, dict):
        raise CoordinatorGone(f"mrserve RPC {method} failed at "
                              f"{socket_path}")
    if reply.get("error"):
        if reply.get("error_type") == "backpressure":
            raise ServeBusy(f"mrserve {method}: {reply['error']}",
                            reply.get("retry_after_s") or 0.5)
        raise RuntimeError(f"mrserve {method}: {reply['error']}")
    return reply


def ping(socket_path: str, timeout: float = 10.0) -> dict:
    return _call(socket_path, "Ping", {}, timeout=timeout)


def wait_ready(socket_path: str, timeout: float = 120.0,
               poll_s: float = 0.1) -> dict:
    """Block until the daemon's scheduler (and its warm) is up."""
    deadline = time.monotonic() + timeout
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            p = ping(socket_path)
            if p.get("ready"):
                return p
        except (CoordinatorGone, OSError) as e:
            last = e
        time.sleep(poll_s)
    raise TimeoutError(f"mrserve at {socket_path} not ready in "
                       f"{timeout}s (last: {last})")


def submit(socket_path: str, tenant: str, files: List[str],
           app: str = "wc", pattern: Optional[str] = None,
           n_reduce: Optional[int] = None,
           priority: Optional[int] = None, retries: int = 0,
           max_backoff_s: float = 5.0, sleep=time.sleep,
           rng=None) -> dict:
    """Submit one job; returns ``{"job_id", "out_dir"}`` (the daemon
    journals the job durably before acking).

    With ``retries`` > 0 a :class:`ServeBusy` answer is retried up to
    that many times, sleeping the daemon's hint scaled by a uniform
    [0.5, 1.5) jitter (clamped to ``max_backoff_s``) so a shed burst of
    clients doesn't re-arrive as the same burst.  ``sleep``/``rng`` are
    injectable for deterministic tests.  The final ServeBusy (or any
    other error) propagates."""
    args = {"tenant": tenant, "app": app,
            "files": [os.path.abspath(f) for f in files]}
    if pattern is not None:
        args["pattern"] = pattern
    if n_reduce is not None:
        args["n_reduce"] = int(n_reduce)
    if priority is not None:
        args["priority"] = int(priority)
    attempts = max(0, int(retries)) + 1
    for attempt in range(attempts):
        try:
            return _call(socket_path, "Submit", args)
        except ServeBusy as e:
            if attempt + 1 >= attempts:
                raise
            hint = max(0.05, e.retry_after_s)
            jitter = 0.5 + (rng() if rng is not None else random.random())
            sleep(min(max_backoff_s, hint * jitter))
    raise AssertionError("unreachable")  # the loop returns or raises


def status(socket_path: str, job_id: Optional[str] = None,
           tenant: Optional[str] = None) -> dict:
    args: dict = {}
    if job_id:
        args["job_id"] = job_id
    if tenant:
        args["tenant"] = tenant
    return _call(socket_path, "Status", args)


def wait(socket_path: str, job_ids: List[str], timeout: float = 300.0,
         poll_s: float = 0.1) -> Dict[str, dict]:
    """Poll until every job is done or failed; returns the final
    records.  Raises TimeoutError with the stragglers listed."""
    deadline = time.monotonic() + timeout
    done: Dict[str, dict] = {}
    while time.monotonic() < deadline:
        for jid in job_ids:
            if jid in done:
                continue
            job = status(socket_path, job_id=jid)["job"]
            if job["state"] in ("done", "failed"):
                done[jid] = job
        if len(done) == len(job_ids):
            return done
        time.sleep(poll_s)
    missing = [j for j in job_ids if j not in done]
    raise TimeoutError(f"jobs not finished in {timeout}s: {missing}")


def shutdown(socket_path: str, timeout: float = 10.0) -> dict:
    return _call(socket_path, "Shutdown", {}, timeout=timeout)
