"""Admission QoS primitives for the serving daemon: priority lanes,
token-bucket rate limits, and the typed backpressure they produce.

The daemon's control plane was first-come-first-served with an
unbounded queue: a chatty tenant could park a thousand jobs ahead of
everyone and the daemon would accept (and durably journal) submissions
it had no hope of running soon.  ISSUE 19 replaces that with an
explicit policy, kept here free of daemon state so every rule is a
deterministic unit test with an injected clock:

* :class:`PriorityQueue` — three strict FIFO lanes (0 = highest).  A
  higher-priority job always admits before a lower one; within a lane,
  submission order.  Strictness is deliberate: the anti-starvation
  valve is the daemon's step-quota eviction (a resident job parks after
  its quota and re-queues at the tail), not a probabilistic pick.
* :class:`TokenBucket` — per-tenant submit rate limiting.  ``take()``
  returns 0.0 on admit or the seconds until a token accrues — the
  retry-after hint the typed backpressure error carries to the client.

Both answers happen BEFORE the journal write: a shed submission leaves
no spool state, so load shedding never fabricates a "lost accepted
job" (the soak's zero-lost invariant counts accepted acks only).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

#: The priority lanes, highest first.  Three is enough to express
#: "interactive / default / batch" and keeps the /metrics queue-depth
#: series bounded.
PRIORITIES = (0, 1, 2)
DEFAULT_PRIORITY = 1


class PriorityQueue:
    """Strict-priority FIFO lanes over job ids (module docstring).

    Not thread-safe by itself — the daemon serializes access under its
    own lock, exactly as it did with the plain deque this replaces.
    """

    def __init__(self):
        self._lanes: Dict[int, deque] = {p: deque() for p in PRIORITIES}

    def __len__(self) -> int:
        return sum(len(d) for d in self._lanes.values())

    def __contains__(self, jid: str) -> bool:
        return any(jid in d for d in self._lanes.values())

    def __iter__(self):
        """Ids in pop order (priority, then FIFO) — the status surface
        and the daemon's parked-job scan."""
        for p in PRIORITIES:
            yield from self._lanes[p]

    def push(self, jid: str, priority: int = DEFAULT_PRIORITY) -> None:
        self._lanes[self._clamp(priority)].append(jid)

    def push_front(self, jid: str,
                   priority: int = DEFAULT_PRIORITY) -> None:
        """Head of the job's own lane — the "resume on the tenant's
        next submission" re-prioritization, which must not let a parked
        batch job cut ahead of the interactive lane."""
        self._lanes[self._clamp(priority)].appendleft(jid)

    def pop(self) -> Optional[str]:
        for p in PRIORITIES:
            if self._lanes[p]:
                return self._lanes[p].popleft()
        return None

    def remove(self, jid: str) -> bool:
        for d in self._lanes.values():
            try:
                d.remove(jid)
                return True
            except ValueError:
                continue
        return False

    def depths(self) -> Tuple[int, ...]:
        """Per-priority queue depths, lane order — the
        ``dsi_serve_queue_depth{priority=...}`` gauge's read side."""
        return tuple(len(self._lanes[p]) for p in PRIORITIES)

    @staticmethod
    def _clamp(priority) -> int:
        try:
            p = int(priority)
        except (TypeError, ValueError):
            return DEFAULT_PRIORITY
        return min(max(p, PRIORITIES[0]), PRIORITIES[-1])


class TokenBucket:
    """One tenant's submit-rate bucket: ``rate`` tokens/second, burst
    capacity ``burst``, lazily refilled from the injected monotonic
    ``clock`` (tests pin it; production uses ``time.monotonic``)."""

    def __init__(self, rate: float, burst: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._clock = clock
        self._tokens = float(self.burst)
        self._last = clock()
        self._lock = threading.Lock()

    def take(self) -> float:
        """0.0 and a consumed token on admit; else the seconds until
        one token accrues (the retry-after hint), nothing consumed."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last)
                               * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            if self.rate <= 0.0:
                return 60.0  # rate 0: effectively shut; a long hint
            return round((1.0 - self._tokens) / self.rate, 4)


#: Shed-hint clamp: clients neither stampede (floor) nor stall on a
#: transient spike (ceiling) — the same bounds the old linear rule used.
SHED_HINT_FLOOR_S = 0.2
SHED_HINT_CEIL_S = 5.0

#: Cold-start fallback slope, seconds of hint per queued job, used only
#: until the daemon has MEASURED its own drain rate.  5 ms/job was the
#: original hard-coded guess; it survives as the no-evidence default.
SHED_HINT_COLD_S_PER_JOB = 0.005


def shed_retry_after(queued: int, drained_jobs_per_sec: float,
                     floor_s: float = SHED_HINT_FLOOR_S,
                     ceil_s: float = SHED_HINT_CEIL_S) -> float:
    """The queue-full retry-after hint, from measured evidence.

    When the daemon knows how fast it actually drains jobs (the
    KeyedHistograms-backed completion-gap estimate,
    ``ServeDaemon._drain_jobs_per_sec``), the hint is the honest
    prediction ``queued / rate`` — a fast daemon under a burst hands
    out short hints, a daemon grinding through multi-GB jobs hands out
    the ceiling instead of inviting a 200 ms stampede.  With no
    evidence yet (fresh boot, nothing finished) the linear
    ``0.005 * queued`` guess stands in.  Clamped either way."""
    if drained_jobs_per_sec > 0.0:
        hint = queued / drained_jobs_per_sec
    else:
        hint = SHED_HINT_COLD_S_PER_JOB * queued
    return max(floor_s, min(ceil_s, hint))


def backpressure_reply(msg: str, retry_after_s: float) -> dict:
    """The one spelling of the typed backpressure RPC error — the
    client (``serve/client.py ServeBusy``) keys on ``error_type`` and
    honors the hint, so both sides must agree here."""
    return {"error": msg, "error_type": "backpressure",
            "retryable": True,
            "retry_after_s": round(max(0.0, retry_after_s), 4)}
