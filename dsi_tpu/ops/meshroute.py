"""On-device key routing for mesh-sharded device services.

The paper's shuffle is a partition function: a key belongs to reduce
task ``ihash(key) % NReduce`` (Dean & Ghemawat §3.1; ``mr/worker.go:76``
— bit-exact here as ``fnv32a(key) & 0x7fffffff``).  The SPMD job step
already runs that rule on device for its *per-step* exchange
(``parallel/shuffle.py``), but the persistent device services
(``dsi_tpu/device/``) historically accepted whatever placement the step
handed them: per-device state islands whose key ownership depended on
``n_reduce % n_dev`` accidents (grep's top-k candidates were not routed
at all — a line's counts lived wherever its chunk happened to land).

This module is the routing half of the mesh-sharded fold programs: one
place that computes, ON DEVICE, the owning shard of every packed row —
``ihash(key) % n_shards`` over the row's actual key bytes — and
exchanges rows over the mesh so each shard folds exactly the keys it
owns.  The fold programs (``device/table.py`` ``mesh_fold_*``,
``device/postings.py`` ``mesh_app_*``) call these helpers inside their
``shard_map`` bodies; the hash is ``ops.wordcount.fnv1a32_packed``, so
the device route agrees byte-for-byte with the host oracle
``mr.worker.ihash`` (the shard-routing property test pins this).

Routing contract, stated exactly:

* a row's key bytes are its ``kk`` big-endian uint32 lanes, hashed over
  the first ``len`` bytes (the lanes' packing rule,
  ``ops/wordcount.py``) — for word keys that IS the word's spelling;
  for opaque keys (grep's global line numbers: kk=2, len=8) it is the
  8-byte big-endian identity, which balances equally well;
* the owning shard is ``(fnv1a32(key) & 0x7fffffff) % n_shards``;
* rows flagged invalid are parked on the exchange's dump row and never
  leave their source device;
* the exchange preserves per-source row order within a destination (the
  all_to_all concatenates source blocks in device order), which is what
  keeps the postings buffer's per-word append order an invariant under
  re-routing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dsi_tpu.ops.wordcount import _PAD_KEY, fnv1a32_packed


def route_dest(keys: jax.Array, lens: jax.Array, valid: jax.Array, *,
               n_shards: int, park: int) -> jax.Array:
    """Owning shard per row: ``ihash(key) % n_shards`` for valid rows,
    ``park`` (the exchange's dump destination, = n_dev) otherwise.

    ``keys`` [rows, kk] uint32 big-endian lanes, ``lens`` [rows] int32
    key byte lengths, ``valid`` [rows] bool.  The hash is the
    reference-exact FNV-1a over the first ``len`` key bytes — the same
    ``ihash`` the host partitioner uses (``mr/worker.py``), so host and
    device can never disagree about ownership.
    """
    kk = keys.shape[1]
    h = fnv1a32_packed(keys, lens, 4 * kk)
    part = h & jnp.uint32(0x7FFFFFFF)
    dest = (part % jnp.uint32(n_shards)).astype(jnp.int32)
    return jnp.where(valid, dest, jnp.int32(park))


def exchange_rows(rows: jax.Array, dest: jax.Array, *, n_dev: int,
                  kk: int) -> jax.Array:
    """All-to-all one device's packed rows to their owning shards.

    ``rows`` [r, kk+p] uint32 (key lanes + payload), ``dest`` [r] int32
    with ``n_dev`` parking invalid rows.  Returns [n_dev*r, kk+p]: the
    rows this shard received, source blocks concatenated in device
    order, each block valid-prefix-then-pad (pad rows carry ``_PAD_KEY``
    key lanes and zero payload, so they sort last and fold as empty).
    Runs inside a ``shard_map`` body over the shared mesh axis.
    """
    from dsi_tpu.parallel.shuffle import shuffle_rows

    return shuffle_rows(rows, dest, n_dev=n_dev,
                        u_cap=int(rows.shape[0]), k=kk)


def compact_received(recv: jax.Array) -> tuple:
    """Compact an :func:`exchange_rows` result: real rows to the front,
    order preserved (stable sort on the pad bit), pad rows after.
    Returns ``(rows, n_valid)`` — the order-preserving prefix the
    postings buffer's append scatter consumes.
    """
    r = recv.shape[0]
    is_pad = (recv[:, 0] == jnp.uint32(_PAD_KEY)).astype(jnp.int32)
    order = jnp.argsort(is_pad, stable=True)
    n_valid = (r - jnp.sum(is_pad)).astype(jnp.int32)
    return recv[order], n_valid


def host_shard_of(word_bytes: bytes, n_shards: int) -> int:
    """The host oracle for :func:`route_dest` — ``mr.worker`` ihash over
    the key bytes, mod the shard count.  Tests pin device == host."""
    from dsi_tpu.mr.worker import fnv32a

    return (fnv32a(word_bytes) & 0x7FFFFFFF) % n_shards


def pack_host_rows(words, n_shards: int, kk: int):
    """Host-side packing of byte-string keys into the routed-row layout
    (big-endian uint32 lanes + length) plus the oracle shard of each —
    the property test's bridge between Python byte strings and the
    device routing program's inputs.  Returns (keys [n, kk] uint32,
    lens [n] int32, shards [n] int32)."""
    import numpy as np

    n = len(words)
    keys = np.zeros((n, kk), dtype=np.uint32)
    lens = np.zeros(n, dtype=np.int32)
    shards = np.zeros(n, dtype=np.int32)
    for i, w in enumerate(words):
        b = w.ljust(4 * kk, b"\x00")[:4 * kk]
        keys[i] = np.frombuffer(b, dtype=">u4").astype(np.uint32)
        lens[i] = len(w)
        shards[i] = host_shard_of(w, n_shards)
    return keys, lens, shards
