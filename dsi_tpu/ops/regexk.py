"""TPU grep kernel for character-class regex patterns.

``ops/grepk.py`` accelerates plain literals; this module widens the device
scope to the next regex tier (VERDICT r3 weakness #6): patterns that are a
fixed-length **sequence of byte classes** — literal characters, ``.``,
``[...]`` / ``[^...]`` classes with ranges, ``\\d``/``\\w``/``\\s``, escaped
literals — optionally anchored with a leading ``^`` or trailing ``$``
(the reference's own harness pattern ``[Tt]he``, ``test-mr.sh:47``, lands
exactly here).  Variable-length operators (``* + ? {} |``) and groups
still fall back to the host app; correctness never depends on the kernel
(``backends/tpu.py`` contract, same as every kernel in this package).

TPU-first shape: each pattern position compiles to a handful of
``lo <= byte <= hi`` range tests over the shifted chunk — static unroll,
vector compares only, no gathers, no scans — then the same
newline-cumsum + sorted ``segment_max`` line machinery as the literal
kernel.  The pattern is STATIC (baked into the compiled program and the
AOT cache key): a grep job runs one pattern over many splits, so one
compile serves the whole job.

Cross-line discipline: every class excludes ``\\n`` (byte 10) and ``\\0``
(padding), so a match window can never span lines or leak into padding —
the per-line ``re.search`` host semantics (``apps/grep.py:34``) are
preserved exactly; inputs containing NUL bytes route to the host.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import dsi_tpu.ops.grepk as _grepk_mod
import dsi_tpu.ops.wordcount as _wordcount_mod
from dsi_tpu.ops.grepk import (
    line_flags_from_match,
    lines_from_flags,
    retry_line_caps,
)
from dsi_tpu.ops.wordcount import _pad_pow2, _shift_left

# Ranges per pattern position beyond which the unrolled compare chain
# stops being a win (a pathological negated class alternates up to ~128
# ranges); and an overall pattern-length cap for the shift unroll.
_MAX_RANGES = 8
_MAX_PATTERN = 32

_ESCAPE_CLASSES = {
    "d": [(0x30, 0x39)],
    "w": [(0x30, 0x39), (0x41, 0x5A), (0x5F, 0x5F), (0x61, 0x7A)],
    # Python re's \s on str matches [ \t\n\v\f\r\x1c-\x1f] within ASCII;
    # \n is excluded here because lines are newline-split before matching.
    "s": [(0x09, 0x09), (0x0B, 0x0D), (0x1C, 0x1F), (0x20, 0x20)],
}


def _find_class_end(pat: str, start: int) -> int:
    """Index of the closing ']' of a class opened at ``start`` ('['),
    honoring backslash escapes (``[a\\]b]`` closes at the FINAL bracket);
    -1 when unterminated.  A ']' directly after '[' or '[^' is literal in
    re, which the caller's empty-body check rejects to the host path."""
    i = start + 1
    if pat[i:i + 1] == "^":
        i += 1
    while i < len(pat):
        if pat[i] == "\\":
            i += 2
        elif pat[i] == "]":
            return i
        else:
            i += 1
    return -1


def _compress(members: set) -> List[Tuple[int, int]]:
    """Sorted byte set -> minimal (lo, hi) range list."""
    out: List[Tuple[int, int]] = []
    for b in sorted(members):
        if out and b == out[-1][1] + 1:
            out[-1] = (out[-1][0], b)
        else:
            out.append((b, b))
    return out


#: Characters that cannot START an atom in any device tier: modifiers,
#: bounded reps, groups, stray anchors.  (Tier 4 consumes ``* + ?`` as
#: modifiers AFTER a valid atom and splits ``|`` before parsing, so one
#: set serves every tier — see ops/nfak.py.)
ATOM_REJECT = "*+?{}()|^$"


def atom_members(pat: str, i: int):
    """Parse one atom starting at ``pat[i]`` — ``.``, an escape, a
    ``[...]`` class, or a literal character — into its byte-member set.

    Returns ``(members, next_i)`` or None when the atom needs the host
    regex engine.  The SINGLE definition of atom/class semantics shared
    by the class tier (here) and the NFA tier (``ops/nfak.py``), so the
    tiers can never disagree on what a class means.  Callers reject
    ``ATOM_REJECT`` characters first.  Members are raw — callers
    subtract ``{0, 10}`` per their padding/newline discipline."""
    c = pat[i]
    if c == ".":
        return set(range(1, 256)) - {10}, i + 1
    if c == "\\":
        if i + 1 >= len(pat):
            return None
        e = pat[i + 1]
        if e in _ESCAPE_CLASSES:
            return ({b for lo, hi in _ESCAPE_CLASSES[e]
                     for b in range(lo, hi + 1)}, i + 2)
        if not e.isalnum():  # \. \[ \\ etc: escaped literal
            return {ord(e)}, i + 2
        return None  # \b \A \Z back-refs etc.: host
    if c == "[":
        j = _find_class_end(pat, i)
        if j == -1:
            return None
        body = pat[i + 1:j]
        negate = body.startswith("^")
        if negate:
            body = body[1:]
        members: set = set()
        k = 0
        while k < len(body):
            if body[k] == "\\" and k + 1 < len(body):
                e = body[k + 1]
                if e in _ESCAPE_CLASSES:
                    members |= {b for lo, hi in _ESCAPE_CLASSES[e]
                                for b in range(lo, hi + 1)}
                elif not e.isalnum():
                    members.add(ord(e))
                else:
                    return None
                k += 2
            elif k + 2 < len(body) and body[k + 1] == "-":
                lo, hi = ord(body[k]), ord(body[k + 2])
                if lo > hi:
                    return None
                members |= set(range(lo, hi + 1))
                k += 3
            else:
                members.add(ord(body[k]))
                k += 1
        if not members:
            return None
        if negate:
            members = set(range(1, 256)) - members
        return members, j + 1
    return {ord(c)}, i + 1


def parse_class_pattern(pat: str):
    """Parse the supported regex subset.

    Returns ``(ranges, anchor_start, anchor_end)`` where ``ranges`` is one
    tuple of ``(lo, hi)`` byte pairs per pattern position, or ``None``
    when the pattern needs the host regex engine.  Every position's class
    excludes bytes 0 and 10 (see module docstring).
    """
    if not pat or not all(0x01 <= ord(c) <= 0x7E for c in pat):
        return None
    anchor_start = pat.startswith("^")
    if anchor_start:
        pat = pat[1:]
    anchor_end = pat.endswith("$") and not pat.endswith("\\$")
    if anchor_end:
        pat = pat[:-1]
    if not pat:
        return None

    positions: List[Tuple[Tuple[int, int], ...]] = []
    i = 0
    while i < len(pat):
        if pat[i] in ATOM_REJECT:
            return None  # variable-length / group / stray anchor: host
        parsed = atom_members(pat, i)
        if parsed is None:
            return None
        members, i = parsed
        members -= {0, 10}
        if not members:
            return None  # class can only match padding/newline: host
        ranges = _compress(members)
        if len(ranges) > _MAX_RANGES:
            return None
        positions.append(tuple(ranges))

    if not positions or len(positions) > _MAX_PATTERN:
        return None
    return tuple(positions), anchor_start, anchor_end


def classgrep_kernel(chunk: jax.Array, *, ranges, anchor_start: bool,
                     anchor_end: bool, l_cap: int):
    """Match lines of ``chunk`` containing the class pattern.

    Same contract as ``grepk.grep_kernel``: returns (line_match [l_cap]
    i32 flags in line order, n_lines i32, overflow bool).
    """
    m = len(ranges)
    match = jnp.ones(chunk.shape[0], jnp.bool_)
    for j, rs in enumerate(ranges):
        c = _shift_left(chunk, j)
        pos_ok = jnp.zeros(chunk.shape[0], jnp.bool_)
        for lo, hi in rs:
            if lo == hi:
                pos_ok |= c == jnp.uint8(lo)
            else:
                pos_ok |= (c >= jnp.uint8(lo)) & (c <= jnp.uint8(hi))
        match &= pos_ok
    if anchor_start:
        prev = jnp.concatenate(
            [jnp.full((1,), 10, jnp.uint8), chunk[:-1]])
        match &= prev == jnp.uint8(10)
    if anchor_end:
        nxt = _shift_left(chunk, m)  # byte just past the window
        match &= (nxt == jnp.uint8(10)) | (nxt == jnp.uint8(0))
    return line_flags_from_match(chunk, match, l_cap)


# The AOT cache fingerprints these sources; _shift_left comes from the
# wordcount module and the line machinery from grepk, so edits there must
# invalidate stale executables.
classgrep_kernel._aot_code_deps = (_wordcount_mod, _grepk_mod)


def _classgrep_example_static(n: int, ranges, anchor_start: bool,
                              anchor_end: bool, l_cap: int):
    example = (jax.ShapeDtypeStruct((n,), np.uint8),)
    return example, {"ranges": ranges, "anchor_start": anchor_start,
                     "anchor_end": anchor_end, "l_cap": l_cap}


@functools.lru_cache(maxsize=64)
def _classgrep_compiled(n: int, ranges, anchor_start: bool,
                        anchor_end: bool, l_cap: int):
    from dsi_tpu.backends.aotcache import cached_compile

    example, static = _classgrep_example_static(n, ranges, anchor_start,
                                                anchor_end, l_cap)
    return cached_compile("classgrep_kernel", classgrep_kernel, example,
                          static=static)


def classgrep_rung_ready(n: int, ranges, anchor_start: bool,
                         anchor_end: bool, l_cap: int) -> bool:
    """Readiness probe for exactly the shape ``_classgrep_compiled``
    builds — shared with the alternation tier (``ops/altk.py``)."""
    from dsi_tpu.ops.grepk import device_ready

    example, static = _classgrep_example_static(n, ranges, anchor_start,
                                                anchor_end, l_cap)
    return device_ready("classgrep_kernel", classgrep_kernel, example,
                        static)


def classgrep_host_result(data: bytes, pattern: str) -> Optional[List[str]]:
    """Matching lines of ``data`` (split on '\\n', in order), or None when
    the pattern or data needs the host regex path.  Same retry discipline
    as ``grepk.grep_host_result``."""
    parsed = parse_class_pattern(pattern)
    if parsed is None:
        return None
    ranges, anchor_start, anchor_end = parsed
    if b"\x00" in data:
        return None  # NUL inside a line would disagree with host re
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        return None
    chunk = jnp.asarray(_pad_pow2(data))
    n = int(chunk.shape[0])
    line_match, nl = retry_line_caps(
        n, lambda l_cap: _classgrep_compiled(
            n, ranges, anchor_start, anchor_end, l_cap)(chunk),
        ready=lambda l_cap: classgrep_rung_ready(
            n, ranges, anchor_start, anchor_end, l_cap))
    if line_match is None:
        return None  # cold remote compile in-task: host serves this job
    return lines_from_flags(text, line_match, nl)
