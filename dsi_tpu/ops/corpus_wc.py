"""Whole-corpus word count: ONE device program, position-coded results.

This is the bench's fast path, redesigned for the measured realities of the
axon-tunneled chip (VERDICT r2 weakness #1): device compute is ~four orders
of magnitude faster than the host<->device wire, so the design minimizes
wire bytes and round trips, not FLOPs.

* **One program, one launch** — every input file is padded into fixed-size
  pieces which the program concatenates in HBM (zero padding separates
  files, so no token can straddle a file boundary); tokenize + sort +
  group + count runs over the whole corpus at once.  This replaces the
  reference's nMap independent map tasks + reduce merge
  (``mr/coordinator.go:152``, ``mr/worker.go:110-146``) with a single
  fused XLA program.
* **Uploads are pieced, with a runtime async/sync switch** — the healthy
  tunnel pipelines small transfers (~60-80 ms latency, bandwidth that only
  pieced/async transfers reach: each piece a separate ``device_put``
  dispatched before any sync), but the DEGRADED tunnel inverts this by
  >10x (2026-07-31: async 0.6 vs single-shot 5.8 MB/s — concurrent
  streams thrash the constrained link), so the piece transfer routes
  through ``ops/xfer.put_views`` honoring ``DSI_UPLOAD_MODE`` (async
  default; sync = one transfer in flight), which bench.py probes per run.
* **Downloads are position-coded** — the host already holds the corpus
  bytes, so the device never ships word spellings back.  Each unique word
  returns as ``(first_occurrence_position << 7 | byte_length, count)`` —
  8 bytes per unique word in ONE contiguous 1-D uint32 pull (including the
  overflow scalars, so there is exactly one D2H round trip).  The host
  slices the spelling out of its own corpus copy.  The round-2 path pulled
  full 131k-row capacity tables per file (~28 MB total); this pulls
  ~2 MB for the whole corpus.
* Tokens are maximal ASCII-letter runs — exactly Go's
  ``strings.FieldsFunc(contents, !unicode.IsLetter)`` on ASCII text
  (``mrapps/wc.go:23``); any byte >= 0x80 is detected on device and the
  caller falls back to the host path (same exactness contract as
  ``ops/wordcount.py``).

The program is compiled through the AOT executable cache
(``backends/aotcache.py``): the first process on a machine pays the XLA
compile, every later process loads the serialized executable in
milliseconds.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import dsi_tpu.ops.wordcount as _wordcount
from dsi_tpu.utils.jaxcompat import enable_x64
from dsi_tpu.ops.wordcount import (
    _PAD_KEY,
    build_lanes,
    exactness_retry,
    group_sorted,
    is_ascii_letter,
    pack_key_lanes,
    rung0_cap,
)

# pos<<7|len packing needs pos < 2**25: cap the padded corpus at 32 MiB per
# program.  (Bigger corpora use more pieces per program invocation or the
# streaming path, parallel/streaming.py.)
_POS_BITS = 25
_LEN_MASK = 0x7F

_FNV_OFFSET = np.uint32(0x811C9DC5)
_FNV_PRIME = np.uint32(0x01000193)


def corpus_kernel(*pieces, max_word_len: int = 16, u_cap: int = 1 << 18,
                  t_cap_frac: int = 4, grouper: str = "sort"):
    """Count every word of the concatenated pieces; emit position-coded rows.

    Returns ONE 1-D uint32 array of length ``2*u_cap + 4``:
    ``rows[u_cap, 2]`` flattened (``pos << 7 | len``, ``count``; with the
    sort grouper rows are in lexicographic word order, with the hash
    grouper in bucket order — the output writer sorts host-side either
    way; pad rows zero) followed by the scalars ``[n_unique, max_len,
    has_high, token_overflow]``.
    """
    import jax.numpy as jnp

    chunk = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
    return _corpus_core(chunk, max_word_len, u_cap, t_cap_frac, grouper)


def corpus_kernel_packed(*pieces_and_table, max_word_len: int = 16,
                         u_cap: int = 1 << 18, t_cap_frac: int = 4,
                         grouper: str = "sort"):
    """``corpus_kernel`` over a 6-bit transport encoding of the corpus.

    The host packs 4 corpus bytes into 3 wire bytes when the corpus uses
    <= 64 distinct byte values (ASCII text trivially does), cutting upload
    bytes by 25% — the upload is the measured end-to-end wall on this
    platform's tunnel.  Inputs: packed pieces (each ``3/4 * piece_size``
    bytes) plus the 64-entry code→byte table; first op on device is the
    exact inverse transform, so everything downstream of ``chunk`` is
    byte-identical to the unpacked path.
    """
    import jax.numpy as jnp

    *pieces, table = pieces_and_table
    pk = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
    b = pk.reshape(-1, 3).astype(jnp.uint32)
    v = (b[:, 0] << 16) | (b[:, 1] << 8) | b[:, 2]
    codes = jnp.stack([(v >> 18) & 63, (v >> 12) & 63,
                       (v >> 6) & 63, v & 63], axis=1).reshape(-1)
    # Table lookup as a 64-way select chain, NOT a gather: the selects fuse
    # into one elementwise pass over the array (a 16M-element gather from a
    # 64-entry table defeats fusion and measured 3x slower end-to-end).
    chunk = jnp.zeros_like(codes, dtype=jnp.uint8)
    for k in range(64):
        chunk = jnp.where(codes == k, table[k], chunk)
    return _corpus_core(chunk, max_word_len, u_cap, t_cap_frac, grouper)


def _corpus_core(chunk, max_word_len: int, u_cap: int, t_cap_frac: int,
                 grouper: str = "sort"):
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = chunk.shape[0]
    if n > 1 << _POS_BITS:
        raise ValueError(f"corpus_kernel caps at {1 << _POS_BITS} bytes")
    k = max_word_len // 4
    t_cap = n // t_cap_frac + 1

    idx = jnp.arange(n, dtype=jnp.int32)
    letter = is_ascii_letter(chunk)
    prev_letter = jnp.concatenate([jnp.zeros((1,), jnp.bool_), letter[:-1]])
    starts = letter & ~prev_letter
    n_tokens = jnp.sum(starts, dtype=jnp.int32)
    token_overflow = n_tokens > t_cap

    # Token length at every position: distance to next non-letter via one
    # log-depth reverse cumulative-min (no gathers; ops/wordcount.py idiom).
    m = jnp.where(letter, n, idx)
    next_nl = lax.associative_scan(jnp.minimum, m, reverse=True)
    length_all = (next_nl - idx).astype(jnp.int32)

    lanes = build_lanes(chunk, length_all, max_word_len)

    (start_pos,) = jnp.nonzero(starts, size=t_cap, fill_value=n - 1)
    valid = jnp.arange(t_cap, dtype=jnp.int32) < n_tokens
    lengths = jnp.where(valid, length_all[start_pos], 0)
    max_len = jnp.max(lengths, initial=0)
    packed_cols = tuple(
        jnp.where(valid, lane[start_pos], jnp.uint32(_PAD_KEY))
        for lane in lanes)
    # Position and length ride grouping as ONE pre-packed payload column
    # (pos << 7 | len — already the wire encoding).
    poslen_tok = jnp.where(
        valid,
        (start_pos.astype(jnp.uint32) << 7)
        | lengths.astype(jnp.uint32), 0)

    if grouper == "hash":
        # Scatter/segment grouping (ops/wordcount.py _hash_group): exact
        # via per-bucket lane verification + dirty-repair sort; the
        # first-occurrence poslen is the per-group MIN of the combined
        # column (length is group-invariant, so min == min position).
        from dsi_tpu.ops.wordcount import _hash_group, fnv1a32_packed

        fnv_t = fnv1a32_packed(jnp.stack(packed_cols, axis=1), lengths,
                               max_word_len)
        _, _, cnt_u, poslen_u, n_unique, group_of = _hash_group(
            packed_cols, lengths, valid, fnv_t, u_cap=u_cap,
            max_word_len=max_word_len, extra=poslen_tok)
        uvalid = jnp.arange(u_cap, dtype=jnp.int32) < n_unique
        poslen = jnp.where(uvalid, poslen_u, 0)
        totals = jnp.where(uvalid, cnt_u, 0)
        token_overflow = token_overflow | group_of
    else:
        # Stable sort over the key lanes packed pairwise into uint64s
        # (same lexicographic order, half the comparator keys —
        # wordcount.py pack_key_lanes): within a group of equal words the
        # original token order (ascending position) survives, so each
        # group's FIRST row carries the word's first occurrence position
        # (its length is group-invariant).
        with enable_x64(True):  # u64 operands need the scoped flag
            keys64 = pack_key_lanes(packed_cols)
            k64 = len(keys64)
            sorted_ops = lax.sort(keys64 + (poslen_tok,),
                                  num_keys=k64, is_stable=True)
            _, totals, upos, ovalid, n_unique = group_sorted(
                sorted_ops[:k64], jnp.ones(t_cap, jnp.int32), u_cap)
        poslen = jnp.where(ovalid, sorted_ops[k64][upos], 0)
    rows = jnp.stack([poslen, totals.astype(jnp.uint32)], axis=1)
    has_high = jnp.any(chunk >= 128)
    scalars = jnp.stack([
        n_unique.astype(jnp.uint32),
        max_len.astype(jnp.uint32),
        has_high.astype(jnp.uint32),
        token_overflow.astype(jnp.uint32)])
    return jnp.concatenate([rows.reshape(-1), scalars])


# The AOT cache fingerprints these modules' sources: editing the kernel or
# the shared helpers it calls invalidates stale executables automatically.
corpus_kernel._aot_code_deps = (_wordcount,)
corpus_kernel_packed._aot_code_deps = (_wordcount,)


def pack6_encode(buf: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """6-bit transport encoding: (packed_bytes [3n/4], code→byte table [64]),
    or None when the corpus uses more than 64 distinct byte values.
    ``len(buf)`` must be a multiple of 4 (piece sizes are powers of two)."""
    used = np.flatnonzero(np.bincount(buf, minlength=256))
    if len(used) > 64:
        return None
    table = np.zeros(64, dtype=np.uint8)
    table[:len(used)] = used.astype(np.uint8)
    lut = np.zeros(256, dtype=np.uint8)
    lut[used] = np.arange(len(used), dtype=np.uint8)
    c = lut[buf].astype(np.uint32).reshape(-1, 4)
    v = (c[:, 0] << 18) | (c[:, 1] << 12) | (c[:, 2] << 6) | c[:, 3]
    packed = np.stack([(v >> 16) & 255, (v >> 8) & 255, v & 255],
                      axis=1).astype(np.uint8).reshape(-1)
    return packed, table


def pack_pieces(raws: Sequence[bytes],
                piece_size: int = 1 << 21) -> Tuple[np.ndarray, int]:
    """Lay the files out as fixed-size zero-padded pieces.

    Returns (buf [n_pieces * piece_size] uint8, n_pieces).  A file larger
    than one piece is split at non-letter boundaries (no token straddles a
    split; same rule as ``parallel/shuffle.shard_text``); zero padding at
    each piece tail separates files.  Positions reported by the kernel index
    into exactly this buffer.
    """
    from dsi_tpu.parallel.shuffle import _is_letter_byte

    spans: List[bytes] = []
    for raw in raws:
        off = 0
        while len(raw) - off > piece_size - 1:
            cut = off + piece_size - 1
            while cut > off and _is_letter_byte(raw[cut - 1]) \
                    and _is_letter_byte(raw[cut]):
                cut -= 1
            if cut == off:  # one >2MB letter run: host path will handle it
                cut = off + piece_size - 1
            spans.append(raw[off:cut])
            off = cut
        spans.append(raw[off:])
    n_pieces = len(spans)
    buf = np.zeros(n_pieces * piece_size, dtype=np.uint8)
    for i, s in enumerate(spans):
        buf[i * piece_size:i * piece_size + len(s)] = np.frombuffer(
            s, dtype=np.uint8)
    return buf, n_pieces


class CorpusResult:
    """Position-coded result + the corpus buffer the positions index."""

    __slots__ = ("buf", "pos", "lens", "cnt")

    def __init__(self, buf: np.ndarray, pos: np.ndarray, lens: np.ndarray,
                 cnt: np.ndarray) -> None:
        self.buf = buf      # [N] uint8, W zero bytes of tail padding
        self.pos = pos      # [nu] int64 first-occurrence byte offsets
        self.lens = lens    # [nu] int64 word byte lengths
        self.cnt = cnt      # [nu] int64 counts; rows in lexicographic order

    def words(self) -> List[str]:
        b = self.buf.tobytes()
        return [b[p:p + l].decode("ascii")
                for p, l in zip(self.pos.tolist(), self.lens.tolist())]

    def to_dict(self, n_reduce: int = 10) -> Dict[str, Tuple[int, int]]:
        """{word: (count, reduce_partition)} — the contract of
        ``count_words_host_result`` for drop-in use."""
        parts = (self.ihashes() % np.uint32(n_reduce)).tolist()
        cnts = self.cnt.tolist()
        return {w: (cnts[i], parts[i])
                for i, w in enumerate(self.words())}

    def byte_matrix(self, width: int) -> np.ndarray:
        """[nu, width] uint8 word-byte matrix, zero past each length."""
        mat = self.buf[self.pos[:, None] + np.arange(width)]
        return np.where(np.arange(width) < self.lens[:, None], mat, 0)

    def ihashes(self, mat: np.ndarray | None = None) -> np.ndarray:
        """Vectorized reference ihash (fnv1a32 & 0x7fffffff,
        mr/worker.go:33-37) over all unique words at once.  Pass a
        pre-built ``byte_matrix`` to avoid materialising it twice."""
        if mat is None:
            mat = self.byte_matrix(int(self.lens.max(initial=1)))
        h = np.full(len(self.pos), _FNV_OFFSET, np.uint32)
        for j in range(mat.shape[1]):
            upd = (h ^ mat[:, j]) * _FNV_PRIME
            h = np.where(j < self.lens, upd, h)
        return h & np.uint32(0x7FFFFFFF)


def corpus_wordcount(raws: Sequence[bytes], *, piece_size: int | None = None,
                     max_word_len: int = 16, u_cap: int = 1 << 18,
                     use_aot: bool = True, pack6: bool = False,
                     grouper: str | None = None) -> Optional[CorpusResult]:
    """Exact whole-corpus counts, or None when the host path is needed
    (non-ASCII bytes or a word longer than 64 — same escape contract as
    ``count_words_host_result``).  Retries wider static shapes on overflow.

    ``pack6=True`` ships the corpus 6 bits per byte (25% fewer upload
    bytes — the upload is this platform's measured wall) when its alphabet
    fits in 64 symbols, transparently reverting to raw bytes when not.

    ``grouper`` (default: the platform-adaptive ``default_grouper``)
    picks the grouping stage; an unresolvable hash-grouper collision
    retries through the sort grouper, the always-exact last rung."""
    import jax

    buf, n_pieces, piece_size = _resolve_pieces(raws, piece_size)
    if n_pieces == 0:
        return CorpusResult(np.zeros(64, np.uint8), *(np.zeros(0, np.int64)
                                                      for _ in range(3)))
    if len(buf) > 1 << _POS_BITS:
        # Position coding needs pos < 2^25: beyond ~32 MiB per program the
        # caller must chunk the corpus (or use parallel/streaming.py) —
        # None routes there, same contract as the other escapes.
        return None
    n = len(buf)
    table = None
    if pack6:
        enc = pack6_encode(buf)
        if enc is None:
            pack6 = False
        else:
            wire, table = enc
    if pack6:
        wire_piece = piece_size * 3 // 4
    else:
        wire, wire_piece = buf, piece_size
    views = [wire[i * wire_piece:(i + 1) * wire_piece]
             for i in range(n_pieces)]
    if table is not None:
        views.append(table)

    from dsi_tpu.ops.wordcount import grouper_ladder

    if grouper is None:
        groupers = grouper_ladder()
    else:
        groupers = (grouper, "sort") if grouper != "sort" else ("sort",)

    def run(mwl: int, cap: int):
        # The shared overflow/retry discipline (exactness_retry) drives mwl
        # and cap; the token-buffer frac and grouper retries are local, as
        # in the other callers (wordcount, shuffle, tfidf).
        for g in groupers:
            for frac in (4, 2):  # exact token bound is n//2+1
                fn = _get_compiled(n_pieces, piece_size, mwl, cap,
                                   frac, use_aot, pack6, g)
                from dsi_tpu.ops import xfer  # host-side; NOT a kernel dep

                dev_args = xfer.put_views(views)  # DSI_UPLOAD_MODE knob
                out = np.asarray(fn(*dev_args))   # the ONE D2H round trip
                nu, max_len, has_high, tok_of = (int(x) for x in out[-4:])
                if not tok_of:
                    break
            if not tok_of:
                break

        def payload():
            rows = out[:-4].reshape(-1, 2)[:nu].astype(np.int64)
            return CorpusResult(np.concatenate([buf, np.zeros(64, np.uint8)]),
                                rows[:, 0] >> 7, rows[:, 0] & _LEN_MASK,
                                rows[:, 1])

        return bool(has_high), nu, max_len, payload

    payload = exactness_retry(run, n, max_word_len, u_cap)
    return None if payload is None else payload()


def _resolve_pieces(raws: Sequence[bytes], piece_size: int | None):
    """Shared piece derivation for the run path and the cache-existence
    probe — one definition, so the probe's key cannot drift from the key
    a real run compiles.  Default piece size: smallest power of two
    holding the largest file plus its separator byte, capped at 2 MiB —
    bigger files split into multiple pieces so uploads stay pieced/async
    (the tunnel's fast path)."""
    if piece_size is None:
        longest = max((len(r) for r in raws), default=1)
        piece_size = min(1 << 21, 1 << max(12, (longest + 1).bit_length()))
    buf, n_pieces = pack_pieces(raws, piece_size)
    return buf, n_pieces, piece_size


def _example_and_fn(n_pieces: int, piece_size: int, pack6: bool):
    import jax

    if pack6:
        example = tuple(
            jax.ShapeDtypeStruct((piece_size * 3 // 4,), np.uint8)
            for _ in range(n_pieces)) + (
            jax.ShapeDtypeStruct((64,), np.uint8),)
        return example, corpus_kernel_packed, "corpus_wc_p6"
    example = tuple(jax.ShapeDtypeStruct((piece_size,), np.uint8)
                    for _ in range(n_pieces))
    return example, corpus_kernel, "corpus_wc"


@functools.lru_cache(maxsize=64)
def _get_compiled(n_pieces: int, piece_size: int, mwl: int, cap: int,
                  frac: int, use_aot: bool, pack6: bool = False,
                  grouper: str = "sort"):
    static = {"max_word_len": mwl, "u_cap": cap, "t_cap_frac": frac}
    example, fn, name = _example_and_fn(n_pieces, piece_size, pack6)
    if grouper != "sort":  # sort keeps its historical, readable name
        static["grouper"] = grouper
        name += f"_g{grouper}"
    from dsi_tpu.backends.aotcache import cached_compile

    # use_aot=False still memoizes in-process and accounts compile time in
    # aotcache.stats; it only stops disk reads/writes.
    return cached_compile(name, fn, example, static=static,
                          persist=None if use_aot else False, x64=True)


def corpus_executable_persisted(raws: Sequence[bytes], *,
                                piece_size: int | None = None,
                                max_word_len: int = 16, u_cap: int = 1 << 18,
                                pack6: bool = False,
                                grouper: str | None = None) -> bool:
    """True when the rung-0 program ``corpus_wordcount(raws, pack6=...)``
    would run first is already in the persistent AOT cache — i.e. touching
    this transport is a millisecond load, not a multi-minute remote
    compile.  Mirrors corpus_wordcount's shape derivation exactly (same
    piece_size rule, same first (mwl, cap, frac=4) rung; the bench corpus
    resolves at rung 0, and on a cold machine rung 0 is the compile that
    dominates).  Escape cases where the program would not run at all
    (empty corpus, >2^25 positions, pack6 alphabet overflow) return False."""
    buf, n_pieces, piece_size = _resolve_pieces(raws, piece_size)
    if n_pieces == 0 or len(buf) > 1 << _POS_BITS:
        return False
    if pack6 and pack6_encode(buf) is None:
        return False
    example, fn, name = _example_and_fn(n_pieces, piece_size, pack6)
    static = {"max_word_len": max_word_len,
              "u_cap": rung0_cap(len(buf), u_cap),
              "t_cap_frac": 4}
    if grouper is None:
        from dsi_tpu.ops.wordcount import grouper_ladder

        grouper = grouper_ladder()[0]  # the program a run reaches first
    if grouper != "sort":
        static["grouper"] = grouper
        name += f"_g{grouper}"
    from dsi_tpu.backends.aotcache import is_persisted

    return is_persisted(name, fn, example, static=static)


def render_lines(mat: np.ndarray, lens: np.ndarray,
                 cnt: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Render ``"<word> <count>\\n"`` lines for every row, fully vectorized.

    Returns (buf [total_bytes] uint8, ends [nu] int64 — exclusive end offset
    of each row's line in ``buf``).  No per-row Python: word bytes come from
    one boolean-mask flatten of the byte matrix, count digits from one
    vectorized divmod grid (counts are int64; rows are word-count totals).
    """
    nu, width = mat.shape
    if nu == 0:
        return np.zeros(0, np.uint8), np.zeros(0, np.int64)
    c = np.maximum(cnt, 1).astype(np.int64)
    dlen = np.full(nu, 1, np.int64)
    p = np.int64(10)
    while True:  # digits(count): bounded by the corpus' total token count
        more = c >= p
        if not more.any():
            break
        dlen += more
        p *= 10
    max_d = int(dlen.max())

    total = lens + 1 + dlen + 1  # word, space, digits, newline
    ends = np.cumsum(total)
    starts = ends - total
    buf = np.zeros(int(ends[-1]), np.uint8)

    col = np.arange(width)
    wmask = col < lens[:, None]
    buf[(starts[:, None] + col)[wmask]] = mat[wmask]
    buf[starts + lens] = 32  # space

    dcol = np.arange(max_d)
    dmask = dcol < dlen[:, None]
    # Most-significant digit first: digit j = cnt // 10^(dlen-1-j) % 10.
    pow10 = np.power(np.int64(10), np.maximum(dlen[:, None] - 1 - dcol, 0))
    digits = (cnt.astype(np.int64)[:, None] // pow10) % 10
    buf[(starts[:, None] + 1 + lens[:, None] + dcol)[dmask]] = \
        (48 + digits[dmask]).astype(np.uint8)
    buf[ends - 1] = 10  # newline
    return buf, ends


def write_corpus_output(res: CorpusResult, n_reduce: int,
                        workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files straight from the position-coded table.

    Rows are first put in lexicographic word order host-side (ASCII byte
    order == Python ``sorted`` order on str; a no-op permutation for the
    sort grouper's already-ordered rows, required for the hash grouper's
    bucket-ordered rows), then a stable sort by partition leaves each
    partition's lines in the reference's within-file order
    (``mr/worker.go:124-146``).  Everything is vectorized numpy — this
    sits inside the bench's timed window (~0.3 s of Python loop before,
    ~30 ms now at 137k unique words).
    """
    from dsi_tpu.utils.atomicio import atomic_write

    width = int(res.lens.max(initial=1))
    mat = res.byte_matrix(width)  # built once: hashes + spellings below
    part = res.ihashes(mat) % np.uint32(n_reduce)

    worder = np.lexsort(tuple(mat[:, j] for j in range(width - 1, -1, -1)))
    mat = mat[worder]
    part = part[worder]
    res = CorpusResult(res.buf, res.pos[worder], res.lens[worder],
                       res.cnt[worder])

    order = np.argsort(part, kind="stable")
    buf, ends = render_lines(mat[order], res.lens[order], res.cnt[order])
    starts = np.concatenate([[0], ends[:-1]]) if len(ends) else ends
    # Partition boundaries in the reordered row space.
    counts = np.bincount(part, minlength=n_reduce)
    row_bounds = np.concatenate([[0], np.cumsum(counts)])

    paths = []
    for r in range(n_reduce):
        lo, hi = int(row_bounds[r]), int(row_bounds[r + 1])
        lo_b = int(starts[lo]) if lo < hi else 0
        hi_b = int(ends[hi - 1]) if lo < hi else 0
        path = os.path.join(workdir, f"mr-out-{r}")
        with atomic_write(path, mode="wb") as f:
            f.write(buf[lo_b:hi_b].tobytes())
        paths.append(path)
    return paths
