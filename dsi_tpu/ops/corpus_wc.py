"""Whole-corpus word count: ONE device program, position-coded results.

This is the bench's fast path, redesigned for the measured realities of the
axon-tunneled chip (VERDICT r2 weakness #1): device compute is ~four orders
of magnitude faster than the host<->device wire, so the design minimizes
wire bytes and round trips, not FLOPs.

* **One program, one launch** — every input file is padded into fixed-size
  pieces which the program concatenates in HBM (zero padding separates
  files, so no token can straddle a file boundary); tokenize + sort +
  group + count runs over the whole corpus at once.  This replaces the
  reference's nMap independent map tasks + reduce merge
  (``mr/coordinator.go:152``, ``mr/worker.go:110-146``) with a single
  fused XLA program.
* **Uploads are pieced and async** — the tunnel pipelines small transfers
  (~60-80 ms latency, bandwidth that only pieced/async transfers reach),
  so each piece is a separate ``device_put`` dispatched before any sync.
* **Downloads are position-coded** — the host already holds the corpus
  bytes, so the device never ships word spellings back.  Each unique word
  returns as ``(first_occurrence_position << 7 | byte_length, count)`` —
  8 bytes per unique word in ONE contiguous 1-D uint32 pull (including the
  overflow scalars, so there is exactly one D2H round trip).  The host
  slices the spelling out of its own corpus copy.  The round-2 path pulled
  full 131k-row capacity tables per file (~28 MB total); this pulls
  ~2 MB for the whole corpus.
* Tokens are maximal ASCII-letter runs — exactly Go's
  ``strings.FieldsFunc(contents, !unicode.IsLetter)`` on ASCII text
  (``mrapps/wc.go:23``); any byte >= 0x80 is detected on device and the
  caller falls back to the host path (same exactness contract as
  ``ops/wordcount.py``).

The program is compiled through the AOT executable cache
(``backends/aotcache.py``): the first process on a machine pays the XLA
compile, every later process loads the serialized executable in
milliseconds.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import dsi_tpu.ops.wordcount as _wordcount
from dsi_tpu.ops.wordcount import (
    _PAD_KEY,
    build_lanes,
    group_sorted,
    is_ascii_letter,
)

# pos<<7|len packing needs pos < 2**25: cap the padded corpus at 32 MiB per
# program.  (Bigger corpora use more pieces per program invocation or the
# streaming path, parallel/streaming.py.)
_POS_BITS = 25
_LEN_MASK = 0x7F

_FNV_OFFSET = np.uint32(0x811C9DC5)
_FNV_PRIME = np.uint32(0x01000193)


def corpus_kernel(*pieces, max_word_len: int = 16, u_cap: int = 1 << 18,
                  t_cap_frac: int = 4):
    """Count every word of the concatenated pieces; emit position-coded rows.

    Returns ONE 1-D uint32 array of length ``2*u_cap + 4``:
    ``rows[u_cap, 2]`` flattened (``pos << 7 | len``, ``count``; rows are in
    lexicographic word order, pad rows zero) followed by the scalars
    ``[n_unique, max_len, has_high, token_overflow]``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    chunk = jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]
    n = chunk.shape[0]
    if n > 1 << _POS_BITS:
        raise ValueError(f"corpus_kernel caps at {1 << _POS_BITS} bytes")
    k = max_word_len // 4
    t_cap = n // t_cap_frac + 1

    idx = jnp.arange(n, dtype=jnp.int32)
    letter = is_ascii_letter(chunk)
    prev_letter = jnp.concatenate([jnp.zeros((1,), jnp.bool_), letter[:-1]])
    starts = letter & ~prev_letter
    n_tokens = jnp.sum(starts, dtype=jnp.int32)
    token_overflow = n_tokens > t_cap

    # Token length at every position: distance to next non-letter via one
    # log-depth reverse cumulative-min (no gathers; ops/wordcount.py idiom).
    m = jnp.where(letter, n, idx)
    next_nl = lax.associative_scan(jnp.minimum, m, reverse=True)
    length_all = (next_nl - idx).astype(jnp.int32)

    lanes = build_lanes(chunk, length_all, max_word_len)

    (start_pos,) = jnp.nonzero(starts, size=t_cap, fill_value=n - 1)
    valid = jnp.arange(t_cap, dtype=jnp.int32) < n_tokens
    lengths = jnp.where(valid, length_all[start_pos], 0)
    max_len = jnp.max(lengths, initial=0)
    packed_cols = tuple(
        jnp.where(valid, lane[start_pos], jnp.uint32(_PAD_KEY))
        for lane in lanes)
    pos_payload = jnp.where(valid, start_pos, 0).astype(jnp.uint32)

    # Stable k-key sort: within a group of equal words the original token
    # order (ascending position) survives, so each group's FIRST row carries
    # the word's first occurrence position.
    sorted_ops = lax.sort(packed_cols + (lengths, pos_payload),
                          num_keys=k, is_stable=True)
    _, totals, upos, ovalid, n_unique = group_sorted(
        sorted_ops[:k], jnp.ones(t_cap, jnp.int32), u_cap)
    len_u = jnp.where(ovalid, sorted_ops[k][upos], 0).astype(jnp.uint32)
    pos_u = jnp.where(ovalid, sorted_ops[k + 1][upos], 0)

    poslen = (pos_u << 7) | len_u
    rows = jnp.stack([poslen, totals.astype(jnp.uint32)], axis=1)
    has_high = jnp.any(chunk >= 128)
    scalars = jnp.stack([
        n_unique.astype(jnp.uint32),
        max_len.astype(jnp.uint32),
        has_high.astype(jnp.uint32),
        token_overflow.astype(jnp.uint32)])
    return jnp.concatenate([rows.reshape(-1), scalars])


# The AOT cache fingerprints these modules' sources: editing the kernel or
# the shared helpers it calls invalidates stale executables automatically.
corpus_kernel._aot_code_deps = (_wordcount,)


def pack_pieces(raws: Sequence[bytes],
                piece_size: int = 1 << 21) -> Tuple[np.ndarray, int]:
    """Lay the files out as fixed-size zero-padded pieces.

    Returns (buf [n_pieces * piece_size] uint8, n_pieces).  A file larger
    than one piece is split at non-letter boundaries (no token straddles a
    split; same rule as ``parallel/shuffle.shard_text``); zero padding at
    each piece tail separates files.  Positions reported by the kernel index
    into exactly this buffer.
    """
    from dsi_tpu.parallel.shuffle import _is_letter_byte

    spans: List[bytes] = []
    for raw in raws:
        off = 0
        while len(raw) - off > piece_size - 1:
            cut = off + piece_size - 1
            while cut > off and _is_letter_byte(raw[cut - 1]) \
                    and _is_letter_byte(raw[cut]):
                cut -= 1
            if cut == off:  # one >2MB letter run: host path will handle it
                cut = off + piece_size - 1
            spans.append(raw[off:cut])
            off = cut
        spans.append(raw[off:])
    n_pieces = len(spans)
    buf = np.zeros(n_pieces * piece_size, dtype=np.uint8)
    for i, s in enumerate(spans):
        buf[i * piece_size:i * piece_size + len(s)] = np.frombuffer(
            s, dtype=np.uint8)
    return buf, n_pieces


class CorpusResult:
    """Position-coded result + the corpus buffer the positions index."""

    __slots__ = ("buf", "pos", "lens", "cnt")

    def __init__(self, buf: np.ndarray, pos: np.ndarray, lens: np.ndarray,
                 cnt: np.ndarray) -> None:
        self.buf = buf      # [N] uint8, W zero bytes of tail padding
        self.pos = pos      # [nu] int64 first-occurrence byte offsets
        self.lens = lens    # [nu] int64 word byte lengths
        self.cnt = cnt      # [nu] int64 counts; rows in lexicographic order

    def words(self) -> List[str]:
        b = self.buf.tobytes()
        return [b[p:p + l].decode("ascii")
                for p, l in zip(self.pos.tolist(), self.lens.tolist())]

    def to_dict(self, n_reduce: int = 10) -> Dict[str, Tuple[int, int]]:
        """{word: (count, reduce_partition)} — the contract of
        ``count_words_host_result`` for drop-in use."""
        parts = (self.ihashes() % np.uint32(n_reduce)).tolist()
        cnts = self.cnt.tolist()
        return {w: (cnts[i], parts[i])
                for i, w in enumerate(self.words())}

    def byte_matrix(self, width: int) -> np.ndarray:
        """[nu, width] uint8 word-byte matrix, zero past each length."""
        mat = self.buf[self.pos[:, None] + np.arange(width)]
        return np.where(np.arange(width) < self.lens[:, None], mat, 0)

    def ihashes(self) -> np.ndarray:
        """Vectorized reference ihash (fnv1a32 & 0x7fffffff,
        mr/worker.go:33-37) over all unique words at once."""
        width = int(self.lens.max(initial=1))
        mat = self.byte_matrix(width)
        h = np.full(len(self.pos), _FNV_OFFSET, np.uint32)
        for j in range(width):
            upd = (h ^ mat[:, j]) * _FNV_PRIME
            h = np.where(j < self.lens, upd, h)
        return h & np.uint32(0x7FFFFFFF)


def corpus_wordcount(raws: Sequence[bytes], *, piece_size: int | None = None,
                     max_word_len: int = 16, u_cap: int = 1 << 18,
                     use_aot: bool = True) -> Optional[CorpusResult]:
    """Exact whole-corpus counts, or None when the host path is needed
    (non-ASCII bytes or a word longer than 64 — same escape contract as
    ``count_words_host_result``).  Retries wider static shapes on overflow."""
    import jax

    if piece_size is None:
        # Smallest power of two holding the largest file plus its separator
        # byte, capped at 2 MiB — bigger files split into multiple pieces so
        # uploads stay pieced/async (the tunnel's fast path).
        longest = max((len(r) for r in raws), default=1)
        piece_size = min(1 << 21, 1 << max(12, (longest + 1).bit_length()))
    buf, n_pieces = pack_pieces(raws, piece_size)
    if n_pieces == 0:
        return CorpusResult(np.zeros(64, np.uint8), *(np.zeros(0, np.int64)
                                                      for _ in range(3)))
    if len(buf) > 1 << _POS_BITS:
        # Position coding needs pos < 2^25: beyond ~32 MiB per program the
        # caller must chunk the corpus (or use parallel/streaming.py) —
        # None routes there, same contract as the other escapes.
        return None
    n = len(buf)
    views = [buf[i * piece_size:(i + 1) * piece_size]
             for i in range(n_pieces)]

    mwl, cap, frac = max_word_len, u_cap, 4
    hard_cap = 1 << (n // 2).bit_length()
    while True:
        fn = _get_compiled(n_pieces, piece_size, mwl, min(cap, hard_cap),
                           frac, use_aot)
        dev_pieces = jax.device_put(views)       # async, pieced
        out = np.asarray(fn(*dev_pieces))        # the ONE D2H round trip
        nu, max_len, has_high, tok_of = (int(x) for x in out[-4:])
        if has_high:
            return None
        if tok_of and frac == 4:
            frac = 2  # exact bound is n//2+1 tokens
            continue
        if nu > min(cap, hard_cap):
            cap = min(cap, hard_cap) * 4
            continue
        if max_len > mwl:
            if mwl >= 64:
                return None  # >64-byte word: host path
            mwl = 64
            continue
        rows = out[:-4].reshape(-1, 2)[:nu].astype(np.int64)
        return CorpusResult(np.concatenate([buf, np.zeros(64, np.uint8)]),
                            rows[:, 0] >> 7, rows[:, 0] & _LEN_MASK,
                            rows[:, 1])


def _get_compiled(n_pieces: int, piece_size: int, mwl: int, cap: int,
                  frac: int, use_aot: bool):
    import jax

    static = {"max_word_len": mwl, "u_cap": cap, "t_cap_frac": frac}
    example = tuple(jax.ShapeDtypeStruct((piece_size,), np.uint8)
                    for _ in range(n_pieces))
    from dsi_tpu.backends.aotcache import cached_compile

    # persist=False (the DSI_AOT_CACHE=0 kill switch) still memoizes
    # in-process and accounts compile time in aotcache.stats; it only stops
    # disk reads/writes.
    persist = use_aot and os.environ.get("DSI_AOT_CACHE", "1") != "0"
    return cached_compile("corpus_wc", corpus_kernel, example,
                          static=static, persist=persist)


def write_corpus_output(res: CorpusResult, n_reduce: int,
                        workdir: str = ".") -> List[str]:
    """Materialise mr-out-<r> files straight from the position-coded table.

    Device rows arrive in lexicographic word order (the kernel's sort), and
    ASCII byte order == Python ``sorted`` order on str, so each partition's
    subsequence is already in the reference's within-file order
    (``mr/worker.go:124-146``) — no host sort at all.
    """
    from dsi_tpu.utils.atomicio import atomic_write

    part = res.ihashes() % np.uint32(n_reduce)
    width = int(res.lens.max(initial=1))
    blob = res.byte_matrix(width).tobytes()
    lens = res.lens.tolist()
    cnts = res.cnt.tolist()
    paths = []
    for r in range(n_reduce):
        idxs = np.nonzero(part == r)[0].tolist()
        lines = [
            f"{blob[i * width:i * width + lens[i]].decode('ascii')} {cnts[i]}\n"
            for i in idxs]
        path = os.path.join(workdir, f"mr-out-{r}")
        with atomic_write(path) as f:
            f.write("".join(lines))
        paths.append(path)
    return paths
