"""TPU grep tier 4: variable-length regex via log-depth NFA matrix scan.

Tiers 1-3 (``grepk``/``regexk``/``altk``) cover fixed-length patterns;
this tier runs the variable-length operators on device: ``* + ?``,
bounded reps ``{m}``/``{m,}``/``{m,n}`` (expanded into optional atoms),
their non-greedy forms (existence per line is greediness-independent),
and top-level alternations mixing them — ``ab*c``, ``[0-9]{2,4}``,
``colou?r``, ``^x.*?y$``.  Groups, backrefs, and nullable patterns
(which match every line) still fall back to the host app — correctness
never depends on a kernel (``backends/tpu.py`` contract).

TPU-first shape — no data-dependent control flow, log-depth, MXU-heavy:

1. The pattern compiles (host-side, Glushkov construction) to an NFA of
   S <= 48 states; every byte value becomes a boolean S x S transition
   matrix, assembled into a ``[256, S, S]`` table.
2. Matching a chunk is then an associative product of per-byte matrices
   over the boolean semiring.  The kernel computes per-block transition
   matrices with a K-step batched-matmul scan, an exclusive
   ``lax.associative_scan`` product across blocks (log depth), and a
   vmapped K-step vector re-walk that emits a per-position "matched"
   latch bit — turned into per-line flags by the same newline-cumsum +
   ``segment_max`` machinery as every other grep tier.
3. The table and start vector are program ARGUMENTS, not constants: one
   compiled executable (per chunk-size/state-bucket/l_cap) serves EVERY
   pattern — warm it once on the chip and all variable-length patterns
   accelerate, which matters on a platform where each remote compile
   costs minutes (BASELINE.md).

Line discipline: content classes exclude ``\\n``/``\\0``, so no match
window spans lines or padding; the line-end bytes reset all NFA states
to the line-start states, and the absorbing "matched" latch survives to
the line's last position where ``segment_max`` picks it up.  Inputs
containing NUL route to the host (NUL acts as a line-end here but not
in ``re``), same as ``regexk``.
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import dsi_tpu.ops.grepk as _grepk_mod
from dsi_tpu.ops.altk import split_top_level
from dsi_tpu.ops.grepk import (
    device_ready,
    line_cap_rungs,
    line_flags_from_match,
    lines_from_flags,
    retry_line_caps,
)
from dsi_tpu.ops.regexk import ATOM_REJECT, atom_members
from dsi_tpu.ops.wordcount import _pad_pow2

#: State-count buckets (compiled-program granularity): S = 4 fixed
#: states + one per pattern atom, rounded up to the smallest bucket.
_S_BUCKETS = (16, 32, 48)
#: Fixed state indices: 0 = always-alive sentinel, 1 = line-start state,
#: atoms at 2..., end-latch = bucket-2, latch = bucket-1 (_build_table).
_S_ANY, _S_LINE = 0, 1
#: Bytes that end a line for the automaton: newline and the chunk's
#: zero padding.
_LINE_END = (0, 10)


class _Atom:
    __slots__ = ("bitmap", "nullable", "repeat")

    def __init__(self, bitmap: np.ndarray, mod: str):
        self.bitmap = bitmap            # [256] bool, False at 0 and 10
        self.nullable = mod in ("?", "*")   # NOT `mod in "?*"`: '' is a
        self.repeat = mod in ("+", "*")     # substring of every string


def _parse_branch(branch: str):
    """One alternation branch -> (atoms, anchor_start, anchor_end) or
    None.  Anchors bind per branch, exactly re's loosest-| semantics."""
    if not branch or not all(0x01 <= ord(c) <= 0x7E for c in branch):
        return None
    a_start = branch.startswith("^")
    if a_start:
        branch = branch[1:]
    a_end = branch.endswith("$") and not branch.endswith("\\$")
    if a_end:
        branch = branch[:-1]
    if not branch:
        return None
    atoms: List[_Atom] = []
    i = 0
    while i < len(branch):
        if branch[i] in ATOM_REJECT and branch[i] not in "{}":
            # Groups, stray anchors — and a modifier with no atom before
            # it ('*a'), which re rejects as an error.  Braces fall
            # through: a lone '}' is a literal in re, and '{' is handled
            # just below.
            return None
        if branch[i] == "{":
            peek, pi = _parse_bounded_rep(branch, i)
            if peek is not None or pi < 0:
                # A VALID rep shape with nothing to repeat: re errors
                # ("nothing to repeat") — host owns it.  An invalid body
                # ('{2,x}') is a literal brace in re; fall through and
                # parse it as a literal atom.
                return None
        parsed = atom_members(branch, i)
        if parsed is None:
            return None
        members, i = parsed
        mod = ""
        reps: Optional[Tuple[int, int]] = None  # (min, max); max<0 = inf
        if i < len(branch) and branch[i] in "*+?":
            mod = branch[i]
            i += 1
        elif i < len(branch) and branch[i] == "{":
            reps, i = _parse_bounded_rep(branch, i)
            if reps is None and i < 0:
                return None  # malformed in a way re also rejects
            if reps is not None and max(reps) > _S_BUCKETS[-1]:
                # Reject oversized counts BEFORE the expansion loop: the
                # parse runs in every worker task on every platform, and
                # 'a{2000000000}' must fail in microseconds, not expand.
                return None
        if (mod or reps is not None) and i < len(branch) \
                and branch[i] == "?":
            # Non-greedy (*? +? ?? {m,n}?): greediness affects WHICH
            # match is found, never WHETHER one exists, and per-line
            # flags only need existence — greedy-equivalent here.
            i += 1
        if (mod or reps is not None) and i < len(branch) \
                and branch[i] in "*+?":
            return None  # stacked modifiers: host
        members = members - {0, 10}
        if not members and mod not in ("?", "*") and (
                reps is None or reps[0] > 0):
            return None  # required atom can only match padding/newline
        bitmap = np.zeros(256, bool)
        bitmap[list(members)] = True
        if reps is None:
            atoms.append(_Atom(bitmap, mod))
        else:
            # X{m,n} expands to m required copies + (n-m) optional ones;
            # X{m,} to m copies with the last one repeating.  The atom
            # budget (state bucket) naturally bounds the expansion.
            lo, hi = reps
            for _ in range(lo):
                atoms.append(_Atom(bitmap, ""))
            if hi < 0:
                if lo == 0:
                    atoms.append(_Atom(bitmap, "*"))
                else:
                    atoms[-1] = _Atom(bitmap, "+")
            else:
                for _ in range(hi - lo):
                    atoms.append(_Atom(bitmap, "?"))
        if len(atoms) > _S_BUCKETS[-1]:
            return None  # expansion exceeds the largest state bucket
    if all(a.nullable for a in atoms):
        return None  # nullable pattern matches EVERY line: host owns it
    return atoms, a_start, a_end


def _parse_bounded_rep(branch: str, i: int):
    """Parse ``{m}``, ``{m,}``, or ``{m,n}`` at ``branch[i]``.

    Returns ``((lo, hi), next_i)`` with ``hi == -1`` for unbounded, or
    ``(None, i)`` when the brace is not a valid bounded rep (re then
    treats it as a literal '{' — the caller re-parses it as an atom), or
    ``(None, -1)`` for ``{m,n}`` with ``m > n`` (re raises: host)."""
    j = branch.find("}", i)
    if j == -1:
        return None, i
    body = branch[i + 1:j]
    parts = body.split(",")
    if not all(p.isdigit() or p == "" for p in parts) or len(parts) > 2:
        return None, i
    if len(parts) == 1:
        if not parts[0]:
            return None, i  # bare '{}' is a literal brace pair in re
        lo = hi = int(parts[0])
    else:
        # re treats '{,n}' as the quantifier {0,n} (and '{,}' as {0,})
        # on every supported interpreter — "omitting m specifies a lower
        # bound of zero" has been documented re behavior since long
        # before 3.10 (verified against re/_parser.py's brace parse).
        lo = int(parts[0]) if parts[0] else 0
        hi = -1 if parts[1] == "" else int(parts[1])
    if hi >= 0 and lo > hi:
        return None, -1
    return (lo, hi), j + 1


def parse_nfa_pattern(pat: str):
    """Full pattern -> (branches, n_atoms) or None, where each branch is
    (atoms, anchor_start, anchor_end)."""
    raw = split_top_level(pat)
    if raw is None:
        return None
    branches = []
    total = 0
    for b in raw:
        parsed = _parse_branch(b)
        if parsed is None:
            return None
        branches.append(parsed)
        total += len(parsed[0])
    if total + 4 > _S_BUCKETS[-1]:
        return None  # pattern too wide for the largest state bucket
    return branches, total


def _bucket(n_atoms: int) -> int:
    need = n_atoms + 4
    for s in _S_BUCKETS:
        if need <= s:
            return s
    raise AssertionError("parse_nfa_pattern admitted an oversized pattern")


def _build_table(branches, n_atoms: int) -> Tuple[np.ndarray, np.ndarray]:
    """Glushkov NFA -> ([256, S, S] float32 transition table, [S] float32
    start vector).  Row-vector convention: v' = v @ M[byte]."""
    S = _bucket(n_atoms)
    latch = S - 1       # persisting: set mid-line, dies at newline
    end_latch = S - 2   # one-position: set BY a line-end byte for $
    M = np.zeros((256, S, S), np.float32)
    content = np.ones(256, bool)
    content[list(_LINE_END)] = False

    # Fixed machinery: the sentinel is always alive; the line-start state
    # is entered (from the sentinel) by every line-end byte; the latch
    # survives every byte except newline (padding keeps the final line's
    # verdict alive for segment_max).
    M[:, _S_ANY, _S_ANY] = 1.0
    for b in _LINE_END:
        M[b, _S_ANY, _S_LINE] = 1.0
    M[content, latch, latch] = 1.0
    M[0, latch, latch] = 1.0

    pos = 2  # first atom state index
    for atoms, a_start, a_end in branches:
        idx = list(range(pos, pos + len(atoms)))
        pos += len(atoms)

        def successors(i: int) -> List[int]:
            out = []
            if atoms[i].repeat:
                out.append(i)
            j = i + 1
            while j < len(atoms):
                out.append(j)
                if not atoms[j].nullable:
                    break
                j += 1
            return out

        firsts = []
        for j, a in enumerate(atoms):
            firsts.append(j)
            if not a.nullable:
                break
        lasts = []
        for j in range(len(atoms) - 1, -1, -1):
            lasts.append(j)
            if not atoms[j].nullable:
                break
        last_set = set(lasts)

        # Start edges: anchored branches begin only at line starts;
        # unanchored also from the always-alive sentinel (match can
        # start anywhere).
        srcs = [_S_LINE] if a_start else [_S_ANY, _S_LINE]
        edges = [(s, j) for s in srcs for j in firsts]
        edges += [(idx[i], j) for i in range(len(atoms))
                  for j in successors(i)]
        for src, j in edges:
            bm = atoms[j].bitmap
            M[bm, src, idx[j]] = 1.0
            if j in last_set and not a_end:
                # Entering an accepting position completes a match.
                M[bm, src, latch] = 1.0
        if a_end:
            # $-anchored: the match completes only when a line-end byte
            # arrives while an accepting position is active.  It must
            # set the ONE-POSITION end-latch, not the persisting latch:
            # a latch born at the newline would survive through (and
            # falsely flag) the entire NEXT line, since the persisting
            # latch only dies at newlines.
            for j in last_set:
                for b in _LINE_END:
                    M[b, idx[j], end_latch] = 1.0

    v0 = np.zeros(S, np.float32)
    v0[_S_ANY] = 1.0
    v0[_S_LINE] = 1.0
    return M, v0


def nfa_kernel(chunk: jax.Array, table: jax.Array, v0: jax.Array, *,
               s_bucket: int, block: int, l_cap: int):
    """Match lines of ``chunk`` against the NFA in ``table``.

    Returns (line_match [l_cap] i32 in line order, n_lines i32,
    overflow bool) — the shared tier contract.  ``table``/``v0`` are
    runtime arguments: the compiled program is pattern-independent.
    """
    n = chunk.shape[0]
    k = min(block, n)
    nb = n // k
    cols = chunk.reshape(nb, k).T.astype(jnp.int32)  # [k, nb]
    latch_idx = s_bucket - 1

    # 1: per-block transition matrices (K-step batched-matmul scan over
    # the boolean semiring; f32 matmul + threshold keeps it exact — row
    # sums are bounded by S, far under f32 integer precision).
    eye = jnp.broadcast_to(jnp.eye(s_bucket, dtype=jnp.float32),
                           (nb, s_bucket, s_bucket))

    def bstep(B, col):
        Mb = table[col]                       # [nb, S, S]
        return (jnp.matmul(B, Mb) > 0).astype(jnp.float32), None

    B, _ = jax.lax.scan(bstep, eye, cols)

    # 2: exclusive prefix product across blocks (log depth).
    P = jax.lax.associative_scan(
        lambda a, b: (jnp.matmul(a, b) > 0).astype(jnp.float32), B, axis=0)
    entry = jnp.concatenate([eye[:1], P[:-1]], axis=0)   # [nb, S, S]
    u = (jnp.einsum("s,bst->bt", v0, entry) > 0).astype(jnp.float32)

    # 3: vector re-walk per block, all blocks in parallel, emitting the
    # per-position latch bit.
    def vstep(v, col):
        Mb = table[col]
        v2 = (jnp.einsum("bs,bst->bt", v, Mb) > 0).astype(jnp.float32)
        # Either latch flavor flags the position: persisting (S-1, set
        # mid-line) or one-position end-latch (S-2, set at line ends).
        return v2, jnp.maximum(v2[:, latch_idx], v2[:, latch_idx - 1])

    _, latch = jax.lax.scan(vstep, u, cols)              # [k, nb]
    mask = latch.T.reshape(n) > 0
    return line_flags_from_match(chunk, mask, l_cap)


# The traced program uses only grepk's line machinery; regexk/altk/
# wordcount contribute HOST-side parsing and padding whose effects reach
# the program through its runtime arguments and shape key, so hashing
# them would only cause spurious multi-minute recompiles of the shared
# pattern-independent executable.
nfa_kernel._aot_code_deps = (_grepk_mod,)


def _nfa_example_static(n: int, s_bucket: int, block: int, l_cap: int):
    sds = jax.ShapeDtypeStruct
    example = (sds((n,), jnp.uint8),
               sds((256, s_bucket, s_bucket), jnp.float32),
               sds((s_bucket,), jnp.float32))
    return example, {"s_bucket": s_bucket, "block": block, "l_cap": l_cap}


@functools.lru_cache(maxsize=64)
def _nfa_compiled(n: int, s_bucket: int, block: int, l_cap: int):
    from dsi_tpu.backends.aotcache import cached_compile

    example, static = _nfa_example_static(n, s_bucket, block, l_cap)
    return cached_compile(f"nfagrep_s{s_bucket}", nfa_kernel, example,
                          static=static)


def _device_ready(n: int, s_bucket: int, block: int, l_cap: int) -> bool:
    """Readiness probe for exactly the shape ``_nfa_compiled`` builds
    (shared rung-gate discipline: ``grepk.device_ready``)."""
    example, static = _nfa_example_static(n, s_bucket, block, l_cap)
    return device_ready(f"nfagrep_s{s_bucket}", nfa_kernel, example,
                        static)


#: In-process view of the persisted calibration table (loaded once; a
#: calibration updates both).
_cost_cache: dict = {}
_cost_loaded = False


def _cost_path() -> str:
    from dsi_tpu.backends.aotcache import cache_dir

    return os.path.join(cache_dir(), "nfa_cost.json")


def _load_costs() -> dict:
    global _cost_loaded
    if not _cost_loaded:
        import json

        try:
            with open(_cost_path()) as f:
                _cost_cache.update(json.load(f))
        except (OSError, ValueError):
            pass
        _cost_loaded = True
    return _cost_cache


def _save_cost(key: str, entry: dict) -> None:
    import json

    costs = _load_costs()
    costs[key] = entry
    tmp = _cost_path() + f".tmp{os.getpid()}"
    try:
        # dsicheck: allow[raw-write] calibration cost cache:
        # temp+rename for atomicity, no fsync — a lost entry just
        # re-measures, and _save_cost already swallows OSError because
        # persistence here is an optimization, never a failure
        with open(tmp, "w") as f:
            json.dump(costs, f, indent=1)
        os.replace(tmp, _cost_path())
    except OSError:
        pass  # cost persistence is an optimization, never a failure


def _cost_key(s_bucket: int) -> str:
    import hashlib

    from dsi_tpu.backends.aotcache import _platform_fingerprint

    fp = hashlib.sha256(_platform_fingerprint().encode()).hexdigest()[:8]
    return f"{jax.devices()[0].platform}-{fp}|s{s_bucket}"


#: Representative calibration pattern per state bucket (must parse into
#: that bucket: atoms + 4 rounded up — see _bucket).
_CAL_PATTERNS = {16: "qu+ick|dogs?$", 32: "a{5,20}b", 48: "a{20,40}b"}


def _cal_text(n_lines: int = 4000) -> bytes:
    lines = []
    for i in range(n_lines):
        lines.append(f"the quick{'k' * (i % 3)} brown fox jumped over "
                     f"line {'x' * (i % 17)} with dog{'s' * (i % 2)} and "
                     f"{'a' * (i % 31)}b tokens".encode())
    return b"\n".join(lines)


def calibrate_tier4(s_bucket: int, quick: bool = False) -> dict:
    """Measure host ``re`` vs the NFA kernel once for this (platform,
    state bucket) and persist the result beside the AOT cache.  On an
    accelerator this COMPILES the kernel if it is not warm — call it
    only where that is acceptable (warm_kernels does, under
    DSI_NFA_COLD_OK; the CPU backend compiles in seconds).

    ``quick=True`` is the inline-dispatch variant (see
    :func:`tier4_preferred`): an ~8x smaller corpus and a single timing
    rep, bounding the cold-task cost on a contended box well under the
    coordinator's 10 s presumed-dead requeue threshold (ADVICE r5
    item 2).  The persisted entry is marked ``{"quick": true}``; a later
    warm-time full calibration simply overwrites it."""
    import re as _re
    import time

    pat = _CAL_PATTERNS[s_bucket]
    data = _cal_text(500 if quick else 4000)
    text = data.decode()
    rx = _re.compile(pat)

    def best(f, reps=1 if quick else 3):
        out = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            out.append(time.perf_counter() - t0)
        return min(out)

    host_s = best(lambda: [ln for ln in text.split("\n") if rx.search(ln)])

    branches, n_atoms = parse_nfa_pattern(pat)
    assert _bucket(n_atoms) == s_bucket, (pat, _bucket(n_atoms))
    table_np, v0_np = _build_table(branches, n_atoms)
    chunk = jnp.asarray(_pad_pow2(data))
    n = int(chunk.shape[0])
    block = min(256, n)
    l_cap = line_cap_rungs(n)[0]
    table = jnp.asarray(table_np)
    v0 = jnp.asarray(v0_np)
    fn = _nfa_compiled(n, s_bucket, block, l_cap)

    def kernel():
        jax.block_until_ready(fn(chunk, table, v0))

    kernel()  # warm (load or compile) outside the timed reps
    kern_s = best(kernel)

    mb = len(data) / 1e6
    entry = {"host_mbps": round(mb / host_s, 3),
             "kernel_mbps": round(mb / kern_s, 3)}
    if quick:
        entry["quick"] = True  # lower-fidelity entry; warm-time overwrites
    _save_cost(_cost_key(s_bucket), entry)
    return entry


def tier4_preferred(s_bucket: int) -> Optional[bool]:
    """Should an eligible variable-length pattern run on the kernel?

    ``DSI_NFA_DISPATCH=device|host`` pins the answer.  Otherwise the
    persisted calibration for this (platform, bucket) decides; with no
    measurement, the CPU backend calibrates on the spot with the BOUNDED
    quick variant (small corpus, one rep — a cold worker task must stay
    far inside the coordinator's 10 s presumed-dead requeue window even
    on a contended box; ADVICE r5 item 2) and an accelerator answers
    False — device dispatch stays opt-in until warm_kernels proves it on
    the chip (VERDICT r4 weakness #3: the S^3-work kernel measured ~10x
    slower than host ``re`` on CPU, and nothing gated dispatch on that
    fact).  warm_kernels' later full calibration replaces the quick
    entry."""
    pin = os.environ.get("DSI_NFA_DISPATCH")
    if pin in ("device", "host"):
        return pin == "device"
    entry = _load_costs().get(_cost_key(s_bucket))
    if entry is None:
        if jax.devices()[0].platform != "cpu":
            return False
        entry = calibrate_tier4(s_bucket, quick=True)
    return entry["kernel_mbps"] > entry["host_mbps"]


def nfagrep_host_result(data: bytes, pattern: str) -> Optional[List[str]]:
    """Matching lines of ``data`` (split on '\\n', in order), or None
    when the pattern or data needs the host regex path.  Same retry
    discipline as the other tiers."""
    parsed = parse_nfa_pattern(pattern)
    if parsed is None:
        return None
    if b"\x00" in data:
        return None  # NUL inside a line would disagree with host re
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        return None
    branches, n_atoms = parsed
    if not tier4_preferred(_bucket(n_atoms)):
        return None  # measured slower than host re here: host serves it
    table_np, v0_np = _build_table(branches, n_atoms)
    s_bucket = table_np.shape[1]
    # _pad_pow2 guarantees >= 1 trailing zero — the line-end byte the
    # $ latch and final-line handling depend on.
    chunk_np = _pad_pow2(data)
    n = len(chunk_np)
    block = min(256, n)
    # Per-RUNG readiness (ADVICE r4) via the shared gated retry
    # (grepk.retry_line_caps): the escalation rung is a separately
    # compiled shape, and an ungated escalation would cold-compile
    # inside a worker task.  Device uploads happen lazily on the first
    # rung that actually runs, so a not-ready refusal stays device-free.
    dev = {}

    def run(l_cap: int):
        if not dev:
            dev["chunk"] = jnp.asarray(chunk_np)
            dev["table"] = jnp.asarray(table_np)
            dev["v0"] = jnp.asarray(v0_np)
        return _nfa_compiled(n, s_bucket, block, l_cap)(
            dev["chunk"], dev["table"], dev["v0"])

    line_match, nl = retry_line_caps(
        n, run, ready=lambda l_cap: _device_ready(n, s_bucket, block, l_cap))
    if line_match is None:
        return None  # cold remote compile in-task: host serves this job
    return lines_from_flags(text, line_match, nl)
