"""TPU grep tier 3: top-level alternation of fixed-length branches.

Widens the device scope one more step past ``ops/regexk.py`` (VERDICT r3
weakness #6): a pattern that is a top-level ``|``-alternation whose every
branch is itself device-eligible — a plain literal (``ops/grepk.py``) or a
fixed-length class pattern (``ops/regexk.py``) — runs as one kernel pass
PER BRANCH with the per-line flags OR-ed on device.  ``the|and``,
``[Cc]at|[Dd]og``, ``^\\d\\d|total`` all land here; variable-length
operators, groups, or an ineligible branch still fall back to the host app
(``backends/tpu.py`` contract: correctness never depends on a kernel).

Python ``re`` semantics hold exactly: alternation binds loosest, so
``re.search(a|b, line)`` is ``search(a) or search(b)`` per line, i.e. the
elementwise max of the branches' line-flag vectors; per-branch anchors
(``^a|b$`` parses as ``(^a)|(b$)``) are handled by each branch's own
parser.  No new kernels and no new AOT entries beyond the branch programs
themselves — an alternation of already-warmed branch shapes reuses their
cached executables as-is.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from dsi_tpu.ops.grepk import (
    _grep_jit,
    is_literal_pattern,
    lines_from_flags,
    retry_line_caps,
)
from dsi_tpu.ops.regexk import _classgrep_compiled, parse_class_pattern
from dsi_tpu.ops.wordcount import _pad_pow2


def split_top_level(pat: str) -> Optional[List[str]]:
    """Split ``pat`` on top-level ``|`` (escape-aware; ``|`` inside a
    ``[...]`` class is a literal) into branches, in order and without
    dedup.  None on an unterminated class or any empty branch (``a|`` —
    the empty regex matches every line; host handles it).  A pattern
    with no top-level ``|`` returns a single-element list.  Shared with
    the NFA tier (``ops/nfak.py``), which accepts single branches."""
    branches, cur, in_class, i = [], [], False, 0
    while i < len(pat):
        c = pat[i]
        if c == "\\" and i + 1 < len(pat):
            cur += [c, pat[i + 1]]
            i += 2
            continue
        if c == "[" and not in_class:
            in_class = True
        elif c == "]" and in_class:
            in_class = False
        elif c == "|" and not in_class:
            branches.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    branches.append("".join(cur))
    if in_class or any(not b for b in branches):
        return None
    return branches


def split_alternation(pat: str) -> Optional[List[str]]:
    """Split ``pat`` on top-level ``|`` into >= 2 non-empty branches, or
    None when it isn't a plain alternation.  Duplicate branches add
    kernel passes but never change the OR, so they are removed; a
    pattern that collapses to one distinct branch ('a|a') is not a real
    alternation — tiers 1/2 or the host own it, keeping the >= 2
    contract exact for callers."""
    branches = split_top_level(pat)
    if branches is None:
        return None
    branches = list(dict.fromkeys(branches))
    if len(branches) < 2:
        return None
    return branches


def _branch_flags(chunk, n_data: int, n_host_lines: int, branch: str,
                  l_cap: int):
    """(line_match, n_lines, overflow) for one branch at one rung —
    literal branches via the shifted-compare kernel, class branches via
    the range-compare kernel.  A literal longer than the DATA (not the
    padded chunk: padding is zeros, unmatchable by printable literals)
    cannot match; its flags are zero without compiling a dead kernel."""
    if is_literal_pattern(branch):
        if len(branch) > n_data:
            return (jnp.zeros(l_cap, jnp.int32), jnp.int32(n_host_lines),
                    jnp.bool_(n_host_lines > l_cap))
        pat = jnp.asarray(
            np.frombuffer(branch.encode("ascii"), dtype=np.uint8))
        return _grep_jit(chunk, pat, l_cap=l_cap)
    ranges, anchor_start, anchor_end = parse_class_pattern(branch)
    return _classgrep_compiled(int(chunk.shape[0]), ranges, anchor_start,
                               anchor_end, l_cap)(chunk)


def altgrep_host_result(data: bytes, pattern: str) -> Optional[List[str]]:
    """Matching lines of ``data`` (split on '\\n', in order), or None when
    the pattern or data needs the host regex path.  Same retry discipline
    as the single-branch tiers (``retry_line_caps``), applied to all
    branches per rung so the flag vectors share one ``l_cap``."""
    branches = split_alternation(pattern)
    if branches is None:
        return None
    any_class = False
    for b in branches:
        if is_literal_pattern(b):
            continue
        if parse_class_pattern(b) is None:
            return None  # branch outside both device tiers
        any_class = True
    if any_class and b"\x00" in data:
        return None  # NUL inside a line would disagree with host re
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        return None
    n_host_lines = data.count(b"\n") + 1
    chunk = jnp.asarray(_pad_pow2(data))
    n = int(chunk.shape[0])

    def run(l_cap: int):
        total, n_lines, overflow = None, None, None
        for b in branches:
            lm, nl, of = _branch_flags(chunk, len(data), n_host_lines, b,
                                       l_cap)
            total = lm if total is None else jnp.maximum(total, lm)
            n_lines, overflow = nl, of  # chunk-derived: same every branch
        return total, n_lines, overflow

    def ready(l_cap: int) -> bool:
        # Every branch's compiled shape must be a warm load at this rung
        # (grepk.device_ready discipline).
        from dsi_tpu.ops.grepk import grep_rung_ready
        from dsi_tpu.ops.regexk import classgrep_rung_ready

        for b in branches:
            if is_literal_pattern(b):
                if len(b) > len(data):
                    continue  # dead branch: no kernel is compiled for it
                if not grep_rung_ready(n, len(b), l_cap):
                    return False
            else:
                ranges, a_s, a_e = parse_class_pattern(b)
                if not classgrep_rung_ready(n, ranges, a_s, a_e, l_cap):
                    return False
        return True

    line_match, nl = retry_line_caps(n, run, ready=ready)
    if line_match is None:
        return None  # cold remote compile in-task: host serves this job
    return lines_from_flags(text, line_match, nl)
