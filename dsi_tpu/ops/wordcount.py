"""TPU word-count kernel: tokenize + group + count, one fused XLA program.

This is the device replacement for the reference's map-side hot path
(``mrapps/wc.go:21-34`` tokenization, ``mr/worker.go:74-78`` bucketing) and
the reduce-side sort/group/count (``mr/worker.go:123-146``), re-designed for
the TPU execution model rather than translated:

* the whole file chunk lives in HBM as one ``uint8`` vector; every step is a
  vectorized op over it (no scalar loops, no dynamic shapes),
* tokens are *maximal runs of ASCII letters* — on ASCII text this is exactly
  Go's ``strings.FieldsFunc(contents, !unicode.IsLetter)`` (``wc.go:23``);
  any byte >= 0x80 is detected and reported so the caller can fall back to
  the host path, keeping Unicode parity without polluting the kernel,
* grouping is by **exact word bytes**, not by hash: each token's first
  ``max_word_len`` bytes are packed big-endian into ``max_word_len/4``
  ``uint32`` lanes and grouped with a multi-key lexicographic ``lax.sort`` +
  segment-sum — no collision risk, and the packed keys double as the exact
  word bytes for host-side detokenization (SURVEY.md §7 hard part 1),
* the partition hash is FNV-1a 32-bit, bit-identical to the reference's
  ``ihash`` (``mr/worker.go:33-37``), computed on-device per *unique* word.

TPU-shaped design decisions (what makes this fast, not just correct):

* **no random byte-gathers**: the packed key lanes are built for every
  position at once from shifted copies of the chunk (pure elementwise
  shifts/ors — HBM-bandwidth bound), instead of gathering ``[tokens, 16]``
  individual bytes, which XLA lowers to millions of scalar loads on TPU;
* **token lengths without a gather**: distance-to-next-non-letter for all
  positions via one reverse ``lax.associative_scan`` (log-depth cumulative
  min), so a token's length is just ``next_nonletter[i] - i``;
* **small sort buffer**: tokens are compacted to ``n // t_cap_frac + 1``
  slots (a token needs ≥ 1 letter + a separator ⇒ ``n//2+1`` is the hard
  bound; real text is ≥ 4 bytes/token, so the default frac=4 buffer is 2×
  smaller and the sort — the kernel's dominant cost — 2× cheaper).  If a
  pathological input overflows the compact buffer the kernel reports it and
  the wrapper retries at the exact ``n//2+1`` bound.

All shapes are static.  Overflow (words longer than ``max_word_len``, more
uniques than ``u_cap``, more tokens than the compact buffer, non-ASCII
bytes) is detected exactly and surfaced as scalars; the host wrapper retries
with a bigger kernel or falls back to the host implementation
(``exactness_retry``), so the result is always exact.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dsi_tpu.utils.jaxcompat import enable_x64, x64_scoped

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193
_PAD_KEY = 0xFFFFFFFF  # sorts after every real word (ASCII first byte < 0x80)


def is_ascii_letter(b: jax.Array) -> jax.Array:
    """[A-Za-z] mask over uint8 bytes (== unicode.IsLetter on ASCII)."""
    return ((b >= 65) & (b <= 90)) | ((b >= 97) & (b <= 122))


def _shift_left(x: jax.Array, s: int) -> jax.Array:
    """x shifted left by s positions, zero-filled: out[i] = x[i+s]."""
    if s == 0:
        return x
    if s >= x.shape[0]:
        return jnp.zeros_like(x)
    return jnp.concatenate([x[s:], jnp.zeros((s,), x.dtype)])


def _byte_mask(keep: jax.Array) -> jax.Array:
    """uint32 mask keeping the first ``keep`` (0..4) big-endian bytes."""
    return jnp.where(
        keep >= 4, jnp.uint32(0xFFFFFFFF),
        jnp.where(keep == 3, jnp.uint32(0xFFFFFF00),
                  jnp.where(keep == 2, jnp.uint32(0xFFFF0000),
                            jnp.where(keep == 1, jnp.uint32(0xFF000000),
                                      jnp.uint32(0)))))


def build_lanes(chunk: jax.Array, length_all: jax.Array, max_word_len: int):
    """Per-position packed key lanes from shifted chunk copies (no gathers).

    lane_j[i] = big-endian uint32 of bytes chunk[i+4j .. i+4j+3], zero-masked
    past the token length at i.  Big-endian packing keeps uint32 order ==
    bytewise order and makes host detokenization one ``.tobytes()``.
    """
    c = chunk.astype(jnp.uint32)
    b32 = ((c << 24) | (_shift_left(c, 1) << 16)
           | (_shift_left(c, 2) << 8) | _shift_left(c, 3))
    lanes = []
    for j in range(max_word_len // 4):
        keep = jnp.clip(length_all - 4 * j, 0, 4)
        lanes.append(_shift_left(b32, 4 * j) & _byte_mask(keep))
    return lanes


def fnv1a32_packed(packed: jax.Array, lengths: jax.Array,
                   max_word_len: int) -> jax.Array:
    """FNV-1a 32-bit over the packed word bytes — bit-exact Go hash/fnv.New32a
    (mr/worker.go:33-37).  Unrolled over the static max_word_len."""
    h = jnp.full(packed.shape[:1], _FNV_OFFSET, jnp.uint32)
    for j in range(max_word_len):
        b = (packed[:, j // 4] >> ((3 - (j % 4)) * 8)) & jnp.uint32(0xFF)
        h = jnp.where(j < lengths, (h ^ b) * jnp.uint32(_FNV_PRIME), h)
    return h


def pack_key_lanes(cols: tuple) -> tuple:
    """Pack uint32 key lanes pairwise into uint64 keys (lane j is the
    high word, lane j+1 the low), preserving lexicographic order with
    half the sort operands and comparator keys — measured ~2x faster in
    XLA's CPU sort, and never slower on TPU (fewer tuple elements per
    comparator).  A missing odd tail lane is filled with the PAD
    constant: order-neutral for real rows (a constant low word) and it
    keeps pad rows at uint64-max so PAD still sorts last and
    ``group_sorted``'s max-value pad detection holds.

    uint64 exists only under the x64 flag; the scoped ``jax.enable_x64``
    context makes these ops real 64-bit without flipping the global
    default (which would change dtype inference package-wide)."""
    out = []
    with enable_x64(True):
        for j in range(0, len(cols), 2):
            hi = cols[j].astype(jnp.uint64) << 32
            lo = (cols[j + 1] if j + 1 < len(cols)
                  else jnp.full_like(cols[j], _PAD_KEY)).astype(jnp.uint64)
            out.append(hi | lo)
    return tuple(out)


# A pad row packs to all-ones in every uint64 column (see pack_key_lanes).
_PAD_KEY64 = 0xFFFFFFFFFFFFFFFF


def unpack_key_lanes(cols64, k: int) -> tuple:
    """Inverse of :func:`pack_key_lanes`: k uint32 lanes back out of the
    packed uint64 columns."""
    out = []
    with enable_x64(True):
        for j in range(k):
            w = cols64[j // 2]
            out.append(((w >> 32) if j % 2 == 0 else w).astype(jnp.uint32))
    return tuple(out)


def unpack_key_rows(rows64: jax.Array, k: int) -> jax.Array:
    """[n, ceil(k/2)] packed uint64 key rows -> [n, k] uint32 lane rows —
    the shared unpack-and-restack step after a packed sort+group."""
    cols = unpack_key_lanes(
        tuple(rows64[:, j] for j in range(rows64.shape[1])), k)
    return jnp.stack(cols, axis=1)


def group_sorted(skeys_cols: tuple, counts: jax.Array, out_cap: int):
    """Group adjacent equal rows of lexicographically sorted key columns.

    The shared reduce idiom (run-boundary detect + segment-sum + compact)
    used by the single-chunk kernel and by the sharded all_to_all merge
    (parallel/shuffle.py).  ``skeys_cols``: k sorted unsigned key columns
    (uint32 lanes or uint64 packed lane pairs), PAD rows last — a pad row
    is all-ones in every lane, i.e. the dtype's max in every column;
    ``counts``: per-row counts to sum within each group.

    Returns (keys2d [t,k], totals [out_cap], upos [out_cap], ovalid
    [out_cap], n_unique) — callers gather their payloads at ``upos`` and
    mask with ``ovalid``.
    """
    t = skeys_cols[0].shape[0]
    k = len(skeys_cols)
    dtype = skeys_cols[0].dtype
    with enable_x64(True):  # 64-bit constants need the scoped flag
        pad = jnp.array(jnp.iinfo(dtype).max, dtype)  # _PAD_KEY for u32
        keys = jnp.stack(skeys_cols, axis=1)
        valid = skeys_cols[0] != pad
        prev = jnp.concatenate(
            [jnp.full((1, k), pad, dtype), keys[:-1]], axis=0)
    is_new = jnp.any(keys != prev, axis=1) & valid
    n_unique = jnp.sum(is_new, dtype=jnp.int32)
    uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    totals = jax.ops.segment_sum(
        jnp.where(valid, counts, 0), jnp.where(valid, uid, out_cap),
        num_segments=out_cap + 1, indices_are_sorted=True)[:out_cap]
    (upos,) = jnp.nonzero(is_new, size=out_cap, fill_value=t - 1)
    # Callers run this under the scoped x64 flag (u64 packed keys), where
    # nonzero yields int64 — pin indices to int32 so they don't drag
    # 64-bit promotion into the caller's non-x64 ops.
    upos = upos.astype(jnp.int32)
    ovalid = jnp.arange(out_cap, dtype=jnp.int32) < n_unique
    return keys, totals, upos, ovalid, n_unique


def _hash_group(packed_cols: tuple, lengths: jax.Array, valid: jax.Array,
                fnv_t: jax.Array, *, u_cap: int, max_word_len: int,
                extra=None):
    """Group identical tokens WITHOUT the big sort: scatter tokens into
    fnv-addressed buckets and verify each bucket holds exactly one
    distinct word (segment-min == segment-max over every packed key
    lane).  Tokens from buckets that fail the check (distinct words
    sharing low hash bits — a few hundred per MiB of real text) are
    compacted into a small fixed buffer and grouped by the exact
    lexicographic sort, so the result is exact regardless of hash
    behavior; only if the dirty set overflows its buffer (pathological
    input) does ``group_overflow`` make the caller re-run the whole
    chunk through the sort grouper.

    Motivation (measured, BASELINE.md round 5): at 1 MiB/4 tokens the
    2xu64-key ``lax.sort`` costs ~99 ms on this CPU while the segment-op
    group + t_cap/8 repair sort costs ~50 ms — the big sort is the
    kernel's dominant cost and this halves it.  The sort grouper remains
    the default for accelerator platforms (TPU scatter characteristics
    differ; switch there only with on-chip evidence).

    ``extra``, when given, is a per-token uint32 payload reduced by MIN
    within each group (the corpus kernel's first-occurrence position
    coding) and returned as a fifth table.

    Returns (keys64_u tuple [u_cap] per lane, len_u, cnt_u, extra_u or
    None, n_unique, group_overflow).
    """
    t_cap = lengths.shape[0]
    # ~1x t_cap buckets, power of two (the index is a low-bits mask):
    # measured on this CPU, the halved segment arrays beat the doubled
    # (still tiny) dirty fraction.  d_cap absorbs the worst realistic
    # dirty set — a hot word ("the" ~6% of English tokens, i.e. about
    # t_cap/4 x 0.24) landing in a dirty bucket — with the
    # group_overflow escape for pathological inputs.
    n_buckets = 1 << max(10, int(t_cap).bit_length() - 1)
    d_cap = max(1 << 8, t_cap // 16)
    keys64 = pack_key_lanes(packed_cols)
    k64 = len(keys64)

    # Level 1: bucket by the (reference-exact) fnv1a hash's low bits.
    idx1 = jnp.where(valid, (fnv_t & jnp.uint32(n_buckets - 1))
                     .astype(jnp.int32), n_buckets)
    tot1 = jax.ops.segment_sum(
        jnp.where(valid, 1, 0), idx1, num_segments=n_buckets + 1)[:n_buckets]
    len1 = jax.ops.segment_max(
        jnp.where(valid, lengths, 0), idx1,
        num_segments=n_buckets + 1)[:n_buckets]
    ex1 = None
    if extra is not None:
        ex1 = jax.ops.segment_min(
            jnp.where(valid, extra, jnp.uint32(0xFFFFFFFF)), idx1,
            num_segments=n_buckets + 1)[:n_buckets]
    keys1 = []
    with enable_x64(True):
        dirty = jnp.zeros(n_buckets, jnp.bool_)
        for kcol in keys64:
            mn = jax.ops.segment_min(
                kcol, idx1, num_segments=n_buckets + 1)[:n_buckets]
            mx = jax.ops.segment_max(
                kcol, idx1, num_segments=n_buckets + 1)[:n_buckets]
            dirty |= mn != mx
            keys1.append(mx)
    occ1 = tot1 > 0
    dirty &= occ1

    # Dirty repair: compact the (few) tokens of dirty buckets and group
    # them with the exact sort — small static buffer, zero collision
    # risk, no retry unless it overflows.
    in_dirty = valid & dirty[jnp.clip(idx1, 0, n_buckets - 1)]
    n_dirty_tokens = jnp.sum(in_dirty, dtype=jnp.int32)
    group_overflow = n_dirty_tokens > d_cap
    (dpos,) = jnp.nonzero(in_dirty, size=d_cap, fill_value=0)
    dvalid = jnp.arange(d_cap, dtype=jnp.int32) < n_dirty_tokens
    dlen = jnp.where(dvalid, lengths[dpos], 0)
    with enable_x64(True):
        dkeys = tuple(jnp.where(dvalid, kcol[dpos], jnp.uint64(_PAD_KEY64))
                      for kcol in keys64)
        if extra is None:
            sorted_ops = lax.sort(dkeys + (dlen,), num_keys=k64)
            dsex = None
        else:
            dex = jnp.where(dvalid, extra[dpos], jnp.uint32(0xFFFFFFFF))
            # extra rides as an additional SORT KEY (not a group key):
            # within a word's run rows order ascending by it, so the
            # run's first row carries the group minimum.
            sorted_ops = lax.sort(dkeys + (dex, dlen), num_keys=k64 + 1)
            dsex = sorted_ops[k64]
        dgk, dtot, dupos, dovalid, n_du = group_sorted(
            sorted_ops[:k64], jnp.ones(d_cap, jnp.int32), u_cap)
        dslens = sorted_ops[-1]

    # Assemble: clean level-1 buckets first, dirty-repair uniques after.
    clean1 = occ1 & ~dirty
    n_clean1 = jnp.sum(clean1, dtype=jnp.int32)
    n_unique = n_clean1 + n_du
    (cpos1,) = jnp.nonzero(clean1, size=u_cap, fill_value=n_buckets - 1)
    v1 = jnp.arange(u_cap, dtype=jnp.int32) < n_clean1
    dst2 = jnp.where(dovalid, jnp.arange(u_cap, dtype=jnp.int32) + n_clean1,
                     u_cap)

    with enable_x64(True):
        out_keys = []
        for j in range(k64):
            # A clean bucket's segment-max IS its one word's lane value.
            col = jnp.where(v1, keys1[j][cpos1], jnp.uint64(0))
            col = col.at[dst2].set(
                jnp.where(dovalid, dgk[dupos, j], jnp.uint64(0)),
                mode="drop")
            out_keys.append(col)
    len_u = jnp.where(v1, len1[cpos1], 0)
    len_u = len_u.at[dst2].set(
        jnp.where(dovalid, dslens[dupos], 0).astype(len_u.dtype),
        mode="drop")
    cnt_u = jnp.where(v1, tot1[cpos1], 0)
    cnt_u = cnt_u.at[dst2].set(jnp.where(dovalid, dtot, 0), mode="drop")
    ex_u = None
    if extra is not None:
        ex_u = jnp.where(v1, ex1[cpos1], jnp.uint32(0))
        ex_u = ex_u.at[dst2].set(
            jnp.where(dovalid, dsex[dupos], jnp.uint32(0)), mode="drop")
    return tuple(out_keys), len_u, cnt_u, ex_u, n_unique, group_overflow


def tokenize_group_core(chunk: jax.Array, *, max_word_len: int = 16,
                        u_cap: int = 1 << 17, t_cap_frac: int = 4,
                        grouper: str = "sort"):
    """Exact unique-word counts over one uint8 chunk (zero-padded tail).

    Returns (packed_u [u_cap, K] uint32, len_u [u_cap] i32, cnt_u [u_cap]
    i32, fnv_u [u_cap] u32, n_unique i32, max_len i32, has_high bool,
    token_overflow bool).

    ``grouper`` selects how identical tokens are grouped: ``"sort"`` (the
    default — lexicographic multi-key ``lax.sort``, right for the TPU) or
    ``"hash"`` (scatter/segment-op bucketing with exact collision
    verification and sort fallback, ~2x faster on the CPU backend where
    XLA's sort is the measured kernel floor — BASELINE.md round 5).  A
    hash-grouper attempt that cannot prove exactness reports
    ``token_overflow`` so the shared retry ladder re-runs it; the wrapper
    then routes the chunk to the sort grouper.

    Not jitted itself so it can be inlined into larger programs (the
    ``shard_map`` SPMD step in ``dsi_tpu/parallel/shuffle.py`` traces it per
    device before the ``all_to_all`` shuffle); ``count_words_kernel`` below
    is the jitted single-chunk entry point.
    """
    n = chunk.shape[0]
    k = max_word_len // 4
    t_cap = n // t_cap_frac + 1

    letter = is_ascii_letter(chunk)
    prev_letter = jnp.concatenate([jnp.zeros((1,), jnp.bool_), letter[:-1]])
    starts = letter & ~prev_letter
    next_letter = jnp.concatenate([letter[1:], jnp.zeros((1,), jnp.bool_)])
    ends = letter & ~next_letter
    n_tokens = jnp.sum(starts, dtype=jnp.int32)
    token_overflow = n_tokens > t_cap

    # Compact to the token buffer.  Token lengths come from the paired
    # start/end compactions (runs cannot nest, so the i-th start matches
    # the i-th end) — cheaper than the former per-position reverse-min
    # scan, whose log-depth passes over the whole chunk were ~10% of the
    # kernel.  Key lanes gather straight from the single packed-bytes
    # array at ``start + 4j`` and are masked AFTER compaction: the same
    # k token-level gathers as before, but the byte-masking runs over
    # t_cap rows instead of building k masked full-chunk lane arrays.
    (start_pos,) = jnp.nonzero(starts, size=t_cap, fill_value=n - 1)
    (end_pos,) = jnp.nonzero(ends, size=t_cap, fill_value=n - 1)
    valid = jnp.arange(t_cap, dtype=jnp.int32) < n_tokens
    lengths = jnp.where(valid, end_pos - start_pos + 1, 0).astype(jnp.int32)
    max_len = jnp.max(lengths, initial=0)
    c = chunk.astype(jnp.uint32)
    b32 = ((c << 24) | (_shift_left(c, 1) << 16)
           | (_shift_left(c, 2) << 8) | _shift_left(c, 3))
    packed_cols = tuple(
        jnp.where(valid,
                  b32[start_pos + 4 * j]
                  & _byte_mask(jnp.clip(lengths - 4 * j, 0, 4)),
                  jnp.uint32(_PAD_KEY))
        for j in range(k))

    if grouper == "hash":
        fnv_t = fnv1a32_packed(jnp.stack(packed_cols, axis=1), lengths,
                               max_word_len)
        keys64_u, len_u, cnt_u, _, n_unique, group_of = _hash_group(
            packed_cols, lengths, valid, fnv_t, u_cap=u_cap,
            max_word_len=max_word_len)
        with enable_x64(True):
            packed_u = unpack_key_rows(jnp.stack(keys64_u, axis=1), k)
        fnv_u = fnv1a32_packed(packed_u, len_u, max_word_len)
        has_high = jnp.any(chunk >= 128)
        return (packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high,
                token_overflow | group_of)

    # Group identical words: lexicographic sort over the key lanes packed
    # pairwise into uint64s (pack_key_lanes: same order, half the
    # comparator keys — the sort is ~3/4 of this kernel's wall on CPU),
    # then run boundaries; lanes unpack only after compaction to u_cap.
    with enable_x64(True):  # every op touching u64 operands needs it
        keys64 = pack_key_lanes(packed_cols)
        k64 = len(keys64)
        sorted_ops = lax.sort(keys64 + (lengths,), num_keys=k64)
        skeys64, totals, upos, ovalid, n_unique = group_sorted(
            sorted_ops[:k64], jnp.ones(t_cap, jnp.int32), u_cap)
        slens = sorted_ops[k64]

        packed_u64 = jnp.where(ovalid[:, None], skeys64[upos],
                               jnp.uint64(0))
        packed_u = unpack_key_rows(packed_u64, k)
    len_u = jnp.where(ovalid, slens[upos], 0)
    fnv_u = fnv1a32_packed(packed_u, len_u, max_word_len)
    has_high = jnp.any(chunk >= 128)
    return (packed_u, len_u, totals, fnv_u, n_unique, max_len, has_high,
            token_overflow)


count_words_kernel = x64_scoped(jax.jit(
    tokenize_group_core,
    static_argnames=("max_word_len", "u_cap", "t_cap_frac", "grouper")))


def default_grouper() -> str:
    """Platform-adaptive grouping strategy: ``hash`` on the CPU backend
    (where the multi-key sort is the measured kernel floor — BASELINE.md
    round 5), ``sort`` on accelerators until on-chip evidence says
    otherwise.  ``DSI_WC_GROUPER`` pins the choice — and because the warm
    ladder persists BOTH variants (``warm_groupers`` below, the ``*_hg``
    AOT entries), pinning ``hash`` on an accelerator is a warm load, not
    a cold remote compile."""
    env = os.environ.get("DSI_WC_GROUPER")
    if env in ("sort", "hash"):
        return env
    return "hash" if jax.devices()[0].platform == "cpu" else "sort"


def grouper_suffix(grouper: str) -> str:
    """AOT program-name suffix for a grouper variant: the sort grouper
    keeps its historical bare names (pre-existing cache entries stay
    valid), the hash grouper gets ``_hg``.  One definition shared by
    every program namer (``wc_kernel`` here, ``stream_step_*`` in
    parallel/streaming.py, ``tfidf_wave_*`` in parallel/tfidf.py) so the
    warm ladder, the persisted probes, and the runs agree on the key by
    construction."""
    if grouper == "sort":
        return ""
    return "_hg" if grouper == "hash" else f"_g{grouper}"


def warm_groupers() -> tuple:
    """The grouper variants the warm AOT ladder compiles+persists for
    every program family: both rungs, on every platform.  Distinct from
    :func:`grouper_ladder` (the rungs ONE run walks, platform/env
    dependent): warming only the ladder would leave an env-selected
    ``DSI_WC_GROUPER=hash`` accelerator run cold exactly where a remote
    compile costs minutes (VERDICT r5 weak #3)."""
    return ("hash", "sort")


def grouper_ladder() -> tuple:
    """The retry rungs every kernel wrapper walks: the platform's
    preferred grouper first, with the sort grouper as the always-exact
    last rung (a hash-grouper collision overflow cannot clear at frac=2;
    the sort can never overflow there).  One definition so the four
    wrappers (here, parallel/shuffle.py, parallel/streaming.py,
    parallel/tfidf.py) cannot drift."""
    g0 = default_grouper()
    return (g0, "sort") if g0 != "sort" else ("sort",)


@functools.lru_cache(maxsize=256)
def _cached_kernel(n: int, max_word_len: int, u_cap: int, t_cap_frac: int,
                   grouper: str = "sort"):
    """The single-chunk kernel via the persistent AOT executable cache
    (backends/aotcache.py): a fresh worker process loads the serialized
    executable in milliseconds instead of re-paying the XLA compile —
    essential on platforms where jit compiles run to minutes and every
    mrworker is its own process (main/test-mr.sh:43-45 spawns three).
    lru_cached so repeat dispatches skip the cache-key fingerprinting.

    The ``grouper`` static enters the key/name only for the hash variant
    (``grouper_suffix``: ``wc_kernel_hg``) — purely so sort-grouper
    cache filenames keep their historical, readable names.  (It is NOT a
    warm-cache-survival guarantee: the key also fingerprints this
    module's source, so any kernel edit misses and recompiles
    regardless.)"""
    from dsi_tpu.backends.aotcache import cached_compile

    example = (jax.ShapeDtypeStruct((n,), np.uint8),)
    static = {"max_word_len": max_word_len, "u_cap": u_cap,
              "t_cap_frac": t_cap_frac}
    name = "wc_kernel"
    if grouper != "sort":
        static["grouper"] = grouper
        name += grouper_suffix(grouper)
    return cached_compile(name, tokenize_group_core, example,
                          static=static, x64=True)


def run_count_kernel(chunk: jax.Array, *, max_word_len: int, u_cap: int,
                     t_cap_frac: int, grouper: str = "sort"):
    """Dispatch one chunk through the AOT-cached executable."""
    fn = _cached_kernel(int(chunk.shape[0]), max_word_len, u_cap, t_cap_frac,
                        grouper)
    return fn(chunk)


def _pad_pow2(data: bytes, min_size: int = 256) -> np.ndarray:
    """Zero-pad to the next power of two so jit caches a few shapes only.
    Zero bytes are non-letters, so padding can't create or extend tokens."""
    n = max(min_size, len(data) + 1)
    size = 1 << (n - 1).bit_length()
    buf = np.zeros(size, dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf


def decode_packed(packed_u: np.ndarray, len_u: np.ndarray,
                  n_unique: int) -> list:
    """Host detokenization: packed big-endian uint32 rows -> word strings.

    One bulk byteswap + tobytes for the whole table, then cheap slices —
    no per-row numpy scalar extraction (this sits on bench.py's timed path).
    """
    nu = int(n_unique)
    rows = np.ascontiguousarray(np.asarray(packed_u[:nu])).astype(">u4")
    buf = rows.tobytes()
    stride = rows.shape[1] * 4
    lens = np.asarray(len_u[:nu]).tolist()
    return [buf[i * stride:i * stride + lens[i]].decode("ascii")
            for i in range(nu)]


def rung0_cap(shard_len: int, u_cap: int) -> int:
    """exactness_retry's starting capacity: ``u_cap`` bounded by the
    token-count hard cap for this shard length (n//2+1, pow2-rounded to
    keep the jit shape-cache small), floored at 1 (a zero/negative start
    could never widen: 0 * 4 == 0).  Shared with cache-existence probes
    (corpus_wc.corpus_executable_persisted) so the key they compute is,
    by construction, the key a real run compiles first."""
    hard_cap = 1 << (shard_len // 2).bit_length()
    return max(1, min(u_cap, hard_cap))


def exactness_retry(run, shard_len: int, max_word_len: int, u_cap: int):
    """Shared overflow/retry discipline for the static-shape kernels.

    ``run(mwl, cap)`` executes a kernel attempt and returns
    ``(has_high, n_unique_max, max_len, payload)`` where the first three are
    host scalars summarising every shard of the attempt.  Retries with
    ``cap*4`` while uniques overflow (bounded by the token-count hard cap
    n//2+1, pow2-rounded to keep the jit shape-cache small), then with a
    64-byte word window if a word overflowed the packed window.  Returns the
    successful payload, or None when the input needs the host path
    (non-ASCII bytes, or words longer than 64)."""
    ladder = (max_word_len, 64) if max_word_len < 64 else (max_word_len,)
    for mwl in ladder:
        cap = rung0_cap(shard_len, u_cap)
        while True:
            has_high, n_unique_max, max_len, payload = run(mwl, cap)
            if has_high:
                return None
            if n_unique_max > cap:
                cap *= 4
                continue
            break
        if max_len > mwl:
            continue  # a word overflowed the packed window: widen kernel
        return payload
    return None


def count_words_host_result(
        data: bytes, *, max_word_len: int = 16,
        u_cap: int = 1 << 17) -> Optional[Dict[str, tuple]]:
    """Run the kernel (retrying with wider kernels on overflow) and return
    ``{word: (count, ihash)}``.

    Returns None if and only if the text needs the host fallback (non-ASCII
    bytes, or words longer than 64 bytes); callers must test ``is None`` —
    letter-free input legitimately returns an empty dict."""
    chunk = _pad_pow2(data)
    dev_chunk = jnp.asarray(chunk)
    groupers = grouper_ladder()

    def run(mwl: int, cap: int):
        for g in groupers:
            for frac in (4, 2):  # exact token bound is n//2+1
                (packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high,
                 tok_of) = run_count_kernel(dev_chunk, max_word_len=mwl,
                                            u_cap=cap, t_cap_frac=frac,
                                            grouper=g)
                if not bool(tok_of):
                    break
            if not bool(tok_of):
                break
        nu = int(n_unique)

        def payload():
            words = decode_packed(np.asarray(packed_u), np.asarray(len_u), nu)
            counts = np.asarray(cnt_u[:nu])
            hashes = np.asarray(fnv_u[:nu]) & 0x7FFFFFFF
            return {w: (int(counts[i]), int(hashes[i]))
                    for i, w in enumerate(words)}

        return bool(has_high), nu, int(max_len), payload

    payload = exactness_retry(run, len(chunk), max_word_len, u_cap)
    return None if payload is None else payload()


def count_words_many(datas, *, max_word_len: int = 16,
                     u_cap: int = 1 << 17) -> list:
    """Pipelined multi-split word count: launch the kernel for EVERY split
    before synchronizing on any, so host↔device transfers and device compute
    overlap (JAX async dispatch).  Splits whose optimistic first attempt
    overflowed re-run through the full retry ladder (rare).

    Returns one ``{word: (count, ihash)} | None`` per input, same contract
    as ``count_words_host_result``.
    """
    launches = []
    g0 = default_grouper()
    for data in datas:
        chunk = _pad_pow2(data)
        cap = rung0_cap(len(chunk), u_cap)
        launches.append((data, cap,
                         run_count_kernel(jnp.asarray(chunk),
                                          max_word_len=max_word_len,
                                          u_cap=cap, t_cap_frac=4,
                                          grouper=g0)))
    results = []
    for data, cap, out in launches:
        (packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high,
         tok_of) = out
        if bool(has_high):
            results.append(None)
            continue
        if bool(tok_of) or int(n_unique) > cap or int(max_len) > max_word_len:
            results.append(count_words_host_result(
                data, max_word_len=max_word_len, u_cap=u_cap))
            continue
        nu = int(n_unique)
        words = decode_packed(np.asarray(packed_u), np.asarray(len_u), nu)
        counts = np.asarray(cnt_u[:nu])
        hashes = np.asarray(fnv_u[:nu]) & 0x7FFFFFFF
        results.append({w: (int(counts[i]), int(hashes[i]))
                        for i, w in enumerate(words)})
    return results
