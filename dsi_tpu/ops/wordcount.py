"""TPU word-count kernel: tokenize + group + count, one fused XLA program.

This is the device replacement for the reference's map-side hot path
(``mrapps/wc.go:21-34`` tokenization, ``mr/worker.go:74-78`` bucketing) and
the reduce-side sort/group/count (``mr/worker.go:123-146``), re-designed for
the TPU execution model rather than translated:

* the whole file chunk lives in HBM as one ``uint8`` vector; every step is a
  vectorized op over it (no scalar loops, no dynamic shapes),
* tokens are *maximal runs of ASCII letters* — on ASCII text this is exactly
  Go's ``strings.FieldsFunc(contents, !unicode.IsLetter)`` (``wc.go:23``);
  any byte >= 0x80 is detected and reported so the caller can fall back to
  the host path, keeping Unicode parity without polluting the kernel,
* grouping is by **exact word bytes**, not by hash: each token's first
  ``max_word_len`` bytes are packed big-endian into ``max_word_len/4``
  ``uint32`` lanes and grouped with a multi-key lexicographic ``lax.sort`` +
  segment-sum — no collision risk, and the packed keys double as the exact
  word bytes for host-side detokenization (SURVEY.md §7 hard part 1),
* the partition hash is FNV-1a 32-bit, bit-identical to the reference's
  ``ihash`` (``mr/worker.go:33-37``), computed on-device per *unique* word.

All shapes are static: the token buffer is ``n//2 + 1`` (a token needs at
least one letter plus a separator), the unique buffer is ``u_cap``.  Overflow
(words longer than ``max_word_len``, more uniques than ``u_cap``, non-ASCII
bytes) is detected exactly and surfaced as scalars; the host wrapper retries
with a bigger kernel or falls back to the host implementation, so the result
is always exact.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193
_PAD_KEY = 0xFFFFFFFF  # sorts after every real word (ASCII first byte < 0x80)


def is_ascii_letter(b: jax.Array) -> jax.Array:
    """[A-Za-z] mask over uint8 bytes (== unicode.IsLetter on ASCII)."""
    return ((b >= 65) & (b <= 90)) | ((b >= 97) & (b <= 122))


def token_bounds(letter: jax.Array):
    """Start/end masks for maximal letter runs (vector form of FieldsFunc)."""
    prev = jnp.concatenate([jnp.zeros((1,), jnp.bool_), letter[:-1]])
    nxt = jnp.concatenate([letter[1:], jnp.zeros((1,), jnp.bool_)])
    return letter & ~prev, letter & ~nxt


def pack_windows(chunk: jax.Array, start_pos: jax.Array, lengths: jax.Array,
                 max_word_len: int):
    """Gather each token's first max_word_len bytes, zero-pad, pack to uint32.

    Big-endian packing keeps uint32 lexicographic order == bytewise order and
    makes host detokenization a single ``.tobytes()``.
    """
    n = chunk.shape[0]
    k = max_word_len // 4
    offs = jnp.arange(max_word_len, dtype=jnp.int32)
    idx = jnp.minimum(start_pos[:, None] + offs[None, :], n - 1)
    win = chunk[idx].astype(jnp.uint32)
    mask = offs[None, :] < jnp.minimum(lengths, max_word_len)[:, None]
    win = jnp.where(mask, win, 0)
    w4 = win.reshape(-1, k, 4)
    return (w4[..., 0] << 24) | (w4[..., 1] << 16) | (w4[..., 2] << 8) | w4[..., 3]


def fnv1a32_packed(packed: jax.Array, lengths: jax.Array,
                   max_word_len: int) -> jax.Array:
    """FNV-1a 32-bit over the packed word bytes — bit-exact Go hash/fnv.New32a
    (mr/worker.go:33-37).  Unrolled over the static max_word_len."""
    h = jnp.full(packed.shape[:1], _FNV_OFFSET, jnp.uint32)
    for j in range(max_word_len):
        b = (packed[:, j // 4] >> ((3 - (j % 4)) * 8)) & jnp.uint32(0xFF)
        h = jnp.where(j < lengths, (h ^ b) * jnp.uint32(_FNV_PRIME), h)
    return h


def tokenize_group_core(chunk: jax.Array, *, max_word_len: int = 16,
                        u_cap: int = 1 << 17):
    """Exact unique-word counts over one uint8 chunk (zero-padded tail).

    Returns (packed_u [u_cap, K] uint32, len_u [u_cap] i32, cnt_u [u_cap] i32,
    fnv_u [u_cap] u32, n_unique i32, max_len i32, has_high bool).

    Not jitted itself so it can be inlined into larger programs (the
    ``shard_map`` SPMD step in ``dsi_tpu/parallel/shuffle.py`` traces it per
    device before the ``all_to_all`` shuffle); ``count_words_kernel`` below is
    the jitted single-chunk entry point.
    """
    n = chunk.shape[0]
    k = max_word_len // 4
    t_cap = n // 2 + 1

    letter = is_ascii_letter(chunk)
    starts, ends = token_bounds(letter)
    n_tokens = jnp.sum(starts, dtype=jnp.int32)
    (start_pos,) = jnp.nonzero(starts, size=t_cap, fill_value=n - 1)
    (end_pos,) = jnp.nonzero(ends, size=t_cap, fill_value=n - 1)
    valid = jnp.arange(t_cap, dtype=jnp.int32) < n_tokens
    lengths = jnp.where(valid, end_pos - start_pos + 1, 0).astype(jnp.int32)
    max_len = jnp.max(lengths, initial=0)

    packed = pack_windows(chunk, start_pos.astype(jnp.int32), lengths,
                          max_word_len)
    packed = jnp.where(valid[:, None], packed, jnp.uint32(_PAD_KEY))

    # Group identical words: K-key lexicographic sort, then run boundaries.
    sorted_ops = lax.sort(tuple(packed[:, j] for j in range(k)) + (lengths,),
                          num_keys=k)
    skeys = jnp.stack(sorted_ops[:k], axis=1)
    slens = sorted_ops[k]
    svalid = skeys[:, 0] != jnp.uint32(_PAD_KEY)
    prev = jnp.concatenate(
        [jnp.full((1, k), _PAD_KEY, jnp.uint32), skeys[:-1]], axis=0)
    is_new = jnp.any(skeys != prev, axis=1) & svalid
    n_unique = jnp.sum(is_new, dtype=jnp.int32)
    uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    cnt_u = jax.ops.segment_sum(
        svalid.astype(jnp.int32),
        jnp.where(svalid, uid, u_cap),
        num_segments=u_cap + 1)[:u_cap]

    (upos,) = jnp.nonzero(is_new, size=u_cap, fill_value=t_cap - 1)
    uvalid = jnp.arange(u_cap, dtype=jnp.int32) < n_unique
    packed_u = jnp.where(uvalid[:, None], skeys[upos], 0)
    len_u = jnp.where(uvalid, slens[upos], 0)
    fnv_u = fnv1a32_packed(packed_u, len_u, max_word_len)
    has_high = jnp.any(chunk >= 128)
    return packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high


count_words_kernel = jax.jit(tokenize_group_core,
                             static_argnames=("max_word_len", "u_cap"))


def _pad_pow2(data: bytes, min_size: int = 256) -> np.ndarray:
    """Zero-pad to the next power of two so jit caches a few shapes only.
    Zero bytes are non-letters, so padding can't create or extend tokens."""
    n = max(min_size, len(data) + 1)
    size = 1 << (n - 1).bit_length()
    buf = np.zeros(size, dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf


def decode_packed(packed_u: np.ndarray, len_u: np.ndarray,
                  n_unique: int) -> list:
    """Host detokenization: packed big-endian uint32 rows -> word strings.

    One bulk byteswap + tobytes for the whole table, then cheap slices —
    no per-row numpy scalar extraction (this sits on bench.py's timed path).
    """
    nu = int(n_unique)
    rows = np.ascontiguousarray(np.asarray(packed_u[:nu])).astype(">u4")
    buf = rows.tobytes()
    stride = rows.shape[1] * 4
    lens = np.asarray(len_u[:nu]).tolist()
    return [buf[i * stride:i * stride + lens[i]].decode("ascii")
            for i in range(nu)]


def exactness_retry(run, shard_len: int, max_word_len: int, u_cap: int):
    """Shared overflow/retry discipline for the static-shape kernels.

    ``run(mwl, cap)`` executes a kernel attempt and returns
    ``(has_high, n_unique_max, max_len, payload)`` where the first three are
    host scalars summarising every shard of the attempt.  Retries with
    ``cap*4`` while uniques overflow (bounded by the token-count hard cap
    n//2+1, pow2-rounded to keep the jit shape-cache small), then with a
    64-byte word window if a word overflowed the packed window.  Returns the
    successful payload, or None when the input needs the host path
    (non-ASCII bytes, or words longer than 64)."""
    hard_cap = 1 << (shard_len // 2).bit_length()
    ladder = (max_word_len, 64) if max_word_len < 64 else (max_word_len,)
    for mwl in ladder:
        cap = min(u_cap, hard_cap)
        while True:
            has_high, n_unique_max, max_len, payload = run(mwl, cap)
            if has_high:
                return None
            if n_unique_max > cap:
                cap *= 4
                continue
            break
        if max_len > mwl:
            continue  # a word overflowed the packed window: widen kernel
        return payload
    return None


def count_words_host_result(
        data: bytes, *, max_word_len: int = 16,
        u_cap: int = 1 << 17) -> Optional[Dict[str, tuple]]:
    """Run the kernel (retrying with wider kernels on overflow) and return
    ``{word: (count, ihash)}``.

    Returns None if and only if the text needs the host fallback (non-ASCII
    bytes, or words longer than 64 bytes); callers must test ``is None`` —
    letter-free input legitimately returns an empty dict."""
    chunk = _pad_pow2(data)
    dev_chunk = jnp.asarray(chunk)

    def run(mwl: int, cap: int):
        packed_u, len_u, cnt_u, fnv_u, n_unique, max_len, has_high = (
            count_words_kernel(dev_chunk, max_word_len=mwl, u_cap=cap))
        nu = int(n_unique)

        def payload():
            words = decode_packed(np.asarray(packed_u), np.asarray(len_u), nu)
            counts = np.asarray(cnt_u[:nu])
            hashes = np.asarray(fnv_u[:nu]) & 0x7FFFFFFF
            return {w: (int(counts[i]), int(hashes[i]))
                    for i, w in enumerate(words)}

        return bool(has_high), nu, int(max_len), payload

    payload = exactness_retry(run, len(chunk), max_word_len, u_cap)
    return None if payload is None else payload()
