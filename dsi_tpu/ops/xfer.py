"""H2D upload strategies for the axon tunnel (the bench's real wall).

The tunnel's fastest upload geometry depends on link health, and the two
states differ by >10x in opposite directions (scripts/probe_tunnel.py):

* healthy wire (round 3): pieced-ASYNC transfers pipeline — 16 x 1 MiB
  observed at 1.2 GB/s once, 29 MB/s under congestion, vs 20-150 MB/s
  single-shot.  Dispatch-all-then-block is the fast path.
* degraded wire (2026-07-31 03:16 UTC): concurrent streams thrash the
  constrained link — 8 x 1 MiB async measured 0.6 MB/s vs 5.8 MB/s for
  one single-shot put.  One-transfer-in-flight recovers the rate.

Neither geometry is safe to hardcode, so the upload mode is a runtime
switch (no program shapes change, no AOT entry is re-fingerprinted by
choosing differently):

* ``DSI_UPLOAD_MODE=async`` (default) — dispatch every piece before any
  sync, then block until all have landed.
* ``DSI_UPLOAD_MODE=sync`` — serialize: put + block one piece at a time.

``corpus_wc`` routes its piece upload through :func:`put_views`, and
``bench.py`` probes both modes on its first reps (like its raw-vs-pack6
transport probe), commits the rest to the winner, and reports ``stats``'
wall time as an ``upload_s`` phase instead of letting it hide inside
``kernel_s``.
"""
from __future__ import annotations

import os
import time
from typing import Any, List, Sequence

#: Upload telemetry: ``upload_s`` ACCUMULATES across calls (a single
#: logical operation may upload more than once, e.g. corpus_wc's
#: token-bound retry rung re-uploads the corpus) until the reader —
#: bench.py's per-rep phase capture — zeroes it.
stats = {"upload_s": 0.0, "upload_mode": "async"}


def upload_mode() -> str:
    mode = os.environ.get("DSI_UPLOAD_MODE", "async")
    return mode if mode in ("async", "sync") else "async"


def put_views(views: Sequence[Any], device=None) -> List[Any]:
    """Transfer ``views`` (host arrays) to ``device`` (default: JAX's
    default device), honoring ``DSI_UPLOAD_MODE``, and record the wall
    time in ``stats``.  Returns device arrays in input order.

    Blocking before return costs nothing real in either mode — a
    consuming program cannot start until all its arguments have landed —
    and gives callers an honest upload phase boundary.
    """
    import jax

    mode = upload_mode()
    t0 = time.perf_counter()
    if mode == "sync":
        out = []
        for v in views:
            d = (jax.device_put(v, device) if device is not None
                 else jax.device_put(v))
            d.block_until_ready()
            out.append(d)
    else:
        out = (jax.device_put(list(views), device) if device is not None
               else jax.device_put(list(views)))
        jax.block_until_ready(out)
    stats["upload_s"] += time.perf_counter() - t0
    stats["upload_mode"] = mode
    return list(out)
