"""TPU grep kernel: literal substring search over a whole chunk.

Device replacement for the grep app's map hot loop (per-line regex scan,
reference intent at ``mrapps/dgrep.go:27-35``): the pattern-match mask for
every byte position is computed with ``len(pattern)`` shifted elementwise
compares (no gathers, no loops over positions), line membership is a cumsum
over newline bytes, and per-line match flags are a sorted segment-max —
the same static-shape, vector-only discipline as ``ops/wordcount.py``.

Scope: fixed ASCII literal patterns without newlines; anything else (regex
metacharacters, non-ASCII) falls back to the host app — correctness never
depends on the kernel (``backends/tpu.py`` contract).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

import dsi_tpu.ops.wordcount as _wordcount_mod
from dsi_tpu.ops.wordcount import _pad_pow2, _shift_left


def cold_ok() -> bool:
    """THE cold-compile bypass knob: ``DSI_COLD_OK=1`` disables every
    device-readiness gate (this module's, the NFA tier's, and anything
    the streaming grep/indexer/top-k programs grow) for processes whose
    JOB the compiles are — scripts/warm_kernels.py sets it around its
    warm blocks.  The historical per-tier names ``DSI_GREP_COLD_OK`` /
    ``DSI_NFA_COLD_OK`` remain as aliases so existing scripts and soak
    recipes keep working, but new gates must consult this one function
    rather than growing a third env var."""
    return any(os.environ.get(v) == "1"
               for v in ("DSI_COLD_OK", "DSI_GREP_COLD_OK",
                         "DSI_NFA_COLD_OK"))


def device_ready(name: str, fn, example, static) -> bool:
    """Whether dispatching this compiled shape NOW is a millisecond load
    or a multi-minute remote compile — the bench's
    ``corpus_executable_persisted`` discipline, shared by every grep
    tier's rung gate (ADVICE r4: the l_cap escalation rung is a
    separately compiled shape, and an ungated escalation cold-compiles
    inside a worker task).  CPU backends are always ready (compiles are
    seconds); ``DSI_COLD_OK=1`` (see :func:`cold_ok`) bypasses the gate
    for scripts/warm_kernels.py, whose job the compiles are."""
    if cold_ok():
        return True
    if jax.devices()[0].platform == "cpu":
        return True
    from dsi_tpu.backends.aotcache import is_persisted

    return is_persisted(name, fn, example, static=static)


def line_flags_from_match(chunk: jax.Array, match: jax.Array, l_cap: int):
    """Per-position match mask -> per-line flags, shared by the literal
    kernel here and the class-pattern kernel (``ops/regexk.py``): line
    membership is a cumsum over newline bytes, per-line flags a sorted
    segment-max.  Returns (line_match [l_cap] i32 in line order,
    n_lines i32, overflow bool)."""
    is_nl = chunk == 10
    cum = jnp.cumsum(is_nl.astype(jnp.int32))
    line_id = cum - is_nl.astype(jnp.int32)  # newlines strictly before i
    n_lines = cum[-1] + 1
    overflow = n_lines > l_cap
    seg = jnp.minimum(line_id, l_cap)
    line_match = jax.ops.segment_max(
        match.astype(jnp.int32), seg, num_segments=l_cap + 1,
        indices_are_sorted=True)[:l_cap]
    return line_match, n_lines, overflow


def line_cap_rungs(n: int):
    """The shared l_cap rung schedule: average line >= 8 bytes first,
    then the n+1 hard bound (every byte a '\\n').  One definition so
    readiness probes (``ops/nfak._device_ready``) and the retry loop can
    never drift onto different compiled shapes."""
    return (max(n // 8, 1), n + 1)


def retry_line_caps(n: int, run, ready=None):
    """Shared l_cap rung schedule (exactness_retry discipline): average
    line >= 8 bytes first, then the n+1 hard bound (every byte a '\\n').
    ``run(l_cap)`` -> (line_match, n_lines, overflow).

    ``ready(l_cap)``, when given, gates EVERY rung (including the
    overflow escalation, a separately compiled shape): a not-ready rung
    returns ``(None, -1)`` and the caller serves the job on the host
    path instead of cold-compiling inside a worker task."""
    for l_cap in line_cap_rungs(n):
        if ready is not None and not ready(l_cap):
            return None, -1
        line_match, n_lines, overflow = run(l_cap)
        if not bool(overflow):
            break
    return line_match, int(n_lines)


def lines_from_flags(text: str, line_match, nl: int) -> Optional[List[str]]:
    """Map device line flags back to text lines; None on a host/device
    line-count disagreement (the host path decides — correctness never
    depends on a kernel, ``backends/tpu.py`` contract)."""
    flags = np.asarray(line_match[:nl])
    lines = text.split("\n")
    if len(lines) != nl:
        return None
    return [lines[i] for i in range(nl) if flags[i]]


def grep_kernel(chunk: jax.Array, pattern: jax.Array, *, l_cap: int):
    """Match lines of ``chunk`` containing the literal ``pattern``.

    Returns (line_match [l_cap] i32 flags in line order, n_lines i32,
    overflow bool).  Lines are '\\n'-delimited; the host maps flags back to
    text with ``text.split('\\n')``.  Padding zeros can never match
    (patterns are printable ASCII).
    """
    m = pattern.shape[0]
    match = jnp.ones(chunk.shape[0], jnp.bool_)
    for j in range(m):  # static unroll over the (short) pattern
        match &= _shift_left(chunk, j) == pattern[j]
    return line_flags_from_match(chunk, match, l_cap)


# The AOT cache fingerprints these sources: grep_kernel uses wordcount
# helpers (_shift_left), so editing them must invalidate stale executables.
grep_kernel._aot_code_deps = (_wordcount_mod,)


def _grep_example(n: int, m: int):
    return (jax.ShapeDtypeStruct((n,), np.uint8),
            jax.ShapeDtypeStruct((m,), np.uint8))


@functools.lru_cache(maxsize=64)
def _grep_compiled(n: int, m: int, l_cap: int):
    from dsi_tpu.backends.aotcache import cached_compile

    return cached_compile("grep_kernel", grep_kernel, _grep_example(n, m),
                          static={"l_cap": l_cap})


def grep_rung_ready(n: int, m: int, l_cap: int) -> bool:
    """Readiness probe for exactly the shape ``_grep_compiled`` builds —
    shared with the alternation tier (``ops/altk.py``)."""
    return device_ready("grep_kernel", grep_kernel, _grep_example(n, m),
                        {"l_cap": l_cap})


def _grep_jit(chunk, pattern, *, l_cap: int):
    """The grep kernel through the persistent AOT executable cache
    (backends/aotcache.py) — fresh worker processes load the serialized
    executable instead of re-paying the XLA compile."""
    fn = _grep_compiled(int(chunk.shape[0]), int(pattern.shape[0]), l_cap)
    return fn(chunk, pattern)


_REGEX_META = set(".^$*+?{}[]()|\\")


def is_literal_pattern(pat: str) -> bool:
    """True when the regex ``pat`` is a plain literal the kernel can run:
    printable ASCII (0x20..0x7E) only — control bytes could match the
    chunk's zero padding — and no regex metacharacters; a match can then
    never span lines, and byte-equality search == regex search."""
    return (bool(pat)
            and all(0x20 <= ord(c) <= 0x7E for c in pat)
            and not set(pat) & _REGEX_META)


def grep_host_result(data: bytes, pattern: str) -> Optional[List[str]]:
    """Matching lines of ``data`` (split on '\\n', in order), or None when
    the pattern needs the host regex path.  Retries the static line buffer
    on overflow (exactness_retry discipline, avg line >= 8 bytes first)."""
    if not is_literal_pattern(pattern):
        return None
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        return None
    if len(pattern) > len(data):
        return []  # a literal longer than the data cannot match any line
    chunk = jnp.asarray(_pad_pow2(data))
    pat = jnp.asarray(np.frombuffer(pattern.encode("ascii"), dtype=np.uint8))
    n = int(chunk.shape[0])
    m = len(pattern)
    line_match, nl = retry_line_caps(
        n, lambda l_cap: _grep_jit(chunk, pat, l_cap=l_cap),
        ready=lambda l_cap: grep_rung_ready(n, m, l_cap))
    if line_match is None:
        return None  # cold remote compile in-task: host serves this job
    return lines_from_flags(text, line_match, nl)
