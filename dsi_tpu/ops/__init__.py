"""Device-side (JAX/XLA) kernels — the TPU execution layer.

These kernels replace the reference's host hot loops (SURVEY.md §3.2-3.3:
``mapf`` over file contents, the ``ihash`` bucketing loop, sort + group +
reduce) with fixed-shape, jit-compiled TPU programs.
"""
