"""Wire codecs: pack the bytes where the link is the bottleneck.

Two independent codecs, one module, because they share the discipline
(ISSUE 13 / Dean & Ghemawat §3.4, §4.3 — the link, not the compute,
sets the ceiling, so compress what crosses it):

**Shuffle-row payloads** (``pack_rows``/``unpack_rows``): the per-step
packed result tables (``shuffle._slice_pack`` layout — ``kk``
big-endian uint32 key lanes + len/count/part columns) re-encoded as a
key DICTIONARY (unique spellings, trailing-zero-trimmed) plus VARINT
row triples (dict index, count, partition).  A raw row costs
``(kk+3)*4`` bytes however short its word; the packed form costs the
word's actual bytes once plus ~3 varints per row — >2x on English
word-count payloads.  Valid rows round-trip bit-identically
(``unpack_rows`` zero-fills the padding beyond each device's occupied
prefix).  Host-side numpy, vectorized varints, no jax — usable by the
bench A/B, the tests, and any future cross-host shuffle transport.

**Chunk uploads** (``encode_chunk`` + the compiled decode prologue):
a per-batch byte-level dictionary-nibble code — the batch's 15 most
frequent byte values ship as 4-bit symbols, everything else escapes to
a bounded per-row literal region — packed host-side into ONE uint8
tensor (``[n_dev, 16 + n/2 + lit_cap]``: per-row dictionary | nibble
pairs | literals) so the tunnel/PCIe sees one transfer of ~0.53-0.77x
the raw bytes, and a tiny compiled DECODE program (vectorized unpack +
two gathers, donated input) rebuilds the exact ``[n_dev, chunk_bytes]``
chunk in HBM before the step program consumes it — the map prologue.
The literal region is rung-laddered (``chunk_bytes/frac`` for
``LIT_FRACS``); a batch whose escapes overflow the widest rung ships
raw (the engine counts it in ``wire_raw_steps``) — exactness never
depends on the codec.  Decode output == input bytes, so every
downstream tensor is bit-identical with the codec on or off.

Program names: ``wire_decode_d{n_dev}_n{chunk_bytes}_l{lit_cap}``,
warmed by ``scripts/warm_kernels.py --phase wire`` and probed by
``wire_programs_persisted`` (the same cold-compile gate discipline as
the step programs).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import numpy as np

#: Literal-region rung ladder for the nibble mode: lit_cap =
#: chunk_bytes // frac, tried smallest-first per batch.  At frac 8 the
#: packed tensor is ~0.63x raw (16 B dict + n/2 nibbles + n/8
#: literals, ratio ~1.6); at frac 4 ~0.77x (ratio ~1.31).  Beyond that
#: the nibble mode would ship MORE than raw, so the ladder stops and
#: the batch falls to the 7-bit mode (all-ASCII, guaranteed 8/7) or
#: raw.
LIT_FRACS = (8, 4)

_WIRE_ENV = "DSI_STREAM_WIRE"

#: The decode program's packed input is NOT donated: its output is
#: LARGER than the input (that is the whole point), so XLA could never
#: alias them and donation would only emit unusable-donation warnings.
#: The packed buffer still frees the moment the prologue consumes it —
#: the caller drops its reference at dispatch — so an in-flight window
#: holds the decoded chunk (donated onward to the step program), never
#: both for longer than the decode itself.
_WIRE_DONATE = ()


def wire_upload_default(flag: Optional[bool] = None) -> bool:
    """Resolve the chunk-upload codec switch: explicit wins, else
    ``DSI_STREAM_WIRE`` (default off — off is the bit-identical
    historical path, and on only pays off where the wire is the
    bottleneck)."""
    if flag is None:
        return os.environ.get(_WIRE_ENV, "").strip().lower() in (
            "1", "true", "on", "yes")
    return bool(flag)


# ── varint streams (LEB128, vectorized) ────────────────────────────────


def varint_encode(vals) -> bytes:
    """LEB128-encode an integer array (values < 2**63) as one byte
    stream, vectorized: per-value byte counts from threshold ladders,
    then one fill pass per byte position (<= 10, not per value)."""
    v = np.asarray(vals, dtype=np.uint64).ravel()
    if v.size == 0:
        return b""
    nb = np.ones(v.size, dtype=np.int64)
    for b in range(1, 10):
        nb += (v >= np.uint64(1) << np.uint64(7 * b)).astype(np.int64)
    ends = np.cumsum(nb)
    starts = ends - nb
    out = np.zeros(int(ends[-1]), dtype=np.uint8)
    for b in range(int(nb.max())):
        m = nb > b
        byte = ((v[m] >> np.uint64(7 * b)) & np.uint64(0x7F)).astype(
            np.uint8)
        cont = ((nb[m] > b + 1).astype(np.uint8)) << 7
        out[starts[m] + b] = byte | cont
    return out.tobytes()


def varint_decode(buf: bytes, count: int,
                  offset: int = 0) -> Tuple[np.ndarray, int]:
    """Decode exactly ``count`` LEB128 values from ``buf[offset:]``;
    returns ``(uint64 array, offset past the stream)``.  Vectorized the
    same way encode is: terminator positions locate the values, then
    one or-in pass per byte position."""
    if count == 0:
        return np.zeros(0, dtype=np.uint64), offset
    b = np.frombuffer(buf, dtype=np.uint8, offset=offset)
    ends = np.flatnonzero(b < 128)
    if ends.size < count:
        raise ValueError("varint stream truncated")
    ends = ends[:count]
    starts = np.concatenate(([0], ends[:-1] + 1))
    nb = ends - starts + 1
    if int(nb.max()) > 10:
        raise ValueError("varint wider than 63 bits")
    vals = np.zeros(count, dtype=np.uint64)
    for k in range(int(nb.max())):
        m = nb > k
        vals[m] |= (b[starts[m] + k] & np.uint8(0x7F)).astype(
            np.uint64) << np.uint64(7 * k)
    return vals, offset + int(ends[-1]) + 1


# ── shuffle-row payload codec ──────────────────────────────────────────

_ROWS_MAGIC = b"DSW1"


def rows_raw_bytes(nus, kk: int) -> int:
    """What the valid rows cost uncompressed — the codec's denominator
    (``wire_ratio`` = raw / packed)."""
    return int(np.asarray(nus, dtype=np.int64).sum()) * (kk + 3) * 4


def pack_rows(rows: np.ndarray, nus) -> bytes:
    """Dictionary + varint encoding of one step's packed result table
    (``[n_dev, mp, kk+3]`` uint32, per-device occupied counts ``nus``).
    Only the valid prefix rows are shipped; ``unpack_rows`` rebuilds
    them bit-identically (padding zero-filled)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint32)
    nus = np.asarray(nus, dtype=np.int64)
    n_dev, mp, w = rows.shape
    kk = w - 3
    valid = np.concatenate([rows[d, :int(nus[d])] for d in range(n_dev)]
                           or [np.zeros((0, w), np.uint32)], axis=0)
    n = valid.shape[0]
    keybytes = np.ascontiguousarray(
        valid[:, :kk].astype(">u4")).view(np.uint8).reshape(n, kk * 4)
    if n:
        uniq, first, inv = np.unique(keybytes, axis=0, return_index=True,
                                     return_inverse=True)
    else:
        uniq = np.zeros((0, kk * 4), np.uint8)
        first = inv = np.zeros(0, np.int64)
    lens_u = valid[first, kk].astype(np.int64) if n else first
    # Trimmed entries are sound only when every byte past a key's length
    # is zero (true for the step programs' zero-padded lanes); fall back
    # to full-width entries when an exotic payload violates it.
    trim_ok = bool(uniq.size == 0 or (
        np.all(lens_u <= kk * 4)
        and not np.any(uniq[np.arange(kk * 4)[None, :]
                            >= lens_u[:, None]])))
    parts = [_ROWS_MAGIC,
             varint_encode([kk, n_dev, mp, uniq.shape[0],
                            1 if trim_ok else 0]),
             varint_encode(nus)]
    if trim_ok:
        parts.append(varint_encode(lens_u))
        if uniq.size:
            flat = np.arange(kk * 4)[None, :] < lens_u[:, None]
            parts.append(uniq[flat].tobytes())
    else:
        parts.append(varint_encode(lens_u))
        parts.append(uniq.tobytes())
    parts.append(varint_encode(inv))
    parts.append(varint_encode(valid[:, kk + 1]))  # counts
    parts.append(varint_encode(valid[:, kk + 2]))  # partitions
    return b"".join(parts)


def unpack_rows(buf: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`pack_rows`: ``(rows [n_dev, mp, kk+3] uint32,
    nus [n_dev] int64)`` with padding rows zeroed."""
    if buf[:4] != _ROWS_MAGIC:
        raise ValueError("not a packed-rows payload")
    hdr, off = varint_decode(buf, 5, 4)
    kk, n_dev, mp, n_uniq, trim = (int(x) for x in hdr)
    nus, off = varint_decode(buf, n_dev, off)
    nus = nus.astype(np.int64)
    lens_u, off = varint_decode(buf, n_uniq, off)
    lens_u = lens_u.astype(np.int64)
    uniq = np.zeros((n_uniq, kk * 4), dtype=np.uint8)
    if trim:
        total = int(lens_u.sum())
        flat = np.frombuffer(buf, np.uint8, count=total, offset=off)
        off += total
        mask = np.arange(kk * 4)[None, :] < lens_u[:, None]
        uniq[mask] = flat
    else:
        total = n_uniq * kk * 4
        uniq = np.frombuffer(buf, np.uint8, count=total,
                             offset=off).reshape(n_uniq, kk * 4).copy()
        off += total
    n = int(nus.sum())
    inv, off = varint_decode(buf, n, off)
    cnts, off = varint_decode(buf, n, off)
    pts, off = varint_decode(buf, n, off)
    keys_u = np.ascontiguousarray(uniq).view(">u4").reshape(
        n_uniq, kk).astype(np.uint32)
    valid = np.zeros((n, kk + 3), dtype=np.uint32)
    idx = inv.astype(np.int64)
    valid[:, :kk] = keys_u[idx]
    valid[:, kk] = lens_u[idx].astype(np.uint32)
    valid[:, kk + 1] = cnts.astype(np.uint32)
    valid[:, kk + 2] = pts.astype(np.uint32)
    rows = np.zeros((n_dev, mp, kk + 3), dtype=np.uint32)
    at = 0
    for d in range(n_dev):
        nu = int(nus[d])
        rows[d, :nu] = valid[at:at + nu]
        at += nu
    return rows, nus


# ── shuffle-partition line codec (network data plane) ──────────────────

_KV_MAGIC = b"DSK1"


def kv_raw_bytes(payload: bytes) -> int:
    """The codec's denominator for ``net_ratio`` attribution — spelled
    as a function for symmetry with :func:`rows_raw_bytes`."""
    return len(payload)


def pack_kv(payload: bytes) -> bytes:
    """Dictionary + varint encoding of one line-oriented shuffle payload.

    The classic map partitions are JSON lines ``{"Key": k, "Value": v}``
    where every occurrence of a key repeats the ENTIRE line verbatim
    (word-count values are all ``"1"``), so a unique-LINE dictionary plus
    varint line indexes collapses them the same way ``pack_rows``
    collapses key lanes — without parsing JSON, which keeps the
    round-trip byte-identical by construction for any line-oriented
    payload (shard outputs included).  Returns magic ``DSK1`` + header
    varints (n_uniq, n_lines, trailing-newline flag) + per-entry length
    varints + dictionary bytes + line-index varints.
    """
    trail = payload.endswith(b"\n")
    body = payload[:-1] if trail else payload
    lines = body.split(b"\n") if body else []
    index: dict = {}
    uniq: list = []
    inv = np.empty(len(lines), dtype=np.int64)
    for i, ln in enumerate(lines):
        at = index.get(ln)
        if at is None:
            at = index[ln] = len(uniq)
            uniq.append(ln)
        inv[i] = at
    parts = [_KV_MAGIC,
             varint_encode([len(uniq), len(lines), 1 if trail else 0]),
             varint_encode([len(u) for u in uniq]),
             b"".join(uniq),
             varint_encode(inv)]
    return b"".join(parts)


def unpack_kv(buf: bytes) -> bytes:
    """Inverse of :func:`pack_kv`: the exact original payload bytes."""
    if buf[:4] != _KV_MAGIC:
        raise ValueError("not a packed-kv payload")
    hdr, off = varint_decode(buf, 3, 4)
    n_uniq, n_lines, trail = (int(x) for x in hdr)
    lens, off = varint_decode(buf, n_uniq, off)
    uniq = []
    for ln in lens.astype(np.int64):
        uniq.append(buf[off:off + int(ln)])
        off += int(ln)
    inv, off = varint_decode(buf, n_lines, off)
    body = b"\n".join(uniq[int(i)] for i in inv)
    return body + (b"\n" if trail else b"")


# ── chunk-upload codec + compiled decode prologue ──────────────────────


def lit_caps(chunk_bytes: int) -> Tuple[int, ...]:
    """The literal-region rung ladder for one chunk shape, smallest
    first (each rung is a distinct compiled decode shape)."""
    return tuple(max(1, chunk_bytes // f) for f in LIT_FRACS)


def packed_width(chunk_bytes: int, lit_cap: int) -> int:
    """Bytes per device row of the nibble-mode packed tensor."""
    return 16 + chunk_bytes // 2 + lit_cap


def packed7_width(chunk_bytes: int) -> int:
    """Bytes per device row of the 7-bit-mode packed tensor."""
    return (chunk_bytes // 8) * 7


def encode_chunk(batch: np.ndarray) -> Optional[Tuple[str, np.ndarray,
                                                      int]]:
    """Encode one ``[n_dev, chunk_bytes]`` uint8 batch for the wire:
    the nibble mode at the smallest literal rung that fits (frequency-
    skewed bytes, ratio 1.3-1.6), else the 7-bit mode (any all-ASCII
    batch, ratio 8/7 — the word-count device path requires ASCII
    anyway), else None (the caller ships the batch raw — exactness
    never depends on the codec).  Returns ``(mode, packed, lit_cap)``
    with mode ``"nib"`` or ``"b7"`` (lit_cap 0 for b7)."""
    batch = np.asarray(batch, dtype=np.uint8)
    n_dev, n = batch.shape
    if n < 8 or n % 8:
        return None
    counts = np.bincount(batch.ravel(), minlength=256)
    top15 = np.argsort(-counts, kind="stable")[:15].astype(np.uint8)
    map_tbl = np.full(256, 15, dtype=np.uint8)
    map_tbl[top15] = np.arange(15, dtype=np.uint8)
    nib = map_tbl[batch]
    esc = nib == 15
    lit_counts = esc.sum(axis=1)
    need = int(lit_counts.max()) if n_dev else 0
    cap = next((c for c in lit_caps(n) if c >= need), None)
    if cap is not None:
        packed = np.zeros((n_dev, packed_width(n, cap)), dtype=np.uint8)
        packed[:, :15] = top15[None, :]
        packed[:, 16:16 + n // 2] = (nib[:, 0::2] << 4) | nib[:, 1::2]
        lit0 = 16 + n // 2
        for d in range(n_dev):
            lc = int(lit_counts[d])
            if lc:
                packed[d, lit0:lit0 + lc] = batch[d, esc[d]]
        return "nib", packed, cap
    if not (counts[128:].any()):
        return "b7", _pack7(batch), 0
    return None


def _pack7(batch: np.ndarray) -> np.ndarray:
    """Pack 8 ASCII bytes (< 128) into 7: groups of 8 symbols become a
    56-bit little-endian field.  Vectorized over all groups at once."""
    n_dev, n = batch.shape
    sym = batch.reshape(n_dev, n // 8, 8).astype(np.uint64)
    val = np.zeros((n_dev, n // 8), dtype=np.uint64)
    for k in range(8):
        val |= sym[:, :, k] << np.uint64(7 * k)
    le = val[..., None] >> (np.uint64(8) * np.arange(7, dtype=np.uint64))
    return (le & np.uint64(0xFF)).astype(np.uint8).reshape(n_dev,
                                                           (n // 8) * 7)


def _unpack7_np(packed: np.ndarray, n: int) -> np.ndarray:
    n_dev = packed.shape[0]
    grp = packed.reshape(n_dev, n // 8, 7).astype(np.uint16)
    out = np.empty((n_dev, n // 8, 8), dtype=np.uint8)
    for k in range(8):
        bit = 7 * k
        a, s = bit // 8, bit % 8
        v = grp[:, :, a] >> s
        if s + 7 > 8 and a + 1 < 7:
            v |= grp[:, :, a + 1] << (8 - s)
        out[:, :, k] = (v & 0x7F).astype(np.uint8)
    return out.reshape(n_dev, n)


def decode_chunk_host(mode: str, packed: np.ndarray,
                      chunk_bytes: int) -> np.ndarray:
    """Numpy reference decode — the oracle the compiled prologue is
    tested against (and the no-jax round-trip check)."""
    packed = np.asarray(packed, dtype=np.uint8)
    n = chunk_bytes
    if mode == "b7":
        return _unpack7_np(packed, n)
    n_dev = packed.shape[0]
    d16 = packed[:, :16]
    nibs = packed[:, 16:16 + n // 2]
    lits = packed[:, 16 + n // 2:]
    nib = np.empty((n_dev, n), dtype=np.uint8)
    nib[:, 0::2] = nibs >> 4
    nib[:, 1::2] = nibs & 15
    esc = nib == 15
    lit_idx = np.clip(np.cumsum(esc, axis=1) - 1, 0,
                      max(0, lits.shape[1] - 1))
    out = np.take_along_axis(d16, nib.astype(np.int64), axis=1)
    out_lit = np.take_along_axis(lits, lit_idx, axis=1)
    return np.where(esc, out_lit, out)


def _decode_impl(packed, *, n: int):
    """The nibble-mode compiled map prologue: nibble unpack + two
    per-row gathers.  Pure elementwise/row-local ops, so a
    mesh-sharded input decodes shard-locally with no collectives."""
    import jax.numpy as jnp

    d16 = packed[:, :16]
    nibs = packed[:, 16:16 + n // 2]
    lits = packed[:, 16 + n // 2:]
    hi = nibs >> 4
    lo = nibs & 15
    nib = jnp.stack([hi, lo], axis=2).reshape(packed.shape[0], n)
    esc = nib == 15
    lit_idx = jnp.clip(jnp.cumsum(esc.astype(jnp.int32), axis=1) - 1,
                       0, lits.shape[1] - 1)
    out = jnp.take_along_axis(d16, nib.astype(jnp.int32), axis=1)
    out_lit = jnp.take_along_axis(lits, lit_idx, axis=1)
    return jnp.where(esc, out_lit, out)


def _decode7_impl(packed, *, n: int):
    """The 7-bit-mode prologue: eight static shift/or lanes per 7-byte
    group — no gathers at all."""
    import jax.numpy as jnp

    n_dev = packed.shape[0]
    grp = packed.reshape(n_dev, n // 8, 7).astype(jnp.uint16)
    lanes = []
    for k in range(8):
        bit = 7 * k
        a, s = bit // 8, bit % 8
        v = grp[:, :, a] >> s
        if s + 7 > 8 and a + 1 < 7:
            v = v | (grp[:, :, a + 1] << (8 - s))
        lanes.append((v & 0x7F).astype(jnp.uint8))
    return jnp.stack(lanes, axis=2).reshape(n_dev, n)


def _decode_program(*, n_dev: int, n: int, lit_cap: int, mode: str):
    """(name, fn) for one compiled decode shape — shared by the
    cached-compile path, the warmer, and the persisted probe, the
    ``_step_program`` discipline."""
    import dsi_tpu.ops.wirecodec as _wc

    if mode == "b7":
        def fn(packed):
            return _decode7_impl(packed, n=n)
        name = f"wire_decode7_d{n_dev}_n{n}"
    else:
        def fn(packed):
            return _decode_impl(packed, n=n)
        name = f"wire_decode_d{n_dev}_n{n}_l{lit_cap}"
    fn._aot_code_deps = (_wc,)
    return name, fn


def _decode_example(n_dev: int, n: int, lit_cap: int, mode: str):
    import jax
    import jax.numpy as jnp

    width = packed7_width(n) if mode == "b7" else packed_width(n, lit_cap)
    return jax.ShapeDtypeStruct((n_dev, width), jnp.uint8)


def aot_decode_fn(example, *, n_dev: int, n: int, lit_cap: int,
                  mode: str):
    """Compiled decode via the persistent AOT executable cache
    (``backends/aotcache.py``)."""
    from dsi_tpu.backends import aotcache

    name, fn = _decode_program(n_dev=n_dev, n=n, lit_cap=lit_cap,
                               mode=mode)
    return aotcache.cached_compile(name, fn, (example,),
                                   donate_argnums=_WIRE_DONATE)


@functools.lru_cache(maxsize=None)
def _jit_decode(n_dev: int, n: int, lit_cap: int, mode: str):
    import jax

    _, fn = _decode_program(n_dev=n_dev, n=n, lit_cap=lit_cap, mode=mode)
    return jax.jit(fn, donate_argnums=_WIRE_DONATE)


def decode_chunk_device(packed_dev, *, n: int, lit_cap: int, mode: str,
                        aot: bool = False):
    """Dispatch the decode prologue on an uploaded packed tensor;
    returns the device-resident ``[n_dev, n]`` chunk, async like any
    jit dispatch (the caller drops the packed reference so its buffer
    frees as soon as the prologue consumes it)."""
    n_dev = packed_dev.shape[0]
    if aot:
        return aot_decode_fn(packed_dev, n_dev=n_dev, n=n,
                             lit_cap=lit_cap, mode=mode)(packed_dev)
    return _jit_decode(n_dev, n, lit_cap, mode)(packed_dev)


def _decode_shapes(n: int):
    """(mode, lit_cap) for every decode program reachable at one chunk
    shape: each nibble rung plus the 7-bit fallback."""
    return [("nib", cap) for cap in lit_caps(n)] + [("b7", 0)]


def warm_wire_aot(mesh=None, chunk_bytes: int = 1 << 20) -> None:
    """Compile + persist every decode program a
    ``--wire-upload``/``DSI_STREAM_WIRE`` run at this chunk shape can
    reach, from shape structs alone (``warm_kernels.py --phase
    wire``)."""
    from dsi_tpu.parallel.shuffle import default_mesh

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    for mode, cap in _decode_shapes(chunk_bytes):
        aot_decode_fn(_decode_example(n_dev, chunk_bytes, cap, mode),
                      n_dev=n_dev, n=chunk_bytes, lit_cap=cap, mode=mode)


def wire_programs_persisted(mesh=None, chunk_bytes: int = 1 << 20) -> bool:
    """True when every decode program at this shape is already
    persisted — the bench/CLI cold-compile gate,
    ``stream_programs_persisted``'s twin."""
    from dsi_tpu.backends.aotcache import is_persisted
    from dsi_tpu.parallel.shuffle import default_mesh

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    for mode, cap in _decode_shapes(chunk_bytes):
        name, fn = _decode_program(n_dev=n_dev, n=chunk_bytes,
                                   lit_cap=cap, mode=mode)
        if not is_persisted(name, fn,
                            (_decode_example(n_dev, chunk_bytes, cap,
                                             mode),),
                            donate_argnums=_WIRE_DONATE):
            return False
    return True
