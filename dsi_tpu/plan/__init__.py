"""dsi_tpu.plan — multi-stage dataflow plans without the host round-trip.

Dean & Ghemawat's production MapReduce was a *sequence* of jobs (the
indexing pipeline, OSDI'04 §6.4); this package chains this repo's
engines so stage N+1's upload IS stage N's device-resident output:

* :mod:`~dsi_tpu.plan.graph`  — the :class:`Plan`/:class:`Stage` DAG
  model (+ the two canonical chains: grep → wordcount-over-matches and
  indexer → df-top-k → postings join);
* :mod:`~dsi_tpu.plan.driver` — :func:`run_plan`, driving each stage as
  a resumable step object with relay handoffs
  (``device/relay.py``), stage-manifest commits through ``ckpt/``, and
  resume-at-the-last-completed-stage semantics.

CLI entry point: ``python -m dsi_tpu.cli.planrun``.  DESIGN.md "Plan
layer" documents the graph model, handoff rules, commit protocol, and
blind spots.
"""

from dsi_tpu.plan.graph import (
    STAGE_KINDS,
    Plan,
    PlanError,
    Stage,
    grep_cascade_plan,
    grep_wordcount_plan,
    indexer_join_plan,
    wordcount_topk_plan,
)
from dsi_tpu.plan.driver import (
    PlanHostPath,
    PlanResult,
    run_plan,
)

__all__ = [
    "STAGE_KINDS",
    "Plan",
    "PlanError",
    "PlanHostPath",
    "PlanResult",
    "Stage",
    "grep_cascade_plan",
    "grep_wordcount_plan",
    "indexer_join_plan",
    "run_plan",
    "wordcount_topk_plan",
]
