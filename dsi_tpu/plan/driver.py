"""Plan driver: run a Stage DAG with device-resident handoffs.

The execution half of ``dsi_tpu/plan`` (graph model in
``plan/graph.py``): stages run in topological order, each as a
resumable step object (``parallel/stepobj.py``) driven one ``advance()``
at a time, and the edge between two stages is a relay
(``device/relay.py``) — stage N+1's upload IS stage N's device-resident
output.  ``staged=True`` swaps every relay for its host flavor (full
materialization between stages), which is both the A/B baseline the
bench row measures against and the bit-parity oracle the tests compare
with: the two modes produce identical results by construction.

## Stage commits (crash-resume at stage granularity)

With ``checkpoint_dir``, each completed stage writes a durable STAGE
MANIFEST through the existing checkpoint machinery
(``ckpt/store.py`` — CRC'd payload + manifest, newest-valid-wins): the
stage's result plus whatever its downstream edge needs (the relay
image, the indexer's service images).  A ``resume=True`` run walks the
stage stores in plan order and skips every stage whose manifest
verifies, reconstructing its outputs host-side — so a crash ANYWHERE in
the chain (including a real ``os._exit`` mid-stage, the CI smoke)
resumes from the last completed stage's commit point, not from zero.  A
torn stage manifest simply fails verification and that stage re-runs
from its upstream's commit — the fallback the ckpt store's
newest-valid-wins walk already owes us.

Fault points (``ckpt/fault.py`` discipline, arbitrary names accepted):
``plan-stage<i>-advance`` fires per ``advance()`` of stage *i* (so
"kill mid-stage-2" is deterministic regardless of how many steps stage
1 ran), and ``post-stage-commit`` right after a stage manifest lands.

Blind spots, stated: intra-stage engine checkpoints are disabled on
chained stages (a byte cursor has no meaning over a device relay), so a
crash mid-stage re-runs THAT stage from its upstream commit; a stream
that needs the host path (non-ASCII, non-literal pattern) fails the
chain loudly instead of silently degrading — run the engines standalone
for host-path inputs.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from dsi_tpu.ckpt import CheckpointStore, fault_point
from dsi_tpu.obs import metrics_scope, span as _span
from dsi_tpu.plan.graph import Plan, PlanError, Stage


class PlanHostPath(RuntimeError):
    """A stage's engine routed to the host path: the chain cannot keep
    the intermediate on device, and silently degrading would invalidate
    the zero-host-bytes contract — the caller decides what to do."""


class StageOut:
    """One stage's outputs in the driver context: ``result`` (the
    stage's value), ``relay`` (the outgoing byte relay, grep), and
    ``handoff`` (exported live services, indexer).  ``relay_spent``
    marks a relay consumed INSIDE the producing run (the pipelined
    handoff): its stage manifest carries no relay image, so a resume
    may trust it only while the consumer's manifest verifies too."""

    __slots__ = ("result", "relay", "handoff", "resumed", "relay_spent")

    def __init__(self, result=None, relay=None, handoff=None,
                 resumed: bool = False, relay_spent: bool = False):
        self.result = result
        self.relay = relay
        self.handoff = handoff
        self.resumed = resumed
        self.relay_spent = relay_spent


class PlanResult:
    """``results[name]`` per stage, ``final`` = last stage's result,
    ``stats`` = the run's plan scope (plan_* keys, obs/registry.py)."""

    def __init__(self, results: Dict, final, stats: Dict):
        self.results = results
        self.final = final
        self.stats = stats


def _spill_bytes(plan: Plan) -> int:
    mb = plan.defaults.get("spill_mb")
    if mb is None:
        try:
            mb = float(os.environ.get("DSI_PLAN_SPILL_MB", "0"))
        except ValueError:
            mb = 0.0
    return int(float(mb) * 1e6)


def _drive(step, i: int):
    """Advance stage *i* to completion (rung restarts included) with
    the per-advance fault point, then close."""
    while True:
        fault_point(f"plan-stage{i}-advance")
        if not step.advance():
            break
    return step.close()


def _drive_many(steps, i: int):
    """Round-robin the K shard attempts of stage *i* to completion —
    one ``advance()`` per live step per pass, so the shards' device
    work interleaves instead of running serially, with the same
    per-advance fault point as the single-step path."""
    live = list(steps)
    while live:
        nxt = []
        for st in live:
            fault_point(f"plan-stage{i}-advance")
            if st.advance():
                nxt.append(st)
        live = nxt
    return [st.close() for st in steps]


def _merge_grep_results(results):
    """Sum-merge K shard-grep results: lines/matched/occurrences/hist
    add exactly (shards partition the line stream at newline cuts);
    per-shard top-k ranks by SHARD-LOCAL line numbers and is not
    globally mergeable, so the merged result omits it — the
    ``mr/shards.merge_grep`` precedent."""
    from dsi_tpu.parallel.grepstream import GrepStreamResult

    hist = None
    lines = matched = occurrences = 0
    for r in results:
        lines += r.lines
        matched += r.matched
        occurrences += r.occurrences
        hist = (list(r.hist) if hist is None
                else [a + b for a, b in zip(hist, r.hist)])
    return GrepStreamResult(lines, matched, occurrences,
                            tuple(hist or ()), ())


def _merge_counts(results):
    """Sum-merge K shard-wordcount results ``{word: (count, part)}``:
    counts add (token-safe cuts), the partition is a pure function of
    the word so any shard's value is THE value."""
    total: Dict = {}
    for res in results:
        for w, (c, part) in res.items():
            prev = total.get(w)
            total[w] = (c + prev[0] if prev else c, part)
    return total


def _shard_specs(plan: Plan, stage: Stage, stage_shards: int):
    """The stage's shard plan, or None when sharding doesn't apply: K<2,
    a non-source stage (its input is an upstream relay, not a byte
    range), or a ``data`` source (``plan_shards`` geometry is
    file-backed).  Uses the SAME newline-aligned splitter as the shard
    scheduler — one geometry, one safety argument."""
    if stage_shards <= 1 or stage.deps:
        return None
    paths = plan.param(stage, "paths")
    if not paths:
        return None
    from dsi_tpu.mr.shards import plan_shards

    specs = plan_shards(list(paths), stage_shards)
    return specs if len(specs) > 1 else None


def _spec_blocks(plan: Plan, stage: Stage, spec):
    from dsi_tpu.mr.shards import read_stream_range

    return read_stream_range(list(plan.param(stage, "paths")),
                             spec.start, spec.end)


def _stage_store(checkpoint_dir: str, i: int, stage: Stage,
                 plan_sig: Dict, staged: bool) -> CheckpointStore:
    """One ckpt store per stage, keyed by the plan signature + handoff
    mode: resuming a chained run from a staged run's manifests (or
    either from a different plan) refuses instead of misreading."""
    d = os.path.join(checkpoint_dir, f"stage{i:02d}-{stage.name}")
    return CheckpointStore(d, f"plan-{stage.kind}",
                           {"plan": plan_sig, "stage": stage.name,
                            "staged": bool(staged)})


# ── result codecs (stage-commit payloads) ─────────────────────────────


def _encode_counts(d: Dict) -> Dict[str, np.ndarray]:
    words = sorted(d)
    joined = "\n".join(words).encode("ascii")
    return {"wc_words": np.frombuffer(joined, np.uint8).copy(),
            "wc_cnt": np.array([d[w][0] for w in words], np.int64),
            "wc_part": np.array([d[w][1] for w in words], np.int64)}


def _decode_counts(arrays: Dict[str, np.ndarray]) -> Dict:
    raw = np.asarray(arrays.get("wc_words", np.zeros(0, np.uint8)),
                     np.uint8).tobytes().decode("ascii")
    words = raw.split("\n") if raw else []
    cnt = np.asarray(arrays.get("wc_cnt", np.zeros(0)), np.int64)
    part = np.asarray(arrays.get("wc_part", np.zeros(0)), np.int64)
    return {w: (int(c), int(p)) for w, c, p in zip(words, cnt, part)}


def _encode_words(words: List[str], prefix: str) -> Dict[str, np.ndarray]:
    joined = "\n".join(words).encode("ascii")
    return {f"{prefix}words": np.frombuffer(joined, np.uint8).copy()}


def _decode_words(arrays: Dict[str, np.ndarray], prefix: str) -> List[str]:
    raw = np.asarray(arrays.get(f"{prefix}words", np.zeros(0, np.uint8)),
                     np.uint8).tobytes().decode("ascii")
    return raw.split("\n") if raw else []


def _encode_join(join: Dict) -> Dict[str, np.ndarray]:
    words = sorted(join, key=lambda w: (-join[w][0], w))
    docs_flat: List[int] = []
    offs = [0]
    for w in words:
        docs_flat.extend(join[w][2])
        offs.append(len(docs_flat))
    out = _encode_words(words, "j_")
    out["j_df"] = np.array([join[w][0] for w in words], np.int64)
    out["j_part"] = np.array([join[w][1] for w in words], np.int64)
    out["j_docs"] = np.array(docs_flat, np.int64)
    out["j_offs"] = np.array(offs, np.int64)
    return out


def _decode_join(arrays: Dict[str, np.ndarray]) -> Dict:
    words = _decode_words(arrays, "j_")
    df = np.asarray(arrays.get("j_df", np.zeros(0)), np.int64)
    part = np.asarray(arrays.get("j_part", np.zeros(0)), np.int64)
    docs = np.asarray(arrays.get("j_docs", np.zeros(0)), np.int64)
    offs = np.asarray(arrays.get("j_offs", np.zeros(1)), np.int64)
    return {w: (int(df[i]), int(part[i]),
                tuple(int(x) for x in docs[offs[i]:offs[i + 1]]))
            for i, w in enumerate(words)}


# ── the driver ────────────────────────────────────────────────────────


def run_plan(plan: Plan, *, mesh=None, staged: bool = False,
             checkpoint_dir: Optional[str] = None, resume: bool = False,
             pipelined: bool = False, stage_shards: int = 0,
             stats: Optional[dict] = None) -> PlanResult:
    """Run ``plan`` end to end (module docstring).  ``staged=True`` is
    the host-materialization baseline; results are bit-identical to the
    chained mode by construction.  ``checkpoint_dir`` turns stage
    boundaries into durable commit points; ``resume=True`` skips every
    stage whose manifest verifies.

    ``pipelined=True`` overlaps a grep→wordcount pair: the wordcount
    consumes relay buffers as they SEAL, while the grep is still
    producing (``plan_overlap_s`` attributes the overlapped wall).
    Chained mode only — staged execution stays strictly sequential and
    remains the bit-parity oracle.  ``stage_shards=K`` runs a
    file-backed source stage as K concurrent newline-aligned shard
    attempts (``mr/shards.plan_shards`` geometry) merged through the
    deterministic shard codecs."""
    from dsi_tpu.parallel.shuffle import default_mesh

    if resume and not checkpoint_dir:
        raise PlanError("resume=True requires checkpoint_dir")
    if mesh is None:
        mesh = default_mesh()
    pipelined = bool(pipelined) and not staged
    stage_shards = max(0, int(stage_shards or 0))
    sc = metrics_scope("plan")
    sc.update({"plan_stages": len(plan), "plan_intermediate_bytes": 0,
               "plan_commit_bytes": 0, "plan_resumed_stages": 0,
               "plan_handoff": "host" if staged else "device",
               "plan_pipelined": int(pipelined),
               "plan_stage_shards": stage_shards,
               "plan_overlap_s": 0.0,
               "plan_s": 0.0, "stage_commit_s": 0.0,
               "plan_stage_walls": {}})
    order = plan.ordered()
    sig = plan.signature()
    ctx: Dict[str, StageOut] = {}
    completed = 0
    if checkpoint_dir:
        if resume:
            for i, stage in enumerate(order):
                loaded = _stage_store(checkpoint_dir, i, stage, sig,
                                      staged).load_latest()
                if loaded is None:
                    break  # this stage (and everything after) re-runs
                meta, arrays = loaded
                ctx[stage.name] = _load_commit(plan, stage, meta, arrays,
                                               mesh, staged, sc)
                completed += 1
            # A spent-relay manifest (pipelined producer) holds no relay
            # image: it is only trustworthy while its consumer's
            # manifest verifies too.  A consumer always sits LATER in
            # topo order, so a spent producer as the LAST loaded stage
            # means its consumer is missing — the producer must re-run
            # as well (resuming it would hand the consumer an empty
            # relay and silently produce empty counts).
            while completed > 0 \
                    and ctx[order[completed - 1].name].relay_spent:
                del ctx[order[completed - 1].name]
                completed -= 1
            sc["plan_resumed_stages"] = completed
        else:
            for i, stage in enumerate(order):
                _stage_store(checkpoint_dir, i, stage, sig,
                             staged).reset()

    def commit(i: int, stage: Stage, out: StageOut) -> None:
        with _span("stage_commit", lane="plan", stats=sc,
                   key="stage_commit_s", stage=stage.name):
            arrays, meta = _commit_payload(plan, stage, out, staged)
            store = _stage_store(checkpoint_dir, i, stage, sig, staged)
            store.save(arrays, meta)
            sc["plan_commit_bytes"] += store.last_payload_bytes
        fault_point("post-stage-commit")

    i = completed
    while i < len(order):
        stage = order[i]
        nxt = order[i + 1] if i + 1 < len(order) else None
        if (pipelined and stage.kind == "grep" and not stage.deps
                and nxt is not None and nxt.kind == "wordcount"
                and list(nxt.deps) == [stage.name]):
            # The fused pair: both stages run interleaved; commits land
            # afterwards, in plan order, with the grep manifest marked
            # relay-spent (its buffers were consumed in flight).
            t0 = time.perf_counter()
            g_out, w_out, g_wall = _run_pipelined_pair(
                plan, i, stage, nxt, mesh, sc, stage_shards)
            ctx[stage.name] = g_out
            ctx[nxt.name] = w_out
            sc["plan_stage_walls"][stage.name] = round(g_wall, 4)
            sc["plan_stage_walls"][nxt.name] = round(
                time.perf_counter() - t0, 4)
            if checkpoint_dir:
                commit(i, stage, g_out)
                commit(i + 1, nxt, w_out)
            i += 2
            continue
        t0 = time.perf_counter()
        with _span("plan", stats=sc, key="plan_s", stage=stage.name,
                   kind=stage.kind):
            out = _run_stage(plan, i, stage, ctx, mesh, staged, sc,
                             stage_shards)
        ctx[stage.name] = out
        sc["plan_stage_walls"][stage.name] = round(
            time.perf_counter() - t0, 4)
        if checkpoint_dir:
            commit(i, stage, out)
        i += 1
    sc["plan_s"] = round(sc["plan_s"], 4)
    sc["stage_commit_s"] = round(sc["stage_commit_s"], 4)
    sc["plan_overlap_s"] = round(sc["plan_overlap_s"], 4)
    if stats is not None:
        stats.update(sc)
    results = {name: out.result for name, out in ctx.items()}
    return PlanResult(results, ctx[order[-1].name].result, sc)


def _engine_kw(plan: Plan, stage: Stage) -> Dict:
    return {
        "chunk_bytes": int(plan.param(stage, "chunk_bytes", 1 << 20)),
        "depth": plan.param(stage, "depth"),
        "aot": bool(plan.param(stage, "aot", False)),
        "device_accumulate": bool(
            plan.param(stage, "device_accumulate", False)),
        "sync_every": plan.param(stage, "sync_every"),
        "mesh_shards": plan.param(stage, "mesh_shards"),
    }


def _source_blocks(plan: Plan, stage: Stage):
    paths = plan.param(stage, "paths")
    data = plan.param(stage, "data")
    if paths:
        from dsi_tpu.parallel.streaming import stream_files

        return stream_files(list(paths))
    if data is not None:
        return [bytes(data)]
    raise PlanError(f"stage {stage.name!r} has neither paths nor data")


class _RelayFeed:
    """Queue-backed ``device_batches`` iterable for the pipelined
    handoff: the driver ``put``s each buffer the moment the producing
    relay seals it, and the consuming wordcount's batch feed blocks on
    the queue instead of on a materialized list.  The driver only
    advances the consumer while fed-but-unconsumed buffers remain
    (one pump dispatches exactly one item — ``pipeline.StepPipeline``
    invariant), so the feed never deadlocks."""

    _DONE = object()

    def __init__(self):
        import queue

        self._q = queue.Queue()

    def put(self, buf) -> None:
        self._q.put(buf)

    def close(self) -> None:
        self._q.put(self._DONE)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                return
            yield item


def _grep_steps(plan: Plan, stage: Stage, relay, mesh, kw,
                stage_shards: int, ctx: Optional[Dict] = None):
    """The stage's grep step(s): K shard steps over newline-aligned
    byte ranges when sharding applies, else one step over the whole
    source (or the upstream relay's line stream — the cascade)."""
    from dsi_tpu.parallel.grepstream import GrepStep

    pattern = plan.param(stage, "pattern")
    topk = int(plan.param(stage, "topk", 16))
    if stage.deps:
        up = ctx[stage.deps[0]]
        src = (up.relay.blocks() if hasattr(up.relay, "blocks")
               else up.relay.host_blocks())
        return [GrepStep(src, pattern, mesh=mesh, topk=topk,
                         line_sink=relay, **kw)], False
    specs = _shard_specs(plan, stage, stage_shards)
    if specs is None:
        return [GrepStep(_source_blocks(plan, stage), pattern, mesh=mesh,
                         topk=topk, line_sink=relay, **kw)], False
    return [GrepStep(_spec_blocks(plan, stage, spec), pattern, mesh=mesh,
                     topk=topk, line_sink=relay, **kw)
            for spec in specs], True


def _run_pipelined_pair(plan: Plan, i: int, g_stage: Stage,
                        wc_stage: Stage, mesh, sc: dict,
                        stage_shards: int):
    """The fused grep→wordcount pair: the wordcount consumes relay
    buffers as they SEAL while the grep(s) keep producing.  The
    consumer is only advanced while fed-but-unconsumed buffers exist,
    so the interleave can never block on an empty feed; wall spent in
    consumer advances BEFORE the producer finishes is the overlap the
    pipelining bought (``plan_overlap_s``, ``stage_overlap`` spans)."""
    from dsi_tpu.device.relay import DeviceRelay
    from dsi_tpu.parallel.streaming import WordcountStep

    kw = _engine_kw(plan, g_stage)
    relay = DeviceRelay(mesh, cap=kw["chunk_bytes"], aot=kw["aot"],
                        stats=sc, spill_bytes=_spill_bytes(plan))
    gsteps, sharded = _grep_steps(plan, g_stage, relay, mesh, kw,
                                  stage_shards)
    wkw = _engine_kw(plan, wc_stage)
    feed = _RelayFeed()
    wc = WordcountStep([], mesh=mesh,
                       n_reduce=int(plan.param(wc_stage, "n_reduce", 10)),
                       u_cap=int(plan.param(wc_stage, "u_cap", 1 << 12)),
                       device_batches=feed, **wkw)
    fed = consumed = 0
    wc_live = True
    t0 = time.perf_counter()
    with _span("plan", stats=sc, key="plan_s", stage=g_stage.name,
               kind="grep"):
        live = list(gsteps)
        while live:
            nxt = []
            for st in live:
                fault_point(f"plan-stage{i}-advance")
                if st.advance():
                    nxt.append(st)
            live = nxt
            for buf in relay.take_sealed():
                feed.put(buf)
                fed += 1
            if wc_live and consumed < fed:
                with _span("stage_overlap", lane="plan", stats=sc,
                           key="plan_overlap_s", stage=wc_stage.name):
                    while wc_live and consumed < fed:
                        fault_point(f"plan-stage{i + 1}-advance")
                        wc_live = wc.advance()
                        consumed += 1
        g_results = [st.close() for st in gsteps]
    g_wall = time.perf_counter() - t0
    if any(r is None for r in g_results):
        feed.close()
        wc.abort()
        raise PlanHostPath(f"stage {g_stage.name!r}: grep needs the "
                           f"host path (non-literal pattern or "
                           f"over-wide line)")
    g_res = (_merge_grep_results(g_results) if sharded
             else g_results[0])
    relay.finish()
    for buf in relay.take_sealed():
        feed.put(buf)
        fed += 1
    feed.close()
    with _span("plan", stats=sc, key="plan_s", stage=wc_stage.name,
               kind="wordcount"):
        while wc_live:
            fault_point(f"plan-stage{i + 1}-advance")
            wc_live = wc.advance()
        w_res = wc.close()
    if w_res is None:
        raise PlanHostPath(f"stage {wc_stage.name!r}: wordcount needs "
                           f"the host path (non-ASCII or >64-byte "
                           f"word)")
    return (StageOut(result=g_res, relay=relay, relay_spent=True),
            StageOut(result=w_res), g_wall)


def _run_stage(plan: Plan, i: int, stage: Stage, ctx: Dict, mesh,
               staged: bool, sc: dict, stage_shards: int = 0) -> StageOut:
    kw = _engine_kw(plan, stage)
    if stage.kind == "grep":
        from dsi_tpu.device.relay import DeviceRelay, HostRelay

        relay = (HostRelay(stats=sc) if staged
                 else DeviceRelay(mesh, cap=kw["chunk_bytes"],
                                  aot=kw["aot"], stats=sc,
                                  spill_bytes=_spill_bytes(plan)))
        steps, sharded = _grep_steps(plan, stage, relay, mesh, kw,
                                     stage_shards, ctx)
        results = _drive_many(steps, i) if sharded \
            else [_drive(steps[0], i)]
        if any(r is None for r in results):
            raise PlanHostPath(f"stage {stage.name!r}: grep needs the "
                               f"host path (non-literal pattern or "
                               f"over-wide line)")
        if sharded:
            res = _merge_grep_results(results)
        else:
            res = results[0]
            if stage.deps:
                # A cascade stage's line numbers follow the relay's
                # buffer order, which legitimately differs between the
                # two handoff modes — drop the (line_no, occ) ranks so
                # staged and chained results stay bit-comparable, the
                # merge_grep precedent.
                res = res._replace(topk=())
        return StageOut(result=res, relay=relay)

    if stage.kind == "wordcount":
        from dsi_tpu.parallel.streaming import WordcountStep

        wc_kw = dict(kw, n_reduce=int(plan.param(stage, "n_reduce", 10)),
                     u_cap=int(plan.param(stage, "u_cap", 1 << 12)))
        if stage.deps:
            up = ctx[stage.deps[0]]
            if hasattr(up.relay, "blocks"):  # staged / restored host
                step = WordcountStep(up.relay.blocks(), mesh=mesh,
                                     **wc_kw)
            else:
                step = WordcountStep([], mesh=mesh,
                                     device_batches=up.relay.batches(),
                                     **wc_kw)
            res = _drive(step, i)
            if res is None:
                raise PlanHostPath(f"stage {stage.name!r}: wordcount "
                                   f"needs the host path (non-ASCII or "
                                   f">64-byte word)")
            return StageOut(result=res)
        # A source wordcount (no upstream): plain stream, K shard
        # attempts when sharding applies.
        specs = _shard_specs(plan, stage, stage_shards)
        if specs is None:
            steps = [WordcountStep(_source_blocks(plan, stage),
                                   mesh=mesh, **wc_kw)]
        else:
            steps = [WordcountStep(_spec_blocks(plan, stage, spec),
                                   mesh=mesh, **wc_kw)
                     for spec in specs]
        results = _drive_many(steps, i) if len(steps) > 1 \
            else [_drive(steps[0], i)]
        if any(r is None for r in results):
            raise PlanHostPath(f"stage {stage.name!r}: wordcount needs "
                               f"the host path (non-ASCII or >64-byte "
                               f"word)")
        return StageOut(result=results[0] if len(results) == 1
                        else _merge_counts(results))

    if stage.kind == "top_k":
        fault_point(f"plan-stage{i}-advance")
        k = int(plan.param(stage, "topk", 16))
        counts = ctx[stage.deps[0]].result
        return StageOut(result=tuple(sorted(
            ((int(c), w) for w, (c, _p) in counts.items()),
            key=lambda r: (-r[0], r[1]))[:k]))

    if stage.kind == "indexer":
        from dsi_tpu.parallel.grepstream import IndexerStep

        step = IndexerStep(list(plan.param(stage, "docs")), mesh=mesh,
                           n_reduce=int(plan.param(stage, "n_reduce", 10)),
                           u_cap=int(plan.param(stage, "u_cap", 1 << 15)),
                           topk=int(plan.param(stage, "topk", 16)),
                           keep_services=not staged,
                           depth=kw["depth"],
                           device_accumulate=kw["device_accumulate"],
                           sync_every=kw["sync_every"],
                           mesh_shards=kw["mesh_shards"])
        res = _drive(step, i)
        if res is None:
            raise PlanHostPath(f"stage {stage.name!r}: indexer needs "
                               f"the host path (non-ASCII or >64-byte "
                               f"word)")
        if staged:
            return StageOut(result=res)
        return StageOut(result=None, handoff=step.exported)

    if stage.kind == "df_topk":
        fault_point(f"plan-stage{i}-advance")
        k = int(plan.param(stage, "topk", 16))
        up = ctx[stage.deps[0]]
        if up.handoff is None:  # staged (or restored) indexer result
            _, top = up.result
            return StageOut(result=tuple(top[:k]))
        return StageOut(result=_df_topk_from_handoff(up.handoff, k))

    if stage.kind == "postings_join":
        fault_point(f"plan-stage{i}-advance")
        up_idx = ctx[stage.deps[0]]
        top = ctx[stage.deps[1]].result
        words = [w for _, w in top]
        if up_idx.handoff is None:
            postings, _ = up_idx.result
            join = {w: (df, postings[w][0], tuple(postings[w][1]))
                    for df, w in top if w in postings}
        else:
            h = up_idx.handoff
            if h.get("postings_svc") is not None:
                h["postings_svc"].close()  # flush the device buffer's
                h["postings_svc"] = None  # remainder into the table
            packed = h["table"].finalize_packed()
            found = packed.lookup_many(words)
            join = {w: (df, found[w][0],
                        tuple(d for d, _ in found[w][1]))
                    for df, w in top if w in found}
        return StageOut(result=join)

    raise PlanError(f"unrunnable stage kind {stage.kind!r}")


def _df_topk_from_handoff(h: Dict, k: int) -> Tuple:
    """The chained df-top-k: a k-row snapshot off the RESIDENT df table
    (no drain-to-host) when it holds the complete state; the exact
    drain fallback when a widen already spilled rows into the host
    accumulator (or there is no device table at all) — the fallback is
    counted pull volume, never a correctness trade."""
    from dsi_tpu.ops.wordcount import decode_packed

    tk = h.get("topk_svc")
    df_acc = h["df_acc"]
    residue = bool(df_acc.snapshot())
    if tk is not None and not residue:
        tk.sync()  # flushes the fold lag, pulls k rows per device
        out = []
        for c, keys, ln in tk.snapshot:
            w = decode_packed(np.array([keys], np.uint32),
                              np.array([int(ln)]), 1)[0]
            out.append((int(c), w))
        h["topk_svc"] = None  # the table is never drained: drop it
        return tuple(out[:k])
    if tk is not None:
        tk.close()  # exact drain into df_acc (the widen-residue path)
        h["topk_svc"] = None
    dfm = {w: c for w, (c, _p) in df_acc.finalize().items()}
    if not dfm:
        # Host-merge indexer (no dacc): document frequency is the
        # postings list length; close any device buffer first.
        if h.get("postings_svc") is not None:
            h["postings_svc"].close()
            h["postings_svc"] = None
        dfm = {w: int(e - s) for w, s, e in _word_spans(h["table"])}
    return tuple(sorted(((c, w) for w, c in dfm.items()),
                        key=lambda r: (-r[0], r[1]))[:k])


def _word_spans(table):
    from dsi_tpu.ops.wordcount import decode_packed

    packed = table.finalize_packed()
    words = decode_packed(packed.skeys, packed.lens, len(packed.skeys))
    for i, w in enumerate(words):
        yield w, int(packed.starts[i]), int(packed.ends[i])


# ── stage-commit payloads ─────────────────────────────────────────────


def _commit_payload(plan: Plan, stage: Stage, out: StageOut,
                    staged: bool) -> Tuple[Dict, Dict]:
    meta = {"stage": stage.name, "kind": stage.kind}
    if stage.kind == "grep":
        res = out.result
        if out.relay_spent:
            # The pipelined producer: its relay was consumed in-flight,
            # so the manifest carries the scalar result only.  The
            # paired resume-invalidation in run_plan drops this
            # manifest whenever its consumer's commit is missing.
            arrays = {}
            meta["relay_spent"] = True
        else:
            arrays = out.relay.capture()
            meta["relay_cap"] = int(plan.param(stage, "chunk_bytes",
                                               1 << 20))
        arrays["g_hist"] = np.array(res.hist, np.int64)
        arrays["g_tot"] = np.array(
            [res.lines, res.matched, res.occurrences], np.int64)
        arrays["g_topk"] = np.array(res.topk, np.int64).reshape(-1, 2)
        return arrays, meta
    if stage.kind == "wordcount":
        return _encode_counts(out.result), meta
    if stage.kind == "top_k":
        arrays = _encode_words([w for _, w in out.result], "t_")
        arrays["t_df"] = np.array([c for c, _ in out.result], np.int64)
        return arrays, meta
    if stage.kind == "indexer":
        if staged:
            postings, top = out.result
            join_like = {w: (len(ds), part, tuple(ds))
                         for w, (part, ds) in postings.items()}
            arrays = _encode_join(join_like)
            arrays.update(_encode_words([w for _, w in top], "t_"))
            arrays["t_df"] = np.array([c for c, _ in top], np.int64)
            return arrays, meta
        h = out.handoff
        arrays: Dict[str, np.ndarray] = {}
        tk = h.get("topk_svc")
        if tk is not None:
            for kk2, v in tk.checkpoint_state().items():
                arrays[f"tk_{kk2}"] = np.asarray(v)
            meta["table_kk"] = tk.kk
        pb = h.get("postings_svc")
        if pb is not None:
            img = pb.checkpoint_state()
            arrays["pb_buf"] = np.asarray(img["buf"])
            arrays["pb_nrows"] = np.asarray(img["nrows"])
        for kk2, v in h["df_acc"].snapshot().items():
            arrays[f"df_{kk2}"] = np.asarray(v)
        for kk2, v in h["table"].snapshot().items():
            arrays[f"pt_{kk2}"] = np.asarray(v)
        meta["kk"] = h["kk"]
        meta["n_real"] = h["n_real"]
        return arrays, meta
    if stage.kind == "df_topk":
        arrays = _encode_words([w for _, w in out.result], "t_")
        arrays["t_df"] = np.array([c for c, _ in out.result], np.int64)
        return arrays, meta
    if stage.kind == "postings_join":
        return _encode_join(out.result), meta
    raise PlanError(f"uncommittable stage kind {stage.kind!r}")


def _load_commit(plan: Plan, stage: Stage, meta: Dict, arrays: Dict,
                 mesh, staged: bool, sc: dict) -> StageOut:
    """Reconstruct a completed stage's outputs from its manifest —
    host-side (device state died with the crashed process; the drain
    path re-derives equivalent host state, the cross-degree-resume
    argument)."""
    if stage.kind == "grep":
        from dsi_tpu.device.relay import DeviceRelay, HostRelay
        from dsi_tpu.parallel.grepstream import GrepStreamResult

        tot = arrays["g_tot"]
        res = GrepStreamResult(
            int(tot[0]), int(tot[1]), int(tot[2]),
            tuple(int(x) for x in arrays["g_hist"]),
            tuple((int(a), int(b)) for a, b in arrays["g_topk"]))
        if meta.get("relay_spent"):
            return StageOut(result=res, relay=None, resumed=True,
                            relay_spent=True)
        if "hbytes" in arrays:
            relay = HostRelay.restore(arrays, stats=sc)
        else:
            relay = DeviceRelay.restore(
                mesh, arrays, cap=int(meta["relay_cap"]), stats=sc)
        return StageOut(result=res, relay=relay, resumed=True)
    if stage.kind == "wordcount":
        return StageOut(result=_decode_counts(arrays), resumed=True)
    if stage.kind == "top_k":
        top = tuple(zip((int(c) for c in arrays.get("t_df", ())),
                        _decode_words(arrays, "t_")))
        return StageOut(result=top, resumed=True)
    if stage.kind == "indexer":
        if staged:
            join_like = _decode_join(arrays)
            postings = {w: (part, list(ds))
                        for w, (_df, part, ds) in join_like.items()}
            top = tuple(zip(
                (int(c) for c in arrays.get("t_df", ())),
                _decode_words(arrays, "t_")))
            return StageOut(result=(postings, top), resumed=True)
        from dsi_tpu.device.postings import DevicePostings
        from dsi_tpu.device.table import DeviceTable
        from dsi_tpu.parallel.merge import PackedCounts, PostingsTable

        kk = int(meta["kk"])
        n_real = int(meta["n_real"])
        df_acc = PackedCounts()
        df_acc.restore({k[3:]: v for k, v in arrays.items()
                        if k.startswith("df_")})
        table = PostingsTable()
        table.restore({k[3:]: v for k, v in arrays.items()
                       if k.startswith("pt_")})
        tk_img = {k[3:]: v for k, v in arrays.items()
                  if k.startswith("tk_")}
        if tk_img:
            DeviceTable.drain_image(df_acc, tk_img)
        if "pb_buf" in arrays:
            def sink(r):
                r = r[r[:, kk + 2] < n_real]
                if len(r):
                    table.add(r, kk)

            DevicePostings.drain_image(
                sink, {"buf": arrays["pb_buf"],
                       "nrows": arrays["pb_nrows"]})
        handoff = {"kk": kk, "n_real": n_real, "topk_svc": None,
                   "postings_svc": None, "df_acc": df_acc,
                   "table": table, "device_accumulate": True}
        return StageOut(result=None, handoff=handoff, resumed=True)
    if stage.kind == "df_topk":
        top = tuple(zip((int(c) for c in arrays.get("t_df", ())),
                        _decode_words(arrays, "t_")))
        return StageOut(result=top, resumed=True)
    if stage.kind == "postings_join":
        return StageOut(result=_decode_join(arrays), resumed=True)
    raise PlanError(f"unloadable stage kind {stage.kind!r}")
