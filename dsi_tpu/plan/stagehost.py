"""Net-served plan stages: one process per stage, relays over TCP.

The plan layer's share-nothing harness (ISSUE 18).  ``planrun --hosts``
runs every stage of a multi-stage plan in its OWN process with a
PRIVATE working directory: a stage host rebuilds the plan from a spec,
fetches its dependencies' sealed stage payloads from the predecessors'
partition servers over the stream transport (the same ``Fetch`` verb +
one-byte wirecodec flag the shuffle uses, prefetch-pipelined when a
stage has several deps), reconstructs them with the stage-commit codec
(``driver._load_commit`` — the checkpoint/resume machinery, so parity
with the in-process modes holds by construction), runs its stage, and
registers its OWN sealed output (``driver._commit_payload`` serialized
to one payload blob) with its partition server.  No stage ever reads
another stage's directory: the only bytes that cross stage boundaries
cross them over TCP.

Payload blob format (``pack_commit``/``unpack_commit``)::

    b"DSP1" [4-byte BE meta length] [meta JSON] [np.savez archive]

``allow_pickle=False`` on load — the payload crosses a network
boundary.

The parent (``cli/planrun.py --hosts``) spawns stage hosts in topo
order, hands each a ``spec.json`` carrying the plan-rebuild arguments
plus its deps' ``{addr, name, crc}``, waits for the stage's
``ready.json``, and finally collects every stage's payload over TCP to
assemble the :class:`~dsi_tpu.plan.driver.PlanResult`.  After writing
``ready.json`` a stage host LINGERS as a server (mrworker discipline)
until the parent terminates it — consumers may not have fetched yet.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import struct
import time
import zlib
from typing import Dict, Tuple

import numpy as np

_MAGIC = b"DSP1"
_LEN = struct.Struct(">I")


def pack_commit(arrays: Dict[str, np.ndarray], meta: Dict) -> bytes:
    """One stage commit (``_commit_payload`` output) as one blob."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    mb = json.dumps(meta, sort_keys=True).encode("utf-8")
    return _MAGIC + _LEN.pack(len(mb)) + mb + buf.getvalue()


def unpack_commit(blob: bytes) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Inverse of :func:`pack_commit`; raises ``ValueError`` on a
    foreign or torn blob (the caller treats it like a CRC failure)."""
    if blob[:4] != _MAGIC:
        raise ValueError(f"not a stage payload (magic {blob[:4]!r})")
    (n,) = _LEN.unpack(blob[4:8])
    meta = json.loads(blob[8:8 + n].decode("utf-8"))
    with np.load(io.BytesIO(blob[8 + n:]), allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    return arrays, meta


def payload_name(i: int, stage_name: str) -> str:
    return f"plan-{i}-{stage_name}"


def build_plan(spec: Dict):
    """Rebuild the canonical plan a spec describes — shared by
    ``planrun`` (which derives the spec from argv) and every stage host
    (which must see the IDENTICAL plan graph)."""
    from dsi_tpu.plan import (grep_cascade_plan, grep_wordcount_plan,
                              indexer_join_plan, wordcount_topk_plan)

    defaults = dict(chunk_bytes=spec.get("chunk_bytes", 1 << 20),
                    depth=spec.get("depth"),
                    device_accumulate=bool(
                        spec.get("device_accumulate", False)),
                    sync_every=spec.get("sync_every"),
                    mesh_shards=spec.get("mesh_shards"),
                    aot=bool(spec.get("aot", False)),
                    n_reduce=spec.get("n_reduce", 10),
                    u_cap=spec.get("u_cap", 1 << 12),
                    topk=spec.get("topk", 16))
    chain = spec["chain"]
    files = list(spec.get("files") or ())
    if chain == "grep-wc":
        return grep_wordcount_plan(spec["pattern"], paths=files,
                                   **defaults)
    if chain == "grep-grep":
        return grep_cascade_plan(spec["pattern"], spec["pattern2"],
                                 paths=files, **defaults)
    if chain == "wc-topk":
        return wordcount_topk_plan(defaults["topk"], paths=files,
                                   **defaults)
    if chain == "indexer":
        docs = []
        for path in files:
            with open(path, "rb") as f:
                docs.append(f.read())
        return indexer_join_plan(docs, **defaults)
    raise ValueError(f"unknown chain {chain!r}")


def fetch_stage_payload(addr: str, name: str, crc: int, *, stats=None,
                        timeout: float = 30.0) -> Tuple[Dict, Dict]:
    """Fetch + verify + decode one stage payload from a peer's
    partition server."""
    from dsi_tpu.net.fetch import FetchFailure, fetch_partition

    raw = fetch_partition(addr, name, stats=stats, timeout=timeout)
    if crc and zlib.crc32(raw) != crc:
        raise FetchFailure(-1, addr, name,
                           ValueError("stage payload crc mismatch"))
    return unpack_commit(raw)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--spec", required=True,
                   help="spec.json: plan-rebuild args + stage_index + "
                        "deps' {addr,name,crc} + spool/ready paths")
    args = p.parse_args(argv)
    with open(args.spec, "r", encoding="utf-8") as f:
        spec = json.load(f)

    from dsi_tpu.utils.platformpin import pin_platform_from_env

    pin_platform_from_env()

    from dsi_tpu.net.fetch import (FetchPipeline, fetch_window_from_env)
    from dsi_tpu.net.partsrv import PartitionServer
    from dsi_tpu.obs import metrics_scope, span
    from dsi_tpu.parallel.shuffle import default_mesh
    from dsi_tpu.plan.driver import (_commit_payload, _load_commit,
                                     _run_stage)
    from dsi_tpu.utils.atomicio import atomic_write

    plan = build_plan(spec["plan"])
    order = plan.ordered()
    i = int(spec["stage_index"])
    stage = order[i]
    mesh = default_mesh(spec["plan"].get("devices"))
    sc = metrics_scope("plan")
    net_io = metrics_scope("net")
    srv = PartitionServer(spec["spool"],
                          bind=os.environ.get("DSI_NET_BIND", ""))
    srv.start()
    try:
        # Dependencies: sealed stage payloads from the predecessors'
        # servers — prefetch-pipelined when there are several.
        stage_by_name = {s.name: (j, s) for j, s in enumerate(order)}
        deps = spec.get("deps") or {}
        ctx: Dict = {}

        def absorb(dep_name: str, raw: bytes) -> None:
            from dsi_tpu.net.fetch import FetchFailure

            d = deps[dep_name]
            if d.get("crc") and zlib.crc32(raw) != int(d["crc"]):
                raise FetchFailure(
                    -1, d["addr"], d["name"],
                    ValueError("stage payload crc mismatch"))
            arrays, meta = unpack_commit(raw)
            _j, dep_stage = stage_by_name[dep_name]
            with span("decode", lane="net", part=d["name"]):
                ctx[dep_name] = _load_commit(plan, dep_stage, meta,
                                             arrays, mesh, True, sc)

        window = fetch_window_from_env()
        dep_names = sorted(deps, key=lambda n: stage_by_name[n][0])
        if len(dep_names) > 1 and window > 1:
            items = [(stage_by_name[n][0], deps[n]["addr"],
                      deps[n]["name"]) for n in dep_names]
            by_index = {stage_by_name[n][0]: n for n in dep_names}
            pipe = FetchPipeline(items, window=window, stats=net_io)
            for j, raw in pipe:
                absorb(by_index[j], raw)
        else:
            from dsi_tpu.net.fetch import fetch_partition

            for n in dep_names:
                absorb(n, fetch_partition(deps[n]["addr"],
                                          deps[n]["name"],
                                          stats=net_io))

        t0 = time.perf_counter()
        out = _run_stage(plan, i, stage, ctx, mesh, True, sc,
                         int(spec.get("stage_shards", 0)))
        wall = round(time.perf_counter() - t0, 4)
        arrays, meta = _commit_payload(plan, stage, out, True)
        blob = pack_commit(arrays, meta)
        name = payload_name(i, stage.name)
        crc = srv.put(name, blob)
        ready = {"addr": srv.address, "name": name, "crc": crc,
                 "payload_bytes": len(blob), "stage_wall_s": wall,
                 "net": dict(net_io)}
        with atomic_write(spec["ready"], mode="w") as f:
            json.dump(ready, f, sort_keys=True)
        # Linger as a server: consumers (later stages, the collecting
        # parent) fetch on their own schedule; the parent terminates us.
        while True:
            time.sleep(3600)
    finally:
        srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
