"""Plan/Stage graph model: multi-stage dataflow without the host trip.

Dean & Ghemawat's flagship production use was a *sequence* of five to
ten MapReduces (the indexing pipeline, OSDI'04 §6.4); the 6.5840
contract this repo reproduces materializes every job's full output
before the next can start.  A :class:`Plan` is the declarative side of
the fix: a small DAG of :class:`Stage` nodes whose edges are
device-resident handoffs (``dsi_tpu/device/relay.py``,
``parallel/stepobj.py`` exports) instead of host materializations.  The
driver (``plan/driver.py``) runs it.

Stage kinds (what the driver knows how to run):

* ``grep``          — streaming literal grep over a byte source,
  emitting the matching lines into the outgoing relay (the
  ``GrepStep(line_sink=...)`` emit path).
* ``wordcount``     — streaming word count consuming an upstream relay
  (``WordcountStep(device_batches=...)``) or a host block stream (the
  staged baseline / a source stage).
* ``indexer``       — wave-walk inverted index over a document list,
  completing with live device services exported
  (``IndexerStep(keep_services=True)``).
* ``df_topk``       — k-row document-frequency snapshot off an upstream
  indexer's resident :class:`DeviceTopK` (no drain-to-host).
* ``postings_join`` — per-term postings lookup for an upstream df_topk's
  terms (selective decode, not the full materialization).
* ``top_k``         — k highest-count words of an upstream wordcount's
  result (count desc, word asc) — a host reduction over an
  already-host value, no engine.
* A ``grep`` stage MAY itself have a grep dep (the grep→grep cascade):
  it consumes the upstream relay's line stream instead of a byte
  source and re-greps it with its own pattern.

A plan is VALIDATED at build time (unique names, known deps, acyclic)
and serializes to a :meth:`Plan.signature` — the job identity its stage
manifests carry, so a resume against a different plan refuses instead of
misreading stage payloads.  Bulk inputs (corpus bytes, document lists)
enter the signature as CRCs, not content.
"""

from __future__ import annotations

import json
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

#: The stage kinds plan/driver.py can run.
STAGE_KINDS = ("grep", "wordcount", "indexer", "df_topk", "postings_join",
               "top_k")

#: Stage params carrying bulk payloads: identity-hashed, never inlined
#: into the signature.
_BULK_PARAMS = ("data", "docs", "paths")


class PlanError(ValueError):
    """A malformed plan: unknown kind, missing dep, duplicate name,
    cycle — raised at build/validate time, never mid-run."""


class Stage:
    """One node: ``name`` (unique), ``kind`` (STAGE_KINDS), ``deps``
    (upstream stage names this one consumes), ``params`` (kind-specific
    knobs; bulk inputs under ``data``/``docs``/``paths``)."""

    def __init__(self, name: str, kind: str,
                 deps: Sequence[str] = (), **params):
        if kind not in STAGE_KINDS:
            raise PlanError(f"unknown stage kind {kind!r} "
                            f"(have: {', '.join(STAGE_KINDS)})")
        self.name = str(name)
        self.kind = kind
        self.deps: Tuple[str, ...] = tuple(deps)
        self.params: Dict = dict(params)

    def identity(self) -> Dict:
        """JSON-ready identity: params with bulk payloads replaced by
        (length, crc32) pairs so the signature stays small and stable."""
        out = {"name": self.name, "kind": self.kind,
               "deps": list(self.deps)}
        for k in sorted(self.params):
            v = self.params[k]
            if k in _BULK_PARAMS and v is not None:
                if k == "docs":
                    crc = 0
                    total = 0
                    for d in v:
                        crc = zlib.crc32(bytes(d), crc)
                        total += len(d)
                    out[k] = {"n": len(v), "bytes": total, "crc32": crc}
                elif k == "data":
                    out[k] = {"bytes": len(v),
                              "crc32": zlib.crc32(bytes(v))}
                else:  # paths: names are identity enough (files change
                    out[k] = list(v)  # under any cursor scheme anyway)
            else:
                out[k] = v
        return out


class Plan:
    """An ordered, validated stage DAG.  ``add`` returns the stage so
    chains read naturally::

        p = Plan("grep-wc", chunk_bytes=1 << 20)
        g = p.add(Stage("grep", "grep", pattern="the", paths=files))
        p.add(Stage("wc", "wordcount", deps=[g.name]))
    """

    def __init__(self, name: str, **defaults):
        self.name = str(name)
        #: Plan-wide engine knobs every stage inherits (chunk_bytes,
        #: depth, device_accumulate, sync_every, mesh_shards, aot, ...);
        #: a stage's own params override.
        self.defaults: Dict = dict(defaults)
        self._stages: List[Stage] = []
        self._by_name: Dict[str, Stage] = {}

    def add(self, stage: Stage) -> Stage:
        if stage.name in self._by_name:
            raise PlanError(f"duplicate stage name {stage.name!r}")
        for d in stage.deps:
            if d not in self._by_name:
                raise PlanError(f"stage {stage.name!r} depends on "
                                f"unknown stage {d!r} (deps must be "
                                f"added first — the DAG is built in "
                                f"topological order)")
        self._stages.append(stage)
        self._by_name[stage.name] = stage
        return stage

    def __len__(self) -> int:
        return len(self._stages)

    def __getitem__(self, name: str) -> Stage:
        return self._by_name[name]

    def ordered(self) -> Tuple[Stage, ...]:
        """The stages in execution order.  Insertion order IS a
        topological order (``add`` refuses forward deps), so this is
        deterministic and needs no tie-breaking."""
        return tuple(self._stages)

    def param(self, stage: Stage, key: str, default=None):
        """Stage-over-plan parameter resolution."""
        if key in stage.params:
            return stage.params[key]
        return self.defaults.get(key, default)

    def signature(self) -> Dict:
        """The plan's job identity (stage-manifest ``job`` field):
        JSON-normalised, bulk inputs as CRCs."""
        return json.loads(json.dumps({
            "plan": self.name,
            "defaults": {k: v for k, v in sorted(self.defaults.items())
                         if not callable(v)},
            "stages": [s.identity() for s in self._stages],
        }))


# ── the two canonical chains ──────────────────────────────────────────


def grep_wordcount_plan(pattern: str, *, paths: Optional[Sequence[str]]
                        = None, data: Optional[bytes] = None,
                        **defaults) -> Plan:
    """grep → wordcount-over-matching-lines: stage 2 counts words over
    exactly the lines stage 1 matched, with the matching-line bytes
    staying device-resident between the stages."""
    p = Plan("grep-wc", **defaults)
    g = p.add(Stage("grep", "grep", pattern=pattern, paths=paths,
                    data=data))
    p.add(Stage("wc", "wordcount", deps=[g.name]))
    return p


def grep_cascade_plan(pattern1: str, pattern2: str, *,
                      paths: Optional[Sequence[str]] = None,
                      data: Optional[bytes] = None, **defaults) -> Plan:
    """grep → grep: stage 2 re-greps exactly the lines stage 1 matched
    (a narrowing filter chain — "lines with A, of those, lines with
    B"), the relay's line stream standing in for the byte source."""
    p = Plan("grep-grep", **defaults)
    g1 = p.add(Stage("grep1", "grep", pattern=pattern1, paths=paths,
                     data=data))
    p.add(Stage("grep2", "grep", deps=[g1.name], pattern=pattern2))
    return p


def wordcount_topk_plan(k: int = 16, *,
                        paths: Optional[Sequence[str]] = None,
                        data: Optional[bytes] = None, **defaults) -> Plan:
    """wordcount → top-k: stage 2 is a host reduction picking the k
    highest-count words of the full count table."""
    p = Plan("wc-topk", **defaults)
    w = p.add(Stage("wc", "wordcount", paths=paths, data=data))
    p.add(Stage("topk", "top_k", deps=[w.name], topk=k))
    return p


def indexer_join_plan(docs: Sequence[bytes], *, topk: int = 16,
                      **defaults) -> Plan:
    """indexer → df-top-k → per-term postings join: stage 2 takes a
    k-row snapshot of the resident df table (no drain), stage 3 decodes
    postings for just those k terms."""
    p = Plan("indexer-join", **defaults)
    i = p.add(Stage("indexer", "indexer", docs=list(docs), topk=topk))
    t = p.add(Stage("dftopk", "df_topk", deps=[i.name], topk=topk))
    p.add(Stage("join", "postings_join", deps=[i.name, t.name]))
    return p
