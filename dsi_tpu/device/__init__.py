"""Device-resident accumulator service.

The layer between the SPMD kernels and the host accumulators: persistent
on-device state that absorbs per-step results with compiled fold/append
programs and meets the host only at sync points — the cross-step
amortization ROADMAP's top open item calls for, and the same shape a
training-stack optimizer/metrics loop needs (device state + periodic
host visibility).

* :mod:`~dsi_tpu.device.table` — :class:`DeviceTable`, the merged
  word/count table the streaming word count folds into.
* :mod:`~dsi_tpu.device.postings` — :class:`DevicePostings`, the
  append-only postings buffer the TF-IDF wave walk batches pulls with.
* :mod:`~dsi_tpu.device.topk` — :class:`DeviceTopK` and
  :class:`DeviceHistogram`, the top-k-by-count table and match-count
  histogram the grep/indexer streaming engines fold into.
* :mod:`~dsi_tpu.device.policy` — :class:`SyncPolicy`, the one owner of
  the every-K-folds pull cadence.
* :mod:`~dsi_tpu.device.relay` — :class:`DeviceRelay` /
  :class:`HostRelay`, the plan layer's inter-stage byte handoff (stage
  N+1's upload IS stage N's device-resident output).
"""

from dsi_tpu.device.policy import (SyncPolicy, mesh_shards_default,
                                   sync_every_default)
from dsi_tpu.device.table import (
    DeviceTable,
    device_fold_persisted,
    warm_device_fold,
)
from dsi_tpu.device.postings import DevicePostings
from dsi_tpu.device.relay import DeviceRelay, HostRelay
from dsi_tpu.device.topk import (
    DeviceHistogram,
    DeviceTopK,
    KeyCounts,
    histogram_persisted,
    topk_service_persisted,
    warm_histogram,
    warm_topk_service,
)

__all__ = [
    "DeviceHistogram",
    "DevicePostings",
    "DeviceRelay",
    "DeviceTable",
    "DeviceTopK",
    "HostRelay",
    "KeyCounts",
    "SyncPolicy",
    "device_fold_persisted",
    "histogram_persisted",
    "mesh_shards_default",
    "sync_every_default",
    "topk_service_persisted",
    "warm_device_fold",
    "warm_histogram",
    "warm_topk_service",
]
