"""Device-resident accumulator service.

The layer between the SPMD kernels and the host accumulators: persistent
on-device state that absorbs per-step results with compiled fold/append
programs and meets the host only at sync points — the cross-step
amortization ROADMAP's top open item calls for, and the same shape a
training-stack optimizer/metrics loop needs (device state + periodic
host visibility).

* :mod:`~dsi_tpu.device.table` — :class:`DeviceTable`, the merged
  word/count table the streaming word count folds into.
* :mod:`~dsi_tpu.device.postings` — :class:`DevicePostings`, the
  append-only postings buffer the TF-IDF wave walk batches pulls with.
* :mod:`~dsi_tpu.device.policy` — :class:`SyncPolicy`, the one owner of
  the every-K-folds pull cadence.
"""

from dsi_tpu.device.policy import SyncPolicy, sync_every_default
from dsi_tpu.device.table import (
    DeviceTable,
    device_fold_persisted,
    warm_device_fold,
)
from dsi_tpu.device.postings import DevicePostings

__all__ = [
    "DevicePostings",
    "DeviceTable",
    "SyncPolicy",
    "device_fold_persisted",
    "sync_every_default",
    "warm_device_fold",
]
